"""Concurrency/race rules over the whole-program call graph.

The async fleet braids three execution contexts through one process:
**thread context** (``threading.Thread`` targets — telemetry exporters,
prefetchers, watchdogs, heartbeats), **loop context** (``async def``
bodies and their sync callees), and the main thread. The bug classes
here are the ones that wedge a fleet or corrupt state silently
(docs/fault_tolerance.md):

- ``thread-unsafe-shared-state`` — instance/module state written from
  thread context and read from async (loop) code with no lock on either
  side: a torn read feeds the rollout loop stale or half-updated state.
- ``asyncio-from-thread`` — asyncio primitives (``asyncio.Queue``,
  ``create_task``, ``loop.call_soon``) touched from thread context:
  asyncio's internals are not thread-safe; the only legal bridges are
  ``run_coroutine_threadsafe`` / ``call_soon_threadsafe``.
- ``lock-order`` — two ``threading`` locks acquired in opposite orders
  on different paths (lexically or through calls): the classic ABBA
  deadlock, invisible until the fleet is under load.
- ``await-in-lock`` (file rule) — ``await`` while holding a
  ``threading.Lock``: every other loop task contending for the lock
  blocks the WHOLE event loop until the awaited I/O completes (and a
  second contender awaiting inside deadlocks it outright).

Context discovery is conservative (docs/static_analysis.md): thread
context is the call-graph closure of ``Thread(target=...)`` entries
traversed only through SYNC functions — an ``async def`` reached from a
thread is being driven by a loop bridge (``asyncio.run`` /
``run_coroutine_threadsafe``) and re-enters loop context, so neither it
nor its callees are treated as thread code. Unresolvable targets and
edges degrade to no-finding.
"""

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.arealint.core import (
    FileContext, ProjectContext, SEVERITY_ERROR, project_rule, rule,
    walk_excluding_nested,
)
from tools.arealint.project import ModuleInfo

# value-constructor classification for ``self.attr = Ctor(...)`` /
# module-level ``name = Ctor(...)``
_THREADING_LOCKS = ("Lock", "RLock", "Condition", "Semaphore",
                    "BoundedSemaphore")
_THREADSAFE_CTORS = {
    # attrs of these kinds are internally synchronized: reading/writing
    # THE ATTR's object from two contexts is their whole point
    "threading": _THREADING_LOCKS + ("Event", "Barrier", "Thread", "local"),
    "queue": ("Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"),
    "collections": ("deque",),
}
_ASYNCIO_QUEUES = ("Queue", "LifoQueue", "PriorityQueue")
_ASYNCIO_QUEUE_METHODS = ("put", "put_nowait", "get", "get_nowait",
                          "task_done", "join")


def _ctor_kind(mod: ModuleInfo, value: ast.expr) -> Optional[str]:
    """'lock' | 'threadsafe' | 'asyncio_queue' | 'asyncio_sync' | None
    for an assigned value expression."""
    if not isinstance(value, ast.Call):
        return None
    f = value.func
    base, name = None, None
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        base, name = f.value.id, f.attr
    elif isinstance(f, ast.Name):
        target = mod.imports.get(f.id, "") if mod else ""
        if "." in target:
            base, name = target.rsplit(".", 1)
    if base == "threading" and name in _THREADING_LOCKS:
        return "lock"
    if base in _THREADSAFE_CTORS and name in _THREADSAFE_CTORS.get(base, ()):
        return "threadsafe"
    if base == "asyncio" and name in _ASYNCIO_QUEUES:
        return "asyncio_queue"
    if base == "asyncio":
        return "asyncio_sync"
    return None


class ModuleModel:
    """Per-module concurrency facts: lock identities and attribute kinds."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        # lock id ("mod.Class.attr" / "mod.name") -> defining line
        self.locks: Dict[str, int] = {}
        # "Class.attr" -> kind (see _ctor_kind)
        self.attr_kinds: Dict[str, str] = {}
        for name, value in mod.assigns.items():
            if _ctor_kind(mod, value) == "lock":
                self.locks[f"{mod.name}.{name}"] = value.lineno
        for ci in mod.classes.values():
            for fi in ci.methods.values():
                for node in ast.walk(fi.node):
                    if not (
                        isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Attribute)
                        and isinstance(node.targets[0].value, ast.Name)
                        and node.targets[0].value.id == "self"
                    ):
                        continue
                    kind = _ctor_kind(mod, node.value)
                    attr = node.targets[0].attr
                    if kind is None:
                        continue
                    self.attr_kinds.setdefault(f"{ci.name}.{attr}", kind)
                    if kind == "lock":
                        self.locks.setdefault(
                            f"{mod.name}.{ci.name}.{attr}", node.lineno
                        )

    def lock_id_for(self, expr: ast.expr, class_name: Optional[str]
                    ) -> Optional[str]:
        """The lock identity a ``with <expr>:`` acquires, or None."""
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and class_name is not None
        ):
            lid = f"{self.mod.name}.{class_name}.{expr.attr}"
            return lid if lid in self.locks else None
        if isinstance(expr, ast.Name):
            lid = f"{self.mod.name}.{expr.id}"
            return lid if lid in self.locks else None
        return None

    def may_be_lock(self, expr: ast.expr, class_name: Optional[str]) -> bool:
        """Degrade-don't-guess companion to :meth:`lock_id_for` for the
        shared-state rules: a ``with`` over a bare name or self/cls
        attribute whose kind this module cannot classify (e.g. a lock
        inherited from a base class in ANOTHER module, or one imported
        from elsewhere) MAY be a lock, so accesses under it count as
        held. Known non-lock kinds stay non-locks."""
        if self.lock_id_for(expr, class_name) is not None:
            return True
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id in ("self", "cls")
        ):
            kind = (
                self.attr_kinds.get(f"{class_name}.{expr.attr}")
                if class_name else None
            )
            return kind is None or kind == "lock"
        if isinstance(expr, ast.Name):
            known = self.mod.assigns.get(expr.id)
            return known is None or _ctor_kind(self.mod, known) == "lock"
        return False


def _models(pctx: ProjectContext) -> Dict[str, ModuleModel]:
    cache = getattr(pctx, "_concurrency_models", None)
    if cache is None:
        cache = {
            name: ModuleModel(mod)
            for name, mod in pctx.project.modules.items()
        }
        pctx._concurrency_models = cache
    return cache


def sync_thread_context(pctx: ProjectContext) -> Set[str]:
    """Qualnames whose bodies run on a spawned thread: closure of
    ``Thread(target=...)`` entries, traversed through sync functions
    only (async callees re-enter loop context via a bridge)."""
    cached = getattr(pctx, "_sync_thread_ctx", None)
    if cached is not None:
        return cached
    graph = pctx.graph
    seen: Set[str] = set()
    work = list(graph.thread_entries)
    while work:
        cur = work.pop()
        if cur in seen:
            continue
        fi = graph.function(cur)
        if fi is not None and fi.is_async:
            continue  # loop context from here on
        seen.add(cur)
        work.extend(graph.edges.get(cur, ()))
    pctx._sync_thread_ctx = seen
    return seen


def _uses_explicit_acquire(
    fnode, model: ModuleModel, class_name: Optional[str]
) -> bool:
    """True when the function's own body calls ``.acquire()`` on a
    possible lock. Flow tracking for acquire/release pairs is out of
    scope — the whole body conservatively counts as lock-held instead
    (degrade to no-finding, never flag correctly-locked code)."""
    return any(
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "acquire"
        and model.may_be_lock(node.func.value, class_name)
        for node in walk_excluding_nested(fnode)
    )


def _self_accesses(
    fnode, model: ModuleModel, class_name: str
) -> Iterable[Tuple[str, str, int, bool]]:
    """(attr, 'load'|'store', line, lock_held) for every ``self.X``
    access in the function's own body (nested defs excluded; a nested
    def is its own context). ``held`` uses :meth:`ModuleModel.may_be_lock`
    — an unclassifiable context manager counts as held, so a lock
    inherited from another module degrades to no-finding."""

    def walk(node, held: bool):
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return
        if isinstance(node, ast.With):
            inner = held or any(
                model.may_be_lock(item.context_expr, class_name)
                for item in node.items
            )
            for item in node.items:
                yield from walk(item, held)
            for stmt in node.body:
                yield from walk(stmt, inner)
            return
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            kind = "store" if isinstance(
                node.ctx, (ast.Store, ast.Del)
            ) else "load"
            yield (node.attr, kind, node.lineno, held)
        for child in ast.iter_child_nodes(node):
            yield from walk(child, held)

    base = _uses_explicit_acquire(fnode, model, class_name)
    for stmt in fnode.body:
        yield from walk(stmt, base)


def _name_accesses(
    fnode, model: ModuleModel, class_name: Optional[str], names: Set[str]
) -> Iterable[Tuple[str, str, int, bool]]:
    """(name, 'load'|'store', line, lock_held) for every bare-Name access
    of ``names`` in the function's own body — the module-global analogue
    of :func:`_self_accesses`, tracking ``with <lock>:`` scopes with the
    same :meth:`ModuleModel.may_be_lock` conservatism."""

    def walk(node, held: bool):
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return
        if isinstance(node, ast.With):
            inner = held or any(
                model.may_be_lock(item.context_expr, class_name)
                for item in node.items
            )
            for item in node.items:
                yield from walk(item, held)
            for stmt in node.body:
                yield from walk(stmt, inner)
            return
        if isinstance(node, ast.Name) and node.id in names:
            kind = "store" if isinstance(
                node.ctx, (ast.Store, ast.Del)
            ) else "load"
            yield (node.id, kind, node.lineno, held)
        for child in ast.iter_child_nodes(node):
            yield from walk(child, held)

    base = _uses_explicit_acquire(fnode, model, class_name)
    for stmt in fnode.body:
        yield from walk(stmt, base)


# --------------------------------------------------------------------- #
# thread-unsafe-shared-state
# --------------------------------------------------------------------- #


@project_rule(
    "thread-unsafe-shared-state", SEVERITY_ERROR,
    "instance attribute written from a threading.Thread target and read "
    "from async (event-loop) code with no lock on either side — torn/stale "
    "reads feed the rollout loop silently",
)
def check_thread_shared_state(pctx: ProjectContext):
    thread_ctx = sync_thread_context(pctx)
    models = _models(pctx)
    for mod_name, mod in pctx.project.modules.items():
        model = models[mod_name]
        for ci in mod.classes.values():
            # accesses per attr from each context
            thread_writes: Dict[str, Tuple[int, bool]] = {}
            async_reads: Dict[str, Tuple[int, bool, str]] = {}
            for fi in ci.methods.values():
                in_thread = fi.qualname in thread_ctx
                if not in_thread and not fi.is_async:
                    continue
                for attr, kind, line, held in _self_accesses(
                    fi.node, model, ci.name
                ):
                    akind = model.attr_kinds.get(f"{ci.name}.{attr}")
                    if akind in ("lock", "threadsafe", "asyncio_queue",
                                 "asyncio_sync"):
                        continue  # internally-synchronized objects
                    if in_thread and kind == "store":
                        # keep the UNheld write if any (that's the bug)
                        prev = thread_writes.get(attr)
                        if prev is None or (prev[1] and not held):
                            thread_writes[attr] = (line, held)
                    if fi.is_async and not in_thread and kind == "load":
                        # loads only: the rule's contract is
                        # written-from-thread / READ-from-async; a
                        # store/store race would mis-cite a write line
                        # as a read and misdirect the fix
                        prev = async_reads.get(attr)
                        if prev is None or (prev[1] and not held):
                            async_reads[attr] = (line, held, fi.name)
            for attr, (wline, wheld) in sorted(thread_writes.items()):
                ar = async_reads.get(attr)
                if ar is None:
                    continue
                rline, rheld, rname = ar
                if wheld and rheld:
                    continue  # both sides under a class lock
                side = (
                    "neither side holds a lock" if not (wheld or rheld)
                    else ("the async reader takes no lock" if wheld
                          else "the thread writer takes no lock")
                )
                yield (
                    mod.path, wline,
                    f"'self.{attr}' is written here on a Thread-target "
                    f"path and read from async {rname}() (line {rline}) "
                    f"— {side}; guard both sides with one threading.Lock, "
                    "use a queue, or annotate a benign/monotonic flag "
                    "with '# arealint: ok(<reason>)'",
                )
        # module-global variant: ``global X`` writes from thread context,
        # loads from async functions in the same module — lock-aware on
        # both sides, like the instance-attribute variant
        g_writes: Dict[str, Tuple[int, str, bool]] = {}
        g_async_reads: Dict[str, Tuple[int, str, bool]] = {}
        for fi in _all_module_functions(mod):
            declared = {
                n for node in ast.walk(fi.node)
                if isinstance(node, ast.Global) for n in node.names
            }
            if fi.qualname in thread_ctx and declared:
                for name, kind, line, held in _name_accesses(
                    fi.node, model, fi.class_name, declared
                ):
                    if kind != "store":
                        continue
                    prev = g_writes.get(name)
                    if prev is None or (prev[2] and not held):
                        g_writes[name] = (line, fi.name, held)
            if fi.is_async and fi.qualname not in thread_ctx:
                # Python scoping: a name ASSIGNED in the function without
                # a ``global`` declaration is local and shadows the
                # module global — reads of it are not global reads
                shadowed = {
                    node.id
                    for node in ast.walk(fi.node)
                    if isinstance(node, ast.Name)
                    and isinstance(node.ctx, (ast.Store, ast.Del))
                } | {a.arg for a in ast.walk(fi.node)
                     if isinstance(a, ast.arg)}
                module_names = (set(mod.assigns) - shadowed) | declared
                for name, kind, line, held in _name_accesses(
                    fi.node, model, fi.class_name, module_names
                ):
                    if kind != "load":
                        continue
                    prev = g_async_reads.get(name)
                    if prev is None or (prev[2] and not held):
                        g_async_reads[name] = (line, fi.name, held)
        for name, (wline, wfn, wheld) in sorted(g_writes.items()):
            ar = g_async_reads.get(name)
            if ar is None:
                continue
            rline, rfn, rheld = ar
            if wheld and rheld:
                continue  # both sides under a module lock
            if _ctor_kind(mod, mod.assigns.get(name, ast.Constant(0))) in (
                "lock", "threadsafe"
            ):
                continue
            side = (
                "neither side holds a lock" if not (wheld or rheld)
                else ("the async reader takes no lock" if wheld
                      else "the thread writer takes no lock")
            )
            yield (
                mod.path, wline,
                f"module global '{name}' is written here in thread-target "
                f"{wfn}() and read from async {rfn}() (line {rline}) "
                f"— {side}; guard both sides or hand the value over "
                "a queue",
            )


def _all_module_functions(mod: ModuleInfo):
    yield from mod.functions.values()
    for ci in mod.classes.values():
        yield from ci.methods.values()


# --------------------------------------------------------------------- #
# asyncio-from-thread
# --------------------------------------------------------------------- #

_ASYNCIO_THREAD_BANNED = ("create_task", "ensure_future",
                          "get_running_loop", "get_event_loop")


@project_rule(
    "asyncio-from-thread", SEVERITY_ERROR,
    "asyncio primitive (asyncio.Queue ops, create_task/ensure_future, "
    "loop.call_soon) touched from threading.Thread context — asyncio is "
    "not thread-safe; bridge with run_coroutine_threadsafe / "
    "call_soon_threadsafe",
)
def check_asyncio_from_thread(pctx: ProjectContext):
    thread_ctx = sync_thread_context(pctx)
    models = _models(pctx)
    for q in sorted(thread_ctx):
        fi = pctx.graph.function(q)
        if fi is None:
            continue
        mod = pctx.project.modules.get(fi.module)
        if mod is None:
            continue
        model = models[fi.module]
        # a function that starts its own loop re-enters loop context for
        # everything it does afterwards; skip its body entirely — but
        # only ITS OWN body: an asyncio.run inside a nested def is a
        # separate execution context and must not exempt the outer
        # thread target
        if any(
            isinstance(n, ast.Call) and _is_asyncio_attr(n.func, "run")
            for n in walk_excluding_nested(fi.node)
        ):
            continue
        for node in walk_excluding_nested(fi.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and _is_asyncio_attr(
                f, None
            ) and f.attr in _ASYNCIO_THREAD_BANNED:
                yield (
                    mod.path, node.lineno,
                    f"asyncio.{f.attr}() called from thread context "
                    f"({fi.name}() runs on a Thread target) — schedule "
                    "onto the loop with asyncio.run_coroutine_threadsafe "
                    "or loop.call_soon_threadsafe instead",
                )
                continue
            if (
                isinstance(f, ast.Attribute)
                and f.attr == "call_soon"
                and _is_loopish(f.value)
            ):
                yield (
                    mod.path, node.lineno,
                    f".call_soon() from thread context ({fi.name}() runs "
                    "on a Thread target) is not thread-safe — use "
                    ".call_soon_threadsafe",
                )
                continue
            # asyncio.Queue-typed attribute ops
            if (
                isinstance(f, ast.Attribute)
                and f.attr in _ASYNCIO_QUEUE_METHODS
                and isinstance(f.value, ast.Attribute)
                and isinstance(f.value.value, ast.Name)
                and f.value.value.id == "self"
                and fi.class_name is not None
                and model.attr_kinds.get(
                    f"{fi.class_name}.{f.value.attr}"
                ) == "asyncio_queue"
            ):
                yield (
                    mod.path, node.lineno,
                    f"asyncio.Queue method .{f.attr}() called on "
                    f"'self.{f.value.attr}' from thread context "
                    f"({fi.name}() runs on a Thread target) — asyncio "
                    "queues are loop-affine; bridge with "
                    "run_coroutine_threadsafe (or use queue.Queue)",
                )


def _is_loopish(expr: ast.AST) -> bool:
    """True when ``expr`` is recognizably an event loop: a name/attribute
    spelled ``*loop`` (self.loop, self._loop, loop) or a direct
    ``asyncio.get_event_loop()/get_running_loop()`` call. Anything else
    (a user object that happens to have a ``call_soon`` method) degrades
    to no-finding."""
    if (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Attribute)
        and expr.func.attr in ("get_event_loop", "get_running_loop")
    ):
        return True
    name = None
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    return name is not None and name.lower().endswith("loop")


def _is_asyncio_attr(f: ast.AST, attr: Optional[str]) -> bool:
    return (
        isinstance(f, ast.Attribute)
        and isinstance(f.value, ast.Name)
        and f.value.id == "asyncio"
        and (attr is None or f.attr == attr)
    )


# --------------------------------------------------------------------- #
# lock-order
# --------------------------------------------------------------------- #


@project_rule(
    "lock-order", SEVERITY_ERROR,
    "two threading locks acquired in opposite orders on different paths "
    "(lexically or across calls) — ABBA deadlock under contention",
)
def check_lock_order(pctx: ProjectContext):
    models = _models(pctx)
    graph = pctx.graph

    # per function: direct acquisitions, nested pairs, calls-under-lock
    acquires: Dict[str, Set[str]] = {}
    pair_sites: Dict[Tuple[str, str], List[Tuple[str, int, str]]] = {}
    calls_held: List[Tuple[str, List[str], str, str, int]] = []

    for mod_name, mod in pctx.project.modules.items():
        model = models[mod_name]
        if not model.locks:
            continue
        for fi in _all_module_functions(mod):
            direct: Set[str] = set()

            def walk(node, held: List[str]):
                if isinstance(
                    node,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                ):
                    return
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    new = list(held)
                    for item in node.items:
                        lid = model.lock_id_for(
                            item.context_expr, fi.class_name
                        )
                        if lid:
                            direct.add(lid)
                            for h in new:
                                if h != lid:
                                    pair_sites.setdefault(
                                        (h, lid), []
                                    ).append(
                                        (mod.path, item.context_expr.lineno,
                                         fi.name)
                                    )
                            new.append(lid)
                    for stmt in node.body:
                        walk(stmt, new)
                    return
                if isinstance(node, ast.Call) and held:
                    site = next(
                        (
                            s for s in graph.sites_by_caller.get(
                                fi.qualname, ()
                            )
                            if s.node is node
                        ),
                        None,
                    )
                    if site is not None:
                        calls_held.append(
                            (fi.qualname, list(held), site.callee,
                             mod.path, node.lineno)
                        )
                for child in ast.iter_child_nodes(node):
                    walk(child, held)

            for stmt in fi.node.body:
                walk(stmt, [])
            if direct:
                acquires[fi.qualname] = direct

    if not pair_sites and not calls_held:
        return

    # closure of locks acquired by each function's callees
    def locks_closure(q: str) -> Set[str]:
        out: Set[str] = set()
        for r in graph.reachable([q]):
            out |= acquires.get(r, set())
        return out

    for caller, held, callee, path, line in calls_held:
        for lid in sorted(locks_closure(callee)):
            for h in held:
                if h != lid:
                    pair_sites.setdefault((h, lid), []).append(
                        (path, line,
                         f"{caller.rsplit('.', 1)[-1]} -> "
                         f"{callee.rsplit('.', 1)[-1]}")
                    )

    # order-graph edges + cycle detection
    order: Dict[str, Set[str]] = {}
    for (a, b) in pair_sites:
        order.setdefault(a, set()).add(b)

    def reaches(src: str, dst: str) -> bool:
        seen, work = set(), [src]
        while work:
            cur = work.pop()
            if cur == dst:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            work.extend(order.get(cur, ()))
        return False

    emitted: Set[Tuple[str, int, str, str]] = set()
    for (a, b), sites in sorted(pair_sites.items()):
        if not reaches(b, a):
            continue
        other = pair_sites.get((b, a), [])
        where = (
            f" (reverse order at {other[0][0]}:{other[0][1]})"
            if other else ""
        )
        for path, line, via in sites:
            key = (path, line, a, b)
            if key in emitted:
                continue
            emitted.add(key)
            yield (
                path, line,
                f"lock '{_short(a)}' is held while acquiring "
                f"'{_short(b)}' here ({via}), but another path acquires "
                f"them in the reverse order{where} — ABBA deadlock; pick "
                "one global order",
            )


def _short(lock_id: str) -> str:
    parts = lock_id.split(".")
    return ".".join(parts[-2:]) if len(parts) > 1 else lock_id


# --------------------------------------------------------------------- #
# await-in-lock (file rule: purely lexical)
# --------------------------------------------------------------------- #


@rule(
    "await-in-lock", SEVERITY_ERROR,
    "await while holding a threading.Lock — the lock is held across the "
    "suspension, blocking every loop task that contends for it (and "
    "deadlocking if one of them awaits inside it too)",
)
def check_await_in_lock(ctx: FileContext):
    mod = ModuleInfo("<file>", ctx.path, ctx.tree, ctx.src)
    # reuse the project indexing for imports/classes on this one file
    from tools.arealint.project import _index_module

    _index_module(mod)
    model = ModuleModel(mod)
    if not model.locks:
        return
    parents = ctx.parents()

    def enclosing_class(node) -> Optional[str]:
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            cur = parents.get(cur)
        return None

    found: List[Tuple[int, str]] = []

    def walk(node, held: Optional[str], cls: Optional[str]):
        if isinstance(node, (ast.FunctionDef, ast.Lambda)):
            return  # sync context: a plain callee may run anywhere
        if isinstance(node, ast.AsyncFunctionDef):
            for stmt in node.body:
                walk(stmt, None, cls)
            return
        if isinstance(node, ast.With):
            lid = held
            for item in node.items:
                got = model.lock_id_for(item.context_expr, cls)
                if got:
                    lid = got
            for stmt in node.body:
                walk(stmt, lid, cls)
            return
        if isinstance(node, ast.Await) and held is not None:
            found.append((
                node.lineno,
                f"await while holding threading lock '{_short(held)}' — "
                "the lock stays held across the suspension and stalls "
                "every contending loop task; release before awaiting, or "
                "use asyncio.Lock for loop-side mutual exclusion",
            ))
        for child in ast.iter_child_nodes(node):
            walk(child, held, cls)

    for fnode in ast.walk(ctx.tree):
        if isinstance(fnode, ast.AsyncFunctionDef):
            cls = enclosing_class(fnode)
            for stmt in fnode.body:
                walk(stmt, None, cls)
    # dedupe nested-async double visits (ast.walk reaches inner async
    # defs both directly and via the outer walk)
    yield from sorted(set(found))
