"""Wire-contract model of the HTTP/SSE control and data plane.

Parsed with ``ast``, never imported — the same provenance contract as the
counter / fault / mesh / resource catalogs: a catalog the linter checks
code against must itself be derived from the tree it checks, so a
refactor that moves an endpoint invalidates the model instead of
silently checking against a stale one.

Two sides are modeled (docs/static_analysis.md "Wire rules"):

- **server endpoints** (:func:`parse_server_module`): every
  ``router.add_post/add_get`` registration with a literal path, plus a
  transitive walk of the handler (same-module helpers only) collecting
  request-body fields read (``d["k"]`` = required, ``d.get("k")`` /
  ``"k" in d`` = optional), response-body keys written
  (``web.json_response({...})``), HTTP statuses emitted
  (``status=`` constants, ``web.HTTPxxx`` raises), and SSE frame keys
  (dict literals reaching ``resp.write(... json.dumps(x) ...)``,
  including frames fed through an ``asyncio.Queue``).
- **client call sites** (:func:`parse_client_modules`): direct
  ``session.post(f"{base}/path", json={...})`` calls and calls through
  client-class wrappers (``GenAPIClient.generate`` → ``_request_json``),
  with the payload fields sent, response / SSE frame keys read
  (including the ``asyncio.gather`` + ``zip`` fan-out idiom), statuses
  branched on, and whether the call path retries on HTTP statuses.

Everything that does not resolve statically DEGRADES: a dynamic path,
computed field name, ``**kwargs`` payload, or unrecognized receiver
produces no model entry (and marks the key set open where one-sided
knowledge would otherwise fabricate a finding). A deliberate one-sided
field is vouched for in place::

    **hbm_gauges,  # arealint: wire(/metrics_json, hbm gauge keys merged from HBMMonitor.check)

The annotation names the ENDPOINT (so a refactor that moves the line to
a different handler invalidates it) and requires a reason, same as
``# arealint: ok`` / ``owns``.
"""

import ast
import dataclasses
import pathlib
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

WIRE_RE = re.compile(
    r"#\s*arealint:\s*wire\(\s*(?P<endpoint>[^,()]+?)\s*,\s*(?P<reason>[^)]+?)\s*\)"
)
WIRE_BARE_RE = re.compile(r"#\s*arealint:\s*wire\b")

# aiohttp's web.HTTPxxx exception classes the tree raises (raising one
# sends that status). Names not listed degrade to no-status.
AIOHTTP_STATUS = {
    "HTTPBadRequest": 400,
    "HTTPUnauthorized": 401,
    "HTTPForbidden": 403,
    "HTTPNotFound": 404,
    "HTTPConflict": 409,
    "HTTPTooManyRequests": 429,
    "HTTPInternalServerError": 500,
    "HTTPBadGateway": 502,
    "HTTPServiceUnavailable": 503,
    "HTTPGatewayTimeout": 504,
}

ROUTE_METHODS = {
    "add_post": "POST",
    "add_get": "GET",
    "add_put": "PUT",
    "add_delete": "DELETE",
}

# Every endpoint can answer 200 (success) and 500 (unhandled handler
# exception — aiohttp converts it); clients may branch on these freely.
IMPLICIT_STATUSES = frozenset({200, 500})

_MAX_DEPTH = 8


# --------------------------------------------------------------------- #
# Declaration + verification (the provenance contract)
# --------------------------------------------------------------------- #


@dataclasses.dataclass(frozen=True)
class WireDefs:
    """DECLARED wire surface: which modules register routes, which
    modules speak to them, and which endpoints must never be re-POSTed
    on an HTTP status (the request may already be executing server-side).
    Verified against the tree before use; entries that no longer match
    are dropped (degrade, never guess)."""

    server_modules: Tuple[str, ...]
    client_modules: Tuple[str, ...]
    non_idempotent: Tuple[str, ...]


DEFAULT_WIRE_DEFS = WireDefs(
    server_modules=(
        "areal_tpu/gateway/api.py",
        "areal_tpu/gen/server.py",
        "areal_tpu/system/gserver_manager.py",
    ),
    client_modules=(
        "areal_tpu/gen/client.py",
        "areal_tpu/gateway/scheduler.py",
        "areal_tpu/gateway/brownout.py",
        "areal_tpu/gateway/autoscaler.py",
        "areal_tpu/system/fleet.py",
        "areal_tpu/system/partial_rollout.py",
        "areal_tpu/system/rollout_worker.py",
        "areal_tpu/system/gserver_manager.py",
        "areal_tpu/apps/launcher.py",
        "areal_tpu/apps/obs.py",
    ),
    # re-sending one of these on a 5xx may double-execute a request the
    # server is still running (double-billed rid / double weight load)
    non_idempotent=(
        "/generate",
        "/generate_stream",
        "/update_weights_from_disk",
    ),
)


@dataclasses.dataclass(frozen=True)
class WireSpec:
    """The VERIFIED declaration ``Config.wire`` carries: only modules
    that exist (servers: and register at least one literal route) and
    non-idempotent paths some surviving server actually registers."""

    servers: Tuple[str, ...]
    clients: Tuple[str, ...]
    non_idempotent: frozenset


def verify_defs(
    root: pathlib.Path, defs: WireDefs = DEFAULT_WIRE_DEFS
) -> Tuple[Optional[WireSpec], List[str]]:
    """Check the declaration against the tree. Returns ``(spec, dropped)``
    where ``dropped`` lists human-readable reasons for every declared
    entry that failed verification. ``spec`` is None when no server
    module survives (wire rules disabled entirely)."""
    dropped: List[str] = []
    servers: List[str] = []
    registered: Set[str] = set()
    for rel in defs.server_modules:
        p = root / rel
        if not p.is_file():
            dropped.append(f"server module {rel}: file missing")
            continue
        try:
            tree = ast.parse(p.read_text(), filename=str(p))
        except SyntaxError:
            dropped.append(f"server module {rel}: does not parse")
            continue
        routes = find_routes(tree)
        if not routes:
            dropped.append(f"server module {rel}: registers no literal route")
            continue
        servers.append(rel)
        registered.update(path for _m, path, _h, _ln in routes)
    clients: List[str] = []
    for rel in defs.client_modules:
        p = root / rel
        if not p.is_file():
            dropped.append(f"client module {rel}: file missing")
            continue
        clients.append(rel)
    non_idem: List[str] = []
    for path in defs.non_idempotent:
        if path in registered:
            non_idem.append(path)
        else:
            dropped.append(f"non-idempotent path {path}: no server registers it")
    if not servers:
        return None, dropped
    return (
        WireSpec(tuple(servers), tuple(clients), frozenset(non_idem)),
        dropped,
    )


def from_repo(root: pathlib.Path) -> Optional[WireSpec]:
    spec, _dropped = verify_defs(pathlib.Path(root))
    return spec


# --------------------------------------------------------------------- #
# Model dataclasses
# --------------------------------------------------------------------- #


@dataclasses.dataclass
class KeySet:
    """A set of wire keys with open ends: literal keys, literal prefixes
    (from ``{f"engine_{k}": v ...}`` comprehensions), and an ``open``
    flag set when any contributor did not resolve (an open set covers
    everything — degrade, never guess)."""

    keys: Dict[str, int] = dataclasses.field(default_factory=dict)
    prefixes: List[str] = dataclasses.field(default_factory=list)
    open: bool = False

    def covers(self, key: str) -> bool:
        return (
            self.open
            or key in self.keys
            or any(key.startswith(p) for p in self.prefixes)
        )

    def merge(self, other: "KeySet") -> None:
        for k, ln in other.keys.items():
            self.keys.setdefault(k, ln)
        for p in other.prefixes:
            if p not in self.prefixes:
                self.prefixes.append(p)
        self.open = self.open or other.open


@dataclasses.dataclass
class Endpoint:
    path: str
    method: str
    module: str
    handler: str
    lineno: int  # registration line
    required: Dict[str, int] = dataclasses.field(default_factory=dict)
    optional: Dict[str, int] = dataclasses.field(default_factory=dict)
    # True when the body escapes (stored / passed outside the module):
    # the handler may read fields we cannot see, so "client sends a field
    # no handler reads" must not fire.
    fields_open: bool = False
    response: KeySet = dataclasses.field(default_factory=KeySet)
    statuses: Dict[int, int] = dataclasses.field(default_factory=dict)
    sse: Optional[KeySet] = None

    def emits(self, status: int) -> bool:
        return status in IMPLICIT_STATUSES or status in self.statuses


@dataclasses.dataclass
class ClientCall:
    """One resolved client-side HTTP call site."""

    module: str
    lineno: int
    method: str
    path: str
    via: str  # "session.post" or "GenAPIClient.generate"
    payload: Optional[Dict[str, int]] = None  # None = unresolved payload
    reads: Dict[str, int] = dataclasses.field(default_factory=dict)
    sse_reads: Dict[str, int] = dataclasses.field(default_factory=dict)
    status_branches: Dict[int, int] = dataclasses.field(default_factory=dict)
    # raise_for_status() (possibly inside the wrapper) or a broad
    # except around the call: non-2xx statuses are handled generically
    generic_status_guard: bool = False
    # the call path re-sends the request on RETRYABLE HTTP statuses
    # (a wrapper with retry_connection_only left False)
    retries_status: bool = False
    # the call returns an SSE frame iterator (generate_stream): reads
    # associate with the endpoint's frame keys, not its response body
    sse_wrapper: bool = False


@dataclasses.dataclass
class WireModel:
    spec: WireSpec
    endpoints: Dict[Tuple[str, str], List[Endpoint]]  # (method, path)
    calls: List[ClientCall]
    servers_present: bool  # all spec.servers were in the scanned set
    clients_present: bool

    def lookup(self, method: str, path: str) -> List[Endpoint]:
        return self.endpoints.get((method, path), [])

    def path_known(self, path: str) -> bool:
        return any(p == path for (_m, p) in self.endpoints)

    def calls_to(self, method: str, path: str) -> List[ClientCall]:
        return [
            c for c in self.calls if c.method == method and c.path == path
        ]


# --------------------------------------------------------------------- #
# Shared AST helpers
# --------------------------------------------------------------------- #


def _dotted(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _unwrap_await(node: ast.AST) -> ast.AST:
    return node.value if isinstance(node, ast.Await) else node


def wire_annotation(
    lines: Sequence[str], lineno: int
) -> Optional[Tuple[Optional[str], Optional[str]]]:
    """The ``# arealint: wire(<endpoint>, <reason>)`` annotation on
    ``lineno`` or a comment-only line directly above. Returns
    ``(endpoint, reason)``; ``(None, None)`` for a present-but-malformed
    annotation; None when absent. 1-indexed."""
    for ln in (lineno, lineno - 1):
        if not (1 <= ln <= len(lines)):
            continue
        text = lines[ln - 1]
        if ln != lineno and not text.strip().startswith("#"):
            continue
        m = WIRE_RE.search(text)
        if m:
            return m.group("endpoint").strip(), m.group("reason").strip()
        if WIRE_BARE_RE.search(text):
            return None, None
    return None


def _vouched(lines: Sequence[str], lineno: int, endpoint: str) -> bool:
    ann = wire_annotation(lines, lineno)
    return ann is not None and ann[0] == endpoint and bool(ann[1])


class _ModuleIndex:
    """Light per-module symbol index: top-level functions, classes with
    their methods, a parent-function map (closures see enclosing params),
    and the import alias table."""

    def __init__(self, tree: ast.Module, src: str):
        self.tree = tree
        self.lines = src.splitlines()
        self.functions: Dict[str, ast.AST] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        self.methods: Dict[str, Dict[str, ast.AST]] = {}
        self.parent_fn: Dict[int, Optional[ast.AST]] = {}
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
                self.methods[node.name] = {
                    n.name: n
                    for n in node.body
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                }
        self._map_parents(tree, None)

    def _map_parents(self, node: ast.AST, fn: Optional[ast.AST]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.parent_fn[id(child)] = fn
                self._map_parents(child, child)
            else:
                self._map_parents(child, fn)

    def param_names(self, fn: ast.AST) -> List[str]:
        a = fn.args
        names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names

    def enclosing_params(self, fn: ast.AST) -> Dict[str, ast.AST]:
        """Param name -> annotation node, walking out through enclosing
        functions (closures)."""
        out: Dict[str, ast.AST] = {}
        cur: Optional[ast.AST] = fn
        while cur is not None:
            a = cur.args
            for p in a.posonlyargs + a.args + a.kwonlyargs:
                out.setdefault(p.arg, p.annotation)
            cur = self.parent_fn.get(id(cur))
        return out


def _walk_fn(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk a function body WITHOUT descending into nested defs (their
    bodies are analyzed on their own when called)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


def find_routes(tree: ast.Module) -> List[Tuple[str, str, str, int]]:
    """``(method, path, handler_name, lineno)`` for every
    ``<x>.router.add_*("/literal", handler)`` call in the module.
    Dynamic paths and unrecognized handler expressions are skipped."""
    out: List[Tuple[str, str, str, int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (
            isinstance(fn, ast.Attribute)
            and fn.attr in ROUTE_METHODS
            and isinstance(fn.value, ast.Attribute)
            and fn.value.attr == "router"
        ):
            continue
        if len(node.args) < 2:
            continue
        path = _const_str(node.args[0])
        if path is None:
            continue  # dynamic path: degrade
        h = node.args[1]
        if isinstance(h, ast.Attribute):
            handler = h.attr
        elif isinstance(h, ast.Name):
            handler = h.id
        else:
            continue
        out.append((ROUTE_METHODS[fn.attr], path, handler, node.lineno))
    return out


# --------------------------------------------------------------------- #
# Server side
# --------------------------------------------------------------------- #


class _HandlerScan:
    """Transitive walk of one handler (same-module helpers only),
    collecting body-field reads, response keys, statuses, and SSE frame
    keys into an :class:`Endpoint`."""

    def __init__(self, idx: _ModuleIndex, cls: Optional[str], ep: Endpoint):
        self.idx = idx
        self.cls = cls
        self.ep = ep
        self._seen: Set[Tuple[int, frozenset]] = set()
        self._queue_frames: Optional[KeySet] = None

    # ---- entry ----

    def run(self, fn: ast.AST) -> None:
        params = self.idx.param_names(fn)
        roles: Dict[str, str] = {}
        for p in params:
            if p in ("self", "cls"):
                continue
            roles[p] = "request"
            break  # the single aiohttp request argument
        self._scan(fn, roles, {}, 0)

    # ---- function-level scan ----

    def _scan(
        self,
        fn: ast.AST,
        roles: Dict[str, str],  # param/var name -> "request" | "body"
        consts: Dict[str, object],  # param name -> constant call-site arg
        depth: int,
    ) -> Tuple[List[str], bool]:
        """Returns ``(return_roles, returns_body)`` where return_roles
        marks tuple slots of the return value that carry the body."""
        key = (
            id(fn),
            frozenset(roles.items()),
            frozenset((k, repr(v)) for k, v in consts.items()),
        )
        if key in self._seen or depth > _MAX_DEPTH:
            return [], False
        self._seen.add(key)
        body_vars = {n for n, r in roles.items() if r == "body"}
        request_vars = {n for n, r in roles.items() if r == "request"}
        stream_vars: Set[str] = set()
        ret_slots: List[str] = []
        returns_body = False

        def is_body(node: ast.AST) -> bool:
            return isinstance(node, ast.Name) and node.id in body_vars

        nodes = list(_walk_fn(fn))
        # pass 1 (to fixpoint): bind body / stream vars before reads are
        # attributed — the AST walk is not source-ordered
        for _pass in range(2):
            for node in nodes:
                if not (
                    isinstance(node, ast.Assign) and len(node.targets) == 1
                ):
                    continue
                tgt, val = node.targets[0], _unwrap_await(node.value)
                if (
                    isinstance(tgt, ast.Name)
                    and isinstance(val, ast.Call)
                    and isinstance(val.func, ast.Attribute)
                    and val.func.attr == "json"
                    and isinstance(val.func.value, ast.Name)
                    and val.func.value.id in request_vars
                ):
                    body_vars.add(tgt.id)
                elif isinstance(val, ast.Call) and _pass == 0:
                    slots = self._local_call_body_slots(
                        val, body_vars, request_vars, consts, depth
                    )
                    if slots is not None:
                        whole, tuple_slots = slots
                        if isinstance(tgt, ast.Name) and whole:
                            body_vars.add(tgt.id)
                        elif isinstance(tgt, ast.Tuple):
                            for i, el in enumerate(tgt.elts):
                                if (
                                    isinstance(el, ast.Name)
                                    and i in tuple_slots
                                ):
                                    body_vars.add(el.id)
                if (
                    isinstance(tgt, ast.Name)
                    and isinstance(val, ast.Call)
                    and (_dotted(val.func) or "").endswith("StreamResponse")
                ):
                    stream_vars.add(tgt.id)
        # pass 2: reads / responses / statuses / SSE / escapes / returns
        for node in nodes:
            if isinstance(node, ast.Subscript) and is_body(node.value):
                k = _const_str(node.slice)
                if k is not None:
                    self.ep.required.setdefault(k, node.lineno)
                else:
                    self.ep.fields_open = True
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and is_body(node.func.value)
                and node.args
            ):
                k = _const_str(node.args[0])
                if k is not None:
                    self.ep.optional.setdefault(k, node.lineno)
                else:
                    self.ep.fields_open = True
            if isinstance(node, ast.Compare) and len(node.ops) == 1:
                if isinstance(node.ops[0], (ast.In, ast.NotIn)) and is_body(
                    node.comparators[0]
                ):
                    k = _const_str(node.left)
                    if k is not None:
                        self.ep.optional.setdefault(k, node.lineno)
            # --- responses / statuses ---
            if isinstance(node, ast.Call):
                d = _dotted(node.func) or ""
                if d.endswith("json_response"):
                    self._take_json_response(node, fn, consts)
                elif node.func and self._is_http_exc(node):
                    pass  # handled at the Raise below
                else:
                    self._maybe_recurse_local(
                        node, fn, body_vars, request_vars, depth
                    )
            if isinstance(node, ast.Raise) and node.exc is not None:
                exc = node.exc
                name = None
                if isinstance(exc, ast.Call):
                    name = (_dotted(exc.func) or "").rsplit(".", 1)[-1]
                elif isinstance(exc, (ast.Name, ast.Attribute)):
                    name = (_dotted(exc) or "").rsplit(".", 1)[-1]
                if name in AIOHTTP_STATUS:
                    self.ep.statuses.setdefault(
                        AIOHTTP_STATUS[name], node.lineno
                    )
            # --- SSE writes ---
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "write"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in stream_vars
                and node.args
            ):
                self._take_sse_write(node.args[0], fn)
            # --- body escaping the module (degrade the warn direction) ---
            if isinstance(node, ast.Call):
                for a in node.args:
                    av = a.value if isinstance(a, ast.Starred) else a
                    if is_body(av) and not self._is_local_call(node):
                        callee = (_dotted(node.func) or "").rsplit(".", 1)[-1]
                        # dict(d) / json.dumps(d) etc read, don't hide reads
                        if callee not in (
                            "dict", "dumps", "len", "str", "repr",
                        ):
                            self.ep.fields_open = True
            # --- returns ---
            if isinstance(node, ast.Return) and node.value is not None:
                v = node.value
                if is_body(v):
                    returns_body = True
                elif isinstance(v, ast.Tuple):
                    for i, el in enumerate(v.elts):
                        if is_body(el):
                            ret_slots.append(str(i))
        return ret_slots, returns_body

    # ---- helpers ----

    def _is_http_exc(self, call: ast.Call) -> bool:
        name = (_dotted(call.func) or "").rsplit(".", 1)[-1]
        return name in AIOHTTP_STATUS

    def _is_local_call(self, call: ast.Call) -> bool:
        return self._resolve_local(call) is not None

    def _resolve_local(self, call: ast.Call) -> Optional[ast.AST]:
        """Same-class method (``self._x(...)``) or same-module function."""
        f = call.func
        if (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id == "self"
            and self.cls is not None
        ):
            return self.idx.methods.get(self.cls, {}).get(f.attr)
        if isinstance(f, ast.Name):
            return self.idx.functions.get(f.id)
        return None

    def _local_call_body_slots(
        self,
        call: ast.Call,
        body_vars: Set[str],
        request_vars: Set[str],
        consts: Dict[str, object],
        depth: int,
    ) -> Optional[Tuple[bool, Set[int]]]:
        """Recurse into a local callee, binding request/body roles from
        the call-site args; returns (returns_body, body_tuple_slots)."""
        target = self._resolve_local(call)
        if target is None:
            return None
        roles = self._bind_roles(call, target, body_vars, request_vars)
        callee_consts = self._bind_consts(call, target)
        ret_slots, returns_body = self._scan(
            target, roles, callee_consts, depth + 1
        )
        return returns_body, {int(s) for s in ret_slots}

    def _maybe_recurse_local(
        self,
        call: ast.Call,
        fn: ast.AST,
        body_vars: Set[str],
        request_vars: Set[str],
        depth: int,
    ) -> None:
        target = self._resolve_local(call)
        if target is None or target is fn:
            return
        roles = self._bind_roles(call, target, body_vars, request_vars)
        self._scan(target, roles, self._bind_consts(call, target), depth + 1)

    def _bind_roles(
        self,
        call: ast.Call,
        target: ast.AST,
        body_vars: Set[str],
        request_vars: Set[str],
    ) -> Dict[str, str]:
        roles: Dict[str, str] = {}
        params = [
            p for p in self.idx.param_names(target) if p not in ("self", "cls")
        ]
        args = list(call.args)
        for i, a in enumerate(args):
            if i >= len(params):
                break
            if isinstance(a, ast.Name):
                if a.id in body_vars:
                    roles[params[i]] = "body"
                elif a.id in request_vars:
                    roles[params[i]] = "request"
        for kw in call.keywords:
            if kw.arg and isinstance(kw.value, ast.Name):
                if kw.value.id in body_vars:
                    roles[kw.arg] = "body"
                elif kw.value.id in request_vars:
                    roles[kw.arg] = "request"
        return roles

    def _bind_consts(self, call: ast.Call, target: ast.AST) -> Dict[str, object]:
        consts: Dict[str, object] = {}
        params = [
            p for p in self.idx.param_names(target) if p not in ("self", "cls")
        ]
        for i, a in enumerate(call.args):
            if i < len(params) and isinstance(a, ast.Constant):
                consts[params[i]] = a.value
        for kw in call.keywords:
            if kw.arg and isinstance(kw.value, ast.Constant):
                consts[kw.arg] = kw.value.value
        return consts

    def _take_json_response(
        self, call: ast.Call, fn: ast.AST, consts: Dict[str, object]
    ) -> None:
        status = 200
        for kw in call.keywords:
            if kw.arg == "status":
                if isinstance(kw.value, ast.Constant) and isinstance(
                    kw.value.value, int
                ):
                    status = kw.value.value
                elif (
                    isinstance(kw.value, ast.Name)
                    and isinstance(consts.get(kw.value.id), int)
                ):
                    status = consts[kw.value.id]  # type: ignore[assignment]
                else:
                    status = -1  # dynamic: record keys, skip status
        if status not in (200, -1):
            self.ep.statuses.setdefault(status, call.lineno)
        if call.args:
            ks = self._resolve_keys(call.args[0], fn, 0)
            self.ep.response.merge(ks)

    def _take_sse_write(self, arg: ast.AST, fn: ast.AST) -> None:
        """A ``resp.write(...)`` on a StreamResponse: find json.dumps
        payloads inside the written expression."""
        if self.ep.sse is None:
            self.ep.sse = KeySet()
        for node in ast.walk(arg):
            if (
                isinstance(node, ast.Call)
                and (_dotted(node.func) or "").endswith("dumps")
                and node.args
            ):
                self.ep.sse.merge(self._resolve_frame(node.args[0], fn))

    def _resolve_frame(self, expr: ast.AST, fn: ast.AST) -> KeySet:
        if isinstance(expr, ast.Dict):
            return self._resolve_keys(expr, fn, 0)
        if isinstance(expr, ast.Name):
            # frame pulled off a queue: the frames are whatever the class
            # puts into queues (put/put_nowait dict literals); any
            # non-literal put opens the set
            for node in _walk_fn(fn):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == expr.id
                ):
                    val = _unwrap_await(node.value)
                    if self._is_queue_get(val):
                        return self._class_queue_frames()
                    if isinstance(val, ast.Dict):
                        return self._resolve_keys(val, fn, 0)
        return KeySet(open=True)

    def _is_queue_get(self, val: ast.AST) -> bool:
        if isinstance(val, ast.Call):
            d = _dotted(val.func) or ""
            if d.endswith(".get") and not val.args:
                return True
            if d.endswith("wait_for") and val.args:
                return self._is_queue_get(val.args[0])
        return False

    def _class_queue_frames(self) -> KeySet:
        if self._queue_frames is not None:
            return self._queue_frames
        ks = KeySet()
        scope: ast.AST = (
            self.idx.classes.get(self.cls) if self.cls else self.idx.tree
        ) or self.idx.tree
        for node in ast.walk(scope):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("put_nowait", "put")
                and node.args
            ):
                if isinstance(node.args[0], ast.Dict):
                    ks.merge(self._resolve_keys(node.args[0], None, 0))
                else:
                    ks.open = True
        return ks

    def _resolve_keys(
        self, expr: ast.AST, fn: Optional[ast.AST], depth: int
    ) -> KeySet:
        """Key set of a response/frame expression. Dict literals resolve
        (recursing through ``**`` splats into nested literals, same-class
        method returns, and prefix comprehensions); a ``wire()``-vouched
        splat is skipped; anything else opens the set."""
        ks = KeySet()
        if depth > _MAX_DEPTH:
            ks.open = True
            return ks
        if isinstance(expr, ast.Dict):
            for k, v in zip(expr.keys, expr.values):
                if k is None:  # **splat
                    ks.merge(self._resolve_splat(v, fn, depth))
                else:
                    key = _const_str(k)
                    if key is not None:
                        ks.keys.setdefault(key, k.lineno)
                    else:
                        ks.open = True
            return ks
        if isinstance(expr, ast.Name) and fn is not None:
            for node in _walk_fn(fn):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == expr.id
                    and isinstance(_unwrap_await(node.value), ast.Dict)
                ):
                    ks.merge(
                        self._resolve_keys(
                            _unwrap_await(node.value), fn, depth + 1
                        )
                    )
                    # constant subscript stores extend the literal
                    for n2 in _walk_fn(fn):
                        if (
                            isinstance(n2, ast.Assign)
                            and len(n2.targets) == 1
                            and isinstance(n2.targets[0], ast.Subscript)
                            and isinstance(n2.targets[0].value, ast.Name)
                            and n2.targets[0].value.id == expr.id
                        ):
                            k2 = _const_str(n2.targets[0].slice)
                            if k2 is not None:
                                ks.keys.setdefault(k2, n2.lineno)
                            else:
                                ks.open = True
                    return ks
            ks.open = True
            return ks
        ks.open = True
        return ks

    def _resolve_splat(
        self, v: ast.AST, fn: Optional[ast.AST], depth: int
    ) -> KeySet:
        if isinstance(v, ast.Dict):
            return self._resolve_keys(v, fn, depth + 1)
        if isinstance(v, ast.DictComp):
            ks = KeySet()
            if (
                isinstance(v.key, ast.JoinedStr)
                and v.key.values
                and isinstance(v.key.values[0], ast.Constant)
                and isinstance(v.key.values[0].value, str)
                and v.key.values[0].value
            ):
                ks.prefixes.append(v.key.values[0].value)
            else:
                k = _const_str(v.key)
                if k is not None:
                    ks.keys[k] = v.lineno
                else:
                    ks.open = True
            return ks
        if isinstance(v, ast.Call):
            # same-class method call: union of its returned dict keys
            target = self._resolve_local(v)
            if target is not None:
                ks = KeySet()
                found = False
                for node in _walk_fn(target):
                    if isinstance(node, ast.Return) and node.value is not None:
                        found = True
                        ks.merge(
                            self._resolve_keys(node.value, target, depth + 1)
                        )
                if found:
                    return ks
        if _vouched(self.idx.lines, v.lineno, self.ep.path):
            return KeySet()  # deliberately one-sided: vouched, not open
        return KeySet(open=True)


def parse_server_module(
    relpath: str, tree: ast.Module, src: str
) -> List[Endpoint]:
    """Endpoint catalog of one route-registering module."""
    idx = _ModuleIndex(tree, src)
    # which class does each handler belong to? (registration happens in a
    # method of the owning class — find the class whose methods include
    # the handler name)
    out: List[Endpoint] = []
    for method, path, handler, lineno in find_routes(tree):
        cls = None
        fn = idx.functions.get(handler)
        if fn is None:
            for cname, methods in idx.methods.items():
                if handler in methods:
                    cls, fn = cname, methods[handler]
                    break
        if fn is None:
            continue  # handler not in this module: degrade
        ep = Endpoint(
            path=path, method=method, module=relpath,
            handler=handler, lineno=lineno,
        )
        _HandlerScan(idx, cls, ep).run(fn)
        out.append(ep)
    return out


# --------------------------------------------------------------------- #
# Client side
# --------------------------------------------------------------------- #

_UNRESOLVED = object()  # payload sentinel: passed but not statically known


@dataclasses.dataclass
class WrapperInfo:
    """A client-class method that performs (or forwards to) an HTTP
    call. ``path`` / ``payload`` / ``method`` are either resolved values
    or ``("param", <name>)`` markers meaning the caller supplies them."""

    cls: str
    name: str
    module: str
    params: Tuple[str, ...]  # in order, excluding self
    method: object  # str | ("param", name) | None
    path: object  # str | ("param", name) | None
    payload: object  # dict | ("param", name) | {} (no body) | _UNRESOLVED
    retry_param: bool  # has a retry_connection_only parameter
    status_retrying: bool  # retries on HTTP statuses unless told not to
    guard: bool  # raise_for_status() somewhere in the chain
    sse: bool  # async generator yielding SSE frames
    lineno: int = 0


def _fn_params(fn: ast.AST) -> Tuple[str, ...]:
    a = fn.args
    return tuple(
        p.arg
        for p in a.posonlyargs + a.args + a.kwonlyargs
        if p.arg not in ("self", "cls")
    )


def _bind_call_args(
    call: ast.Call, params: Sequence[str]
) -> Dict[str, ast.AST]:
    """Map a call's args onto the callee's (self-less) param names.
    ``**kwargs`` splats make the binding unresolvable -> empty map for
    those names (degrade)."""
    out: Dict[str, ast.AST] = {}
    for i, a in enumerate(call.args):
        if isinstance(a, ast.Starred):
            break
        if i < len(params):
            out[params[i]] = a
    for kw in call.keywords:
        if kw.arg:
            out[kw.arg] = kw.value
    return out


def _ann_class_name(ann: Optional[ast.AST]) -> Optional[str]:
    """Bare class name of an annotation, unwrapping Optional[...] and
    string annotations."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        text = ann.value.strip().strip("'\"")
        return text.split("[")[-1].rstrip("]").split(".")[-1] or None
    if isinstance(ann, ast.Subscript):
        base = (_dotted(ann.value) or "").rsplit(".", 1)[-1]
        if base in ("Optional", "Union"):
            inner = ann.slice
            if isinstance(inner, ast.Tuple):
                for el in inner.elts:
                    n = _ann_class_name(el)
                    if n is not None and n != "None":
                        return n
                return None
            return _ann_class_name(inner)
        return None
    d = _dotted(ann)
    return d.rsplit(".", 1)[-1] if d else None


def _resolve_url(
    url: ast.AST, fn_params: Sequence[str], idx: _ModuleIndex, fn: ast.AST
) -> object:
    """A request URL expression -> literal "/path", ("param", name) when
    the trailing component is a parameter of the enclosing function
    (chain), or None (dynamic: degrade)."""
    if isinstance(url, ast.JoinedStr) and url.values:
        last = url.values[-1]
        s = _const_str(last)
        if s is not None and s.startswith("/"):
            return s
        if isinstance(last, ast.FormattedValue) and isinstance(
            last.value, ast.Name
        ):
            name = last.value.id
            if name in idx.enclosing_params(fn) or name in fn_params:
                return ("param", name)
    return None


def _resolve_payload_expr(
    expr: Optional[ast.AST],
    fn: ast.AST,
    fn_params: Sequence[str],
) -> object:
    """json= expression -> dict of field->lineno, ("param", name), {} for
    an absent body, or _UNRESOLVED."""
    if expr is None:
        return {}
    if isinstance(expr, ast.Dict):
        out: Dict[str, int] = {}
        for k in expr.keys:
            key = _const_str(k) if k is not None else None
            if key is None:
                return _UNRESOLVED  # splat / computed field name
            out[key] = k.lineno
        return out
    if isinstance(expr, ast.Name):
        if expr.id in fn_params:
            return ("param", expr.id)
        # local dict literal, possibly extended by constant subscript
        # stores (body["deadline_s"] = ...)
        base: Optional[Dict[str, int]] = None
        for node in _walk_fn(fn):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == expr.id
            ):
                val = _unwrap_await(node.value)
                if isinstance(val, ast.Dict):
                    r = _resolve_payload_expr(val, fn, fn_params)
                    base = r if isinstance(r, dict) else None
                else:
                    base = None
        if base is None:
            return _UNRESOLVED
        for node in _walk_fn(fn):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Subscript)
                and isinstance(node.targets[0].value, ast.Name)
                and node.targets[0].value.id == expr.id
            ):
                k = _const_str(node.targets[0].slice)
                if k is None:
                    return _UNRESOLVED
                base.setdefault(k, node.lineno)
        return base
    if isinstance(expr, ast.Constant) and expr.value is None:
        return {}
    return _UNRESOLVED


def _direct_http_call(
    call: ast.Call,
) -> Optional[Tuple[object, ast.AST, Optional[ast.AST], Optional[ast.AST]]]:
    """Recognize ``<...session...>.post/get/request(url, ...)``. Returns
    ``(method, url_expr, json_expr, kwargs_splat)`` with method a str or
    the raw arg node (for .request); None when the receiver does not look
    like an aiohttp session."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr in ("post", "get", "request")):
        return None
    recv = _dotted(f.value) or ""
    if "session" not in recv.rsplit(".", 1)[-1].lower():
        return None
    json_expr = None
    kwargs_splat = None
    for kw in call.keywords:
        if kw.arg == "json":
            json_expr = kw.value
        elif kw.arg is None:
            kwargs_splat = kw.value
    if f.attr == "request":
        if len(call.args) < 2:
            return None
        return call.args[0], call.args[1], json_expr, kwargs_splat
    if not call.args:
        return None
    method = "POST" if f.attr == "post" else "GET"
    return method, call.args[0], json_expr, kwargs_splat


def _resolve_kwargs_json(
    splat: ast.AST, fn: ast.AST
) -> Optional[ast.AST]:
    """``**req_kw`` where ``req_kw = {"json": <expr>, ...}`` locally
    (plain or annotated assignment)."""
    if not isinstance(splat, ast.Name):
        return None
    for node in _walk_fn(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt: ast.AST = node.targets[0]
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            tgt = node.target
        else:
            continue
        if (
            isinstance(tgt, ast.Name)
            and tgt.id == splat.id
            and isinstance(node.value, ast.Dict)
        ):
            for k, v in zip(node.value.keys, node.value.values):
                if _const_str(k) == "json":
                    return v
    return None


class _ClientScan:
    """Per-module client-side scan. Shares a cross-module wrapper table
    (client classes are defined in one module, used from others)."""

    def __init__(
        self,
        relpath: str,
        tree: ast.Module,
        src: str,
        wrappers: Dict[Tuple[str, str], WrapperInfo],
    ):
        self.relpath = relpath
        self.idx = _ModuleIndex(tree, src)
        self.wrappers = wrappers
        self.client_classes = {c for (c, _n) in wrappers}

    # ---- all functions (methods + module functions + closures) ----

    def _all_functions(self) -> List[Tuple[Optional[str], ast.AST]]:
        out: List[Tuple[Optional[str], ast.AST]] = []
        for node in ast.walk(self.idx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cls = None
                for cname, methods in self.idx.methods.items():
                    if methods.get(node.name) is node:
                        cls = cname
                        break
                out.append((cls, node))
        return out

    # ---- wrapper discovery ----

    def discover_wrappers(self) -> bool:
        """One discovery round: direct HTTP calls plus forwarding to
        already-known wrappers. Returns True when a new wrapper was
        registered (caller iterates to fixpoint)."""
        changed = False
        for cls, fn in self._all_functions():
            if cls is None:
                continue
            if (cls, fn.name) in self.wrappers:
                continue
            info = self._wrapper_from_fn(cls, fn)
            if info is not None:
                self.wrappers[(cls, fn.name)] = info
                self.client_classes.add(cls)
                changed = True
        return changed

    def _fn_facts(self, fn: ast.AST) -> Tuple[bool, bool, bool]:
        guard = sse = False
        for node in _walk_fn(fn):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "raise_for_status"
            ):
                guard = True
            if isinstance(node, ast.Yield):
                sse = True
        retry_param = "retry_connection_only" in _fn_params(fn)
        return guard, sse, retry_param

    def _wrapper_from_fn(self, cls: str, fn: ast.AST) -> Optional[WrapperInfo]:
        params = _fn_params(fn)
        guard, sse, retry_param = self._fn_facts(fn)
        # (a) a direct session call
        for node in _walk_fn(fn):
            if not isinstance(node, ast.Call):
                continue
            direct = _direct_http_call(node)
            if direct is None:
                continue
            method_raw, url, json_expr, splat = direct
            splat_opaque = False
            if json_expr is None and splat is not None:
                json_expr = _resolve_kwargs_json(splat, fn)
                # a **kwargs splat that does not resolve to a local
                # literal may still carry a json body: degrade
                splat_opaque = json_expr is None
            method: object
            if isinstance(method_raw, str):
                method = method_raw
            elif isinstance(method_raw, ast.Constant):
                method = str(method_raw.value)
            elif (
                isinstance(method_raw, ast.Name)
                and method_raw.id in params
            ):
                method = ("param", method_raw.id)
            else:
                method = None
            path = _resolve_url(url, params, self.idx, fn)
            payload = (
                _UNRESOLVED
                if splat_opaque
                else _resolve_payload_expr(json_expr, fn, params)
            )
            return WrapperInfo(
                cls=cls, name=fn.name, module=self.relpath, params=params,
                method=method, path=path, payload=payload,
                retry_param=retry_param,
                status_retrying=retry_param,  # retries unless flag passed
                guard=guard, sse=sse, lineno=fn.lineno,
            )
        # (b) forwards to a known wrapper of the same class
        for node in _walk_fn(fn):
            if not isinstance(node, ast.Call):
                continue
            base = self._self_wrapper(cls, node)
            if base is None:
                continue
            bound = _bind_call_args(node, base.params)
            method = self._forward(base.method, bound, params)
            path = self._forward(base.path, bound, params)
            payload = self._forward_payload(base.payload, bound, params, fn)
            retrying = base.status_retrying and not self._retry_flag_true(
                bound
            )
            return WrapperInfo(
                cls=cls, name=fn.name, module=self.relpath, params=params,
                method=method, path=path, payload=payload,
                retry_param=False, status_retrying=retrying,
                guard=guard or base.guard, sse=sse or base.sse,
                lineno=fn.lineno,
            )
        return None

    def _self_wrapper(self, cls: str, call: ast.Call) -> Optional[WrapperInfo]:
        f = call.func
        if (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id == "self"
        ):
            return self.wrappers.get((cls, f.attr))
        return None

    @staticmethod
    def _retry_flag_true(bound: Dict[str, ast.AST]) -> bool:
        v = bound.get("retry_connection_only")
        return (
            isinstance(v, ast.Constant) and v.value is True
        )

    def _forward(
        self, slot: object, bound: Dict[str, ast.AST], params: Sequence[str]
    ) -> object:
        """Resolve a wrapper slot through one forwarding level: constant
        stays, a param slot takes the call-site arg (constant -> value,
        enclosing param -> new param slot, else unresolved None)."""
        if not (isinstance(slot, tuple) and slot and slot[0] == "param"):
            return slot
        arg = bound.get(slot[1])
        if arg is None:
            return None
        s = _const_str(arg)
        if s is not None:
            return s
        if isinstance(arg, ast.Name) and arg.id in params:
            return ("param", arg.id)
        return None

    def _forward_payload(
        self,
        slot: object,
        bound: Dict[str, ast.AST],
        params: Sequence[str],
        fn: ast.AST,
    ) -> object:
        if not (isinstance(slot, tuple) and slot and slot[0] == "param"):
            return slot
        arg = bound.get(slot[1])
        if arg is None:
            return {}
        if isinstance(arg, ast.Name) and arg.id in params:
            return ("param", arg.id)
        return _resolve_payload_expr(arg, fn, params)

    # ---- receiver typing ----

    def _receiver_class(
        self, recv: ast.AST, fn: ast.AST
    ) -> Optional[str]:
        """Conservative client-class typing of a call receiver:
        annotated params (walking out through closures), ``CLS(...)``
        constructor assignments, ``async with CLS(...) as x``, and
        ``self.attr`` assigned from any of those inside the class."""
        if isinstance(recv, ast.Name):
            ann = self.idx.enclosing_params(fn).get(recv.id, _UNRESOLVED)
            if ann is not _UNRESOLVED:
                name = _ann_class_name(ann)
                if name in self.client_classes:
                    return name
            scope: Optional[ast.AST] = fn
            while scope is not None:
                name = self._bound_class_in(scope, recv.id)
                if name is not None:
                    return name
                scope = self.idx.parent_fn.get(id(scope))
            return None
        if (
            isinstance(recv, ast.Attribute)
            and isinstance(recv.value, ast.Name)
            and recv.value.id == "self"
        ):
            cls = self._class_of(fn)
            if cls is None:
                return None
            for m in self.idx.methods.get(cls, {}).values():
                for node in _walk_fn(m):
                    if not (
                        isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Attribute)
                        and isinstance(node.targets[0].value, ast.Name)
                        and node.targets[0].value.id == "self"
                        and node.targets[0].attr == recv.attr
                    ):
                        continue
                    name = self._value_class(node.value, m)
                    if name is not None:
                        return name
        return None

    def _class_of(self, fn: ast.AST) -> Optional[str]:
        for cname, methods in self.idx.methods.items():
            if methods.get(getattr(fn, "name", "")) is fn:
                return cname
        return None

    def _bound_class_in(self, scope: ast.AST, var: str) -> Optional[str]:
        for node in _walk_fn(scope):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == var
            ):
                name = self._value_class(node.value, scope)
                if name is not None:
                    return name
            if isinstance(node, (ast.AsyncWith, ast.With)):
                for item in node.items:
                    if (
                        isinstance(item.optional_vars, ast.Name)
                        and item.optional_vars.id == var
                    ):
                        name = self._value_class(item.context_expr, scope)
                        if name is not None:
                            return name
        return None

    def _value_class(self, value: ast.AST, fn: ast.AST) -> Optional[str]:
        value = _unwrap_await(value)
        if isinstance(value, ast.BoolOp):
            for v in value.values:
                name = self._value_class(v, fn)
                if name is not None:
                    return name
            return None
        if isinstance(value, ast.Call):
            name = (_dotted(value.func) or "").rsplit(".", 1)[-1]
            if name in self.client_classes:
                return name
        if isinstance(value, ast.Name):
            ann = self.idx.enclosing_params(fn).get(value.id, _UNRESOLVED)
            if ann is not _UNRESOLVED:
                name = _ann_class_name(ann)
                if name in self.client_classes:
                    return name
        return None

    # ---- call-site collection ----

    def collect_calls(self, lines: Sequence[str]) -> List[ClientCall]:
        out: List[ClientCall] = []
        for cls, fn in self._all_functions():
            out.extend(self._calls_in_fn(cls, fn, lines))
        return out

    def _calls_in_fn(
        self, cls: Optional[str], fn: ast.AST, lines: Sequence[str]
    ) -> List[ClientCall]:
        out: List[ClientCall] = []
        params = _fn_params(fn)
        for node in _walk_fn(fn):
            if not isinstance(node, ast.Call):
                continue
            made = self._direct_site(node, fn, params)
            if made is None:
                made = self._wrapper_site(cls, node, fn, params)
            if made is None:
                continue
            for call in made:
                self._associate_reads(call, node, fn)
                self._associate_status(call, node, fn)
                out.append(call)
        return out

    def _direct_site(
        self, node: ast.Call, fn: ast.AST, params: Sequence[str]
    ) -> Optional[List[ClientCall]]:
        direct = _direct_http_call(node)
        if direct is None:
            return None
        method_raw, url, json_expr, splat = direct
        splat_opaque = False
        if json_expr is None and splat is not None:
            json_expr = _resolve_kwargs_json(splat, fn)
            splat_opaque = json_expr is None
        if isinstance(method_raw, str):
            method = method_raw
        elif isinstance(method_raw, ast.Constant):
            method = str(method_raw.value)
        else:
            return []  # dynamic method: degrade
        path = _resolve_url(url, params, self.idx, fn)
        if not isinstance(path, str):
            return []  # param/dynamic path: wrapper machinery owns it
        payload = (
            _UNRESOLVED
            if splat_opaque
            else _resolve_payload_expr(json_expr, fn, params)
        )
        return [
            ClientCall(
                module=self.relpath, lineno=node.lineno, method=method,
                path=path, via=f"session.{method.lower()}",
                payload=payload if isinstance(payload, dict) else None,
            )
        ]

    def _wrapper_site(
        self,
        cls: Optional[str],
        node: ast.Call,
        fn: ast.AST,
        params: Sequence[str],
    ) -> Optional[List[ClientCall]]:
        f = node.func
        if not isinstance(f, ast.Attribute):
            return None
        w: Optional[WrapperInfo] = None
        if (
            isinstance(f.value, ast.Name)
            and f.value.id == "self"
            and cls is not None
        ):
            w = self.wrappers.get((cls, f.attr))
        else:
            rcls = self._receiver_class(f.value, fn)
            if rcls is not None:
                w = self.wrappers.get((rcls, f.attr))
        if w is None:
            return None
        bound = _bind_call_args(node, w.params)
        method = self._forward(w.method, bound, params)
        path = self._forward(w.path, bound, params)
        payload = self._forward_payload(w.payload, bound, params, fn)
        if isinstance(w.payload, dict) and isinstance(payload, dict):
            # payload baked into the wrapper body: its key linenos point
            # into the wrapper's module, so report at this call site
            payload = {k: node.lineno for k in payload}
        retrying = w.status_retrying and not self._retry_flag_true(bound)
        paths: List[str] = []
        if isinstance(path, str):
            paths = [path]
        elif isinstance(path, tuple):
            return []  # still parameterized at this site: degrade
        else:
            # IfExp with two literal paths resolves as both calls
            slot = w.path
            if isinstance(slot, tuple) and slot and slot[0] == "param":
                arg = bound.get(slot[1])
                if isinstance(arg, ast.IfExp):
                    a, b = _const_str(arg.body), _const_str(arg.orelse)
                    if a is not None and b is not None:
                        paths = [a, b]
            if not paths:
                return []
        if not isinstance(method, str):
            return []  # dynamic method: degrade
        return [
            ClientCall(
                module=self.relpath, lineno=node.lineno, method=method,
                path=p, via=f"{w.cls}.{w.name}",
                payload=payload if isinstance(payload, dict) else None,
                generic_status_guard=w.guard,
                retries_status=retrying,
                sse_wrapper=w.sse,
            )
            for p in paths
        ]

    # ---- read / status association ----

    def _associate_reads(
        self, call: ClientCall, node: ast.Call, fn: ast.AST
    ) -> None:
        sse = call.sse_wrapper
        sink = call.sse_reads if sse else call.reads
        # pass 1: names bound to the call / a gather over it (the AST
        # walk is not source-ordered, so bind before the loop pass)
        gen_names: Set[str] = set()
        for st in _walk_fn(fn):
            if (
                isinstance(st, ast.Assign)
                and len(st.targets) == 1
                and isinstance(st.targets[0], ast.Name)
                and self._contains(st.value, node)
            ):
                tgt = st.targets[0].id
                val = _unwrap_await(st.value)
                if val is node and sse:
                    gen_names.add(tgt)
                elif val is node:
                    self._collect_var_reads(fn, tgt, sink)
                elif self._is_gather_of(val, node):
                    self._gather_reads(fn, tgt, sink)
        for st in _walk_fn(fn):
            if isinstance(st, ast.AsyncFor):
                it = st.iter
                if it is node or (
                    isinstance(it, ast.Name) and it.id in gen_names
                ):
                    if isinstance(st.target, ast.Name):
                        self._collect_var_reads(
                            st, st.target.id, sink, include_self=True
                        )
            # async with session.post(...) as resp: -> resp.json() var
            if isinstance(st, (ast.AsyncWith, ast.With)):
                for item in st.items:
                    if item.context_expr is node and isinstance(
                        item.optional_vars, ast.Name
                    ):
                        self._resp_obj_reads(
                            st, item.optional_vars.id, call
                        )

    def _contains(self, tree: ast.AST, target: ast.AST) -> bool:
        return any(n is target for n in ast.walk(tree))

    def _is_gather_of(self, val: ast.AST, node: ast.Call) -> bool:
        if not (
            isinstance(val, ast.Call)
            and (_dotted(val.func) or "").endswith("gather")
        ):
            return False
        return self._contains(val, node)

    def _gather_reads(
        self, fn: ast.AST, coll: str, sink: Dict[str, int]
    ) -> None:
        """``results = await gather(*(c.metrics(u) for u in ...))`` then
        ``for u, r in zip(urls, results): r.get("k")`` — bind the zip/
        direct loop element and collect its reads inside the loop."""
        for st in _walk_fn(fn):
            if not isinstance(st, (ast.For, ast.AsyncFor)):
                continue
            it = st.iter
            elem: Optional[str] = None
            if isinstance(it, ast.Name) and it.id == coll:
                if isinstance(st.target, ast.Name):
                    elem = st.target.id
            elif (
                isinstance(it, ast.Call)
                and (_dotted(it.func) or "").endswith("zip")
                and isinstance(st.target, ast.Tuple)
            ):
                for i, a in enumerate(it.args):
                    if (
                        isinstance(a, ast.Name)
                        and a.id == coll
                        and i < len(st.target.elts)
                        and isinstance(st.target.elts[i], ast.Name)
                    ):
                        elem = st.target.elts[i].id
            if elem is not None:
                self._collect_var_reads(st, elem, sink, include_self=True)

    def _collect_var_reads(
        self,
        scope: ast.AST,
        var: str,
        sink: Dict[str, int],
        include_self: bool = False,
    ) -> None:
        nodes = (
            ast.walk(scope) if include_self else _walk_fn(scope)
        )
        for n in nodes:
            if (
                isinstance(n, ast.Subscript)
                and isinstance(n.value, ast.Name)
                and n.value.id == var
            ):
                k = _const_str(n.slice)
                if k is not None:
                    sink.setdefault(k, n.lineno)
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "get"
                and isinstance(n.func.value, ast.Name)
                and n.func.value.id == var
                and n.args
            ):
                k = _const_str(n.args[0])
                if k is not None:
                    sink.setdefault(k, n.lineno)

    def _resp_obj_reads(
        self, with_node: ast.AST, resp: str, call: ClientCall
    ) -> None:
        for n in ast.walk(with_node):
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "raise_for_status"
                and isinstance(n.func.value, ast.Name)
                and n.func.value.id == resp
            ):
                call.generic_status_guard = True
            if (
                isinstance(n, ast.Assign)
                and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
            ):
                val = _unwrap_await(n.value)
                if (
                    isinstance(val, ast.Call)
                    and isinstance(val.func, ast.Attribute)
                    and val.func.attr == "json"
                    and isinstance(val.func.value, ast.Name)
                    and val.func.value.id == resp
                ):
                    # reads happen in the enclosing function after the
                    # with-block too; collect across the whole function
                    fn = self._enclosing_fn(with_node)
                    self._collect_var_reads(
                        fn if fn is not None else with_node,
                        n.targets[0].id, call.reads, include_self=True,
                    )
            if isinstance(n, ast.Compare) and len(n.ops) == 1:
                left = n.left
                if (
                    isinstance(left, ast.Attribute)
                    and left.attr == "status"
                    and isinstance(left.value, ast.Name)
                    and left.value.id == resp
                ):
                    self._take_status_compare(n, call)

    def _enclosing_fn(self, node: ast.AST) -> Optional[ast.AST]:
        for _cls, fn in self._all_functions():
            if any(n is node for n in ast.walk(fn)):
                return fn
        return None

    def _take_status_compare(self, cmp: ast.Compare, call: ClientCall) -> None:
        op, right = cmp.ops[0], cmp.comparators[0]
        if isinstance(op, (ast.Eq, ast.NotEq)):
            if isinstance(right, ast.Constant) and isinstance(
                right.value, int
            ):
                call.status_branches.setdefault(right.value, cmp.lineno)
        elif isinstance(op, (ast.Lt, ast.LtE, ast.Gt, ast.GtE)):
            call.generic_status_guard = True
        elif isinstance(op, (ast.In, ast.NotIn)):
            if isinstance(right, (ast.Tuple, ast.List, ast.Set)):
                for el in right.elts:
                    if isinstance(el, ast.Constant) and isinstance(
                        el.value, int
                    ):
                        call.status_branches.setdefault(el.value, cmp.lineno)

    def _associate_status(
        self, call: ClientCall, node: ast.Call, fn: ast.AST
    ) -> None:
        """``except ... as e: e.status == N`` branches in any try that
        encloses the call site."""
        for st in _walk_fn(fn):
            if not isinstance(st, ast.Try):
                continue
            if not any(
                self._contains(body_stmt, node) for body_stmt in st.body
            ):
                continue
            for h in st.handlers:
                if h.name is None:
                    continue
                call.generic_status_guard = True
                for n in ast.walk(h):
                    if (
                        isinstance(n, ast.Compare)
                        and len(n.ops) == 1
                        and isinstance(n.left, ast.Attribute)
                        and n.left.attr == "status"
                        and isinstance(n.left.value, ast.Name)
                        and n.left.value.id == h.name
                    ):
                        self._take_status_compare(n, call)


# --------------------------------------------------------------------- #
# Assembly
# --------------------------------------------------------------------- #


def parse_client_modules(
    modules: Dict[str, Tuple[ast.Module, str]]
) -> List[ClientCall]:
    """Client call sites across ``{relpath: (tree, src)}``. Wrapper
    discovery runs to fixpoint across ALL modules first (a wrapper class
    defined in one module is typed at its use sites in others)."""
    wrappers: Dict[Tuple[str, str], WrapperInfo] = {}
    scans = [
        _ClientScan(rel, tree, src, wrappers)
        for rel, (tree, src) in sorted(modules.items())
    ]
    for _round in range(4):
        changed = False
        for s in scans:
            s.client_classes = {c for (c, _n) in wrappers} | s.client_classes
            changed = s.discover_wrappers() or changed
        if not changed:
            break
    calls: List[ClientCall] = []
    for s in scans:
        s.client_classes = {c for (c, _n) in wrappers}
        calls.extend(s.collect_calls(s.idx.lines))
    return calls


def build_model(
    spec: WireSpec, modules: Dict[str, Tuple[ast.Module, str]]
) -> WireModel:
    """Assemble the wire model from the SCANNED module set (``modules``
    maps repo-relative posix paths to parsed trees). Modules the spec
    declares but the scan does not include leave ``servers_present`` /
    ``clients_present`` False — rules needing the full surface degrade."""
    endpoints: Dict[Tuple[str, str], List[Endpoint]] = {}
    for rel in spec.servers:
        if rel not in modules:
            continue
        tree, src = modules[rel]
        for ep in parse_server_module(rel, tree, src):
            endpoints.setdefault((ep.method, ep.path), []).append(ep)
    client_modules = {
        rel: modules[rel] for rel in spec.clients if rel in modules
    }
    calls = parse_client_modules(client_modules)
    return WireModel(
        spec=spec,
        endpoints=endpoints,
        calls=calls,
        servers_present=all(rel in modules for rel in spec.servers),
        clients_present=all(rel in modules for rel in spec.clients),
    )
