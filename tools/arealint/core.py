"""arealint core: Finding/Rule types, the rule registry, suppression
parsing, and the scan driver.

Design (docs/static_analysis.md):

- Rules are plain functions registered with :func:`rule`; each receives a
  :class:`FileContext` (source + AST + catalogs) and yields
  ``(lineno, message)`` pairs. The driver turns them into
  :class:`Finding`\\ s, applies inline suppressions, and sorts by line.
- Everything is stdlib-only and purely static: no areal_tpu import, no
  jax import — the linter must run in a bare CI container and never
  execute repo code.
- Per-rule severity: ``error`` findings fail the CLI (exit 1), ``warn``
  findings are reported but non-fatal.
- Inline suppression: ``# arealint: ok(<reason>)`` on the finding line or
  on a comment-only line directly above. The reason is REQUIRED — a bare
  ``# arealint: ok`` / empty ``ok()`` does not suppress and is itself
  flagged (rule ``suppression-missing-reason``). The legacy
  ``# async-hygiene: ok`` token still suppresses the four migrated async
  rules so annotations that predate the framework keep working.
- ``# arealint: hot`` on a ``def`` line (or the comment line above it)
  marks a function as a hot-path root for the host-sync rule.
"""

import ast
import dataclasses
import pathlib
import re
from typing import (
    Callable, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple,
)

SUPPRESS_RE = re.compile(r"#\s*arealint:\s*ok\(\s*(?P<reason>[^)]*?)\s*\)")
SUPPRESS_BARE_RE = re.compile(r"#\s*arealint:\s*ok\b(?!\s*\()")
HOT_RE = re.compile(r"#\s*arealint:\s*hot\b")
LEGACY_SUPPRESS = "# async-hygiene: ok"
# The four rules migrated from tools/check_async_hygiene.py honor the
# legacy suppression token too (annotations in the tree predate arealint).
LEGACY_RULES = frozenset(
    {"bare-gather", "discarded-task", "live-checkpoint-rmtree",
     "sleep-in-async"}
)

SEVERITY_ERROR = "error"
SEVERITY_WARN = "warn"


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str
    severity: str = SEVERITY_ERROR

    def __str__(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# --------------------------------------------------------------------- #
# Catalogs (metrics counters, fault points) — parsed from the repo's
# catalog modules with ast, never imported.
# --------------------------------------------------------------------- #


@dataclasses.dataclass
class Config:
    """Catalog + repo context a scan runs against. Tests build one by hand
    to lint fixture snippets against a synthetic catalog; the CLI loads
    the real catalogs from the repo."""

    # Registered counter name VALUES (e.g. "ft/evictions") and the
    # UPPERCASE constant NAMES that hold them (e.g. "FT_EVICTIONS").
    counter_values: Optional[frozenset] = None
    counter_names: Optional[frozenset] = None
    # Registered fault injection points (base/faults.py FAULT_POINTS).
    fault_points: Optional[frozenset] = None
    repo_root: Optional[pathlib.Path] = None

    @classmethod
    def from_repo(cls, root: Optional[pathlib.Path] = None) -> "Config":
        root = pathlib.Path(root) if root else default_repo_root()
        cfg = cls(repo_root=root)
        metrics_py = root / "areal_tpu" / "base" / "metrics.py"
        faults_py = root / "areal_tpu" / "base" / "faults.py"
        if metrics_py.is_file():
            names, values = _module_str_constants(metrics_py)
            cfg.counter_names = frozenset(names)
            cfg.counter_values = frozenset(values)
        if faults_py.is_file():
            cfg.fault_points = _fault_points(faults_py)
        return cfg


def default_repo_root() -> pathlib.Path:
    # tools/arealint/core.py -> tools/arealint -> tools -> repo
    return pathlib.Path(__file__).resolve().parents[2]


def _module_str_constants(path: pathlib.Path) -> Tuple[List[str], List[str]]:
    """Module-level ``UPPER_NAME = "literal"`` assignments: the catalog
    convention of base/metrics.py."""
    tree = ast.parse(path.read_text(), filename=str(path))
    names, values = [], []
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        t = node.targets[0]
        if (
            isinstance(t, ast.Name)
            and t.id == t.id.upper()
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            names.append(t.id)
            values.append(node.value.value)
    return names, values


def _fault_points(path: pathlib.Path) -> Optional[frozenset]:
    """The ``FAULT_POINTS`` tuple in base/faults.py."""
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "FAULT_POINTS"
            and isinstance(node.value, (ast.Tuple, ast.List, ast.Set))
        ):
            return frozenset(
                e.value for e in node.value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            )
    return None


_DEFAULT_CONFIG: Optional[Config] = None


def default_config() -> Config:
    global _DEFAULT_CONFIG
    if _DEFAULT_CONFIG is None:
        _DEFAULT_CONFIG = Config.from_repo()
    return _DEFAULT_CONFIG


# --------------------------------------------------------------------- #
# File context
# --------------------------------------------------------------------- #


class FileContext:
    """One file's parse state handed to every rule."""

    def __init__(self, src: str, path: str, tree: ast.AST, config: Config):
        self.src = src
        self.path = path.replace("\\", "/")
        self.lines = src.splitlines()
        self.tree = tree
        self.config = config
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def parents(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    self._parents[child] = node
        return self._parents

    def enclosing_function(self, node: ast.AST):
        """Nearest enclosing FunctionDef/AsyncFunctionDef, or None."""
        parents = self.parents()
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = parents.get(cur)
        return None

    def hot_marked(self, fdef) -> bool:
        """``# arealint: hot`` on the def line or the comment line above
        the first decorator/def."""
        first = min(
            [fdef.lineno] + [d.lineno for d in fdef.decorator_list]
        )
        for ln in (fdef.lineno, first - 1):
            text = self.line_text(ln)
            if ln != fdef.lineno and not text.strip().startswith("#"):
                continue
            if HOT_RE.search(text):
                return True
        return False

    def path_endswith(self, *suffixes: str) -> bool:
        return any(self.path.endswith(s) for s in suffixes)


def walk_excluding_nested(fdef) -> Iterator[ast.AST]:
    """Nodes of a function's OWN body — nested function/lambda bodies are
    separate execution contexts and are excluded (they are scanned when
    the call graph reaches them)."""

    def _walk(node):
        for child in ast.iter_child_nodes(node):
            yield child
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            yield from _walk(child)

    for stmt in fdef.body:
        yield stmt
        if not isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            yield from _walk(stmt)


# --------------------------------------------------------------------- #
# Rule registry
# --------------------------------------------------------------------- #

CheckFn = Callable[[FileContext], Iterable[Tuple[int, str]]]


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    severity: str
    doc: str
    check: CheckFn


RULES: Dict[str, Rule] = {}


def rule(rule_id: str, severity: str, doc: str):
    """Register a rule. ``doc`` is the one-line catalog entry shown by
    ``--list-rules`` and docs/static_analysis.md."""
    assert severity in (SEVERITY_ERROR, SEVERITY_WARN), severity

    def deco(fn: CheckFn) -> CheckFn:
        assert rule_id not in RULES, f"duplicate rule id {rule_id}"
        RULES[rule_id] = Rule(rule_id, severity, doc, fn)
        return fn

    return deco


# --------------------------------------------------------------------- #
# Suppression
# --------------------------------------------------------------------- #


def is_suppressed(ctx: FileContext, rule_id: str, lineno: int) -> bool:
    """Valid ``# arealint: ok(<reason>)`` on the line (or a comment-only
    line above); legacy ``# async-hygiene: ok`` for the migrated rules."""
    for ln in (lineno, lineno - 1):
        text = ctx.line_text(ln)
        if ln != lineno and not text.strip().startswith("#"):
            continue
        m = SUPPRESS_RE.search(text)
        if m and m.group("reason").strip():
            return True
        if rule_id in LEGACY_RULES and LEGACY_SUPPRESS in text:
            return True
    return False


# --------------------------------------------------------------------- #
# Scan driver
# --------------------------------------------------------------------- #


def _resolve_rules(rules: Optional[Sequence[str]]) -> List[Rule]:
    if rules is None:
        return list(RULES.values())
    unknown = [r for r in rules if r not in RULES]
    if unknown:
        raise KeyError(f"unknown rule(s): {', '.join(unknown)}")
    return [RULES[r] for r in rules]


def scan_source(
    src: str,
    path: str = "<string>",
    rules: Optional[Sequence[str]] = None,
    config: Optional[Config] = None,
) -> List[Finding]:
    config = config if config is not None else default_config()
    selected = _resolve_rules(rules)
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [
            Finding(
                path.replace("\\", "/"), e.lineno or 0, "parse-error",
                f"could not parse: {e.msg}", SEVERITY_ERROR,
            )
        ]
    ctx = FileContext(src, path, tree, config)
    out: List[Finding] = []
    for r in selected:
        for lineno, message in r.check(ctx):
            if not is_suppressed(ctx, r.id, lineno):
                out.append(Finding(ctx.path, lineno, r.id, message, r.severity))
    out.sort(key=lambda f: (f.line, f.rule))
    return out


def scan_paths(
    paths: Iterable,
    rules: Optional[Sequence[str]] = None,
    config: Optional[Config] = None,
) -> List[Finding]:
    findings: List[Finding] = []
    for p in paths:
        p = pathlib.Path(p)
        files = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for f in files:
            findings.extend(
                scan_source(f.read_text(), str(f), rules=rules, config=config)
            )
    return findings


def has_errors(findings: Iterable[Finding]) -> bool:
    return any(f.severity == SEVERITY_ERROR for f in findings)
