"""arealint core: Finding/Rule types, the rule registry, suppression
parsing, and the scan driver.

Design (docs/static_analysis.md):

- Rules are plain functions registered with :func:`rule`; each receives a
  :class:`FileContext` (source + AST + catalogs) and yields
  ``(lineno, message)`` pairs. The driver turns them into
  :class:`Finding`\\ s, applies inline suppressions, and sorts by line.
- Everything is stdlib-only and purely static: no areal_tpu import, no
  jax import — the linter must run in a bare CI container and never
  execute repo code.
- Per-rule severity: ``error`` findings fail the CLI (exit 1), ``warn``
  findings are reported but non-fatal.
- Inline suppression: ``# arealint: ok(<reason>)`` on the finding line or
  on a comment-only line directly above. The reason is REQUIRED — a bare
  ``# arealint: ok`` / empty ``ok()`` does not suppress and is itself
  flagged (rule ``suppression-missing-reason``). The legacy
  ``# async-hygiene: ok`` token still suppresses the four migrated async
  rules so annotations that predate the framework keep working.
- ``# arealint: hot`` on a ``def`` line (or the comment line above it)
  marks a function as a hot-path root for the host-sync rule.
"""

import ast
import concurrent.futures
import dataclasses
import pathlib
import re
from typing import (
    Callable, Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple,
)

SUPPRESS_RE = re.compile(r"#\s*arealint:\s*ok\(\s*(?P<reason>[^)]*?)\s*\)")
SUPPRESS_BARE_RE = re.compile(r"#\s*arealint:\s*ok\b(?!\s*\()")
HOT_RE = re.compile(r"#\s*arealint:\s*hot\b")
LEGACY_SUPPRESS = "# async-hygiene: ok"
# The four rules migrated from tools/check_async_hygiene.py honor the
# legacy suppression token too (annotations in the tree predate arealint).
LEGACY_RULES = frozenset(
    {"bare-gather", "discarded-task", "live-checkpoint-rmtree",
     "sleep-in-async"}
)

SEVERITY_ERROR = "error"
SEVERITY_WARN = "warn"

# ------------------------------------------------------------------ #
# Path profiles: test code runs under a relaxed ruleset — tests set env
# knobs freely, build deliberate bug fixtures, clean tmp dirs, and never
# run on a hot path, so these rules would only generate annotation noise
# there. Everything NOT listed (async hygiene, the concurrency family,
# donation dataflow) stays enforced in tests: a race in a test harness
# wedges CI just as hard as one in the stack.
# ------------------------------------------------------------------ #

TEST_RELAXED_RULES = frozenset({
    "env-knob",
    "host-sync-in-hot-path",
    "host-sync-cross-module",
    "live-checkpoint-rmtree",
    "retrace-hazard",
    "unregistered-counter",
    "unregistered-fault-point",
    "suppression-missing-reason",
    # SPMD family: tests build synthetic meshes with their own axis
    # names, reshard deliberately in fixtures, and run single-process
    # where host divergence cannot straddle a real collective. The
    # arity rule (shard-map-spec-arity) STAYS enforced — a wrong-arity
    # shard_map wedges a test exactly like stack code.
    "unknown-mesh-axis",
    "mesh-axis-reuse",
    "hot-path-reshard",
    "donation-sharding-mismatch",
    "host-divergence-collective",
    # Lifecycle family: tests build deliberate leak/double-free fixtures
    # (test_paged_engine asserts the double-free ValueError, gateway
    # tests charge buckets without refunding) and tear the world down
    # wholesale afterwards — enforcing typestate there is pure noise.
    "leak-on-exception-path",
    "leak-on-cancellation",
    "double-release",
    "release-without-acquire",
    "charge-refund-asymmetry",
})
# The linter's own sources quote suppression tokens in rule docs and
# docstrings; policing them there is self-noise.
SELF_EXEMPT_RULES = frozenset({"suppression-missing-reason"})


def is_test_path(path: str) -> bool:
    p = "/" + str(path).replace("\\", "/")
    return "/tests/" in p or "/test/" in p


def _is_linter_path(path: str) -> bool:
    return "/tools/arealint/" in "/" + str(path).replace("\\", "/")


def excluded_rules_for_path(path: str) -> frozenset:
    """Rule ids NOT applied to ``path`` (the tests profile and the
    linter's self-exemption). Empty for regular stack code."""
    if is_test_path(path):
        return TEST_RELAXED_RULES
    if _is_linter_path(path):
        return SELF_EXEMPT_RULES
    return frozenset()


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str
    severity: str = SEVERITY_ERROR

    def __str__(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# --------------------------------------------------------------------- #
# Catalogs (metrics counters, fault points) — parsed from the repo's
# catalog modules with ast, never imported.
# --------------------------------------------------------------------- #


@dataclasses.dataclass
class Config:
    """Catalog + repo context a scan runs against. Tests build one by hand
    to lint fixture snippets against a synthetic catalog; the CLI loads
    the real catalogs from the repo."""

    # Registered counter name VALUES (e.g. "ft/evictions") and the
    # UPPERCASE constant NAMES that hold them (e.g. "FT_EVICTIONS").
    counter_values: Optional[frozenset] = None
    counter_names: Optional[frozenset] = None
    # Registered fault injection points (base/faults.py FAULT_POINTS).
    fault_points: Optional[frozenset] = None
    # Mesh axis names + logical rules parsed from parallel/mesh.py
    # (tools.arealint.meshmodel.MeshModel); None disables the mesh-axis
    # rule family (degrade, never guess).
    mesh: Optional[object] = None
    # Resource acquire/release catalog parsed from the runtime modules
    # (tools.arealint.resources.ResourceCatalog); None disables the
    # lifecycle rule family (degrade, never guess).
    resources: Optional[object] = None
    # HTTP/SSE wire spec (tools.arealint.wiremodel.WireSpec): the
    # verified server/client module lists the wire-contract rules build
    # their endpoint catalog from; None disables the wire rule family
    # (degrade, never guess).
    wire: Optional[object] = None
    repo_root: Optional[pathlib.Path] = None

    @classmethod
    def from_repo(cls, root: Optional[pathlib.Path] = None) -> "Config":
        from tools.arealint import meshmodel
        from tools.arealint import resources as resources_mod
        from tools.arealint import wiremodel

        root = pathlib.Path(root) if root else default_repo_root()
        cfg = cls(repo_root=root)
        metrics_py = root / "areal_tpu" / "base" / "metrics.py"
        faults_py = root / "areal_tpu" / "base" / "faults.py"
        if metrics_py.is_file():
            names, values = _module_str_constants(metrics_py)
            cfg.counter_names = frozenset(names)
            cfg.counter_values = frozenset(values)
        if faults_py.is_file():
            cfg.fault_points = _fault_points(faults_py)
        cfg.mesh = meshmodel.from_repo(root)
        cfg.resources = resources_mod.from_repo(root)
        cfg.wire = wiremodel.from_repo(root)
        return cfg


def default_repo_root() -> pathlib.Path:
    # tools/arealint/core.py -> tools/arealint -> tools -> repo
    return pathlib.Path(__file__).resolve().parents[2]


def _module_str_constants(path: pathlib.Path) -> Tuple[List[str], List[str]]:
    """Module-level ``UPPER_NAME = "literal"`` assignments: the catalog
    convention of base/metrics.py."""
    tree = ast.parse(path.read_text(), filename=str(path))
    names, values = [], []
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        t = node.targets[0]
        if (
            isinstance(t, ast.Name)
            and t.id == t.id.upper()
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            names.append(t.id)
            values.append(node.value.value)
    return names, values


def _fault_points(path: pathlib.Path) -> Optional[frozenset]:
    """The ``FAULT_POINTS`` tuple in base/faults.py."""
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "FAULT_POINTS"
            and isinstance(node.value, (ast.Tuple, ast.List, ast.Set))
        ):
            return frozenset(
                e.value for e in node.value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            )
    return None


_DEFAULT_CONFIG: Optional[Config] = None


def default_config() -> Config:
    global _DEFAULT_CONFIG
    if _DEFAULT_CONFIG is None:
        _DEFAULT_CONFIG = Config.from_repo()
    return _DEFAULT_CONFIG


# --------------------------------------------------------------------- #
# File context
# --------------------------------------------------------------------- #


class FileContext:
    """One file's parse state handed to every rule."""

    def __init__(self, src: str, path: str, tree: ast.AST, config: Config):
        self.src = src
        self.path = path.replace("\\", "/")
        self.lines = src.splitlines()
        self.tree = tree
        self.config = config
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def parents(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    self._parents[child] = node
        return self._parents

    def enclosing_function(self, node: ast.AST):
        """Nearest enclosing FunctionDef/AsyncFunctionDef, or None."""
        parents = self.parents()
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = parents.get(cur)
        return None

    def hot_marked(self, fdef) -> bool:
        """``# arealint: hot`` on the def line or the comment line above
        the first decorator/def."""
        first = min(
            [fdef.lineno] + [d.lineno for d in fdef.decorator_list]
        )
        for ln in (fdef.lineno, first - 1):
            text = self.line_text(ln)
            if ln != fdef.lineno and not text.strip().startswith("#"):
                continue
            if HOT_RE.search(text):
                return True
        return False

    def path_endswith(self, *suffixes: str) -> bool:
        return any(self.path.endswith(s) for s in suffixes)


def walk_excluding_nested(fdef) -> Iterator[ast.AST]:
    """Nodes of a function's OWN body — nested function/lambda bodies are
    separate execution contexts and are excluded (they are scanned when
    the call graph reaches them). Also accepts a bare statement/node
    list (a branch body), so rules walking an If's arms share the same
    exclusion semantics instead of re-implementing them."""

    def _walk(node):
        for child in ast.iter_child_nodes(node):
            yield child
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            yield from _walk(child)

    body = fdef if isinstance(fdef, (list, tuple)) else fdef.body
    for stmt in body:
        yield stmt
        if not isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            yield from _walk(stmt)


# --------------------------------------------------------------------- #
# Rule registry
# --------------------------------------------------------------------- #

CheckFn = Callable[[FileContext], Iterable[Tuple[int, str]]]


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    severity: str
    doc: str
    check: CheckFn


RULES: Dict[str, Rule] = {}


def rule(rule_id: str, severity: str, doc: str):
    """Register a rule. ``doc`` is the one-line catalog entry shown by
    ``--list-rules`` and docs/static_analysis.md."""
    assert severity in (SEVERITY_ERROR, SEVERITY_WARN), severity

    def deco(fn: CheckFn) -> CheckFn:
        assert rule_id not in RULES, f"duplicate rule id {rule_id}"
        assert rule_id not in PROJECT_RULES, f"duplicate rule id {rule_id}"
        RULES[rule_id] = Rule(rule_id, severity, doc, fn)
        return fn

    return deco


# --------------------------------------------------------------------- #
# Project (whole-program) rule registry
# --------------------------------------------------------------------- #

# A project rule sees the whole indexed file set at once (cross-module
# call graph, thread/async contexts, donation dataflow) and yields
# ``(path, lineno, message)`` triples. The driver applies the same
# inline-suppression, baseline, and path-profile machinery as file rules.
ProjectCheckFn = Callable[["ProjectContext"], Iterable[Tuple[str, int, str]]]


@dataclasses.dataclass(frozen=True)
class ProjectRule:
    id: str
    severity: str
    doc: str
    check: ProjectCheckFn


PROJECT_RULES: Dict[str, ProjectRule] = {}


def project_rule(rule_id: str, severity: str, doc: str):
    assert severity in (SEVERITY_ERROR, SEVERITY_WARN), severity

    def deco(fn: ProjectCheckFn) -> ProjectCheckFn:
        assert rule_id not in RULES, f"duplicate rule id {rule_id}"
        assert rule_id not in PROJECT_RULES, f"duplicate rule id {rule_id}"
        PROJECT_RULES[rule_id] = ProjectRule(rule_id, severity, doc, fn)
        return fn

    return deco


def all_rules() -> Dict[str, object]:
    """File + project rules in one catalog (CLI ``--list-rules``,
    ``--rules`` validation, SARIF rule metadata)."""
    out: Dict[str, object] = dict(RULES)
    out.update(PROJECT_RULES)
    return out


class ProjectContext:
    """Whole-program state handed to every project rule: the index, the
    call graph, the catalogs, and lazily-built per-file contexts (for
    hot markers and suppression checks)."""

    def __init__(self, project, graph, config: Config):
        self.project = project
        self.graph = graph
        self.config = config
        self._file_ctx: Dict[str, FileContext] = {}

    def file_ctx(self, path: str) -> Optional[FileContext]:
        posix = path.replace("\\", "/")
        ctx = self._file_ctx.get(posix)
        if ctx is None:
            mod = self.project.by_path.get(posix)
            if mod is None:
                return None
            ctx = FileContext(mod.src, mod.path, mod.tree, self.config)
            self._file_ctx[posix] = ctx
        return ctx


# --------------------------------------------------------------------- #
# Suppression
# --------------------------------------------------------------------- #


def is_suppressed(ctx: FileContext, rule_id: str, lineno: int) -> bool:
    """Valid ``# arealint: ok(<reason>)`` on the line (or a comment-only
    line above); legacy ``# async-hygiene: ok`` for the migrated rules."""
    for ln in (lineno, lineno - 1):
        text = ctx.line_text(ln)
        if ln != lineno and not text.strip().startswith("#"):
            continue
        m = SUPPRESS_RE.search(text)
        if m and m.group("reason").strip():
            return True
        if rule_id in LEGACY_RULES and LEGACY_SUPPRESS in text:
            return True
    return False


# --------------------------------------------------------------------- #
# Scan driver
# --------------------------------------------------------------------- #


def _resolve_rules(rules: Optional[Sequence[str]]) -> List[Rule]:
    if rules is None:
        return list(RULES.values())
    unknown = [
        r for r in rules if r not in RULES and r not in PROJECT_RULES
    ]
    if unknown:
        raise KeyError(f"unknown rule(s): {', '.join(unknown)}")
    # project-rule ids are valid selections but run in the project pass
    return [RULES[r] for r in rules if r in RULES]


def scan_source(
    src: str,
    path: str = "<string>",
    rules: Optional[Sequence[str]] = None,
    config: Optional[Config] = None,
    apply_profile: bool = True,
) -> List[Finding]:
    config = config if config is not None else default_config()
    selected = _resolve_rules(rules)
    if apply_profile:
        excluded = excluded_rules_for_path(path)
        selected = [r for r in selected if r.id not in excluded]
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [
            Finding(
                path.replace("\\", "/"), e.lineno or 0, "parse-error",
                f"could not parse: {e.msg}", SEVERITY_ERROR,
            )
        ]
    ctx = FileContext(src, path, tree, config)
    out: List[Finding] = []
    for r in selected:
        for lineno, message in r.check(ctx):
            if not is_suppressed(ctx, r.id, lineno):
                out.append(Finding(ctx.path, lineno, r.id, message, r.severity))
    out.sort(key=lambda f: (f.line, f.rule))
    return out


def _collect_files(paths: Iterable) -> List[pathlib.Path]:
    files: List[pathlib.Path] = []
    for p in paths:
        p = pathlib.Path(p)
        files.extend(sorted(p.rglob("*.py")) if p.is_dir() else [p])
    return files


def _scan_file_worker(args) -> Tuple[str, str, List[Finding]]:
    """Process-pool worker: re-triggers rule registration (spawn-safe),
    then scans one file. Returns ``(path, src, findings)`` — the source
    rides back so the parent's project pass doesn't re-read the tree."""
    path, rules, config = args
    import tools.arealint  # noqa: F401  (registers every rule module)

    src = pathlib.Path(path).read_text()
    return path, src, scan_source(src, path, rules=rules, config=config)


def scan_paths(
    paths: Iterable,
    rules: Optional[Sequence[str]] = None,
    config: Optional[Config] = None,
    jobs: int = 1,
    project: bool = True,
) -> List[Finding]:
    """Scan ``paths``: per-file rules (optionally on a process pool) plus
    the whole-program rules over the same file set. Output order is
    deterministic regardless of ``jobs``: sorted by (path, line, rule).
    """
    config = config if config is not None else default_config()
    files = _collect_files(paths)
    findings: List[Finding] = []
    read_sources: Dict[str, str] = {}
    if jobs > 1 and len(files) > 1:
        work = [(str(f), rules, config) for f in files]
        with concurrent.futures.ProcessPoolExecutor(
            max_workers=min(jobs, len(files))
        ) as pool:
            # map preserves submission order -> deterministic output
            for path, src, result in pool.map(
                _scan_file_worker, work,
                chunksize=max(1, len(work) // (jobs * 4)),
            ):
                read_sources[path] = src
                findings.extend(result)
    else:
        for f in files:
            src = f.read_text()
            read_sources[str(f)] = src
            findings.extend(
                scan_source(src, str(f), rules=rules, config=config)
            )
    if project:
        findings.extend(
            scan_project_files(
                files, rules=rules, config=config,
                sources=read_sources or None,
            )
        )
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def _resolve_project_rules(
    rules: Optional[Sequence[str]],
) -> List[ProjectRule]:
    if rules is None:
        return list(PROJECT_RULES.values())
    return [PROJECT_RULES[r] for r in rules if r in PROJECT_RULES]


def scan_project_files(
    files: Sequence,
    rules: Optional[Sequence[str]] = None,
    config: Optional[Config] = None,
    root: Optional[pathlib.Path] = None,
    sources: Optional[Dict[str, str]] = None,
) -> List[Finding]:
    """Run the whole-program rules over a file set. ``root`` anchors
    dotted module names (defaults to the config's repo root);
    ``sources`` forwards already-read file text to skip re-reading."""
    from tools.arealint.callgraph import build_call_graph
    from tools.arealint.project import Project

    config = config if config is not None else default_config()
    selected = _resolve_project_rules(rules)
    if not selected or not files:
        return []
    if root is None:
        root = config.repo_root or default_repo_root()
        # a scan outside the repo (fixtures, ad-hoc trees) must anchor
        # dotted module names at the scanned tree, not the repo — else
        # every cross-module import fails to resolve and the project
        # rules silently degrade to intra-file
        resolved_root = pathlib.Path(root).resolve()
        def _under_root(f):
            try:
                pathlib.Path(f).resolve().relative_to(resolved_root)
                return True
            except ValueError:
                return False
        if not all(_under_root(f) for f in files):
            root = None  # Project.from_paths falls back to common parent
    proj = Project.from_paths(files, root=root, sources=sources)
    pctx = ProjectContext(proj, build_call_graph(proj), config)
    return run_project_rules(pctx, selected)


def scan_sources(
    sources: Dict[str, str],
    rules: Optional[Sequence[str]] = None,
    config: Optional[Config] = None,
) -> List[Finding]:
    """Fixture-friendly whole-program scan: ``{relpath: src}`` becomes a
    synthetic project rooted at ``/proj`` and BOTH rule layers run.
    Used by the rule tests; file paths in findings are root-relative."""
    from tools.arealint.callgraph import build_call_graph
    from tools.arealint.project import Project

    config = config if config is not None else default_config()
    findings: List[Finding] = []
    for rel in sorted(sources):
        findings.extend(
            scan_source(sources[rel], rel, rules=rules, config=config)
        )
    proj = Project.from_sources(sources)
    pctx = ProjectContext(proj, build_call_graph(proj), config)
    root_prefix = str(proj.root).replace("\\", "/").rstrip("/") + "/"
    for f in run_project_rules(pctx, _resolve_project_rules(rules)):
        p = f.path[len(root_prefix):] if f.path.startswith(root_prefix) else f.path
        findings.append(dataclasses.replace(f, path=p))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def run_project_rules(
    pctx: ProjectContext, selected: Sequence[ProjectRule]
) -> List[Finding]:
    out: List[Finding] = []
    for r in selected:
        for item in r.check(pctx):
            # (path, lineno, msg) or (path, lineno, msg, severity) — a
            # rule family with a hard and a soft direction (wire drift)
            # downgrades individual findings without a second rule id
            path, lineno, message = item[0], item[1], item[2]
            severity = item[3] if len(item) > 3 else r.severity
            posix = path.replace("\\", "/")
            if r.id in excluded_rules_for_path(posix):
                continue
            ctx = pctx.file_ctx(posix)
            if ctx is not None and is_suppressed(ctx, r.id, lineno):
                continue
            out.append(Finding(posix, lineno, r.id, message, severity))
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def has_errors(findings: Iterable[Finding]) -> bool:
    return any(f.severity == SEVERITY_ERROR for f in findings)
