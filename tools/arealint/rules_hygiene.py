"""Repo-hygiene rules: the env-knob catalog, the metrics-counter catalog,
the fault-point catalog, and suppression-comment hygiene.

The survivability planes (docs/fault_tolerance.md) are driven by AREAL_*
env knobs and observed through ``metrics.counters`` / ``faults`` injection
points. All three have a single source of truth:

- env knobs are read in ``areal_tpu/base/constants.py`` (or via a
  ``worker_base._env_*`` tolerant parser) so every knob has a documented
  default in one place;
- counter names are UPPERCASE constants in ``areal_tpu/base/metrics.py``;
- fault points are listed in ``FAULT_POINTS`` in ``areal_tpu/base/faults.py``.

A name used but not registered is exactly how a knob/counter silently
falls out of the docs and the ``get_env_vars`` forwarding list — these
rules make the catalogs load-bearing.
"""

import ast
from typing import Optional

from tools.arealint.core import (
    SUPPRESS_BARE_RE, SUPPRESS_RE, FileContext, SEVERITY_ERROR,
    SEVERITY_WARN, rule,
)

ENV_CATALOG_SUFFIXES = ("base/constants.py",)
ENV_HELPER_FILE = "system/worker_base.py"
OS_ALIASES = ("os", "_os")


def _is_os_environ(node: ast.AST) -> bool:
    if (
        isinstance(node, ast.Attribute)
        and node.attr == "environ"
        and isinstance(node.value, ast.Name)
        and node.value.id in OS_ALIASES
    ):
        return True
    # from os import environ
    return isinstance(node, ast.Name) and node.id == "environ"


def _env_read(node: ast.AST) -> Optional[str]:
    """An env READ form; writes (assignment/setdefault/pop/del) stay legal
    everywhere — propagating knobs to child processes is not a read."""
    if isinstance(node, ast.Call):
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "getenv"
            and isinstance(f.value, ast.Name)
            and f.value.id in OS_ALIASES
        ):
            return "os.getenv"
        # from os import getenv
        if isinstance(f, ast.Name) and f.id == "getenv":
            return "getenv"
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "get"
            and _is_os_environ(f.value)
        ):
            return "os.environ.get"
    if (
        isinstance(node, ast.Subscript)
        and _is_os_environ(node.value)
        and isinstance(node.ctx, ast.Load)
    ):
        return "os.environ[...]"
    if isinstance(node, ast.Compare) and any(
        isinstance(op, (ast.In, ast.NotIn)) for op in node.ops
    ):
        if any(_is_os_environ(c) for c in node.comparators):
            return "'in os.environ'"
    return None


@rule(
    "env-knob", SEVERITY_ERROR,
    "os.environ/os.getenv read outside the knob catalog "
    "(base/constants.py accessors or a worker_base._env_* parser) — "
    "undocumented knobs bypass defaults, docs, and worker env forwarding",
)
def check_env_knob(ctx: FileContext):
    if ctx.path_endswith(*ENV_CATALOG_SUFFIXES):
        return
    in_helper_file = ctx.path_endswith(ENV_HELPER_FILE)
    for node in ast.walk(ctx.tree):
        form = _env_read(node)
        if form is None:
            continue
        if in_helper_file:
            enc = ctx.enclosing_function(node)
            if enc is not None and enc.name.startswith("_env_"):
                continue
        yield (
            node.lineno,
            f"{form} outside the knob catalog — add an accessor with a "
            "default and docstring to areal_tpu/base/constants.py (or use "
            "a worker_base._env_* parser) so the knob stays documented "
            "and forwarded to workers",
        )


# --------------------------------------------------------------------- #
# metrics counter registry
# --------------------------------------------------------------------- #

COUNTER_METHODS = ("add", "peak", "get", "clear", "observe")


@rule(
    "unregistered-counter", SEVERITY_ERROR,
    "metrics.counters.add/peak/get/clear/observe with a name that is not "
    "a registered constant in the base/metrics.py catalog (histogram keys "
    "included)",
)
def check_counters(ctx: FileContext):
    values = ctx.config.counter_values
    names = ctx.config.counter_names
    if values is None or ctx.path_endswith("base/metrics.py"):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        if not (
            isinstance(f, ast.Attribute) and f.attr in COUNTER_METHODS
        ):
            continue
        v = f.value
        is_counters = (
            (isinstance(v, ast.Name) and v.id == "counters")
            or (isinstance(v, ast.Attribute) and v.attr == "counters")
        )
        if not is_counters:
            continue
        a0 = node.args[0]
        if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
            if a0.value not in values:
                yield (
                    a0.lineno,
                    f"counter {a0.value!r} is not registered in the "
                    "catalog in areal_tpu/base/metrics.py — add a named "
                    "constant there (and use it here) so dashboards/tests "
                    "have one authoritative name list",
                )
        elif isinstance(a0, (ast.Attribute, ast.Name)):
            last = a0.attr if isinstance(a0, ast.Attribute) else a0.id
            if last == last.upper() and last.isidentifier() and names:
                if last not in names:
                    yield (
                        a0.lineno,
                        f"counter constant {last!r} is not defined in "
                        "areal_tpu/base/metrics.py — register it in the "
                        "catalog",
                    )
        # f-strings / variables: dynamic names (e.g. tracing.span's
        # "<span>_s") cannot be checked statically; skipped.


# --------------------------------------------------------------------- #
# fault injection point registry
# --------------------------------------------------------------------- #

FAULT_FUNCS = ("maybe_fail", "maybe_trip", "maybe_fail_async", "inject")


@rule(
    "unregistered-fault-point", SEVERITY_ERROR,
    "faults.maybe_fail/maybe_trip/maybe_fail_async/inject with a point "
    "name missing from FAULT_POINTS in base/faults.py",
)
def check_fault_points(ctx: FileContext):
    points = ctx.config.fault_points
    if points is None or ctx.path_endswith("base/faults.py"):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else ""
        )
        if name not in FAULT_FUNCS:
            continue
        a0 = node.args[0]
        if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
            if a0.value not in points:
                yield (
                    a0.lineno,
                    f"fault point {a0.value!r} is not registered in "
                    "FAULT_POINTS in areal_tpu/base/faults.py — register "
                    "it (and its docstring-table row) so scripted "
                    "scenarios and docs stay in sync",
                )


# --------------------------------------------------------------------- #
# suppression hygiene
# --------------------------------------------------------------------- #


@rule(
    "suppression-missing-reason", SEVERITY_WARN,
    "'# arealint: ok' without a reason — suppressions must say WHY "
    "(# arealint: ok(<reason>)); a bare token does not suppress",
)
def check_suppressions(ctx: FileContext):
    for i, line in enumerate(ctx.lines, 1):
        m = SUPPRESS_RE.search(line)
        if m is not None:
            if not m.group("reason").strip():
                yield (
                    i,
                    "inline suppression without a reason — write "
                    "'# arealint: ok(<why this is deliberate>)'; the "
                    "empty form does not suppress",
                )
        elif SUPPRESS_BARE_RE.search(line):
            yield (
                i,
                "inline suppression without a reason — write "
                "'# arealint: ok(<why this is deliberate>)'; the bare "
                "token does not suppress",
            )
