"""SPMD/sharding-safety rules: mesh-axis checking, sharding-propagation
lite, and host-divergence-before-collective detection.

The trainer is 3D-parallel (``parallel/mesh.py``: axes ``data/fsdp/ctx/
model``) and its worst failure modes are SPMD-shaped — a typo'd mesh
axis in a ``PartitionSpec`` silently replicates a tensor, an implicit
reshard inside the decode/train hot path moves gigabytes per step, and
host-divergent control flow ahead of a collective wedges every process
in the pod at once. Four rule families catch these at lint time:

- **mesh-axis family** (file rules) — ``unknown-mesh-axis`` (axis name
  not in the parsed mesh catalog, see :mod:`tools.arealint.meshmodel`),
  ``mesh-axis-reuse`` (one axis used for two dims of one spec),
  ``shard-map-spec-arity`` (``in_specs``/``out_specs`` arity vs. the
  wrapped function's signature and the immediate invocation), and
  ``donation-sharding-mismatch`` (a donated operand whose inferred
  sharding matches no ``out_shardings`` entry — XLA cannot alias the
  buffer, so the donation is a silent copy).
- **sharding-propagation lite** (project rules) — a per-function
  inference pass tracks ``NamedSharding``-typed locals/attributes and
  the placements ``device_put``/``with_sharding_constraint`` establish.
  ``hot-path-reshard`` flags a placement call that CHANGES the inferred
  spec of a value inside a jitted / ``# arealint: hot`` root (or
  anything reachable from one); ``jit-sharding-disagreement`` flags
  call sites of one jitted function passing differently-sharded
  operands at the same position (one trace per layout + a reshard at
  the losing sites).
- **host divergence** (project rule) — ``host-divergence-collective``:
  host-local nondeterminism (``time.*``, runtime ``os.environ`` reads
  outside the knob catalog, ``random``/``secrets``/``uuid``,
  ``process_index()`` comparisons, queue state) flowing — through
  assignments and cross-module return values — into a branch whose body
  reaches a collective (``multihost.barrier/allreduce_*`` etc., a
  function containing ``lax.psum``-family ops, ``with mesh:`` entry)
  without being routed through ``multihost.main_decides``. The exact
  class PR 3 hand-fixed for SIGTERM timing.

Everything degrades (docs/static_analysis.md): a spec the inference
cannot resolve, an axis entry that is not a literal, or an unresolvable
call edge produces NO finding — the propagation pass never guesses.
"""

import ast
import collections
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from tools.arealint.core import (
    FileContext, ProjectContext, SEVERITY_ERROR, SEVERITY_WARN,
    project_rule, rule, walk_excluding_nested,
)
from tools.arealint.project import FunctionInfo, _dotted, collect_aliases
from tools.arealint.rules_dataflow import _short
from tools.arealint.rules_hygiene import (
    ENV_CATALOG_SUFFIXES, ENV_HELPER_FILE, _env_read,
)
from tools.arealint.rules_jax import (
    _donated_positions, _has_jit_decorator, _is_jit_call,
    file_hot_roots, intra_hot_reachable,
)

# --------------------------------------------------------------------- #
# alias table + constructor recognition
# --------------------------------------------------------------------- #


def _file_aliases(ctx: FileContext) -> Dict[str, str]:
    cached = getattr(ctx, "_spmd_aliases", None)
    if cached is None:
        cached = collect_aliases(ctx.tree)
        ctx._spmd_aliases = cached
    return cached


def _ctor_matches(
    aliases: Dict[str, str], func: ast.AST, name: str
) -> bool:
    """``X.<name>(...)`` attribute form, a bare ``<name>`` import, or an
    alias whose import target ends in ``.<name>``."""
    if isinstance(func, ast.Attribute):
        return func.attr == name
    if isinstance(func, ast.Name):
        if func.id == name:
            return True
        return aliases.get(func.id, "").split(".")[-1] == name
    return False


def _is_pspec_ctor(aliases, call: ast.AST) -> bool:
    return isinstance(call, ast.Call) and _ctor_matches(
        aliases, call.func, "PartitionSpec"
    )


def _is_named_sharding_ctor(aliases, call: ast.AST) -> bool:
    return isinstance(call, ast.Call) and _ctor_matches(
        aliases, call.func, "NamedSharding"
    )


def _is_shard_map_call(aliases, call: ast.AST) -> bool:
    return isinstance(call, ast.Call) and _ctor_matches(
        aliases, call.func, "shard_map"
    )


def _is_placement_call(call: ast.AST) -> Optional[str]:
    """``jax.device_put`` / ``with_sharding_constraint`` (any spelling) —
    the two ops that *establish* a value's sharding."""
    if not isinstance(call, ast.Call):
        return None
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else ""
    )
    if name in ("device_put", "with_sharding_constraint"):
        return name
    return None


# --------------------------------------------------------------------- #
# spec parsing / canonicalization
# --------------------------------------------------------------------- #

_UNRESOLVED = object()


def _pspec_entries(call: ast.Call) -> List[Tuple[ast.AST, object]]:
    """Per positional arg of a ``P(...)`` call: (node, parsed) where
    parsed is None (replicated), a str axis, a tuple of str axes, or
    ``_UNRESOLVED`` (dynamic expression)."""
    out: List[Tuple[ast.AST, object]] = []
    for a in call.args:
        if isinstance(a, ast.Constant) and a.value is None:
            out.append((a, None))
        elif isinstance(a, ast.Constant) and isinstance(a.value, str):
            out.append((a, a.value))
        elif isinstance(a, (ast.Tuple, ast.List)) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in a.elts
        ):
            out.append((a, tuple(e.value for e in a.elts)))
        else:
            out.append((a, _UNRESOLVED))
    return out


def _canonical_pspec(call: ast.Call) -> Optional[tuple]:
    """Fully-literal spec as a canonical tuple (trailing replicated dims
    stripped — ``P('data', None)`` == ``P('data')``); None when any
    entry is dynamic (degrade)."""
    entries = _pspec_entries(call)
    if any(parsed is _UNRESOLVED for _, parsed in entries):
        return None
    spec = [parsed for _, parsed in entries]
    while spec and spec[-1] is None:
        spec.pop()
    return tuple(spec)


def _fmt_spec(spec: tuple) -> str:
    def one(e):
        if e is None:
            return "None"
        if isinstance(e, tuple):
            return "(" + ",".join(repr(x) for x in e) + ")"
        return repr(e)

    return "P(" + ", ".join(one(e) for e in spec) + ")"


def _spec_axis_names(call: ast.Call) -> Iterator[Tuple[ast.AST, str]]:
    """Every literal axis-name string in a ``P(...)`` call, including
    inside tuple entries — dynamic entries are simply skipped."""
    for node, parsed in _pspec_entries(call):
        if isinstance(parsed, str):
            yield node, parsed
        elif isinstance(parsed, tuple):
            for e, v in zip(node.elts, parsed):
                yield e, v


def _kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _sharding_spec_of(
    aliases, expr: ast.AST, shvars: Dict[str, tuple]
) -> Optional[tuple]:
    """Canonical spec of a sharding-valued EXPRESSION: an inline
    ``NamedSharding(mesh, P(...))``, an inline ``P(...)``, or a name /
    ``self.attr`` previously bound to one (``shvars``)."""
    if _is_named_sharding_ctor(aliases, expr):
        spec_arg = (
            expr.args[1] if len(expr.args) > 1 else _kwarg(expr, "spec")
        )
        if spec_arg is not None and _is_pspec_ctor(aliases, spec_arg):
            return _canonical_pspec(spec_arg)
        return None
    if _is_pspec_ctor(aliases, expr):
        return _canonical_pspec(expr)
    d = _dotted(expr)
    if d is not None:
        return shvars.get(d)
    return None


# --------------------------------------------------------------------- #
# class-attribute sharding specs ("self._repl" -> P())
# --------------------------------------------------------------------- #


def _class_attr_specs(aliases, tree: ast.AST) -> Dict[str, tuple]:
    """``self.<attr>`` -> canonical spec, from ``self.attr =
    NamedSharding(mesh, P(<literal>))`` assignments anywhere in the
    file's classes. An attr bound twice with different specs, or also
    bound to anything unresolvable, is dropped (ambiguous — degrade)."""
    specs: Dict[str, tuple] = {}
    dropped: Set[str] = set()
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for node in ast.walk(cls):
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Attribute)
                and isinstance(node.targets[0].value, ast.Name)
                and node.targets[0].value.id == "self"
            ):
                continue
            attr = node.targets[0].attr
            spec = (
                _sharding_spec_of(aliases, node.value, {})
                if isinstance(node.value, ast.Call) else None
            )
            if spec is None:
                # ANY unresolvable rebinding (a forwarded parameter, a
                # helper result, a dynamic spec) makes the attr's spec
                # unknowable — drop it, never keep a stale literal
                dropped.add(attr)
                continue
            if attr in specs and specs[attr] != spec:
                dropped.add(attr)
            specs.setdefault(attr, spec)
    return {
        f"self.{a}": s for a, s in specs.items() if a not in dropped
    }


# --------------------------------------------------------------------- #
# per-function spec inference (the "propagation lite" pass)
# --------------------------------------------------------------------- #


class FnSpecs:
    """One ordered pass over a function's own body:

    - ``shvars``: sharding OBJECTS (``sh = NamedSharding(mesh, P(..))``,
      plus the file's ``self.<attr>`` specs handed in);
    - array placements: ``x = device_put(v, sh)`` / ``x =
      with_sharding_constraint(v, sh)`` bind x's inferred spec;
    - ``events``: placement calls whose operand already had a DIFFERENT
      inferred spec (an implicit reshard);
    - ``call_arg_specs``: id(Call) -> per-positional-arg inferred spec
      snapshot taken in source order (for the call-site rules).

    Any expression the pass cannot resolve invalidates the binding —
    inference degrades, never guesses.
    """

    def __init__(self, aliases, fnode, attr_specs: Dict[str, tuple]):
        self.aliases = aliases
        self.shvars: Dict[str, tuple] = dict(attr_specs)
        self.arr: Dict[str, tuple] = {}
        self.events: List[Tuple[ast.AST, str, str, tuple, tuple]] = []
        self.call_arg_specs: Dict[int, List[Optional[tuple]]] = {}
        self._run(fnode)

    def _run(self, fnode):
        handled: Set[int] = set()
        for node in walk_excluding_nested(fnode):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                self._assign(node, handled)
            elif isinstance(node, ast.Assign):
                # a = b = value: every target rebinds to an unknown
                for t in node.targets:
                    self._invalidate(t)
            elif isinstance(node, ast.AnnAssign):
                self._invalidate(node.target)
            elif isinstance(node, (ast.AugAssign, ast.NamedExpr)):
                self._invalidate(node.target)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                self._invalidate(node.target)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is not None:
                        self._invalidate(item.optional_vars)
            elif isinstance(node, ast.Call) and id(node) not in handled:
                self._snapshot(node)
                kind = _is_placement_call(node)
                if kind:
                    self._placement(node, kind, target=None)

    def _invalidate(self, target: ast.AST):
        """Rebinding through any form the pass doesn't model drops the
        binding — degrade, never keep a stale spec."""
        elts = (
            target.elts
            if isinstance(target, (ast.Tuple, ast.List)) else [target]
        )
        for e in elts:
            d = _dotted(e)
            if d is not None:
                self.arr.pop(d, None)
                self.shvars.pop(d, None)

    def _snapshot(self, call: ast.Call):
        specs = [
            self.arr.get(d) if (d := _dotted(a)) else None
            for a in call.args
        ]
        if any(s is not None for s in specs):
            self.call_arg_specs[id(call)] = specs

    def _sharding_expr(self, call: ast.Call) -> Optional[ast.expr]:
        """The sharding operand of a placement call."""
        if len(call.args) > 1:
            return call.args[1]
        for name in ("device", "sharding", "shardings"):
            got = _kwarg(call, name)
            if got is not None:
                return got
        return None

    def _placement(self, call: ast.Call, kind: str, target: Optional[str]):
        sh = self._sharding_expr(call)
        spec = (
            _sharding_spec_of(self.aliases, sh, self.shvars)
            if sh is not None else None
        )
        opd = _dotted(call.args[0]) if call.args else None
        if spec is None:
            if target:
                self.arr.pop(target, None)
            return
        if opd is not None and opd in self.arr and self.arr[opd] != spec:
            self.events.append((call, kind, opd, self.arr[opd], spec))
        if target:
            self.arr[target] = spec
        # no target (the result is returned/passed on directly): the
        # OPERAND's own binding is unchanged — device_put/wsc produce a
        # new value, they don't mutate their input

    def _assign(self, node: ast.Assign, handled: Set[int]):
        t0 = node.targets[0]
        if isinstance(t0, (ast.Tuple, ast.List)):
            # tuple unpacking rebinds every element to an unknown value
            if isinstance(node.value, ast.Call):
                handled.add(id(node.value))
                self._snapshot(node.value)
            for e in t0.elts:
                d = _dotted(e)
                if d is not None:
                    self.arr.pop(d, None)
                    self.shvars.pop(d, None)
            return
        td = _dotted(t0)
        v = node.value
        if isinstance(v, ast.Call):
            handled.add(id(v))
            self._snapshot(v)
            spec = None
            if _is_named_sharding_ctor(self.aliases, v) or _is_pspec_ctor(
                self.aliases, v
            ):
                spec = _sharding_spec_of(self.aliases, v, self.shvars)
                if td is not None:
                    if spec is not None:
                        self.shvars[td] = spec
                    else:
                        self.shvars.pop(td, None)
                    self.arr.pop(td, None)
                return
            kind = _is_placement_call(v)
            if kind:
                self._placement(v, kind, target=td)
                return
        # opaque value: drop whatever we believed about the target
        if td is not None:
            self.arr.pop(td, None)
            self.shvars.pop(td, None)


# --------------------------------------------------------------------- #
# unknown-mesh-axis + mesh-axis-reuse (file rules)
# --------------------------------------------------------------------- #


@rule(
    "unknown-mesh-axis", SEVERITY_ERROR,
    "axis name in a PartitionSpec/NamedSharding/shard_map spec that is "
    "not an axis of the mesh built by parallel/mesh.py:make_mesh — the "
    "spec silently replicates (or errors at trace time on hardware)",
)
def check_unknown_mesh_axis(ctx: FileContext):
    mesh = ctx.config.mesh
    if mesh is None:
        return
    aliases = _file_aliases(ctx)
    known = ", ".join(mesh.axes)
    for node in ast.walk(ctx.tree):
        if not _is_pspec_ctor(aliases, node):
            continue
        for entry, axis in _spec_axis_names(node):
            if not mesh.known_axis(axis):
                yield (
                    entry.lineno,
                    f"unknown mesh axis {axis!r} in PartitionSpec — the "
                    f"mesh built by make_mesh has axes ({known}); a "
                    "typo'd axis silently replicates the tensor instead "
                    "of sharding it",
                )


@rule(
    "mesh-axis-reuse", SEVERITY_ERROR,
    "one mesh axis named twice in a single PartitionSpec — an axis can "
    "shard only one dim; jax rejects the spec at trace time, on "
    "hardware, hours in",
)
def check_mesh_axis_reuse(ctx: FileContext):
    aliases = _file_aliases(ctx)
    for node in ast.walk(ctx.tree):
        if not _is_pspec_ctor(aliases, node):
            continue
        seen: Dict[str, int] = {}
        for entry, axis in _spec_axis_names(node):
            if axis in seen:
                yield (
                    entry.lineno,
                    f"mesh axis {axis!r} is used twice in one "
                    "PartitionSpec (first at line "
                    f"{seen[axis]}) — an axis can shard only one dim "
                    "of a value",
                )
            else:
                seen[axis] = entry.lineno


# --------------------------------------------------------------------- #
# shard-map-spec-arity (file rule)
# --------------------------------------------------------------------- #


def _positional_arity(fdef) -> Optional[Tuple[int, int]]:
    """(min, max) positional args a def accepts; None when *args makes
    the upper bound open."""
    args = fdef.args
    if args.vararg is not None:
        return None
    pos = list(getattr(args, "posonlyargs", [])) + list(args.args)
    return (len(pos) - len(args.defaults), len(pos))


def _resolve_shard_map_body(
    aliases, call: ast.Call, defs_by_name: Dict[str, List],
    shadowed: Set[str],
) -> Optional[Tuple[str, Tuple[int, int]]]:
    """(name, (min, max) arity) of the wrapped callable when it resolves
    to exactly one same-file def — directly or through a keyword-only
    ``functools.partial``; anything else degrades. ``shadowed`` holds
    names bound as plain variables in the enclosing scope — those may
    refer to ANYTHING (e.g. a partial assigned to a name that collides
    with an unrelated def), so they never resolve."""
    if not call.args:
        return None
    body = call.args[0]
    extra = 0
    partial_kwargs: List[str] = []
    if isinstance(body, ast.Call) and _ctor_matches(
        aliases, body.func, "partial"
    ):
        if not body.args:
            return None
        extra = len(body.args) - 1  # positionals pre-bound by partial
        partial_kwargs = [kw.arg for kw in body.keywords if kw.arg]
        body = body.args[0]
    d = _dotted(body)
    if d is None or "." in d or d in shadowed:
        return None
    cands = defs_by_name.get(d, [])
    if len(cands) != 1:
        return None
    fdef = cands[0]
    if partial_kwargs:
        # a partial keyword that names a POSITIONAL-or-keyword param
        # removes it from the callable's positional surface in a way
        # simple subtraction can't model — degrade. Keyword-ONLY params
        # (after ``*``, the _ring_shard idiom) don't affect arity.
        pos_names = {
            a.arg
            for a in list(getattr(fdef.args, "posonlyargs", []))
            + list(fdef.args.args)
        }
        if pos_names & set(partial_kwargs):
            return None
    arity = _positional_arity(fdef)
    if arity is None:
        return None
    lo, hi = arity
    return d, (max(lo - extra, 0), hi - extra)


def _tuple_return_arity(fdef) -> Optional[int]:
    """Length of the def's returned tuple when EVERY return is a literal
    tuple of one consistent length; None otherwise (degrade)."""
    lengths: Set[int] = set()
    for node in walk_excluding_nested(fdef):
        if isinstance(node, ast.Return):
            if not isinstance(node.value, ast.Tuple):
                return None
            lengths.add(len(node.value.elts))
    return lengths.pop() if len(lengths) == 1 else None


@rule(
    "shard-map-spec-arity", SEVERITY_ERROR,
    "shard_map in_specs/out_specs arity disagrees with the wrapped "
    "function's signature or the immediate invocation — jax errors at "
    "trace time, typically only on hardware where the mesh is real",
)
def check_shard_map_arity(ctx: FileContext):
    aliases = _file_aliases(ctx)
    defs_by_name: Dict[str, List] = {}
    for n in ast.walk(ctx.tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(n.name, []).append(n)
    parents = ctx.parents()
    for node in ast.walk(ctx.tree):
        if not _is_shard_map_call(aliases, node):
            continue
        in_specs = _kwarg(node, "in_specs")
        n_in = (
            len(in_specs.elts)
            if isinstance(in_specs, (ast.Tuple, ast.List)) else None
        )
        enc = ctx.enclosing_function(node)
        shadowed: Set[str] = set()
        if enc is not None:
            # anything locally (re)bound — plain assignments AND
            # parameters: `def outer(kernel, ...)` must not resolve
            # `kernel` to an unrelated module-level def
            shadowed = {
                n.id for n in ast.walk(enc)
                if isinstance(n, ast.Name)
                and isinstance(n.ctx, (ast.Store, ast.Del))
            } | {
                a.arg for a in ast.walk(enc) if isinstance(a, ast.arg)
            }
        body = _resolve_shard_map_body(aliases, node, defs_by_name, shadowed)
        sig_mismatch = False
        if n_in is not None and body is not None:
            name, (lo, hi) = body
            if not (lo <= n_in <= hi):
                sig_mismatch = True
                want = str(hi) if lo == hi else f"{lo}..{hi}"
                yield (
                    in_specs.lineno,
                    f"shard_map in_specs has {n_in} entries but "
                    f"{name}() takes {want} positional argument(s) — "
                    "every operand needs exactly one spec",
                )
        # immediate invocation: shard_map(...)(a, b, c) — skipped when
        # the signature check above already reported this defect
        parent = parents.get(node)
        if (
            n_in is not None
            and not sig_mismatch
            and isinstance(parent, ast.Call)
            and parent.func is node
            and not any(isinstance(a, ast.Starred) for a in parent.args)
            and not parent.keywords
            and len(parent.args) != n_in
        ):
            yield (
                parent.lineno,
                f"shard_map in_specs has {n_in} entries but the call "
                f"passes {len(parent.args)} operand(s)",
            )
        out_specs = _kwarg(node, "out_specs")
        if (
            isinstance(out_specs, (ast.Tuple, ast.List))
            and body is not None
        ):
            name = body[0]
            n_ret = (
                _tuple_return_arity(defs_by_name[name][0])
                if len(defs_by_name.get(name, [])) == 1 else None
            )
            if n_ret is not None and n_ret != len(out_specs.elts):
                yield (
                    out_specs.lineno,
                    f"shard_map out_specs has {len(out_specs.elts)} "
                    f"entries but {name}() returns a {n_ret}-tuple",
                )


# --------------------------------------------------------------------- #
# donation-sharding-mismatch (file rule)
# --------------------------------------------------------------------- #


def _jit_donation_info(
    call: ast.Call,
) -> Optional[Tuple[Tuple[int, ...], Optional[ast.expr]]]:
    """(donated positions, out_shardings expr) of a jit(...) build."""
    if not _is_jit_call(call):
        return None
    pos = _donated_positions(call)
    if not pos:
        return None
    return pos, _kwarg(call, "out_shardings")


@rule(
    "donation-sharding-mismatch", SEVERITY_WARN,
    "an operand donated to a jitted call has an inferred sharding that "
    "matches no out_shardings entry — XLA cannot alias the buffer, so "
    "the donation silently degrades to a copy (HBM spike on hardware)",
)
def check_donation_sharding(ctx: FileContext):
    # cheap pre-pass: almost no file donates — don't pay a spec
    # inference pass (or the class-attr scan) for files/functions that
    # can't produce a finding
    if "donate_argnums" not in ctx.src:
        return
    aliases = _file_aliases(ctx)
    attr_specs = None
    for fdef in ast.walk(ctx.tree):
        if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        # donated jitted callables bound in this scope
        donors: Dict[str, Tuple[Tuple[int, ...], Optional[ast.expr]]] = {}
        has_inline = False
        for node in walk_excluding_nested(fdef):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                info = _jit_donation_info(node.value)
                if info:
                    donors[node.targets[0].id] = info
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Call)
                and _jit_donation_info(node.func) is not None
            ):
                has_inline = True
        if not donors and not has_inline:
            continue
        if attr_specs is None:
            attr_specs = _class_attr_specs(aliases, ctx.tree)
        fs = FnSpecs(aliases, fdef, attr_specs)
        for node in walk_excluding_nested(fdef):
            if not isinstance(node, ast.Call):
                continue
            info = None
            if isinstance(node.func, ast.Name) and node.func.id in donors:
                info = donors[node.func.id]
            elif isinstance(node.func, ast.Call):
                info = _jit_donation_info(node.func)
            if info is None:
                continue
            positions, out_sh = info
            if not isinstance(out_sh, (ast.Tuple, ast.List)):
                continue  # single/absent out_shardings: degrade
            out_specs = []
            for e in out_sh.elts:
                s = _sharding_spec_of(aliases, e, fs.shvars)
                out_specs.append(s)
            if any(s is None for s in out_specs):
                continue  # an unresolvable output spec: degrade
            arg_specs = fs.call_arg_specs.get(id(node), [])
            for p in positions:
                if p >= len(arg_specs) or arg_specs[p] is None:
                    continue
                s_in = arg_specs[p]
                if s_in not in out_specs:
                    d = _dotted(node.args[p]) or f"argument {p}"
                    outs = ", ".join(_fmt_spec(s) for s in out_specs)
                    yield (
                        node.lineno,
                        f"{d!r} (inferred {_fmt_spec(s_in)}) is donated "
                        f"but no out_shardings entry [{outs}] matches "
                        "its sharding — XLA cannot alias the donated "
                        "buffer and the donation becomes a silent copy; "
                        "align the output binding's sharding or drop "
                        "the donation",
                    )


# --------------------------------------------------------------------- #
# hot-path-reshard (project rule)
# --------------------------------------------------------------------- #


def _project_hot_roots(pctx: ProjectContext) -> List[str]:
    # delegate to rules_dataflow's detector so the SPMD and host-sync
    # rules can never disagree about what a hot root is
    from tools.arealint.rules_dataflow import _project_hot_roots as f

    return f(pctx)


def _module_aliases(pctx: ProjectContext, path: str) -> Dict[str, str]:
    cache = getattr(pctx, "_spmd_mod_aliases", None)
    if cache is None:
        cache = {}
        pctx._spmd_mod_aliases = cache
    got = cache.get(path)
    if got is None:
        ctx = pctx.file_ctx(path)
        got = collect_aliases(ctx.tree) if ctx is not None else {}
        cache[path] = got
    return got


def _module_attr_specs(pctx: ProjectContext, path: str) -> Dict[str, tuple]:
    cache = getattr(pctx, "_spmd_attr_specs", None)
    if cache is None:
        cache = {}
        pctx._spmd_attr_specs = cache
    got = cache.get(path)
    if got is None:
        ctx = pctx.file_ctx(path)
        got = (
            _class_attr_specs(_module_aliases(pctx, path), ctx.tree)
            if ctx is not None else {}
        )
        cache[path] = got
    return got


def _fn_specs(pctx: ProjectContext, fi: FunctionInfo) -> FnSpecs:
    cache = getattr(pctx, "_spmd_fn_specs", None)
    if cache is None:
        cache = {}
        pctx._spmd_fn_specs = cache
    got = cache.get(id(fi.node))
    if got is None:
        got = FnSpecs(
            _module_aliases(pctx, fi.path),
            fi.node,
            _module_attr_specs(pctx, fi.path),
        )
        cache[id(fi.node)] = got
    return got


@project_rule(
    "hot-path-reshard", SEVERITY_ERROR,
    "with_sharding_constraint/device_put changes the inferred sharding "
    "of a value inside a jitted or '# arealint: hot' root (or anything "
    "reachable from one) — an implicit reshard moves the value across "
    "devices every step of the decode/train loop",
)
def check_hot_path_reshard(pctx: ProjectContext):
    graph = pctx.graph
    roots = _project_hot_roots(pctx)
    # BFS with root attribution (sorted edges -> deterministic chains)
    pred: Dict[str, str] = {}
    work: collections.deque = collections.deque()
    for r in roots:
        if r not in pred:
            pred[r] = r
            work.append(r)
    while work:
        cur = work.popleft()
        for nxt in sorted(graph.edges.get(cur, ())):
            if nxt not in pred:
                pred[nxt] = pred[cur]
                work.append(nxt)

    seen_nodes: Set[int] = set()
    todo: List[Tuple[str, FunctionInfo, str]] = []
    for q in sorted(pred):
        fi = graph.function(q)
        if fi is not None:
            seen_nodes.add(id(fi.node))
            todo.append((fi.path, fi, f"hot root {_short(pred[q])}()"))
    # nested defs (jitted local step functions) are hot but not indexed;
    # pick them up from the intra-file closure
    for mod_name in sorted(pctx.project.modules):
        mod = pctx.project.modules[mod_name]
        ctx = pctx.file_ctx(mod.path)
        if ctx is None:
            continue
        for fnode in sorted(
            intra_hot_reachable(ctx), key=lambda n: n.lineno
        ):
            if id(fnode) in seen_nodes:
                continue
            seen_nodes.add(id(fnode))
            fi = FunctionInfo(
                qualname=f"{mod.name}.<local>.{fnode.name}",
                module=mod.name, name=fnode.name, class_name=None,
                node=fnode, path=mod.path,
            )
            todo.append(
                (mod.path, fi, "a jitted/'# arealint: hot' root here")
            )

    for path, fi, root_desc in todo:
        fs = _fn_specs(pctx, fi)
        for call, kind, var, old, new in fs.events:
            yield (
                path, call.lineno,
                f"{kind}() changes the inferred sharding of {var!r} "
                f"from {_fmt_spec(old)} to {_fmt_spec(new)} in "
                f"{fi.name}() (reachable from {root_desc}) — an "
                "implicit reshard on the hot path; produce the value "
                "in its target sharding, or annotate a deliberate "
                "reshard with '# arealint: ok(<reason>)'",
            )


# --------------------------------------------------------------------- #
# jit-sharding-disagreement (project rule)
# --------------------------------------------------------------------- #


@project_rule(
    "jit-sharding-disagreement", SEVERITY_WARN,
    "call sites of one jitted function pass differently-sharded "
    "operands at the same position — each layout compiles its own "
    "trace and the losing sites pay a reshard on entry",
)
def check_jit_sharding_disagreement(pctx: ProjectContext):
    graph = pctx.graph
    for q in sorted(graph.sites_by_callee):
        fi = graph.function(q)
        if fi is None or not _has_jit_decorator(fi.node):
            continue
        sites = graph.sites_by_callee[q]
        if len(sites) < 2:
            continue
        per_pos: Dict[int, List[Tuple[object, tuple]]] = {}
        for site in sites:
            caller = graph.function(site.caller)
            if caller is None:
                continue
            specs = _fn_specs(pctx, caller).call_arg_specs.get(
                id(site.node)
            )
            if not specs:
                continue
            for p, s in enumerate(specs):
                if s is not None:
                    per_pos.setdefault(p, []).append((site, s))
        for p in sorted(per_pos):
            known = sorted(
                per_pos[p], key=lambda e: (e[0].path, e[0].line)
            )
            distinct = {s for _, s in known}
            if len(distinct) < 2:
                continue
            # one defect ("pick one sharding"), one finding: report at
            # the first site and name the first disagreeing sibling
            site, s = known[0]
            other, other_s = next(
                (e for e in known if e[1] != s)
            )
            yield (
                site.path, site.line,
                f"jitted {fi.name}() receives an operand inferred as "
                f"{_fmt_spec(s)} at position {p} here, but "
                f"{other.path}:{other.line} passes one inferred as "
                f"{_fmt_spec(other_s)} — each layout traces separately "
                "and the losing sites reshard on entry; pick one "
                "sharding for this operand",
            )


# --------------------------------------------------------------------- #
# host-divergence-collective (project rule)
# --------------------------------------------------------------------- #

_TIME_ATTRS = frozenset({
    "time", "time_ns", "monotonic", "monotonic_ns", "perf_counter",
    "perf_counter_ns", "process_time", "process_time_ns",
})
_RANDOM_BASES = frozenset({"random", "secrets", "uuid"})
# no-arg method calls that read host-local queue/signal/flag state: a
# queue fills, a signal lands, a thread sets an Event at a different
# instant on every host
_HOST_STATE_METHODS = frozenset({
    "empty", "qsize", "full", "is_set", "should_stop",
})
_MULTIHOST_COLLECTIVES = frozenset({
    "barrier", "allreduce_sum", "allreduce_max", "allreduce_min",
    "allgather_rows", "assert_same_across_hosts",
    "gather_params_to_host", "main_decides",
})
_MULTIHOST_UTILS = frozenset({
    "process_allgather", "sync_global_devices", "broadcast_one_to_all",
})
_LAX_COLLECTIVES = frozenset({
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "psum_scatter", "pshuffle",
})


def _env_exempt(path: str, fn_name: str) -> bool:
    """Env reads in the knob catalog (and the worker_base ``_env_*``
    parsers — that file only, matching the env-knob rule's scoping) are
    uniform across hosts BY CONSTRUCTION: the launcher forwards the
    same values to every process (that is the env-knob rule's whole
    contract), so they are not divergence sources."""
    p = path.replace("\\", "/")
    return any(p.endswith(s) for s in ENV_CATALOG_SUFFIXES) or (
        p.endswith(ENV_HELPER_FILE) and fn_name.startswith("_env_")
    )


def _is_gate(call: ast.AST) -> bool:
    """A ``main_decides(...)`` call (any spelling): process 0 broadcasts
    the decision, so everything inside its arguments is host-uniform by
    the time the branch tests it. Name-based on the SUPPRESSION side —
    the conservative direction."""
    if not isinstance(call, ast.Call):
        return False
    d = _dotted(call.func)
    return d is not None and d.split(".")[-1] == "main_decides"


def _walk_ungated(expr: ast.AST) -> Iterator[ast.AST]:
    stack = [expr]
    while stack:
        n = stack.pop()
        if _is_gate(n):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _divergent_call(aliases, node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Attribute):
        attr = f.attr
        if isinstance(f.value, ast.Name):
            base = aliases.get(f.value.id, f.value.id)
            if base == "time" and attr in _TIME_ATTRS:
                return f"time.{attr}()"
            if base in _RANDOM_BASES and attr != "Random":
                return f"{base}.{attr}()"
        if attr == "process_index":
            return "process_index() (differs on every host)"
        if attr in _HOST_STATE_METHODS and not node.args:
            return f".{attr}() (host-local queue/signal state)"
    elif isinstance(f, ast.Name):
        t = aliases.get(f.id, "")
        head, _, last = t.rpartition(".")
        if head == "time" and last in _TIME_ATTRS:
            return f"time.{last}()"
        if head in _RANDOM_BASES and last != "Random":
            return f"{t}()"
        if f.id == "process_index" or last == "process_index":
            return "process_index() (differs on every host)"
    return None


def _expr_divergence(
    aliases,
    expr: ast.AST,
    tainted: Dict[str, str],
    callee_of: Dict[int, str],
    ret_div: Dict[str, str],
    env_ok: bool,
) -> Optional[str]:
    """Why ``expr`` is host-divergent, or None. ``main_decides`` call
    subtrees are skipped (gated = uniform)."""
    for node in _walk_ungated(expr):
        if isinstance(node, ast.Call):
            d = _divergent_call(aliases, node)
            if d:
                return d
            q = callee_of.get(id(node))
            if q is not None and q in ret_div:
                return (
                    f"{_short(q)}() (returns {ret_div[q]})"
                )
        if not env_ok:
            e = _env_read(node)
            if e:
                return f"{e} (host-local env read)"
        if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
            getattr(node, "ctx", None), ast.Load
        ):
            d = _dotted(node)
            if d is not None and d in tainted:
                return tainted[d]
    return None


def _scan_divergence(
    aliases,
    fnode,
    callee_of: Dict[int, str],
    ret_div: Dict[str, str],
    env_ok: bool,
    on_branch=None,
) -> Optional[str]:
    """One scoped walk of a function's own body tracking host-divergence:

    - value taint: ``x = time.monotonic()`` taints ``x``; rebinding from
      a uniform expression untaints;
    - CONTROL-dependence taint: an assignment (or return) inside a
      branch whose test is divergent is divergent even when the
      assigned expression is a constant (``if time...: fire = True``);
    - ``on_branch(node, desc, arms)`` fires for every If/While/IfExp
      whose test is divergent (the reshard... the collective check runs
      there).

    Returns the divergence description of the function's RESULT (first
    divergent return), or None.
    """
    tainted: Dict[str, str] = {}
    ret_desc: List[Optional[str]] = [None]

    def taint_targets(targets, d: Optional[str], lineno: int):
        for t in targets:
            elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for e in elts:
                td = _dotted(e)
                if td is None:
                    continue
                if d:
                    tainted[td] = (
                        f"'{td}' (assigned under/from {d} on line "
                        f"{lineno})"
                    )
                else:
                    tainted.pop(td, None)

    def div(expr) -> Optional[str]:
        return _expr_divergence(
            aliases, expr, tainted, callee_of, ret_div, env_ok
        )

    def scan_expr_branches(stmt, div_ctx):
        if on_branch is None:
            return
        for node in ast.walk(stmt):
            if isinstance(node, (
                ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda
            )):
                continue
            if isinstance(node, ast.IfExp):
                d = div(node.test) or div_ctx
                if d:
                    on_branch(node, d, [node.body, node.orelse])

    def walk(body, div_ctx: Optional[str]):
        for node in body:
            if isinstance(node, (
                ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda
            )):
                continue
            if isinstance(node, ast.Assign):
                d = div(node.value) or div_ctx
                taint_targets(node.targets, d, node.lineno)
                scan_expr_branches(node, div_ctx)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                d = div(node.value) or div_ctx
                taint_targets([node.target], d, node.lineno)
            elif isinstance(node, ast.AugAssign):
                d = div(node.value) or div_ctx
                if d:  # += only adds taint, never clears it
                    taint_targets([node.target], d, node.lineno)
            elif isinstance(node, (ast.If, ast.While)):
                d = div(node.test)
                arms = list(node.body) + list(node.orelse)
                if d and on_branch is not None:
                    on_branch(node, d, arms)
                inner = d or div_ctx
                walk(node.body, inner)
                walk(node.orelse, inner)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                taint_targets([node.target], div(node.iter) or div_ctx,
                              node.lineno)
                walk(node.body, div_ctx)
                walk(node.orelse, div_ctx)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                walk(node.body, div_ctx)
            elif isinstance(node, ast.Try):
                walk(node.body, div_ctx)
                for h in node.handlers:
                    walk(h.body, div_ctx)
                walk(node.orelse, div_ctx)
                walk(node.finalbody, div_ctx)
            elif isinstance(node, ast.Return):
                d = (
                    div(node.value) if node.value is not None else None
                ) or div_ctx
                if d and ret_desc[0] is None:
                    ret_desc[0] = d
                scan_expr_branches(node, div_ctx)
            else:
                scan_expr_branches(node, div_ctx)

    walk(fnode.body, None)
    return ret_desc[0]


def _direct_collective(aliases, node: ast.AST) -> Optional[str]:
    """A call/with that IS a collective (every process must reach it)."""
    if isinstance(node, (ast.With, ast.AsyncWith)):
        for item in node.items:
            e = item.context_expr
            name = None
            if isinstance(e, ast.Call):
                f = e.func
                name = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None
                )
                if name == "Mesh":
                    return "Mesh(...) context entry"
                name = None
            d = _dotted(e)
            if d is not None:
                last = d.split(".")[-1]
                if last == "mesh" or last.endswith("_mesh"):
                    return f"'with {d}:' (mesh context entry)"
        return None
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, (ast.Name,
                                                            ast.Attribute)):
        base_d = _dotted(f.value) or ""
        base_last = base_d.split(".")[-1]
        base_t = aliases.get(base_last, base_last)
        if f.attr in _MULTIHOST_COLLECTIVES and (
            base_last == "multihost" or base_t.endswith("multihost")
        ):
            return f"multihost.{f.attr}()"
        if f.attr in _MULTIHOST_UTILS and base_last == "multihost_utils":
            return f"multihost_utils.{f.attr}()"
        if f.attr in _LAX_COLLECTIVES and (
            base_last == "lax" or base_d.endswith("lax")
        ):
            return f"lax.{f.attr}()"
    elif isinstance(f, ast.Name):
        t = aliases.get(f.id, "")
        head, _, last = t.rpartition(".")
        if last in _MULTIHOST_COLLECTIVES and head.endswith("multihost"):
            return f"multihost.{last}()"
        if last in _MULTIHOST_UTILS and head.endswith("multihost_utils"):
            return f"multihost_utils.{last}()"
    return None


def _all_indexed_functions(pctx: ProjectContext) -> Iterator[FunctionInfo]:
    for mod_name in sorted(pctx.project.modules):
        mod = pctx.project.modules[mod_name]
        for fi in mod.functions.values():
            yield fi
        for ci in mod.classes.values():
            yield from ci.methods.values()


def _divergence_state(pctx: ProjectContext):
    """(ret_div, direct_descs, reaches) memoized on the context.

    - ``ret_div``: qualname -> source description, for functions whose
      RETURN value is host-divergent (fixpoint over the call graph, so
      ``is_main()`` -> ``process_index() == 0`` propagates);
    - ``direct_descs``: qualname -> (collective description, line) for
      functions whose body contains a collective;
    - ``reaches``: every qualname that transitively calls one of them.
    """
    cached = getattr(pctx, "_spmd_divergence", None)
    if cached is not None:
        return cached
    graph = pctx.graph

    direct_descs: Dict[str, Tuple[str, int]] = {}
    for fi in _all_indexed_functions(pctx):
        aliases = _module_aliases(pctx, fi.path)
        for node in walk_excluding_nested(fi.node):
            d = _direct_collective(aliases, node)
            if d:
                direct_descs[fi.qualname] = (d, node.lineno)
                break
    reaches = graph.callers_closure(direct_descs)

    ret_div: Dict[str, str] = {}
    fns = list(_all_indexed_functions(pctx))
    for _ in range(8):
        changed = False
        for fi in fns:
            if fi.qualname in ret_div:
                continue
            found = _scan_divergence(
                _module_aliases(pctx, fi.path),
                fi.node,
                graph.callees_by_node(fi.qualname),
                ret_div,
                _env_exempt(fi.path, fi.name),
            )
            if found:
                ret_div[fi.qualname] = found
                changed = True
        if not changed:
            break

    cached = (ret_div, direct_descs, reaches)
    pctx._spmd_divergence = cached
    return cached


def _collective_in_body(
    pctx: ProjectContext,
    aliases,
    stmts,
    callee_of: Dict[int, str],
    direct_descs: Dict[str, Tuple[str, int]],
    reaches: Set[str],
) -> Optional[str]:
    """Description of the first collective the branch body reaches —
    directly, or through resolved call edges (via-chain named)."""
    graph = pctx.graph
    for node in walk_excluding_nested(list(stmts)):
        d = _direct_collective(aliases, node)
        if d:
            return d
        if isinstance(node, ast.Call):
            q = callee_of.get(id(node))
            if q is not None and q in reaches:
                # shortest chain q -> some direct-collective function
                chain = _chain_to_collective(graph, q, direct_descs)
                if chain:
                    via = " -> ".join(_short(c) + "()" for c in chain)
                    desc = direct_descs[chain[-1]][0]
                    return f"{desc} via {via}"
    return None


def _chain_to_collective(
    graph, start: str, direct_descs: Dict[str, Tuple[str, int]]
) -> Optional[List[str]]:
    if start in direct_descs:
        return [start]
    pred: Dict[str, Optional[str]] = {start: None}
    work: collections.deque = collections.deque([start])
    while work:
        cur = work.popleft()
        for nxt in sorted(graph.edges.get(cur, ())):
            if nxt in pred:
                continue
            pred[nxt] = cur
            if nxt in direct_descs:
                chain = [nxt]
                back = cur
                while back is not None:
                    chain.append(back)
                    back = pred[back]
                chain.reverse()
                return chain[:4] + ([chain[-1]] if len(chain) > 4 else [])
            work.append(nxt)
    return None


@project_rule(
    "host-divergence-collective", SEVERITY_ERROR,
    "a branch on host-local state (time.*, runtime os.environ, random, "
    "process_index(), queue state) guards a collective without going "
    "through multihost.main_decides — processes can take different "
    "branches and the straggling collective deadlocks the pod",
)
def check_host_divergence(pctx: ProjectContext):
    graph = pctx.graph
    ret_div, direct_descs, reaches = _divergence_state(pctx)
    for fi in _all_indexed_functions(pctx):
        aliases = _module_aliases(pctx, fi.path)
        callee_of = graph.callees_by_node(fi.qualname)
        findings: List[Tuple[int, str]] = []

        def on_branch(node, d, arms, _fi=fi, _aliases=aliases,
                      _callee_of=callee_of):
            c = _collective_in_body(
                pctx, _aliases, arms, _callee_of, direct_descs, reaches
            )
            if c:
                findings.append((
                    node.lineno,
                    f"branch in {_fi.name}() depends on host-local {d} "
                    f"but guards collective {c} — processes can take "
                    "different branches and the pod deadlocks at the "
                    "straggling collective; route the decision through "
                    "multihost.main_decides() (process 0 decides for "
                    "everyone) or annotate '# arealint: ok(<reason>)'",
                ))

        _scan_divergence(
            aliases, fi.node, callee_of, ret_div,
            _env_exempt(fi.path, fi.name), on_branch=on_branch,
        )
        for line, msg in findings:
            yield (fi.path, line, msg)
