"""SARIF 2.1.0 output: the interchange format CI annotators (GitHub code
scanning, VS Code SARIF viewers) ingest. One run, one result per
finding; rule metadata comes from the live registry so the catalog in
the report always matches the code.

Determinism contract (golden-file tested): findings are emitted in the
order given (the driver sorts by path/line/rule), rules sorted by id,
paths repo-relative posix — so the same tree produces byte-identical
SARIF everywhere.
"""

import json
from typing import Iterable, List, Optional

from tools.arealint.baseline import norm_path
from tools.arealint.core import Finding, SEVERITY_ERROR, all_rules

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemas/sarif-schema-2.1.0.json"
)
TOOL_NAME = "arealint"


def _level(severity: str) -> str:
    return "error" if severity == SEVERITY_ERROR else "warning"


def to_sarif(
    findings: Iterable[Finding],
    root=None,
    rule_ids: Optional[List[str]] = None,
) -> dict:
    """The SARIF log object for ``findings``. ``rule_ids`` limits the
    reported rule catalog (default: every registered rule)."""
    catalog = all_rules()
    ids = sorted(rule_ids) if rule_ids is not None else sorted(catalog)
    rules = [
        {
            "id": rid,
            "shortDescription": {"text": catalog[rid].doc},
            "defaultConfiguration": {
                "level": _level(catalog[rid].severity)
            },
        }
        for rid in ids
        if rid in catalog
    ]
    results = [
        {
            "ruleId": f.rule,
            "level": _level(f.severity),
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": norm_path(f.path, root),
                            "uriBaseId": "SRCROOT",
                        },
                        "region": {"startLine": max(f.line, 1)},
                    }
                }
            ],
        }
        for f in findings
    ]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": TOOL_NAME,
                        "informationUri": "docs/static_analysis.md",
                        "rules": rules,
                    }
                },
                "results": results,
            }
        ],
    }


def dumps(findings: Iterable[Finding], root=None, **kw) -> str:
    return json.dumps(to_sarif(findings, root=root, **kw), indent=2)
