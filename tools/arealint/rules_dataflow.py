"""Whole-program JAX dataflow rules: the cross-module extensions of the
intra-file host-sync and donation rules, plus a jit-boundary weak-type
drift check.

- ``host-sync-cross-module`` — the v1 host-sync rule could only follow
  same-file calls; a ``.item()`` two modules away from the
  ``# arealint: hot`` root in ``train/engine.py`` was invisible
  (docs/static_analysis.md:55 in v1). This rule walks the project call
  graph from every hot root (jitted, ``# arealint: hot``) and flags sync
  matches in functions the INTRA-file rule cannot reach — each defect is
  reported by exactly one of the two rules.
- ``donation-cross-call`` — donation-after-use across call boundaries,
  both directions: (a) a helper donates its own parameter to a jitted
  call, so the CALLER's variable is invalidated by the call and any read
  after it observes an aliased buffer; (b) a value is handed to a helper
  that STORES it (``self.x = p`` / ``container.append(p)``) and later
  donated by the caller — the stored alias outlives the donation.
- ``jit-weak-type-drift`` — call sites of one jitted function disagree
  on whether an operand is a Python scalar literal: literals trace
  weak-typed, so the callable silently compiles a second trace and the
  two sites can produce different result dtypes.

All resolution degrades: an edge the index cannot follow produces no
finding (docs/static_analysis.md "Call-graph semantics").
"""

import ast
import collections
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.arealint.core import (
    ProjectContext, SEVERITY_ERROR, SEVERITY_WARN,
    project_rule, walk_excluding_nested,
)
from tools.arealint.project import FunctionInfo, _dotted
from tools.arealint.rules_jax import (
    _donated_positions, _has_jit_decorator, _is_jit_call, _sync_match,
    file_hot_roots, intra_hot_reachable,
)


def _project_hot_roots(pctx: ProjectContext) -> List[str]:
    """Qualnames of indexed functions that are hot roots — delegates the
    detection to :func:`rules_jax.file_hot_roots` so the intra-file and
    cross-module rules can never disagree about what a root is."""
    roots: List[str] = []
    for mod in pctx.project.modules.values():
        ctx = pctx.file_ctx(mod.path)
        if ctx is None:
            continue
        hot_ids = {id(n) for n in file_hot_roots(ctx)}
        for fi in _indexed_functions(mod):
            if id(fi.node) in hot_ids:
                roots.append(fi.qualname)
    return sorted(roots)


def _indexed_functions(mod) -> Iterator[FunctionInfo]:
    yield from mod.functions.values()
    for ci in mod.classes.values():
        yield from ci.methods.values()


# --------------------------------------------------------------------- #
# host-sync-cross-module
# --------------------------------------------------------------------- #


@project_rule(
    "host-sync-cross-module", SEVERITY_ERROR,
    "host<->device sync in a function reachable from a jitted or "
    "'# arealint: hot' root through the CROSS-MODULE call graph — "
    "invisible to the intra-file rule, same pipeline stall",
)
def check_host_sync_cross_module(pctx: ProjectContext):
    graph = pctx.graph
    roots = _project_hot_roots(pctx)
    if not roots:
        return
    # FIFO BFS over SORTED edges with predecessor tracking: the chain in
    # the message is a shortest one with lexicographic tie-breaks, so the
    # attribution (and the SARIF byte-identity contract) is deterministic
    # even when a sync is reachable from several roots/callers
    pred: Dict[str, Tuple[Optional[str], str]] = {}
    work: collections.deque = collections.deque()
    for r in roots:
        if r not in pred:
            pred[r] = (None, r)
            work.append(r)
    while work:
        cur = work.popleft()
        root = pred[cur][1]
        for nxt in sorted(graph.edges.get(cur, ())):
            if nxt not in pred:
                pred[nxt] = (cur, root)
                work.append(nxt)

    intra_cache: Dict[str, Set[int]] = {}

    def intra_ids(path: str) -> Set[int]:
        got = intra_cache.get(path)
        if got is None:
            ctx = pctx.file_ctx(path)
            got = (
                {id(n) for n in intra_hot_reachable(ctx)}
                if ctx is not None else set()
            )
            intra_cache[path] = got
        return got

    for q in sorted(pred):
        fi = graph.function(q)
        if fi is None:
            continue
        if id(fi.node) in intra_ids(fi.path):
            continue  # the intra-file rule already owns this function
        caller, root = pred[q]
        via = f" via {_short(caller)}()" if caller and caller != root else ""
        for node in walk_excluding_nested(fi.node):
            m = _sync_match(node)
            if m:
                yield (
                    fi.path, node.lineno,
                    f"{m} in {fi.name}() forces a host<->device sync on a "
                    f"hot path — reachable from hot root {_short(root)}()"
                    f"{via} through the project call graph; move it off "
                    "the step path or annotate a deliberate sync with "
                    "'# arealint: ok(<reason>)'",
                )


def _short(qualname: Optional[str]) -> str:
    if not qualname:
        return "?"
    parts = qualname.split(".")
    return ".".join(parts[-2:]) if len(parts) > 1 else qualname


# --------------------------------------------------------------------- #
# donation-cross-call
# --------------------------------------------------------------------- #


def _param_names(fnode) -> List[str]:
    args = fnode.args
    return [
        a.arg
        for a in list(getattr(args, "posonlyargs", [])) + list(args.args)
    ]


def _donating_calls(fnode) -> Iterator[Tuple[ast.Call, Tuple[int, ...]]]:
    """(call node, donated positions) for every donating jitted call in
    the function's own body: direct ``jax.jit(f, donate_argnums=..)(x)``
    and calls through a locally-bound donated callable."""
    donors: Dict[str, Tuple[int, ...]] = {}
    for node in walk_excluding_nested(fnode):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and _is_jit_call(node.value)
        ):
            pos = _donated_positions(node.value)
            if pos:
                donors[node.targets[0].id] = pos
    for node in walk_excluding_nested(fnode):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Name) and node.func.id in donors:
            yield node, donors[node.func.id]
        elif _is_jit_call(node.func):
            pos = _donated_positions(node.func)
            if pos:
                yield node, pos


def _donated_param_positions(fi: FunctionInfo) -> Dict[int, str]:
    """{caller-arg position: param name} for parameters of ``fi`` that
    its body donates to a jitted call while still bound to the CALLER's
    buffer — a param rebound before the donating call (``x = x * 2``)
    donates the new buffer, not the caller's, and is excluded. Positions
    are as the caller sees them (``self``/``cls`` stripped for methods).
    """
    params = _param_names(fi.node)
    offset = 1 if fi.class_name is not None and params[:1] in (
        ["self"], ["cls"]
    ) else 0
    stores: Dict[str, List[Tuple[int, int]]] = {}
    for node in walk_excluding_nested(fi.node):
        if (
            isinstance(node, ast.Name)
            and isinstance(node.ctx, (ast.Store, ast.Del))
            and node.id in params
        ):
            stores.setdefault(node.id, []).append(
                (node.lineno, node.col_offset)
            )
    out: Dict[int, str] = {}
    for call, positions in _donating_calls(fi.node):
        call_pos = (call.lineno, call.col_offset)
        for p in positions:
            if p >= len(call.args):
                continue
            a = call.args[p]
            if isinstance(a, ast.Name) and a.id in params:
                if any(s < call_pos for s in stores.get(a.id, ())):
                    continue  # rebound before the donation
                idx = params.index(a.id) - offset
                if idx >= 0:
                    out[idx] = a.id
    return out


def _loads_after(
    fnode, dotted: str, after: Tuple[int, int]
) -> Optional[int]:
    """First Load line of ``dotted`` after position ``after`` in the
    function's own body; None if it is stored first (rebound) or never
    read."""
    events: List[Tuple[Tuple[int, int], str]] = []
    for node in walk_excluding_nested(fnode):
        if isinstance(node, (ast.Name, ast.Attribute)):
            if _dotted(node) == dotted:
                kind = "load" if isinstance(node.ctx, ast.Load) else "store"
                events.append(((node.lineno, node.col_offset), kind))
    events.sort()
    for pos, kind in events:
        if pos <= after:
            continue
        return pos[0] if kind == "load" else None
    return None


def _stored_param_positions(fi: FunctionInfo) -> Dict[int, int]:
    """{caller-arg position: store line} for parameters the function
    body STORES (assigns to an attribute/subscript, or appends/adds to a
    container) — the alias outlives the call."""
    params = _param_names(fi.node)
    offset = 1 if fi.class_name is not None and params[:1] in (
        ["self"], ["cls"]
    ) else 0
    stored: Dict[int, int] = {}

    def record(name: str, line: int):
        if name in params:
            idx = params.index(name) - offset
            if idx >= 0:
                stored.setdefault(idx, line)

    for node in walk_excluding_nested(fi.node):
        if isinstance(node, ast.Assign):
            escapes = any(
                isinstance(t, (ast.Attribute, ast.Subscript))
                for t in node.targets
            )
            if escapes and isinstance(node.value, ast.Name):
                record(node.value.id, node.lineno)
        elif isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in ("append", "add", "appendleft", "setdefault")
            ):
                for a in node.args:
                    if isinstance(a, ast.Name):
                        record(a.id, node.lineno)
    return stored


def _rebound_at_call(
    pctx: ProjectContext, caller: FunctionInfo, call: ast.Call
) -> Set[str]:
    """Names rebound by the assignment the call sits in
    (``x, y = helper(x, y)``): they hold the NEW buffer afterwards."""
    ctx = pctx.file_ctx(caller.path)
    if ctx is None:
        return set()
    parent = ctx.parents().get(call)
    out: Set[str] = set()
    if isinstance(parent, ast.Assign):
        for t in parent.targets:
            elts = t.elts if isinstance(t, ast.Tuple) else [t]
            for e in elts:
                d = _dotted(e)
                if d:
                    out.add(d)
    return out


def _arg_at(call: ast.Call, pos: int, param: str) -> Optional[ast.expr]:
    if pos < len(call.args):
        return call.args[pos]
    for kw in call.keywords:
        if kw.arg == param:
            return kw.value
    return None


@project_rule(
    "donation-cross-call", SEVERITY_ERROR,
    "donation-after-use across a call boundary: a helper donates its "
    "parameter (caller's variable read after the call observes an aliased "
    "buffer), or a donated value was stored by a helper beforehand (the "
    "stored alias survives donation) — fails only on hardware",
)
def check_donation_cross_call(pctx: ProjectContext):
    graph = pctx.graph
    # (a) helper donates its own parameter; caller reads the arg after
    for q in sorted(graph.sites_by_callee):
        fi = graph.function(q)
        if fi is None:
            continue
        donated = _donated_param_positions(fi)
        if not donated:
            continue
        for site in graph.sites_by_callee[q]:
            caller = graph.function(site.caller)
            if caller is None:
                continue
            rebound = _rebound_at_call(pctx, caller, site.node)
            for pos, pname in sorted(donated.items()):
                arg = _arg_at(site.node, pos, pname)
                if arg is None:
                    continue
                d = _dotted(arg)
                if d is None or d in ("self", "cls") or d in rebound:
                    continue
                end = (
                    getattr(site.node, "end_lineno", site.node.lineno),
                    getattr(site.node, "end_col_offset",
                            site.node.col_offset),
                )
                read = _loads_after(caller.node, d, end)
                if read is not None:
                    yield (
                        caller.path, read,
                        f"{d!r} is read here, but {fi.name}() (called on "
                        f"line {site.line}) donates that parameter "
                        f"({pname!r}) to a jitted call — the buffer may "
                        "already be aliased in place; rebind from the "
                        "helper's result or copy before the call",
                    )
    # (b) caller passes a value to a storing helper, then donates it
    for caller_q in sorted(graph.sites_by_caller):
        caller = graph.function(caller_q)
        if caller is None:
            continue
        donations: List[Tuple[str, int]] = []
        for call, positions in _donating_calls(caller.node):
            for p in positions:
                if p < len(call.args):
                    d = _dotted(call.args[p])
                    if d:
                        donations.append((d, call.lineno))
        if not donations:
            continue
        stored_cache: Dict[str, Dict[int, int]] = {}
        for site in graph.sites_by_caller[caller_q]:
            callee = graph.function(site.callee)
            if callee is None:
                continue
            stored = stored_cache.get(site.callee)
            if stored is None:
                stored = _stored_param_positions(callee)
                stored_cache[site.callee] = stored
            if not stored:
                continue
            params = _param_names(callee.node)
            offset = 1 if callee.class_name is not None and params[:1] in (
                ["self"], ["cls"]
            ) else 0
            for pos, store_line in sorted(stored.items()):
                pname = (
                    params[pos + offset]
                    if pos + offset < len(params) else ""
                )
                arg = _arg_at(site.node, pos, pname)
                if arg is None:
                    continue
                d = _dotted(arg)
                if d is None:
                    continue
                for donated, don_line in donations:
                    if donated == d and site.line <= don_line:
                        yield (
                            caller.path, don_line,
                            f"{d!r} is donated here, but "
                            f"{callee.name}() (called on line "
                            f"{site.line}) stored it (line {store_line} "
                            f"of {callee.path}) — the stored alias "
                            "survives donation and reads garbage on "
                            "hardware; copy before storing or don't "
                            "donate this operand",
                        )


# --------------------------------------------------------------------- #
# jit-weak-type-drift
# --------------------------------------------------------------------- #


def _lit_kind(expr: ast.expr) -> str:
    if isinstance(expr, ast.Constant) and not isinstance(expr.value, bool):
        if isinstance(expr.value, int):
            return "int literal"
        if isinstance(expr.value, float):
            return "float literal"
    if isinstance(expr, ast.UnaryOp) and isinstance(
        expr.op, (ast.USub, ast.UAdd)
    ):
        inner = _lit_kind(expr.operand)
        if inner != "other":
            return inner
    return "other"


@project_rule(
    "jit-weak-type-drift", SEVERITY_WARN,
    "call sites of one jitted function disagree on whether an operand is "
    "a Python scalar literal — weak-typed literals compile a second trace "
    "and can drift the result dtype between sites",
)
def check_weak_type_drift(pctx: ProjectContext):
    graph = pctx.graph
    for q in sorted(graph.sites_by_callee):
        fi = graph.function(q)
        if fi is None or not _has_jit_decorator(fi.node):
            continue
        sites = graph.sites_by_callee[q]
        if len(sites) < 2:
            continue
        max_args = max(len(s.node.args) for s in sites)
        for pos in range(max_args):
            kinds: Dict[str, List] = {}
            for s in sites:
                if pos < len(s.node.args):
                    kinds.setdefault(
                        _lit_kind(s.node.args[pos]), []
                    ).append(s)
            if len(kinds) < 2:
                continue
            other = kinds.get("other", [])
            for kind, lit_sites in sorted(kinds.items()):
                if kind == "other":
                    continue
                vs = (
                    f"a non-literal at {other[0].path}:{other[0].line}"
                    if other else
                    "a different literal kind at another site"
                )
                article = "an" if kind.startswith("int") else "a"
                for s in lit_sites:
                    yield (
                        s.path, s.line,
                        f"jitted {fi.name}() receives {article} {kind} at "
                        f"position {pos} here but {vs} — the weak-typed "
                        "scalar traces separately and the result dtype "
                        "can drift between call sites; pass "
                        "jnp.asarray(x, dtype) consistently",
                    )
