"""Cross-module, name-qualified call graph over a :class:`Project`.

For every indexed function the builder resolves its call expressions to
canonical qualified names using the module's import table plus a small,
deliberately conservative local type pass:

- ``f(...)``                  -> module function / import target
- ``self.m(...)``/``cls.m``   -> method of the enclosing class (bases
  followed when resolvable in the index)
- ``mod.f(...)``, ``pkg.mod.Class.m(...)`` -> dotted walk through imports
- ``Class(...)``              -> ``Class.__init__`` when indexed
- ``x.m(...)`` where ``x`` was assigned ``Class(...)`` in the same
  function, or is a parameter annotated ``x: Class`` -> ``Class.m``
- ``self.attr.m(...)`` where the class assigns
  ``self.attr = Class(...)`` anywhere -> ``Class.m``

Anything else (external callables, dynamic dispatch, star imports)
lands in ``unresolved`` — downstream rules treat an unresolved edge as
"no edge", never as a finding (docs/static_analysis.md).

Thread/async entry discovery also lives here because both need the same
resolution machinery: ``threading.Thread(target=...)`` targets (and
``executor.submit(fn, ...)``-style escapes are NOT included — only real
thread spawns) seed the thread-context closure used by
:mod:`tools.arealint.rules_concurrency`.
"""

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Set

from tools.arealint.core import walk_excluding_nested
from tools.arealint.project import (
    FunctionInfo, ModuleInfo, Project, _dotted,
)


@dataclasses.dataclass
class CallSite:
    caller: str              # canonical qualname of the calling function
    callee: str              # canonical qualname of the resolved target
    node: ast.Call
    path: str
    line: int


class CallGraph:
    def __init__(self, project: Project):
        self.project = project
        self.edges: Dict[str, Set[str]] = {}           # caller -> callees
        self.redges: Dict[str, Set[str]] = {}          # callee -> callers
        self.sites: List[CallSite] = []
        self.sites_by_caller: Dict[str, List[CallSite]] = {}
        self.sites_by_callee: Dict[str, List[CallSite]] = {}
        # calls that could not be resolved, per caller (bookkeeping only)
        self.unresolved: Dict[str, Set[str]] = {}
        # thread entry points: functions handed to threading.Thread(target=)
        self.thread_entries: Set[str] = set()
        # synthesized nodes for local-def thread targets
        # ("caller.<local>.name" -> FunctionInfo)
        self.local_functions: Dict[str, FunctionInfo] = {}
        # memoized per-caller {id(Call node): callee} maps
        self._callees_by_node: Dict[str, Dict[int, str]] = {}

    def function(self, qualname: str) -> Optional[FunctionInfo]:
        """FunctionInfo for any graph node, including synthesized
        local-def thread targets."""
        return self.local_functions.get(qualname) or self.project.function(
            qualname
        )

    def callees_by_node(self, caller: str) -> Dict[int, str]:
        """``id(call AST node) -> resolved callee qualname`` for one
        caller — the lookup rules doing their own AST walk over a
        function body need to map the Call nodes they encounter back to
        graph edges (memoized; rules_spmd's taint and branch passes hit
        this for every analyzed function)."""
        got = self._callees_by_node.get(caller)
        if got is None:
            got = {
                id(s.node): s.callee
                for s in self.sites_by_caller.get(caller, ())
            }
            self._callees_by_node[caller] = got
        return got

    # ----------------------------------------------------------------- #

    def add_edge(self, caller: str, callee: str, node: ast.Call, path: str):
        self.edges.setdefault(caller, set()).add(callee)
        self.redges.setdefault(callee, set()).add(caller)
        site = CallSite(caller, callee, node, path, node.lineno)
        self.sites.append(site)
        self.sites_by_caller.setdefault(caller, []).append(site)
        self.sites_by_callee.setdefault(callee, []).append(site)

    def reachable(self, roots: Iterable[str]) -> Set[str]:
        """Transitive closure over resolved edges, roots included."""
        seen: Set[str] = set()
        work = [r for r in roots]
        while work:
            cur = work.pop()
            if cur in seen:
                continue
            seen.add(cur)
            work.extend(self.edges.get(cur, ()))
        return seen

    def callers_closure(self, targets: Iterable[str]) -> Set[str]:
        """Everything that (transitively) calls one of ``targets``."""
        seen: Set[str] = set()
        work = [t for t in targets]
        while work:
            cur = work.pop()
            if cur in seen:
                continue
            seen.add(cur)
            work.extend(self.redges.get(cur, ()))
        return seen


# --------------------------------------------------------------------- #
# builder
# --------------------------------------------------------------------- #


def build_call_graph(project: Project) -> CallGraph:
    graph = CallGraph(project)
    for mod in project.modules.values():
        _scan_module(project, graph, mod)
    return graph


def _scan_module(project: Project, graph: CallGraph, mod: ModuleInfo):
    # class attribute types: "Class.attr" -> resolved class qualname, from
    # ``self.attr = Ctor(...)`` assignments anywhere in the class
    attr_types: Dict[str, str] = {}
    for ci in mod.classes.values():
        for fi in ci.methods.values():
            for node in ast.walk(fi.node):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Attribute)
                    and isinstance(node.targets[0].value, ast.Name)
                    and node.targets[0].value.id == "self"
                    and isinstance(node.value, ast.Call)
                ):
                    ctor = _resolve_ctor(project, mod, node.value)
                    if ctor:
                        attr_types[f"{ci.name}.{node.targets[0].attr}"] = ctor

    for fi in _functions_of(mod):
        _scan_function(project, graph, mod, fi, attr_types)
        _scan_thread_targets(project, graph, mod, fi)


def _functions_of(mod: ModuleInfo) -> Iterable[FunctionInfo]:
    yield from mod.functions.values()
    for ci in mod.classes.values():
        yield from ci.methods.values()


def _resolve_ctor(
    project: Project, mod: ModuleInfo, call: ast.Call
) -> Optional[str]:
    """``Ctor(...)`` -> class qualname when the ctor resolves to an
    indexed class."""
    d = _dotted(call.func)
    if not d:
        return None
    target = project.resolve_in_module(mod, d)
    if target and project.class_info(target) is not None:
        return target
    return None


def _local_types(
    project: Project, mod: ModuleInfo, fi: FunctionInfo
) -> Dict[str, str]:
    """Conservative local var -> class qualname map: ``x = Class(...)``
    assignments plus ``x: Class`` parameter annotations."""
    types: Dict[str, str] = {}
    args = fi.node.args
    for a in list(args.args) + list(args.kwonlyargs) + list(
        getattr(args, "posonlyargs", [])
    ):
        if a.annotation is not None:
            d = _dotted(a.annotation)
            if d:
                target = project.resolve_in_module(mod, d)
                if target and project.class_info(target) is not None:
                    types[a.arg] = target
    for node in ast.walk(fi.node):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Call)
        ):
            ctor = _resolve_ctor(project, mod, node.value)
            if ctor:
                types[node.targets[0].id] = ctor
            else:
                # reassigned to something unresolvable: drop the binding
                types.pop(node.targets[0].id, None)
    return types


def _resolve_call(
    project: Project,
    mod: ModuleInfo,
    fi: FunctionInfo,
    call: ast.Call,
    attr_types: Dict[str, str],
    local_types: Dict[str, str],
) -> Optional[str]:
    """Canonical callee qualname, or None (degrade to no edge)."""
    f = call.func
    # self.m(...) / cls.m(...)
    if (
        isinstance(f, ast.Attribute)
        and isinstance(f.value, ast.Name)
        and f.value.id in ("self", "cls")
        and fi.class_name is not None
    ):
        ci = mod.classes.get(fi.class_name)
        if ci is not None:
            m = project._method(ci, f.attr)
            if m is not None:
                return m.qualname
        return None
    # self.attr.m(...) via recorded attribute types
    if (
        isinstance(f, ast.Attribute)
        and isinstance(f.value, ast.Attribute)
        and isinstance(f.value.value, ast.Name)
        and f.value.value.id == "self"
        and fi.class_name is not None
    ):
        cls_q = attr_types.get(f"{fi.class_name}.{f.value.attr}")
        if cls_q:
            ci = project.class_info(cls_q)
            if ci is not None:
                m = project._method(ci, f.attr)
                if m is not None:
                    return m.qualname
        return None
    # x.m(...) via local type bindings
    if (
        isinstance(f, ast.Attribute)
        and isinstance(f.value, ast.Name)
        and f.value.id in local_types
    ):
        ci = project.class_info(local_types[f.value.id])
        if ci is not None:
            m = project._method(ci, f.attr)
            if m is not None:
                return m.qualname
        return None
    d = _dotted(f)
    if not d:
        return None
    target = project.resolve_in_module(mod, d)
    if target is None:
        return None
    ci = project.class_info(target)
    if ci is not None:
        # instantiation -> __init__ when defined (else the class itself
        # is recorded so reachability still crosses the ctor)
        m = project._method(ci, "__init__")
        return m.qualname if m is not None else target
    if project.function(target) is not None:
        return target
    return None


def _scan_function(
    project: Project,
    graph: CallGraph,
    mod: ModuleInfo,
    fi: FunctionInfo,
    attr_types: Dict[str, str],
):
    local_types = _local_types(project, mod, fi)
    for node in walk_excluding_nested(fi.node):
        if not isinstance(node, ast.Call):
            continue
        callee = _resolve_call(
            project, mod, fi, node, attr_types, local_types
        )
        if callee is not None:
            graph.add_edge(fi.qualname, callee, node, mod.path)
        else:
            d = _dotted(node.func)
            if d:
                graph.unresolved.setdefault(fi.qualname, set()).add(d)


# --------------------------------------------------------------------- #
# thread targets
# --------------------------------------------------------------------- #


def _is_thread_ctor(mod: ModuleInfo, call: ast.Call) -> bool:
    """``threading.Thread(...)`` / ``Thread(...)`` (from-import)."""
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "Thread":
        return isinstance(f.value, ast.Name) and f.value.id == "threading"
    if isinstance(f, ast.Name) and f.id == "Thread":
        return mod.imports.get("Thread", "").endswith("threading.Thread")
    return False


def _scan_thread_targets(
    project: Project, graph: CallGraph, mod: ModuleInfo, fi: FunctionInfo
):
    """Record ``threading.Thread(target=X)`` targets as thread entries
    (including local ``def`` targets, resolved by name against the
    enclosing function's OWN nested defs)."""
    nested = {
        n.name: n for n in ast.walk(fi.node)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and n is not fi.node
    }
    for node in ast.walk(fi.node):
        if not (isinstance(node, ast.Call) and _is_thread_ctor(mod, node)):
            continue
        target = next(
            (kw.value for kw in node.keywords if kw.arg == "target"), None
        )
        if target is None:
            continue
        # self._method
        if (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
            and fi.class_name is not None
        ):
            ci = mod.classes.get(fi.class_name)
            m = project._method(ci, target.attr) if ci else None
            if m is not None:
                graph.thread_entries.add(m.qualname)
            continue
        d = _dotted(target)
        if d is None:
            continue
        if d in nested:
            # local def target: synthesize a node id and wire its calls
            q = f"{fi.qualname}.<local>.{d}"
            graph.thread_entries.add(q)
            local_fi = FunctionInfo(
                qualname=q, module=mod.name, name=d,
                class_name=fi.class_name, node=nested[d], path=mod.path,
            )
            graph.local_functions[q] = local_fi
            _scan_function(project, graph, mod, local_fi, {})
            continue
        resolved = project.resolve_in_module(mod, d)
        if resolved is not None and project.function(resolved) is not None:
            graph.thread_entries.add(resolved)


def thread_context(graph: CallGraph) -> Set[str]:
    """Qualnames executing on a spawned thread: the reachability closure
    from every ``Thread(target=...)`` entry. Functions that START their
    own event loop (``asyncio.run``) re-enter async context and are NOT
    excluded here — the concurrency rules handle that distinction."""
    return graph.reachable(graph.thread_entries)
