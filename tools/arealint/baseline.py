"""Checked-in baseline: lets a NEW rule land enforced without first fixing
(or arguing about) every legacy finding.

``tools/arealint_baseline.json``::

    {
      "version": 1,
      "entries": [
        {"rule": "env-knob", "path": "areal_tpu/x/y.py",
         "reason": "why this legacy finding is tolerated", "max": 2}
      ]
    }

Semantics:

- An entry suppresses up to ``max`` findings (default 1) of ``rule`` in
  ``path`` (repo-relative, posix separators). The ``reason`` is REQUIRED.
- An entry that matches zero findings is **stale**: the violation was
  fixed, so the entry must be deleted. Stale entries are reported by the
  CLI (and surfaced in ``--format json`` under ``stale_baseline``) —
  baselines only ever shrink.
- Baselining is for legacy findings at rule-introduction time. New code
  uses an inline ``# arealint: ok(<reason>)`` (visible at the call site)
  or gets fixed.
"""

import json
import pathlib
from typing import Iterable, List, Optional, Tuple

from tools.arealint.core import Finding

DEFAULT_BASELINE = "tools/arealint_baseline.json"


class BaselineError(ValueError):
    """Malformed baseline file (CLI exit 2 — a usage error, not a lint
    failure)."""


def load_baseline(path) -> List[dict]:
    path = pathlib.Path(path)
    try:
        data = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as e:
        raise BaselineError(f"cannot read baseline {path}: {e}") from e
    if not isinstance(data, dict) or not isinstance(
        data.get("entries"), list
    ):
        raise BaselineError(
            f"baseline {path} must be an object with an 'entries' list"
        )
    entries = data["entries"]
    for i, e in enumerate(entries):
        if not isinstance(e, dict):
            raise BaselineError(f"baseline entry #{i} is not an object")
        for key in ("rule", "path", "reason"):
            if not isinstance(e.get(key), str) or not e[key].strip():
                raise BaselineError(
                    f"baseline entry #{i} needs a non-empty {key!r} "
                    "(every baselined finding records WHY it is tolerated)"
                )
        if "max" in e and (not isinstance(e["max"], int) or e["max"] < 1):
            raise BaselineError(
                f"baseline entry #{i}: 'max' must be a positive int"
            )
    return entries


def norm_path(path: str, root: Optional[pathlib.Path] = None) -> str:
    """Repo-relative posix path when under ``root``; unchanged otherwise."""
    p = pathlib.PurePosixPath(str(path).replace("\\", "/"))
    if root is not None:
        rootp = str(root).replace("\\", "/").rstrip("/") + "/"
        s = str(p)
        if s.startswith(rootp):
            return s[len(rootp):]
    return str(p)


def apply_baseline(
    findings: Iterable[Finding],
    entries: List[dict],
    root: Optional[pathlib.Path] = None,
) -> Tuple[List[Finding], List[dict]]:
    """Returns ``(remaining_findings, stale_entries)``."""
    used = [0] * len(entries)
    remaining: List[Finding] = []
    for f in findings:
        fpath = norm_path(f.path, root)
        matched = False
        for i, e in enumerate(entries):
            if (
                e["rule"] == f.rule
                and norm_path(e["path"]) == fpath
                and used[i] < e.get("max", 1)
            ):
                used[i] += 1
                matched = True
                break
        if not matched:
            remaining.append(f)
    stale = [e for i, e in enumerate(entries) if used[i] == 0]
    return remaining, stale
