"""The resource catalog: paired acquire/release operations the lifecycle
rules (``rules_lifecycle.py``) check typestate against, parsed from the
runtime modules with ``ast`` — never imported.

Same provenance contract as the counter/fault/mesh catalogs
(docs/static_analysis.md "Lifecycle rules"): the linter runs in a bare CI
container with no jax/aiohttp, so the single source of truth for each
acquire/release pair is read statically from the module that defines it.
A declared ``(class, method)`` pair the module no longer contains drops
the whole spec (every rule over that resource degrades to no-finding
rather than guessing), and ``tests/test_arealint_lifecycle.py`` pins the
parsed pairs against the runtime modules so catalog drift fails loudly.

Resource kinds:

- ``handle`` — the acquire yields (or takes, for ``handle_from_arg``
  ops like ``PagePool.ref``) a trackable value the release consumes:
  KV pages, a rank lease, an aiohttp session. Obligations follow the
  bound NAME; attribute-bound handles (``self._session = ...``) hand
  ownership to the object and degrade (cross-method protocols are out
  of scope — the ``owns`` annotation documents them instead).
- ``charge`` — a counted grant keyed to the acquiring object, with no
  handle: a ``TokenBucket`` charge, a WFQ queue entry, an engine slot
  grant, a manager rollout slot. The release is any matching release
  op (or a callee that transitively performs one).
- ``context`` — must be entered via ``with``/``async with``
  (``tracing.span``): a bare call opens nothing and leaks the close.
"""

import ast
import dataclasses
import pathlib
from typing import Dict, Iterable, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class ResourceSpec:
    """One acquire/release protocol the typestate pass tracks."""

    name: str                 # "gen.kv-pages" — the owns()/finding label
    kind: str                 # "handle" | "charge" | "context"
    # repo-relative defining module ("" for external specs)
    module: str = ""
    # (ClassName, method) pairs; receiver must TYPE-resolve to ClassName
    acquires: Tuple[Tuple[str, str], ...] = ()
    releases: Tuple[Tuple[str, str], ...] = ()
    # release methods called ON the handle itself (session.close())
    release_on_handle: Tuple[str, ...] = ()
    # dotted qualnames acquired by plain call (module functions /
    # external ctors); matched by resolved-qualname suffix
    func_acquires: Tuple[str, ...] = ()
    # acquire methods whose handle is the FIRST ARGUMENT (PagePool.ref)
    handle_from_arg: Tuple[str, ...] = ()
    # acquire methods whose handle is the RECEIVER (RankLease.start)
    handle_is_receiver: Tuple[str, ...] = ()
    external: bool = False    # not tree-parsed (aiohttp) — no provenance
    doc: str = ""

    def acquire_methods(self) -> frozenset:
        return frozenset(m for _, m in self.acquires)

    def release_methods(self) -> frozenset:
        return frozenset(m for _, m in self.releases) | frozenset(
            self.release_on_handle
        )

    def acquire_classes(self) -> frozenset:
        return frozenset(c for c, _ in self.acquires)

    def release_classes(self) -> frozenset:
        return frozenset(c for c, _ in self.releases)

    def func_tails(self) -> frozenset:
        return frozenset(q.rsplit(".", 1)[-1] for q in self.func_acquires)


class ResourceCatalog:
    """The enabled specs plus the lookup maps the rules scan with."""

    def __init__(self, specs: Iterable[ResourceSpec]):
        self.specs: Tuple[ResourceSpec, ...] = tuple(specs)
        self.by_name: Dict[str, ResourceSpec] = {
            s.name: s for s in self.specs
        }
        # method name -> [(class, spec)] for acquire ops
        self.acquire_index: Dict[str, List[Tuple[str, ResourceSpec]]] = {}
        self.release_index: Dict[str, List[Tuple[str, ResourceSpec]]] = {}
        for s in self.specs:
            for cls, m in s.acquires:
                self.acquire_index.setdefault(m, []).append((cls, s))
            for cls, m in s.releases:
                self.release_index.setdefault(m, []).append((cls, s))
        # every attr name that can START an obligation — the per-function
        # pre-scan gate (pay typestate inference only where one appears,
        # mirroring the v3 donation-rule pre-scan)
        self.acquire_names: frozenset = frozenset(
            self.acquire_index
        ) | frozenset(
            t for s in self.specs for t in s.func_tails()
        )

    def __iter__(self):
        return iter(self.specs)

    def __len__(self):
        return len(self.specs)


# --------------------------------------------------------------------- #
# The declared catalog. Every non-external entry is verified against its
# module before it is enabled (parse_resources); the tuple below is the
# DECLARATION, the tree is the authority.
# --------------------------------------------------------------------- #

DEFAULT_RESOURCE_DEFS: Tuple[ResourceSpec, ...] = (
    ResourceSpec(
        name="gen.kv-pages",
        kind="handle",
        module="areal_tpu/gen/pages.py",
        acquires=(
            ("PagePool", "alloc"),
            ("PagePool", "ref"),
            ("PrefixRegistry", "lookup"),
        ),
        releases=(("PagePool", "release"),),
        handle_from_arg=("ref",),
        doc="paged-KV page refcounts: alloc/ref/prefix-lookup must be "
        "balanced by PagePool.release (orphaned pages starve admission)",
    ),
    ResourceSpec(
        name="gen.engine-slot",
        kind="charge",
        module="areal_tpu/gen/engine.py",
        acquires=(("GenerationEngine", "submit"),),
        releases=(
            ("GenerationEngine", "cancel"),
            ("GenerationEngine", "pause"),
        ),
        doc="engine slot grant: a submitted request must be harvested, "
        "cancelled, or drained (the PR-10 orphaned-slot cancel race)",
    ),
    ResourceSpec(
        name="gateway.token-bucket",
        kind="charge",
        module="areal_tpu/gateway/qos.py",
        acquires=(("TokenBucket", "try_acquire"),),
        releases=(("TokenBucket", "refund"),),
        doc="QoS token charge: the budgeted cost charged at admission "
        "must be refunded on every exit path or the tenant starves",
    ),
    ResourceSpec(
        name="gateway.wfq",
        kind="charge",
        module="areal_tpu/gateway/qos.py",
        acquires=(("WeightedFairQueue", "push"),),
        releases=(
            ("WeightedFairQueue", "pop"),
            ("WeightedFairQueue", "drop_where"),
        ),
        doc="fair-queue entry: pushed work must be popped or dropped "
        "(with the virtual-clock rollback drop_where performs)",
    ),
    ResourceSpec(
        name="gateway.request",
        kind="charge",
        module="areal_tpu/gateway/scheduler.py",
        acquires=(("ContinuousBatchScheduler", "submit"),),
        releases=(("ContinuousBatchScheduler", "cancel"),),
        doc="gateway request admission: a submitted request must be "
        "consumed to completion or cancelled on disconnect",
    ),
    ResourceSpec(
        name="rollout.manager-slot",
        kind="charge",
        module="areal_tpu/system/rollout_worker.py",
        acquires=(("RolloutWorker", "allocate_new_rollout"),),
        releases=(("RolloutWorker", "finish_rollout"),),
        doc="gserver-manager capacity slot: every successful allocate "
        "must reach finish_rollout or the staleness gate tightens "
        "forever",
    ),
    ResourceSpec(
        name="elastic.rank-lease",
        kind="handle",
        module="areal_tpu/parallel/elastic.py",
        acquires=(("RankLease", "start"),),
        releases=(),
        release_on_handle=("stop",),
        handle_is_receiver=("start",),
        doc="liveness-lease refresh thread: started leases must be "
        "stopped or the thread outlives the epoch",
    ),
    ResourceSpec(
        name="tracing.span",
        kind="context",
        module="areal_tpu/base/tracing.py",
        func_acquires=("areal_tpu.base.tracing.span",),
        doc="data-plane span: must be entered via 'with' — a bare call "
        "never opens (or closes) the span",
    ),
    ResourceSpec(
        name="aiohttp.client-session",
        kind="handle",
        external=True,
        func_acquires=("aiohttp.ClientSession",),
        release_on_handle=("close",),
        doc="HTTP session: use 'async with', or close() in a finally — "
        "an abandoned session leaks its connector sockets",
    ),
)


# --------------------------------------------------------------------- #
# provenance: verify declared pairs against the tree
# --------------------------------------------------------------------- #


def _module_symbols(path: pathlib.Path) -> Optional[Dict[str, frozenset]]:
    """``{"": module-level def names, ClassName: method names}`` for one
    file, or None when it cannot be parsed."""
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except (OSError, SyntaxError):
        return None
    out: Dict[str, frozenset] = {}
    funcs = set()
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs.add(node.name)
        elif isinstance(node, ast.ClassDef):
            out[node.name] = frozenset(
                n.name
                for n in node.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            )
    out[""] = frozenset(funcs)
    return out


def spec_pairs(spec: ResourceSpec) -> List[Tuple[str, str]]:
    """Every (class, method) pair the spec declares against its module
    (the drift test pins these against the runtime classes)."""
    pairs = list(spec.acquires) + list(spec.releases)
    for q in spec.func_acquires:
        if not spec.external:
            pairs.append(("", q.rsplit(".", 1)[-1]))
    return pairs


def verify_spec(spec: ResourceSpec, root: pathlib.Path) -> bool:
    """True when every declared operation exists in the spec's module.
    External specs (aiohttp) are declaration-only and always pass."""
    if spec.external:
        return True
    syms = _module_symbols(pathlib.Path(root) / spec.module)
    if syms is None:
        return False
    for cls, method in spec_pairs(spec):
        if method not in syms.get(cls, frozenset()):
            return False
    return True


def parse_resources(
    root, defs: Tuple[ResourceSpec, ...] = DEFAULT_RESOURCE_DEFS
) -> Tuple[ResourceCatalog, List[str]]:
    """The enabled catalog for a tree plus the names of DROPPED specs
    (declared ops missing from the module — degrade, never guess)."""
    root = pathlib.Path(root)
    enabled: List[ResourceSpec] = []
    dropped: List[str] = []
    for spec in defs:
        if verify_spec(spec, root):
            enabled.append(spec)
        else:
            dropped.append(spec.name)
    return ResourceCatalog(enabled), dropped


def from_repo(root) -> Optional[ResourceCatalog]:
    """Catalog for the repo at ``root``; None when NOTHING verified
    (e.g. scanning a foreign tree) so the rule family disables whole."""
    catalog, _dropped = parse_resources(root)
    return catalog if len(catalog) else None
