"""Async-hygiene rules, migrated from tools/check_async_hygiene.py.

The four bug classes behind the fleet-wedging failures the fault-tolerance
subsystem fixed (docs/fault_tolerance.md): a bare ``asyncio.gather`` aborts
the whole fan-out on one dead peer; a discarded ``create_task`` can be
GC'd mid-flight and its exceptions vanish; ``shutil.rmtree`` outside the
checkpoint commit helper can destroy the only restore point; ``time.sleep``
inside ``async def`` stalls every heartbeat and in-flight rollout on the
loop.
"""

import ast

from tools.arealint.core import FileContext, SEVERITY_ERROR, rule

# The one module where deleting checkpoint-capable dirs is legal: the
# commit protocol itself.
RMTREE_ALLOWED_SUFFIXES = ("base/recover.py",)


def _is_gather(call: ast.Call) -> bool:
    """``asyncio.gather(...)`` and bare ``gather(...)`` (from-import), but
    not e.g. ``SequenceSample.gather`` (a data join)."""
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "gather":
        return isinstance(f.value, ast.Name) and f.value.id == "asyncio"
    return isinstance(f, ast.Name) and f.id == "gather"


def _is_spawn(call: ast.Call) -> bool:
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else ""
    )
    return name in ("create_task", "ensure_future")


def _is_rmtree(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "rmtree":
        return isinstance(f.value, ast.Name) and f.value.id == "shutil"
    return isinstance(f, ast.Name) and f.id == "rmtree"


def _is_time_sleep(call: ast.Call) -> bool:
    f = call.func
    return (
        isinstance(f, ast.Attribute)
        and f.attr == "sleep"
        and isinstance(f.value, ast.Name)
        and f.value.id == "time"
    )


def _is_bare_sleep(call: ast.Call) -> bool:
    """``sleep(...)`` via from-import — blocking unless awaited (an awaited
    bare ``sleep`` is asyncio's, imported the same way)."""
    return isinstance(call.func, ast.Name) and call.func.id == "sleep"


@rule(
    "bare-gather", SEVERITY_ERROR,
    "asyncio.gather without return_exceptions: one failed awaitable aborts "
    "the whole fan-out and every sibling result is lost",
)
def check_bare_gather(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and _is_gather(node):
            if not any(k.arg == "return_exceptions" for k in node.keywords):
                yield (
                    node.lineno,
                    "asyncio.gather without return_exceptions — one failed "
                    "awaitable aborts the whole fan-out",
                )


@rule(
    "discarded-task", SEVERITY_ERROR,
    "create_task/ensure_future result discarded: the unreferenced task may "
    "be GC'd mid-flight and its exceptions vanish",
)
def check_discarded_task(ctx: FileContext):
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Expr)
            and isinstance(node.value, ast.Call)
            and _is_spawn(node.value)
        ):
            yield (
                node.lineno,
                "create_task result discarded — task is unreferenced "
                "(may be GC'd) and never awaited (exceptions vanish)",
            )


@rule(
    "live-checkpoint-rmtree", SEVERITY_ERROR,
    "shutil.rmtree outside base/recover's commit helpers can destroy the "
    "only committed checkpoint",
)
def check_rmtree(ctx: FileContext):
    if ctx.path_endswith(*RMTREE_ALLOWED_SUFFIXES):
        return
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and _is_rmtree(node):
            yield (
                node.lineno,
                "shutil.rmtree outside base/recover's commit helpers — "
                "a crash mid-save can destroy the only committed "
                "checkpoint; stage + commit via areal_tpu.base.recover",
            )


@rule(
    "sleep-in-async", SEVERITY_ERROR,
    "time.sleep inside async def blocks the event loop (use await "
    "asyncio.sleep)",
)
def check_sleep_in_async(ctx: FileContext):
    """``time.sleep`` (attribute or from-import form) reachable from an
    ``async def`` body — nested SYNC defs are excluded (they run where
    they are called, which may be an executor thread)."""
    found = []

    def walk_async_body(node, awaited=False):
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return  # a new (possibly sync) execution context
        if isinstance(node, ast.Call) and (
            _is_time_sleep(node) or (_is_bare_sleep(node) and not awaited)
        ):
            found.append((
                node.lineno,
                "time.sleep inside async def blocks the event loop — "
                "use await asyncio.sleep",
            ))
        for child in ast.iter_child_nodes(node):
            walk_async_body(child, awaited=isinstance(node, ast.Await))

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.AsyncFunctionDef):
            for stmt in node.body:
                walk_async_body(stmt)
    yield from found
