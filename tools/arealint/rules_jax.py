"""JAX/TPU performance-invariant rules.

These encode the bug classes the ROADMAP's "fast as the hardware allows"
goal cannot survive silently (docs/static_analysis.md has the catalog):

- **host-sync-in-hot-path** — a hidden host↔device sync (``device_get``,
  ``.item()``, ``float(step(...))``, ``np.asarray(fn(...))``) inside a
  function reachable from a jitted or ``# arealint: hot``-annotated root
  serializes the dispatch-ahead pipeline (docs/pipelined_data_plane.md):
  the device drains while the host blocks.
- **retrace-hazard** — building a fresh jitted callable per call/iteration
  (``jax.jit(f)(x)`` inline, ``jax.jit`` inside a loop) throws away the
  trace cache and re-traces every time; non-hashable operands at
  ``static_argnums`` positions fail or retrace per call; a closure-captured
  ``jnp`` array is baked into the trace as a constant and silently
  re-embedded on every rebuild.
- **donation-after-use** — reading an argument after it was donated to a
  jitted call (``donate_argnums``): XLA may have aliased its buffer in
  place, so the read observes garbage (or errors) — and only on hardware,
  never under the CPU tests.

Reachability is intra-file and name-based (calls ``f(...)`` / ``self.f(...)``
resolve to same-file ``def f``). Cross-module hot paths are annotated at
their entry point with ``# arealint: hot`` instead.
"""

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tools.arealint.core import (
    FileContext, SEVERITY_ERROR, SEVERITY_WARN, rule, walk_excluding_nested,
)

JIT_NAMES = ("jit", "pjit")
JNP_CTORS = (
    "array", "asarray", "zeros", "ones", "full", "arange", "linspace", "eye",
)


def _is_jit_call(node: ast.AST) -> bool:
    """``jax.jit(...)`` / ``jax.pjit(...)`` / bare ``jit(...)``/``pjit(...)``."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in JIT_NAMES:
        return isinstance(f.value, ast.Name) and f.value.id == "jax"
    return isinstance(f, ast.Name) and f.id in JIT_NAMES


def _has_jit_decorator(fdef) -> bool:
    """``@jax.jit`` / ``@jit`` / ``@functools.partial(jax.jit, ...)``."""
    for dec in fdef.decorator_list:
        if isinstance(dec, ast.Attribute) and dec.attr in JIT_NAMES:
            return True
        if isinstance(dec, ast.Name) and dec.id in JIT_NAMES:
            return True
        if isinstance(dec, ast.Call):
            if _is_jit_call(dec):
                return True
            f = dec.func
            is_partial = (
                isinstance(f, ast.Attribute) and f.attr == "partial"
            ) or (isinstance(f, ast.Name) and f.id == "partial")
            if is_partial and dec.args:
                a0 = dec.args[0]
                if isinstance(a0, ast.Attribute) and a0.attr in JIT_NAMES:
                    return True
                if isinstance(a0, ast.Name) and a0.id in JIT_NAMES:
                    return True
    return False


def _all_functions(ctx: FileContext) -> List[ast.AST]:
    return [
        n for n in ast.walk(ctx.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]


def _dotted(node: ast.AST) -> Optional[str]:
    """``x`` -> "x", ``self.params`` -> "self.params" (Name chains only)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


# --------------------------------------------------------------------- #
# host-sync-in-hot-path
# --------------------------------------------------------------------- #


def _sync_match(node: ast.AST) -> Optional[str]:
    """A call that forces (or strongly implies) a host↔device sync."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr in ("device_get", "block_until_ready") and isinstance(
            f.value, ast.Name
        ) and f.value.id == "jax":
            return f"jax.{f.attr}"
        if f.attr == "block_until_ready" and not node.args:
            return ".block_until_ready()"
        if f.attr == "item" and not node.args:
            return ".item()"
        if (
            f.attr in ("asarray", "array", "copy")
            and isinstance(f.value, ast.Name)
            and f.value.id in ("np", "numpy", "onp")
            and node.args
            and isinstance(node.args[0], ast.Call)
        ):
            # np.asarray(fn(...)): materializing a call result on host —
            # the canonical accidental fetch. np.asarray(name) stays quiet
            # (usually host data already).
            return f"np.{f.attr}(<call result>)"
    if (
        isinstance(f, ast.Name)
        and f.id == "float"
        and len(node.args) == 1
        and isinstance(node.args[0], ast.Call)
    ):
        # float(step(...)): blocks on the device scalar. float(name) stays
        # quiet (commonly an already-fetched host scalar).
        return "float(<call result>)"
    return None


def _funcs_by_name(ctx: FileContext):
    funcs = _all_functions(ctx)
    by_name: Dict[str, List[ast.AST]] = {}
    for f in funcs:
        by_name.setdefault(f.name, []).append(f)
    return funcs, by_name


def file_hot_roots(ctx: FileContext, _index=None) -> Set[ast.AST]:
    """Function nodes that are hot roots in this file: jit-decorated,
    ``# arealint: hot``-annotated, or handed to ``jax.jit(fn)`` by name.
    ``_index`` is an optional precomputed ``(funcs, by_name)`` pair so
    callers that already walked the file don't walk it again."""
    funcs, by_name = _index if _index is not None else _funcs_by_name(ctx)
    hot: Set[ast.AST] = set()
    for f in funcs:
        if _has_jit_decorator(f) or ctx.hot_marked(f):
            hot.add(f)
    for node in ast.walk(ctx.tree):
        if _is_jit_call(node) and node.args and isinstance(
            node.args[0], ast.Name
        ):
            hot.update(by_name.get(node.args[0].id, []))
    return hot


def intra_hot_reachable(ctx: FileContext) -> Set[ast.AST]:
    """Function nodes reachable from this file's hot roots through the
    INTRA-FILE name-based call graph (``f(...)`` / ``self.f(...)`` resolve
    to same-file ``def f``). The cross-module project rule subtracts this
    set so each defect is reported by exactly one rule."""
    funcs, by_name = _funcs_by_name(ctx)
    hot = file_hot_roots(ctx, _index=(funcs, by_name))

    calls: Dict[ast.AST, Set[str]] = {}
    for f in funcs:
        names: Set[str] = set()
        for node in ast.walk(f):
            if isinstance(node, ast.Call):
                cf = node.func
                if isinstance(cf, ast.Name):
                    names.add(cf.id)
                elif isinstance(cf, ast.Attribute) and isinstance(
                    cf.value, ast.Name
                ) and cf.value.id in ("self", "cls"):
                    names.add(cf.attr)
        calls[f] = names

    reach: Set[ast.AST] = set(hot)
    work = list(hot)
    while work:
        f = work.pop()
        for name in calls.get(f, ()):
            for g in by_name.get(name, ()):
                if g not in reach:
                    reach.add(g)
                    work.append(g)
    return reach


@rule(
    "host-sync-in-hot-path", SEVERITY_ERROR,
    "host<->device sync (device_get / .item() / float(call) / "
    "np.asarray(call) / block_until_ready) reachable from a jitted or "
    "'# arealint: hot' root — serializes the dispatch-ahead pipeline",
)
def check_host_sync(ctx: FileContext):
    reach = intra_hot_reachable(ctx)
    for f in sorted(reach, key=lambda n: n.lineno):
        for node in walk_excluding_nested(f):
            m = _sync_match(node)
            if m:
                yield (
                    node.lineno,
                    f"{m} in {f.name}() forces a host<->device sync on a "
                    "hot path (reachable from a jitted or '# arealint: hot' "
                    "root) — move it off the step path, batch it into the "
                    "deferred stats fetch, or annotate a deliberate sync "
                    "with '# arealint: ok(<reason>)'",
                )


# --------------------------------------------------------------------- #
# retrace-hazard
# --------------------------------------------------------------------- #


def _static_positions(jit_call: ast.Call) -> Tuple[int, ...]:
    for kw in jit_call.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, int)
                )
    return ()


@rule(
    "retrace-hazard", SEVERITY_WARN,
    "jax.jit built per call/iteration (trace cache discarded), non-hashable "
    "operand at a static_argnums position, or a closure-captured jnp array "
    "baked into the trace",
)
def check_retrace(ctx: FileContext):
    seen: Set[Tuple[int, str]] = set()

    def emit(lineno: int, msg: str):
        key = (lineno, msg)
        if key not in seen:
            seen.add(key)
            yield (lineno, msg)

    # (a) jit built inside a loop — every iteration re-traces from scratch
    in_loop: Set[int] = set()
    for loop in ast.walk(ctx.tree):
        if isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
            for node in ast.walk(loop):
                if _is_jit_call(node):
                    in_loop.add(id(node))
                    yield from emit(
                        node.lineno,
                        "jax.jit/pjit built inside a loop — the compiled "
                        "trace is discarded every iteration; hoist the "
                        "jitted callable out of the loop and reuse it",
                    )

    # (b) immediate invocation inside a function: jax.jit(f)(x) builds a
    # fresh callable (and trace cache) on every call of the enclosing
    # function. (c) non-hashable operands at its static positions.
    for f in _all_functions(ctx):
        for node in walk_excluding_nested(f):
            if isinstance(node, ast.Call) and _is_jit_call(node.func):
                # already reported by (a) — one defect, one finding
                if id(node.func) not in in_loop:
                    yield from emit(
                        node.lineno,
                        "jax.jit(...)(...) immediately invoked inside a "
                        "function — a fresh jitted callable (empty trace "
                        "cache) is built on every call of "
                        f"{f.name}(); cache the jitted callable instead",
                    )
                for p in _static_positions(node.func):
                    if p < len(node.args) and isinstance(
                        node.args[p], (ast.List, ast.Dict, ast.Set)
                    ):
                        yield from emit(
                            node.args[p].lineno,
                            f"non-hashable operand at static_argnums "
                            f"position {p} — static arguments are hashed "
                            "into the trace-cache key; pass a hashable "
                            "(tuple/int/str) or make the argument traced",
                        )

    # (d) closure-captured jnp arrays: jitting a local def that reads an
    # enclosing-scope name bound to a jnp constructor call
    for f in _all_functions(ctx):
        local_defs = {
            n.name: n for n in f.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        jnp_names: Dict[str, int] = {}
        for node in walk_excluding_nested(f):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and isinstance(node.value.func.value, ast.Name)
                and node.value.func.value.id == "jnp"
                and node.value.func.attr in JNP_CTORS
            ):
                jnp_names[node.targets[0].id] = node.lineno
        if not jnp_names:
            continue
        for node in walk_excluding_nested(f):
            if _is_jit_call(node) and node.args and isinstance(
                node.args[0], ast.Name
            ):
                target = local_defs.get(node.args[0].id)
                if target is None:
                    continue
                free = _free_loads(target)
                captured = sorted(free & set(jnp_names))
                for name in captured:
                    yield from emit(
                        node.lineno,
                        f"jitted local function {target.name}() closes "
                        f"over jnp array {name!r} (built at line "
                        f"{jnp_names[name]}) — the array is baked into "
                        "the trace as a constant and re-embedded on every "
                        "rebuild; pass it as an argument instead",
                    )


def _free_loads(fdef) -> Set[str]:
    """Names loaded in fdef that are neither its params nor stored in it."""
    bound: Set[str] = {a.arg for a in fdef.args.args}
    bound.update(a.arg for a in fdef.args.kwonlyargs)
    if fdef.args.vararg:
        bound.add(fdef.args.vararg.arg)
    if fdef.args.kwarg:
        bound.add(fdef.args.kwarg.arg)
    loads: Set[str] = set()
    for node in ast.walk(fdef):
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load):
                loads.add(node.id)
            else:
                bound.add(node.id)
    return loads - bound


# --------------------------------------------------------------------- #
# donation-after-use
# --------------------------------------------------------------------- #


def _donated_positions(jit_call: ast.Call) -> Tuple[int, ...]:
    for kw in jit_call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                return tuple(
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, int)
                )
    return ()


def _pos(node: ast.AST) -> Tuple[int, int]:
    return (node.lineno, node.col_offset)


def _end_pos(node: ast.AST) -> Tuple[int, int]:
    return (
        getattr(node, "end_lineno", node.lineno),
        getattr(node, "end_col_offset", node.col_offset),
    )


@rule(
    "donation-after-use", SEVERITY_ERROR,
    "an argument listed in donate_argnums is read after the jitted call — "
    "its buffer may be aliased/invalidated on device (fails only on "
    "hardware, never under the CPU tests)",
)
def check_donation(ctx: FileContext):
    parents = ctx.parents()
    for f in _all_functions(ctx):
        # donated jitted callables bound in this scope
        donors: Dict[str, Tuple[int, ...]] = {}
        for node in walk_excluding_nested(f):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and _is_jit_call(node.value)
            ):
                pos = _donated_positions(node.value)
                if pos:
                    donors[node.targets[0].id] = pos

        # calls that donate: tracked (donated expr, call end position)
        tracked: List[Tuple[str, Tuple[int, int], int]] = []
        for node in walk_excluding_nested(f):
            if not isinstance(node, ast.Call):
                continue
            positions: Tuple[int, ...] = ()
            if isinstance(node.func, ast.Name) and node.func.id in donors:
                positions = donors[node.func.id]
            elif _is_jit_call(node.func):
                positions = _donated_positions(node.func)
            if not positions:
                continue
            # rebinding at the call site (x, y = step(x, y, ...)) keeps the
            # name valid: it now holds the NEW buffer
            rebound: Set[str] = set()
            parent = parents.get(node)
            if isinstance(parent, ast.Assign):
                for t in parent.targets:
                    elts = t.elts if isinstance(t, ast.Tuple) else [t]
                    for e in elts:
                        d = _dotted(e)
                        if d:
                            rebound.add(d)
            for p in positions:
                if p < len(node.args):
                    d = _dotted(node.args[p])
                    if d and d not in rebound:
                        tracked.append((d, _end_pos(node), node.lineno))
        if not tracked:
            continue

        # loads/stores of tracked exprs after each donating call
        exprs = {t[0] for t in tracked}
        events: List[Tuple[Tuple[int, int], str, str]] = []
        for node in walk_excluding_nested(f):
            if isinstance(node, (ast.Name, ast.Attribute)):
                d = _dotted(node)
                if d in exprs:
                    kind = (
                        "load"
                        if isinstance(node.ctx, ast.Load) else "store"
                    )
                    # an Attribute chain's inner Name is a Load even when
                    # the chain is stored (params.grad = x); climb to the
                    # outermost chain and skip only if THAT is a store —
                    # params.mean() is still a read of the donated buffer
                    parent = parents.get(node)
                    if isinstance(parent, ast.Attribute):
                        top = parent
                        while isinstance(parents.get(top), ast.Attribute):
                            top = parents[top]
                        if not isinstance(top.ctx, ast.Load):
                            continue
                    events.append((_pos(node), kind, d))
        events.sort()
        for expr, call_end, call_line in tracked:
            for pos, kind, d in events:
                if d != expr or pos <= call_end:
                    continue
                if kind == "store":
                    break
                yield (
                    pos[0],
                    f"{expr!r} was donated to the jitted call on line "
                    f"{call_line} (donate_argnums) and is read afterwards "
                    "— the buffer may already be aliased in place; rebind "
                    "the result or copy before donating",
                )
                break
