"""Resource-lifecycle / cancellation-safety typestate rules.

The review history's single largest class of hardening fixes (PRs 10-12)
was acquire/release protocols broken on exception and cancellation
paths: leaked engine slots on cancel races, unrefunded ``TokenBucket``
charges, orphaned pages on disconnect. This family machine-checks that
bug class against the resource catalog (``tools/arealint/resources.py``,
parsed from the tree — never imported):

- ``leak-on-exception-path`` — a ``handle``/``context`` acquire whose
  release is not dominated by a ``finally`` / context manager: any
  exception between acquire and release leaks the resource.
- ``leak-on-cancellation`` — an ``await`` sits between acquire and
  release with no enclosing ``try/finally`` (or a handler that catches
  ``CancelledError``): the exact shape of PR-10's orphaned-slot cancel
  race. ``except Exception`` does NOT protect this path — CancelledError
  is a BaseException.
- ``double-release`` — the same handle released twice on one
  straight-line path (or once inside a loop): refcount underflow.
- ``release-without-acquire`` — the matching acquire happens only on
  SOME path (a conditional branch) while the release is unconditional.
- ``charge-refund-asymmetry`` — a counted charge (``charge`` kind)
  whose refund is unreachable on an error path.

Ownership transfer resolves through the project call graph: a resolved
callee that (transitively) performs a matching release DISCHARGES the
obligation; a callee that stores the handle, an unresolvable callee, a
return/yield, or a store into an attribute/container DEGRADES it to
no-finding — the v2/v3 degradation contract. Deliberate cross-function
handoffs the graph cannot see are annotated at the acquire site::

    self.engine.submit(req)  # arealint: owns(gen.engine-slot, <reason>)

The annotation names the RESOURCE (so a later refactor that changes what
the line acquires invalidates it) and requires a reason, same as
``# arealint: ok``. A malformed ``owns`` (missing reason, wrong resource
name) does not discharge — the finding message says so.
"""

import ast
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from tools.arealint.core import (
    ProjectContext, SEVERITY_ERROR, SUPPRESS_RE, project_rule,
    walk_excluding_nested,
)
from tools.arealint.project import FunctionInfo, _dotted, collect_aliases
from tools.arealint.rules_dataflow import _stored_param_positions

OWNS_RE = re.compile(
    r"#\s*arealint:\s*owns\(\s*(?P<res>[^,()]+?)\s*,\s*(?P<reason>[^)]+?)\s*\)"
)
OWNS_BARE_RE = re.compile(r"#\s*arealint:\s*owns\b")

RULE_LEAK_EXC = "leak-on-exception-path"
RULE_LEAK_CANCEL = "leak-on-cancellation"
RULE_DOUBLE = "double-release"
RULE_REL_NO_ACQ = "release-without-acquire"
RULE_ASYM = "charge-refund-asymmetry"

_MAX_TRANSFER_DEPTH = 6

# builtins that READ a handle without capturing it: not an escape, still
# a risky call like any other
_PURE_BUILTINS = frozenset({
    "len", "sorted", "sum", "min", "max", "enumerate", "reversed",
    "int", "float", "str", "bool", "repr", "print", "zip", "isinstance",
    "any", "all", "range",
})


def _pos(n) -> Tuple[int, int]:
    return (n.lineno, n.col_offset)


def _end(n) -> Tuple[int, int]:
    return (
        getattr(n, "end_lineno", n.lineno),
        getattr(n, "end_col_offset", n.col_offset),
    )


# --------------------------------------------------------------------- #
# receiver / handle typing (conservative: unresolvable -> no obligation)
# --------------------------------------------------------------------- #


def _class_name_of(pctx: ProjectContext, mod, dotted: str) -> Optional[str]:
    """Bare class NAME when ``dotted`` (as seen in ``mod``) resolves to an
    indexed class; None otherwise."""
    if mod is None or not dotted:
        return None
    target = pctx.project.resolve_in_module(mod, dotted)
    if target is None:
        return None
    ci = pctx.project.class_info(target)
    return ci.name if ci is not None else None


def _ctor_class(pctx: ProjectContext, mod, call: ast.Call) -> Optional[str]:
    d = _dotted(call.func)
    return _class_name_of(pctx, mod, d) if d else None


def _return_class(pctx: ProjectContext, call: ast.Call, callees) -> Optional[str]:
    """Class name from a resolved callee's return annotation
    (``def _bucket(...) -> TokenBucket``)."""
    q = callees.get(id(call))
    if not q:
        return None
    cfi = pctx.graph.function(q)
    if cfi is None or cfi.node.returns is None:
        return None
    d = _dotted(cfi.node.returns)
    if not d:
        return None
    cmod = pctx.project.modules.get(cfi.module)
    return _class_name_of(pctx, cmod, d)


def _module_attr_types(pctx: ProjectContext, mod) -> Dict[str, str]:
    """``"Class.attr" -> class NAME`` from ``self.attr = Ctor(...)`` and
    ``self.attr = <annotated param>`` assignments anywhere in the class."""
    out: Dict[str, str] = {}
    for ci in mod.classes.values():
        for fi in ci.methods.values():
            ann = {}
            args = fi.node.args
            for a in (
                list(getattr(args, "posonlyargs", []))
                + list(args.args) + list(args.kwonlyargs)
            ):
                if a.annotation is not None:
                    d = _dotted(a.annotation)
                    cn = _class_name_of(pctx, mod, d) if d else None
                    if cn:
                        ann[a.arg] = cn
            for node in ast.walk(fi.node):
                if not (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Attribute)
                    and isinstance(node.targets[0].value, ast.Name)
                    and node.targets[0].value.id == "self"
                ):
                    continue
                key = f"{ci.name}.{node.targets[0].attr}"
                cn = None
                if isinstance(node.value, ast.Call):
                    cn = _ctor_class(pctx, mod, node.value)
                elif isinstance(node.value, ast.Name):
                    cn = ann.get(node.value.id)
                if cn:
                    out[key] = cn
                else:
                    out.pop(key, None)
    return out


def _local_types(pctx: ProjectContext, mod, fi: FunctionInfo) -> Dict[str, str]:
    """name -> class NAME: ctor assigns, annotated params, and
    return-annotated resolved calls."""
    types: Dict[str, str] = {}
    args = fi.node.args
    for a in (
        list(getattr(args, "posonlyargs", []))
        + list(args.args) + list(args.kwonlyargs)
    ):
        if a.annotation is not None:
            d = _dotted(a.annotation)
            cn = _class_name_of(pctx, mod, d) if d else None
            if cn:
                types[a.arg] = cn
    callees = pctx.graph.callees_by_node(fi.qualname)
    for node in walk_excluding_nested(fi.node):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Call)
        ):
            cn = _ctor_class(pctx, mod, node.value) or _return_class(
                pctx, node.value, callees
            )
            if cn:
                types[node.targets[0].id] = cn
            else:
                types.pop(node.targets[0].id, None)
    return types


def _receiver_class(
    pctx, mod, fi, call: ast.Call, local_types, attr_types, callees,
) -> Optional[str]:
    """Resolved class NAME of a method call's receiver, or None."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    v = f.value
    if isinstance(v, ast.Name):
        if v.id == "self" and fi.class_name is not None:
            return fi.class_name
        return local_types.get(v.id)
    if (
        isinstance(v, ast.Attribute)
        and isinstance(v.value, ast.Name)
        and v.value.id == "self"
        and fi.class_name is not None
    ):
        return attr_types.get(f"{fi.class_name}.{v.attr}")
    if isinstance(v, ast.Call):
        return _ctor_class(pctx, mod, v) or _return_class(pctx, v, callees)
    return None


# --------------------------------------------------------------------- #
# spec matching
# --------------------------------------------------------------------- #


def _match_acquire(
    pctx, mod, fi, call, catalog, local_types, attr_types, aliases, callees,
):
    """The ResourceSpec a call acquires, or None (degrade)."""
    f = call.func
    if isinstance(f, ast.Attribute):
        entries = catalog.acquire_index.get(f.attr)
        if entries:
            rc = _receiver_class(
                pctx, mod, fi, call, local_types, attr_types, callees
            )
            if rc:
                for cls, spec in entries:
                    if cls == rc:
                        return spec
    d = _dotted(f)
    if not d:
        return None
    tail = d.rsplit(".", 1)[-1]
    candidates = [
        s for s in catalog.specs if s.func_acquires and tail in s.func_tails()
    ]
    if not candidates:
        return None
    resolved = pctx.project.resolve_in_module(mod, d)
    full = None
    if resolved is None:
        head, _, rest = d.partition(".")
        base = aliases.get(head)
        if base:
            full = f"{base}.{rest}" if rest else base
    for spec in candidates:
        for q in spec.func_acquires:
            tail2 = ".".join(q.split(".")[-2:])
            for got in (resolved, full):
                if got and (got == q or got.endswith("." + tail2)):
                    return spec
    return None


def _match_release(
    pctx, mod, fi, call, catalog, local_types, attr_types, callees,
):
    """(spec, handle-dotted-or-None) when the call is a typed release
    (``pool.release(pages)`` / ``lease.stop()``); None otherwise."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    entries = catalog.release_index.get(f.attr)
    if entries:
        rc = _receiver_class(
            pctx, mod, fi, call, local_types, attr_types, callees
        )
        if rc:
            for cls, spec in entries:
                if cls == rc:
                    handle = _dotted(call.args[0]) if call.args else None
                    return spec, handle
    # release-on-handle: lease.stop() / session.close() — the receiver IS
    # the handle; the spec is decided by matching an open obligation
    for spec in catalog.specs:
        if f.attr in spec.release_on_handle:
            h = _dotted(f.value)
            if h:
                return spec, h
    return None


def _releases_transitively(pctx, qualname: str, spec, _depth=0, _seen=None):
    """Permissive ownership-transfer classifier: does the callee (or
    anything it resolves to, bounded depth) perform — or hold a reference
    to — a release op of ``spec``? Name-based on the release side: this
    only DISCHARGES obligations, so permissiveness is the conservative
    direction."""
    cache = getattr(pctx, "_lifecycle_transfer_cache", None)
    if cache is None:
        cache = {}
        pctx._lifecycle_transfer_cache = cache
    key = (qualname, spec.name)
    if key in cache:
        return cache[key]
    if _seen is None:
        _seen = set()
    if qualname in _seen or _depth > _MAX_TRANSFER_DEPTH:
        return False
    _seen.add(qualname)
    fi = pctx.graph.function(qualname)
    if fi is None:
        return False
    rel = spec.release_methods()
    hit = False
    for n in walk_excluding_nested(fi.node):
        if isinstance(n, ast.Attribute) and n.attr in rel:
            hit = True
            break
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) and (
            n.func.id in rel
        ):
            hit = True
            break
    if not hit:
        for nxt in sorted(pctx.graph.edges.get(qualname, ())):
            if _releases_transitively(pctx, nxt, spec, _depth + 1, _seen):
                hit = True
                break
    # True is depth-independent (a release found within the bound from a
    # DEEPER start is also within it from depth 0); a False computed near
    # the depth bound or inside a cycle's _seen set is weaker than a
    # fresh depth-0 answer, so only root-level negatives are cached —
    # caching truncated negatives would deny real ownership transfers
    # and fire error findings on clean code
    if hit or _depth == 0:
        cache[key] = hit
    return hit


def _callee_stores(pctx, qualname: str) -> bool:
    fi = pctx.graph.function(qualname)
    if fi is None:
        return True  # class ctor / unclassifiable: treat as capturing
    cache = getattr(pctx, "_lifecycle_store_cache", None)
    if cache is None:
        cache = {}
        pctx._lifecycle_store_cache = cache
    got = cache.get(qualname)
    if got is None:
        got = bool(_stored_param_positions(fi))
        cache[qualname] = got
    return got


# --------------------------------------------------------------------- #
# structural helpers
# --------------------------------------------------------------------- #


def _chain(parents, node, stop) -> List[ast.AST]:
    """Ancestors of ``node`` up to (excluding) ``stop``, innermost first."""
    out = []
    cur = parents.get(node)
    while cur is not None and cur is not stop:
        out.append(cur)
        cur = parents.get(cur)
    return out


def _in_subtree(parents, node, roots: Sequence[ast.AST], stop) -> bool:
    cur = node
    while cur is not None and cur is not stop:
        if any(cur is r for r in roots):
            return True
        cur = parents.get(cur)
    return False


def _stmt_of(parents, node, stop) -> ast.AST:
    cur = node
    while cur is not None and cur is not stop:
        if isinstance(cur, ast.stmt):
            return cur
        cur = parents.get(cur)
    return node


def _branch_arms(parents, node, fdef) -> frozenset:
    """Conditional arms enclosing ``node``: (id(ctrl), arm) pairs for If
    body/orelse, Try body/handlers, and loop bodies. Try orelse/finalbody
    are transparent (they execute on the fall-through path). A try-BODY
    release and a HANDLER release are mutually exclusive paths — the
    body arm keeps double-release honest there; the obligation pass only
    treats If arms ("body"/"orelse") as conditional discharge."""
    arms: Set[Tuple[int, str]] = set()
    cur, child = parents.get(node), node
    while cur is not None and child is not fdef:
        if isinstance(cur, ast.If):
            if _contains(cur.body, child):
                arms.add((id(cur), "body"))
            elif _contains(cur.orelse, child):
                arms.add((id(cur), "orelse"))
        elif isinstance(cur, ast.Try):
            if any(_contains([h], child) for h in cur.handlers):
                arms.add((id(cur), "handler"))
            elif _contains(cur.body, child):
                arms.add((id(cur), "trybody"))
        elif isinstance(cur, (ast.While, ast.For, ast.AsyncFor)):
            if _contains(cur.body, child):
                arms.add((id(cur), "loop"))
        child, cur = cur, parents.get(cur)
    return frozenset(arms)


def _contains(body, node) -> bool:
    return any(n is node for n in body)


def _enclosing_tries(parents, node, fdef) -> List[Tuple[ast.Try, str]]:
    """(Try, arm) for every Try enclosing ``node``, innermost first; arm
    in body/handler/orelse/finalbody."""
    out = []
    cur, child = parents.get(node), node
    while cur is not None and child is not fdef:
        if isinstance(cur, ast.Try):
            if _contains(cur.body, child):
                out.append((cur, "body"))
            elif any(_contains([h], child) for h in cur.handlers):
                out.append((cur, "handler"))
            elif _contains(cur.orelse, child):
                out.append((cur, "orelse"))
            elif _contains(cur.finalbody, child):
                out.append((cur, "finalbody"))
        child, cur = cur, parents.get(cur)
    return out


def _handler_cancel_safe(handler: ast.ExceptHandler) -> bool:
    """Does the handler catch CancelledError? (bare except /
    BaseException / CancelledError)."""
    t = handler.type
    if t is None:
        return True
    names = []
    for e in t.elts if isinstance(t, ast.Tuple) else [t]:
        d = _dotted(e)
        if d:
            names.append(d.rsplit(".", 1)[-1])
    return any(n in ("BaseException", "CancelledError") for n in names)


def _owns_match(ctx, spec, lineno: int) -> Tuple[bool, bool]:
    """(discharged, malformed-annotation-present) for the acquire line
    and the comment line above. A reasoned ``# arealint: ok(...)`` on the
    ACQUIRE line also discharges: the leak-on-cancellation finding lands
    on the await line, but the natural place to annotate is the acquire."""
    malformed = False
    for ln in (lineno, lineno - 1):
        text = ctx.line_text(ln)
        if ln != lineno and not text.strip().startswith("#"):
            continue
        m = OWNS_RE.search(text)
        if m:
            if m.group("res").strip() == spec.name:
                return True, False
            malformed = True
        elif OWNS_BARE_RE.search(text):
            malformed = True
        m = SUPPRESS_RE.search(text)
        if m and m.group("reason").strip():
            return True, False
    return False, malformed


# --------------------------------------------------------------------- #
# the per-function typestate pass
# --------------------------------------------------------------------- #


class _Obligation:
    def __init__(self, call, spec, handle, stmt):
        self.call = call
        self.spec = spec
        self.handle = handle      # dotted name or None (charge kind)
        self.stmt = stmt


def _maximal_loads(parents, fdef, dotted: str) -> List[ast.AST]:
    """Load occurrences of ``dotted`` that are not a prefix of a longer
    attribute chain and not a method-call receiver."""
    out = []
    for n in walk_excluding_nested(fdef):
        if not isinstance(n, (ast.Name, ast.Attribute)):
            continue
        if _dotted(n) != dotted:
            continue
        ctx_ = getattr(n, "ctx", None)
        if not isinstance(ctx_, ast.Load):
            continue
        par = parents.get(n)
        if isinstance(par, ast.Attribute) and par.value is n:
            continue  # base of a longer chain (self.engine.cfg)
        if isinstance(par, ast.Call) and par.func is n:
            continue
        out.append(n)
    return out


def _load_role(pctx, parents, fdef, load, callees) -> Tuple[str, Optional[ast.AST]]:
    """Classify one maximal load of a tracked handle/receiver:

    - ``("with", node)``     — a with-item: the CM closes it
    - ``("escape", node)``   — capture the analysis cannot follow
    - ``("transfer", call)`` — arg of a resolved transitively-releasing
                               callee (decided by the caller per spec)
    - ``("use", None)``      — plain read
    """
    cur, child = parents.get(load), load
    while cur is not None and child is not fdef:
        if isinstance(cur, ast.Call) and child is not cur.func:
            q = callees.get(id(cur))
            if q is None:
                f = cur.func
                if isinstance(f, ast.Name) and f.id in _PURE_BUILTINS:
                    child, cur = cur, parents.get(cur)
                    continue
                return "escape", cur
            return "call", cur
        if isinstance(cur, (ast.Return, ast.Yield, ast.YieldFrom)):
            return "escape", cur
        if isinstance(cur, ast.withitem) and cur.context_expr is child:
            return "with", cur
        if isinstance(cur, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = cur.value
            if value is child or _in_expr(value, load):
                return "escape", cur  # aliased / stored
        child, cur = cur, parents.get(cur)
    return "use", None


def _in_expr(expr, node) -> bool:
    if expr is None:
        return False
    return any(n is node for n in ast.walk(expr))


def _acquire_if_test(parents, call, fdef):
    """(If, negated) when the acquire sits in an If test (``elif await
    self.allocate_new_rollout(...)`` / ``if not bucket.try_acquire(c)``),
    else (None, False)."""
    negated = False
    cur, child = parents.get(call), call
    while cur is not None and child is not fdef:
        if isinstance(cur, ast.UnaryOp) and isinstance(cur.op, ast.Not):
            negated = not negated
        if isinstance(cur, ast.If) and cur.test is child:
            return cur, negated
        if isinstance(cur, ast.stmt):
            return None, False
        child, cur = cur, parents.get(cur)
    return None, False


def _unsupported_shape(parents, call, fdef) -> bool:
    """Acquires inside comprehensions, lambdas, IfExps, or nested as an
    argument of another call degrade — the binding cannot be tracked."""
    cur, child = parents.get(call), call
    while cur is not None and child is not fdef:
        if isinstance(
            cur,
            (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp,
             ast.Lambda, ast.IfExp),
        ):
            return True
        if isinstance(cur, ast.Call) and child is not cur.func:
            return True  # f(pool.alloc(n)): handed off at birth
        if isinstance(cur, ast.BoolOp):
            # only the `x = acquire(...) or default` shape is tracked
            par = parents.get(cur)
            if not (
                isinstance(par, ast.Assign) and cur.values[0] is child
            ):
                return True
        if isinstance(cur, ast.stmt):
            return False
        child, cur = cur, parents.get(cur)
    return False


def _bound_handle(parents, call, fdef) -> Tuple[Optional[str], bool]:
    """(handle dotted, bound) for a normal acquire: the single Name an
    enclosing Assign binds. ``bound`` False means the result is
    discarded (an Expr statement)."""
    cur, child = parents.get(call), call
    while cur is not None and child is not fdef:
        if isinstance(cur, ast.Await):
            child, cur = cur, parents.get(cur)
            continue
        if isinstance(cur, ast.BoolOp):
            child, cur = cur, parents.get(cur)
            continue
        if isinstance(cur, ast.Assign):
            if len(cur.targets) == 1 and isinstance(cur.targets[0], ast.Name):
                return cur.targets[0].id, True
            return None, True  # tuple/attribute target: untrackable
        if isinstance(cur, ast.Expr):
            return None, False
        return None, True  # any other statement context: untrackable
    return None, True


def _analyze_function(pctx, mod, fi, catalog, attr_types, aliases):
    ctx = pctx.file_ctx(fi.path)
    if ctx is None:
        return
    parents = ctx.parents()
    callees = pctx.graph.callees_by_node(fi.qualname)
    local_types = _local_types(pctx, mod, fi)
    nodes = list(walk_excluding_nested(fi.node))
    calls = [n for n in nodes if isinstance(n, ast.Call)]

    # one pass: classify every call once
    acquire_sites: List[Tuple[ast.Call, object]] = []
    release_sites: List[Tuple[ast.Call, object, Optional[str]]] = []
    for c in calls:
        spec = _match_acquire(
            pctx, mod, fi, c, catalog, local_types, attr_types, aliases,
            callees,
        )
        if spec is not None:
            acquire_sites.append((c, spec))
        rel = _match_release(
            pctx, mod, fi, c, catalog, local_types, attr_types, callees
        )
        if rel is not None:
            release_sites.append((c, rel[0], rel[1]))

    yield from _check_obligations(
        pctx, mod, fi, ctx, parents, callees, nodes,
        acquire_sites, release_sites,
    )
    yield from _check_double_release(
        fi, parents, nodes, acquire_sites, release_sites
    )
    yield from _check_release_without_acquire(
        fi, parents, nodes, acquire_sites, release_sites
    )


def _handle_stores(nodes, handle: str) -> List[Tuple[int, int]]:
    out = []
    for n in nodes:
        if isinstance(n, ast.Name) and n.id == handle and isinstance(
            n.ctx, (ast.Store, ast.Del)
        ):
            out.append(_pos(n))
        elif isinstance(n, (ast.For, ast.AsyncFor)):
            for t in ast.walk(n.target):
                if isinstance(t, ast.Name) and t.id == handle:
                    out.append(_pos(t))
    return out


def _check_obligations(
    pctx, mod, fi, ctx, parents, callees, nodes, acquire_sites, release_sites,
):
    fdef = fi.node
    release_by_id = {id(c): (spec, h) for c, spec, h in release_sites}
    for call, spec in acquire_sites:
        # context managers discharge every kind at the acquire site
        par = parents.get(call)
        if isinstance(par, ast.Await):
            par = parents.get(par)
        if isinstance(par, ast.withitem) and (
            par.context_expr is call
            or (
                isinstance(par.context_expr, ast.Await)
                and par.context_expr.value is call
            )
        ):
            continue
        discharged, malformed = _owns_match(ctx, spec, call.lineno)
        if discharged:
            continue
        owns_hint = (
            " (a malformed '# arealint: owns(...)' annotation on this "
            "line was ignored — the grammar is owns(<resource>, <reason>) "
            "with the exact catalog name)"
            if malformed else ""
        )
        if _unsupported_shape(parents, call, fdef):
            continue

        acq_if, negated = _acquire_if_test(parents, call, fdef)
        excluded: List[ast.AST] = []
        if acq_if is not None:
            excluded = (
                list(acq_if.body) + list(acq_if.orelse)
                if negated else list(acq_if.orelse)
            )
        acq_tries = _enclosing_tries(parents, call, fdef)
        for t, arm in acq_tries:
            if arm == "body":
                # an exception inside the handlers of the acquiring try
                # means the acquire itself raised (or the obligation is
                # being settled there) — skip risky accounting in them
                excluded.extend(t.handlers)

        handle: Optional[str] = None
        if spec.kind in ("handle", "context"):
            m = call.func.attr if isinstance(call.func, ast.Attribute) else ""
            if m in spec.handle_from_arg:
                handle = _dotted(call.args[0]) if call.args else None
                if handle is None or "." in handle:
                    continue  # untrackable (literal / attribute chain)
            elif m in spec.handle_is_receiver:
                handle = _dotted(call.func.value)
                if handle is None or "." in handle:
                    continue  # attribute handle: object owns it (degrade)
            else:
                handle, bound = _bound_handle(parents, call, fdef)
                if handle is None and bound:
                    continue  # untrackable binding: degrade
                if handle is None and not bound:
                    if spec.kind == "context":
                        yield (
                            RULE_LEAK_EXC, fi.path, call.lineno,
                            f"{spec.name} acquired here is never entered: "
                            "a bare call opens nothing and the close never "
                            "runs — use 'with'/'async with'" + owns_hint,
                        )
                    else:
                        yield (
                            RULE_LEAK_EXC, fi.path, call.lineno,
                            f"{spec.name} acquired here is discarded — the "
                            "handle is never bound, so no path can release "
                            "it" + owns_hint,
                        )
                    continue

        acq_end = _end(call)
        stores = (
            [p for p in _handle_stores(nodes, handle) if p > acq_end]
            if handle else []
        )
        first_store = min(stores) if stores else None

        # ---- collect events after the acquire ------------------------ #
        events: List[Tuple[Tuple[int, int], str, ast.AST]] = []
        # releases (direct)
        for c, rspec, rh in release_sites:
            if rspec is not spec or _pos(c) <= acq_end:
                continue
            if _in_subtree(parents, c, excluded, fdef):
                continue
            if spec.kind in ("handle", "context"):
                if rh != handle:
                    continue
            events.append((_pos(c), "release", c))
        # loads of the handle / charge receiver
        track = handle
        if spec.kind == "charge":
            f = call.func
            track = _dotted(f.value) if isinstance(f, ast.Attribute) else None
            if track is not None and track.startswith("self"):
                track = None  # attribute receivers don't escape locally
        if track:
            for load in _maximal_loads(parents, fdef, track):
                if _pos(load) <= acq_end:
                    continue
                if _in_subtree(parents, load, excluded, fdef):
                    continue
                role, where = _load_role(pctx, parents, fdef, load, callees)
                if role == "use":
                    continue
                if role == "with":
                    events.append((_pos(load), "release", load))
                elif role == "escape":
                    events.append((_pos(load), "escape", load))
                elif role == "call":
                    if id(where) in release_by_id:
                        continue  # already recorded as a release
                    q = callees.get(id(where))
                    cfi = pctx.graph.function(q) if q else None
                    if cfi is None:
                        events.append((_pos(load), "escape", load))
                    elif _releases_transitively(pctx, q, spec):
                        events.append((_pos(where), "transfer", where))
                    elif _callee_stores(pctx, q):
                        events.append((_pos(load), "escape", load))
                    # else: plain use of the handle — no event
        # charge kind: ANY later call to a transitively-releasing callee
        # settles the charge (the receiver is shared state the callee can
        # reach — e.g. create_task(self._rollout_task(...)))
        if spec.kind == "charge":
            for c in nodes:
                if not isinstance(c, ast.Call) or _pos(c) <= acq_end:
                    continue
                if _in_subtree(parents, c, excluded, fdef):
                    continue
                if id(c) in release_by_id:
                    continue
                q = callees.get(id(c))
                if q and _releases_transitively(pctx, q, spec):
                    events.append((_pos(c), "transfer", c))
        if first_store is not None:
            events.append((first_store, "stop", call))
        events.sort(key=lambda e: (e[0], e[1]))

        # ---- first decisive event ------------------------------------ #
        discharge = None
        discharge_kind = None
        partial: List[ast.AST] = []
        degraded = False
        acq_arms = _branch_arms(parents, call, fdef)
        if acq_if is not None and not negated:
            # the true branch IS the obligation path: releases there are
            # unconditional relative to the acquire
            acq_arms = acq_arms | {(id(acq_if), "body")}
        for pos_, kind, node in events:
            if kind in ("escape", "stop"):
                degraded = True
                break
            # release: handler-arm releases protect the exception path
            # but do not close the fall-through obligation; extra If arms
            # (unless guarded by the handle's own truthiness) are partial
            arms = _branch_arms(parents, node, fdef)
            extra = arms - acq_arms
            in_handler = any(a[1] == "handler" for a in extra)
            cond = [a for a in extra if a[1] in ("body", "orelse")]
            if in_handler:
                partial.append(node)
                continue
            if cond and not _truthiness_guarded(
                parents, node, fdef, handle
            ):
                partial.append(node)
                continue
            discharge = node
            discharge_kind = kind
            break
        if degraded:
            continue
        if discharge_kind == "transfer":
            # ownership handed to a callee that (transitively) releases:
            # the obligation is discharged and the window degrades with
            # it — the release lives in another function, so "wrap it in
            # a finally here" would be wrong advice (the v2/v3 call-graph
            # contract: resolution discharges, it never accuses)
            continue

        acq_desc = ast.unparse(call.func) + "()"
        if discharge is None:
            if spec.kind == "context":
                yield (
                    RULE_LEAK_EXC, fi.path, call.lineno,
                    f"{spec.name} acquired by {acq_desc} is never entered "
                    "via 'with' — the span never opens and never closes"
                    + owns_hint,
                )
                continue
            where_txt = (
                f" (released only on some paths: line "
                f"{partial[0].lineno})" if partial else ""
            )
            rule = RULE_ASYM if spec.kind == "charge" else RULE_LEAK_EXC
            verb = "charged" if spec.kind == "charge" else "acquired"
            fix = (
                "refund it on every exit (try/finally), hand it to a "
                "callee that settles it, or annotate the deliberate "
                "handoff with "
                f"'# arealint: owns({spec.name}, <reason>)'"
                if spec.kind == "charge" else
                "release it in a finally / context manager, or annotate "
                "the deliberate handoff with "
                f"'# arealint: owns({spec.name}, <reason>)'"
            )
            yield (
                rule, fi.path, call.lineno,
                f"{spec.name} {verb} by {acq_desc} is not released on "
                f"every path out of {fi.name}(){where_txt} — {fix}"
                + owns_hint,
            )
            continue

        # ---- risky window between acquire and discharge -------------- #
        d_start = _pos(discharge)
        d_stmt = _stmt_of(parents, discharge, fdef)
        protectors = [
            n for _, k, n in events if k in ("release", "transfer")
        ]
        first_await = None
        has_sync_risk = False
        for n in nodes:
            if not isinstance(n, (ast.Await, ast.Call, ast.Raise)):
                continue
            if not (acq_end < _pos(n) < d_start):
                continue
            if _in_subtree(parents, n, excluded, fdef):
                continue
            if _stmt_of(parents, n, fdef) is d_stmt:
                continue
            if isinstance(n, ast.Call) and any(n is p for p in protectors):
                continue
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Name)
                and n.func.id in _PURE_BUILTINS
            ):
                continue  # len()/range()/... don't realistically raise
            if _after_release_in_same_handler(
                parents, n, fdef, protectors
            ):
                # release-then-reraise cleanup arm: the obligation is
                # already settled by the time this node runs
                continue
            if isinstance(n, ast.Await) and (
                n.value is call or any(n.value is p for p in protectors)
            ):
                continue
            if _is_protected(
                parents, n, fdef, protectors,
                cancel=isinstance(n, ast.Await),
            ):
                continue
            if isinstance(n, ast.Await):
                if first_await is None:
                    first_await = n
            else:
                has_sync_risk = True
        if first_await is not None:
            yield (
                RULE_LEAK_CANCEL, fi.path, first_await.lineno,
                f"this await can be cancelled while {spec.name} (acquired "
                f"line {call.lineno} by {acq_desc}) is held — a "
                "CancelledError skips the release on line "
                f"{discharge.lineno}; wrap the window in try/finally "
                "(note: 'except Exception' does not catch CancelledError)"
                + owns_hint,
            )
        elif has_sync_risk:
            rule = RULE_ASYM if spec.kind == "charge" else RULE_LEAK_EXC
            what = "the refund" if spec.kind == "charge" else "the release"
            yield (
                rule, fi.path, call.lineno,
                f"{spec.name} acquired by {acq_desc} reaches {what} on "
                f"line {discharge.lineno} only if nothing in between "
                "raises — move the release into a finally / context "
                "manager (or annotate "
                f"'# arealint: owns({spec.name}, <reason>)')" + owns_hint,
            )


def _truthiness_guarded(parents, node, fdef, handle: Optional[str]) -> bool:
    """Is ``node`` under ``if <handle>:`` / ``if <handle> is not None:``?
    (The release is guarded by whether the acquire happened — standard
    conditional-acquire cleanup, not a partial release.)"""
    if handle is None:
        return False
    cur, child = parents.get(node), node
    while cur is not None and child is not fdef:
        if isinstance(cur, ast.If) and _contains(cur.body, child):
            t = cur.test
            if isinstance(t, (ast.Name, ast.Attribute)) and (
                _dotted(t) == handle
            ):
                return True
            if (
                isinstance(t, ast.Compare)
                and _dotted(t.left) == handle
                and len(t.ops) == 1
                and isinstance(t.ops[0], ast.IsNot)
            ):
                return True
        child, cur = cur, parents.get(cur)
    return False


def _after_release_in_same_handler(parents, node, fdef, protectors) -> bool:
    """True when ``node`` sits in an except handler that already released
    the obligation earlier in the handler body (release-then-reraise)."""
    cur, child = parents.get(node), node
    while cur is not None and child is not fdef:
        if isinstance(cur, ast.ExceptHandler):
            for p in protectors:
                if _in_expr(cur, p) and _pos(p) <= _pos(node):
                    return True
        child, cur = cur, parents.get(cur)
    return False


def _is_protected(parents, risky, fdef, protectors, cancel: bool) -> bool:
    """A risky node is protected when a release event sits in the
    finalbody of an enclosing try — or in a handler, except that only
    handlers catching CancelledError protect an ``await``."""
    for t, arm in _enclosing_tries(parents, risky, fdef):
        if arm not in ("body", "orelse"):
            continue
        for p in protectors:
            for fb in t.finalbody:
                if _in_expr(fb, p) or fb is p:
                    return True
        for h in t.handlers:
            if cancel and not _handler_cancel_safe(h):
                continue
            for p in protectors:
                if _in_expr(h, p):
                    return True
    return False


# --------------------------------------------------------------------- #
# double-release
# --------------------------------------------------------------------- #


def _acquire_handle(parents, call, spec, fdef) -> Optional[str]:
    """The local Name an acquire binds/targets (None: untrackable)."""
    if isinstance(call.func, ast.Attribute):
        m = call.func.attr
        if m in spec.handle_from_arg:
            h = _dotted(call.args[0]) if call.args else None
            return h if h and "." not in h else None
        if m in spec.handle_is_receiver:
            h = _dotted(call.func.value)
            return h if h and "." not in h else None
    h, _bound = _bound_handle(parents, call, fdef)
    return h if h and "." not in h else None


def _check_double_release(fi, parents, nodes, acquire_sites, release_sites):
    fdef = fi.node
    by_handle: Dict[Tuple[str, str], List[ast.Call]] = {}
    for c, spec, h in release_sites:
        if spec.kind != "handle" or not h or "." in h:
            continue
        by_handle.setdefault((spec.name, h), []).append(c)
    acquired_handles = set()
    acquire_pos: Dict[str, Tuple[int, int]] = {}
    for c, spec in acquire_sites:
        h = _acquire_handle(parents, c, spec, fdef)
        if h:
            acquired_handles.add((spec.name, h))
            acquire_pos[h] = _pos(c)
    for (sname, h), rels in sorted(by_handle.items()):
        if (sname, h) not in acquired_handles:
            continue  # settle-elsewhere pattern: out of scope
        stores = _handle_stores(nodes, h)
        rels.sort(key=_pos)
        # (a) two releases on one straight-line path
        flagged = set()
        for i, r1 in enumerate(rels):
            for r2 in rels[i + 1:]:
                if id(r2) in flagged:
                    continue
                if any(_pos(r1) < s < _pos(r2) for s in stores):
                    continue
                a1 = _branch_arms(parents, r1, fdef)
                a2 = _branch_arms(parents, r2, fdef)
                if a1 <= a2:
                    flagged.add(id(r2))
                    yield (
                        RULE_DOUBLE, fi.path, r2.lineno,
                        f"{sname} ({h!r}) is released again here — already "
                        f"released on line {r1.lineno} with no re-acquire "
                        "in between; the second release underflows the "
                        "refcount (double free)",
                    )
        # (b) one release inside a loop, handle acquired outside it
        for r in rels:
            if id(r) in flagged:
                continue
            loop = next(
                (
                    a for a in _chain(parents, r, fdef)
                    if isinstance(a, (ast.While, ast.For, ast.AsyncFor))
                ),
                None,
            )
            if loop is None:
                continue
            apos = acquire_pos.get(h)
            if apos is None or _pos(loop) <= apos:
                continue  # acquired inside the loop: rebound per iteration
            if any(_pos(loop) < s for s in stores):
                continue
            yield (
                RULE_DOUBLE, fi.path, r.lineno,
                f"{sname} ({h!r}) is released inside a loop but acquired "
                f"once outside it (line {apos[0]}) — the second iteration "
                "double-frees it",
            )


# --------------------------------------------------------------------- #
# release-without-acquire
# --------------------------------------------------------------------- #


def _check_release_without_acquire(
    fi, parents, nodes, acquire_sites, release_sites
):
    fdef = fi.node
    for r, spec, h in release_sites:
        if spec.kind == "handle" and (not h or "." in h):
            continue
        matching = []
        for c, aspec in acquire_sites:
            if aspec is not spec:
                continue
            if spec.kind == "handle" and (
                _acquire_handle(parents, c, spec, fdef) != h
            ):
                continue
            matching.append(c)
        if not matching:
            continue  # settle-elsewhere refund: out of scope
        def _cond_arms(n):
            # only If arms and except handlers make an acquire
            # conditional here — try bodies and loop bodies execute on
            # the fall-through path
            return frozenset(
                a for a in _branch_arms(parents, n, fdef)
                if a[1] in ("body", "orelse", "handler")
            )

        r_arms = _cond_arms(r)
        if any(
            _cond_arms(a) <= r_arms and _pos(a) < _pos(r)
            for a in matching
        ):
            continue  # some acquire dominates the release
        if spec.kind == "handle":
            if _truthiness_guarded(parents, r, fdef, h):
                continue
            # a binding before the conditional acquire (``h = []``) makes
            # the unconditional release well-defined — the acquire's own
            # assignment target does not count (compare statement starts)
            first_stmt = min(
                _pos(_stmt_of(parents, a, fdef)) for a in matching
            )
            if any(s < first_stmt for s in _handle_stores(nodes, h)):
                continue
        acq_lines = ", ".join(str(a.lineno) for a in matching)
        yield (
            RULE_REL_NO_ACQ, fi.path, r.lineno,
            f"{spec.name} is released here on every path, but the "
            f"matching acquire (line {acq_lines}) happens only on some — "
            "the no-acquire path releases a resource it never held; "
            "guard the release with the same condition (or the handle's "
            "truthiness)",
        )


# --------------------------------------------------------------------- #
# driver + rule registration
# --------------------------------------------------------------------- #


def _functions_of(mod) -> Iterator[FunctionInfo]:
    yield from mod.functions.values()
    for ci in mod.classes.values():
        yield from ci.methods.values()


def _compute(pctx: ProjectContext) -> List[Tuple[str, str, int, str]]:
    catalog = getattr(pctx.config, "resources", None)
    if not catalog or not len(catalog):
        return []
    out: List[Tuple[str, str, int, str]] = []
    acquire_names = catalog.acquire_names
    for mname in sorted(pctx.project.modules):
        mod = pctx.project.modules[mname]
        attr_types = None
        aliases = None
        for fi in sorted(
            _functions_of(mod), key=lambda f: _pos(f.node)
        ):
            # pre-scan: pay typestate inference only where an acquire
            # name appears (the v3 donation-rule pattern)
            present = set()
            for n in walk_excluding_nested(fi.node):
                if isinstance(n, ast.Call):
                    f = n.func
                    if isinstance(f, ast.Attribute):
                        present.add(f.attr)
                    elif isinstance(f, ast.Name):
                        present.add(f.id)
            if not (present & acquire_names):
                continue
            if attr_types is None:
                attr_types = _module_attr_types(pctx, mod)
                aliases = collect_aliases(mod.tree)
            out.extend(
                _analyze_function(pctx, mod, fi, catalog, attr_types, aliases)
            )
    out.sort(key=lambda t: (t[1], t[2], t[0]))
    return out


def _findings(pctx: ProjectContext):
    cached = getattr(pctx, "_lifecycle_findings", None)
    if cached is None:
        cached = _compute(pctx)
        pctx._lifecycle_findings = cached
    return cached


def _family(rule_id: str):
    def check(pctx: ProjectContext):
        for rid, path, line, msg in _findings(pctx):
            if rid == rule_id:
                yield path, line, msg
    return check


project_rule(
    RULE_LEAK_EXC, SEVERITY_ERROR,
    "a cataloged resource acquire whose release is not dominated by a "
    "finally/context manager — an exception in between leaks it "
    "(pages, leases, sessions, spans)",
)(_family(RULE_LEAK_EXC))

project_rule(
    RULE_LEAK_CANCEL, SEVERITY_ERROR,
    "an await between a resource acquire and its release with no "
    "try/finally — task cancellation leaks the resource (the PR-10 "
    "orphaned-slot cancel-race shape)",
)(_family(RULE_LEAK_CANCEL))

project_rule(
    RULE_DOUBLE, SEVERITY_ERROR,
    "the same handle released twice on one straight-line path (or once "
    "inside a loop it was acquired outside of) — refcount underflow",
)(_family(RULE_DOUBLE))

project_rule(
    RULE_REL_NO_ACQ, SEVERITY_ERROR,
    "a release whose matching acquire happens only on some paths — the "
    "no-acquire path releases a resource it never held",
)(_family(RULE_REL_NO_ACQ))

project_rule(
    RULE_ASYM, SEVERITY_ERROR,
    "a counted charge (token bucket, queue entry, slot grant) whose "
    "refund is unreachable on an error path — the budget drifts until "
    "the tenant/fleet starves",
)(_family(RULE_ASYM))
