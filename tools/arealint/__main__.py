"""CLI: ``python -m tools.arealint [paths...]``.

Exit codes (stable — CI keys off them):

- ``0`` — clean, or only ``warn``-severity findings
- ``1`` — at least one ``error``-severity finding survived the baseline
- ``2`` — usage error (bad flag, unknown rule, malformed baseline)

The default scan covers the stack AND its tooling/tests (``areal_tpu/
tools/ tests/``); test files run under the relaxed profile
(docs/static_analysis.md "Path profiles"). ``--jobs N`` fans the
per-file pass out over a process pool — output order is deterministic
either way.
"""

import argparse
import json
import os
import pathlib
import sys
from typing import List, Optional

from tools.arealint import (
    DEFAULT_BASELINE, BaselineError, RULES, all_rules, apply_baseline,
    default_repo_root, load_baseline, scan_paths,
)

DEFAULT_PATHS = ["areal_tpu", "tools", "tests"]


def _default_jobs() -> int:
    # leave a core for the driver; the project pass is serial anyway
    return max(1, min(4, (os.cpu_count() or 2) - 1))


def changed_paths(scan_paths_arg, root, stream) -> List[str]:
    """The ``--changed-only`` file set: repo-relative paths read from
    ``stream`` (the caller pipes ``git diff --name-only <ref>`` in),
    restricted to existing ``*.py`` files inside the requested scan
    paths. Deleted files (in the diff but gone from disk) and files
    outside the scan set are dropped. Pure stdlib — the linter never
    runs git itself. The result is exactly what passing the surviving
    files as explicit CLI paths would scan (pinned by a test)."""
    roots = [pathlib.Path(p).resolve() for p in scan_paths_arg]
    out: List[str] = []
    seen = set()
    for line in stream:
        rel = line.strip()
        if not rel or not rel.endswith(".py"):
            continue
        p = pathlib.Path(rel)
        if not p.is_absolute():
            p = pathlib.Path(root) / rel
        p = p.resolve()
        if not p.is_file() or str(p) in seen:
            continue
        in_scope = False
        for r in roots:
            if p == r:
                in_scope = True
                break
            try:
                p.relative_to(r)
                in_scope = True
                break
            except ValueError:
                continue
        if in_scope:
            seen.add(str(p))
            out.append(str(p))
    return sorted(out)


def _build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m tools.arealint",
        description="JAX/TPU-aware whole-program static analysis for the "
        "areal_tpu stack (docs/static_analysis.md)",
    )
    ap.add_argument(
        "paths", nargs="*", default=None,
        help=f"files/dirs to scan (default: {' '.join(DEFAULT_PATHS)})",
    )
    ap.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
        help="output format (json/sarif are stable for tooling)",
    )
    ap.add_argument(
        "--rules", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    ap.add_argument(
        "--jobs", type=int, default=None, metavar="N",
        help="process-pool width for the per-file pass "
        f"(default: {_default_jobs()} on this machine; 1 = serial)",
    )
    ap.add_argument(
        "--baseline", default=None,
        help=f"baseline file (default: <repo>/{DEFAULT_BASELINE})",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline (report every finding)",
    )
    ap.add_argument(
        "--no-project", action="store_true",
        help="skip the whole-program pass (file rules only)",
    )
    ap.add_argument(
        "--changed-only", action="store_true",
        help="scan only the files named on STDIN (one repo-relative "
        "path per line — pipe `git diff --name-only <ref>` in); "
        "non-Python paths and files outside the scan set are ignored. "
        "Equivalent to passing the surviving files as explicit paths, "
        "so pre-commit stays under ~2 s. No subprocess runs inside the "
        "linter: the caller owns the git invocation (see `make "
        "lint-fast`).",
    )
    ap.add_argument(
        "--since", metavar="REF", default=None,
        help="label for the diff base (display only — the caller "
        "already resolved it with `git diff --name-only REF`); "
        "requires --changed-only",
    )
    ap.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog (file + project rules) and exit",
    )
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    ap = _build_parser()
    args = ap.parse_args(argv)  # argparse exits 2 on usage errors

    catalog = all_rules()
    if args.list_rules:
        width = max(len(r) for r in catalog)
        for rid in sorted(catalog):
            r = catalog[rid]
            kind = "file" if rid in RULES else "project"
            print(f"{r.id:<{width}}  {r.severity:<5}  {kind:<7}  {r.doc}")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in catalog]
        if unknown:
            print(
                f"unknown rule(s): {', '.join(unknown)} "
                f"(see --list-rules)", file=sys.stderr,
            )
            return 2
    if args.jobs is not None and args.jobs < 1:
        print("--jobs must be >= 1", file=sys.stderr)
        return 2
    if args.since and not args.changed_only:
        print("--since requires --changed-only", file=sys.stderr)
        return 2

    root = default_repo_root()
    paths = args.paths or [str(root / p) for p in DEFAULT_PATHS]
    if args.changed_only:
        if sys.stdin.isatty():
            print(
                "--changed-only reads the file list from stdin — pipe "
                "`git diff --name-only <ref>` in (see `make lint-fast`)",
                file=sys.stderr,
            )
            return 2
        paths = changed_paths(paths, root, sys.stdin)
        if not paths and args.format == "text":
            label = f" vs {args.since}" if args.since else ""
            print(
                "arealint clean (no changed Python files"
                f"{label} inside the scan set)."
            )
            return 0
        # json/sarif consumers get the SAME zero-findings document an
        # empty scan produces — the machine formats stay parseable on
        # docs-only diffs (scan_paths([]) yields no findings)
    findings = scan_paths(
        paths,
        rules=rules,
        # changed-only is the pre-commit fast path: a handful of files
        # scans faster serially than a process pool spins up
        jobs=args.jobs if args.jobs is not None else (
            1 if args.changed_only else _default_jobs()
        ),
        project=not args.no_project,
    )

    entries: List[dict] = []
    if not args.no_baseline:
        bl_path = (
            pathlib.Path(args.baseline)
            if args.baseline else root / DEFAULT_BASELINE
        )
        if args.baseline or bl_path.is_file():
            try:
                entries = load_baseline(bl_path)
            except BaselineError as e:
                print(str(e), file=sys.stderr)
                return 2
    findings, stale = apply_baseline(findings, entries, root=root)

    n_err = sum(1 for f in findings if f.severity == "error")
    n_warn = len(findings) - n_err

    if args.format == "json":
        print(json.dumps({
            "version": 1,
            "findings": [f.to_dict() for f in findings],
            "stale_baseline": stale,
            "errors": n_err,
            "warnings": n_warn,
        }, indent=2))
    elif args.format == "sarif":
        from tools.arealint import sarif

        print(sarif.dumps(findings, root=root, rule_ids=rules))
    else:
        for f in findings:
            print(f"{f.path}:{f.line}: [{f.rule}] {f.severity}: {f.message}")
        for e in stale:
            print(
                f"stale baseline entry (violation fixed — delete it): "
                f"{e['path']} [{e['rule']}] ({e['reason']})"
            )
        if findings:
            print(f"\n{n_err} error(s), {n_warn} warning(s).")
        else:
            print("arealint clean.")
    return 1 if n_err else 0


if __name__ == "__main__":
    sys.exit(main())
