"""CLI: ``python -m tools.arealint [paths...]``.

Exit codes (stable — CI keys off them):

- ``0`` — clean, or only ``warn``-severity findings
- ``1`` — at least one ``error``-severity finding survived the baseline
- ``2`` — usage error (bad flag, unknown rule, malformed baseline)

The default scan covers the stack AND its tooling/tests (``areal_tpu/
tools/ tests/``); test files run under the relaxed profile
(docs/static_analysis.md "Path profiles"). ``--jobs N`` fans the
per-file pass out over a process pool — output order is deterministic
either way.
"""

import argparse
import json
import os
import pathlib
import sys
from typing import List, Optional

from tools.arealint import (
    DEFAULT_BASELINE, BaselineError, RULES, all_rules, apply_baseline,
    default_repo_root, load_baseline, scan_paths,
)

DEFAULT_PATHS = ["areal_tpu", "tools", "tests"]


def _default_jobs() -> int:
    # leave a core for the driver; the project pass is serial anyway
    return max(1, min(4, (os.cpu_count() or 2) - 1))


def _build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m tools.arealint",
        description="JAX/TPU-aware whole-program static analysis for the "
        "areal_tpu stack (docs/static_analysis.md)",
    )
    ap.add_argument(
        "paths", nargs="*", default=None,
        help=f"files/dirs to scan (default: {' '.join(DEFAULT_PATHS)})",
    )
    ap.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
        help="output format (json/sarif are stable for tooling)",
    )
    ap.add_argument(
        "--rules", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    ap.add_argument(
        "--jobs", type=int, default=None, metavar="N",
        help="process-pool width for the per-file pass "
        f"(default: {_default_jobs()} on this machine; 1 = serial)",
    )
    ap.add_argument(
        "--baseline", default=None,
        help=f"baseline file (default: <repo>/{DEFAULT_BASELINE})",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline (report every finding)",
    )
    ap.add_argument(
        "--no-project", action="store_true",
        help="skip the whole-program pass (file rules only)",
    )
    ap.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog (file + project rules) and exit",
    )
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    ap = _build_parser()
    args = ap.parse_args(argv)  # argparse exits 2 on usage errors

    catalog = all_rules()
    if args.list_rules:
        width = max(len(r) for r in catalog)
        for rid in sorted(catalog):
            r = catalog[rid]
            kind = "file" if rid in RULES else "project"
            print(f"{r.id:<{width}}  {r.severity:<5}  {kind:<7}  {r.doc}")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in catalog]
        if unknown:
            print(
                f"unknown rule(s): {', '.join(unknown)} "
                f"(see --list-rules)", file=sys.stderr,
            )
            return 2
    if args.jobs is not None and args.jobs < 1:
        print("--jobs must be >= 1", file=sys.stderr)
        return 2

    root = default_repo_root()
    paths = args.paths or [str(root / p) for p in DEFAULT_PATHS]
    findings = scan_paths(
        paths,
        rules=rules,
        jobs=args.jobs if args.jobs is not None else _default_jobs(),
        project=not args.no_project,
    )

    entries: List[dict] = []
    if not args.no_baseline:
        bl_path = (
            pathlib.Path(args.baseline)
            if args.baseline else root / DEFAULT_BASELINE
        )
        if args.baseline or bl_path.is_file():
            try:
                entries = load_baseline(bl_path)
            except BaselineError as e:
                print(str(e), file=sys.stderr)
                return 2
    findings, stale = apply_baseline(findings, entries, root=root)

    n_err = sum(1 for f in findings if f.severity == "error")
    n_warn = len(findings) - n_err

    if args.format == "json":
        print(json.dumps({
            "version": 1,
            "findings": [f.to_dict() for f in findings],
            "stale_baseline": stale,
            "errors": n_err,
            "warnings": n_warn,
        }, indent=2))
    elif args.format == "sarif":
        from tools.arealint import sarif

        print(sarif.dumps(findings, root=root, rule_ids=rules))
    else:
        for f in findings:
            print(f"{f.path}:{f.line}: [{f.rule}] {f.severity}: {f.message}")
        for e in stale:
            print(
                f"stale baseline entry (violation fixed — delete it): "
                f"{e['path']} [{e['rule']}] ({e['reason']})"
            )
        if findings:
            print(f"\n{n_err} error(s), {n_warn} warning(s).")
        else:
            print("arealint clean.")
    return 1 if n_err else 0


if __name__ == "__main__":
    sys.exit(main())
