"""The mesh model: the SPMD rules' catalog, parsed from
``areal_tpu/parallel/mesh.py`` with ``ast`` — never imported.

Same provenance contract as the counter/fault catalogs
(docs/static_analysis.md "Knob/registry hygiene"): the linter runs in a
bare CI container with no jax, so the single source of truth for mesh
axis names and logical→mesh rules is read statically from the module
that defines them:

- **axis names** come from the ``Mesh(devs, ("data", "fsdp", "ctx",
  "model"))`` construction inside ``make_mesh`` (the tuple literal is
  the authoritative axis order);
- **logical rules** come from the module-level ``DEFAULT_RULES`` dict
  literal (logical axis name → mesh axis name or None=replicated).

Catalog drift fails loudly twice over: the parser returns ``None`` when
the expected shapes are missing (every mesh-axis rule degrades to
no-finding rather than guessing), and ``tests/test_arealint_spmd.py``
pins the parsed axis tuple against the tuple ``make_mesh`` actually
builds at runtime.
"""

import ast
import dataclasses
import pathlib
from typing import Dict, Optional, Tuple

MESH_MODULE = pathlib.Path("areal_tpu") / "parallel" / "mesh.py"


@dataclasses.dataclass(frozen=True)
class MeshModel:
    """Parsed mesh facts the SPMD rules check against."""

    # mesh axis names, in construction order ("data", "fsdp", "ctx", "model")
    axes: Tuple[str, ...]
    # logical axis name -> mesh axis name (None = replicated)
    logical_rules: Optional[Dict[str, Optional[str]]] = None
    # where the model was parsed from (diagnostics only)
    source: str = ""

    @property
    def axis_set(self) -> frozenset:
        return frozenset(self.axes)

    def known_axis(self, name: str) -> bool:
        return name in self.axes


def _mesh_axes_from_tree(tree: ast.Module) -> Optional[Tuple[str, ...]]:
    """The axis tuple of the ``Mesh(devs, (...))`` call. Preference order:
    a call inside a ``def make_mesh``, else any Mesh call in the module —
    ambiguity (two calls with different tuples) degrades to None."""

    def mesh_calls(root) -> list:
        out = []
        for node in ast.walk(root):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else ""
            )
            if name != "Mesh" or len(node.args) < 2:
                continue
            axes_node = node.args[1]
            if isinstance(axes_node, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in axes_node.elts
            ):
                out.append(tuple(e.value for e in axes_node.elts))
        return out

    scopes = [
        n for n in ast.walk(tree)
        if isinstance(n, ast.FunctionDef) and n.name == "make_mesh"
    ]
    found = []
    for scope in scopes:
        found.extend(mesh_calls(scope))
    if not found:
        # no literal tuple inside make_mesh (or no make_mesh at all):
        # fall back to the whole module before giving up
        found = mesh_calls(tree)
    distinct = sorted(set(found))
    return distinct[0] if len(distinct) == 1 else None


def _logical_rules_from_tree(
    tree: ast.Module,
) -> Optional[Dict[str, Optional[str]]]:
    """The module-level ``DEFAULT_RULES = {"logical": "mesh"|None, ...}``
    dict literal; None when absent or not fully literal."""
    for node in tree.body:
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target = node.target
            value = node.value
        else:
            continue
        if not (isinstance(target, ast.Name) and target.id == "DEFAULT_RULES"):
            continue
        if not isinstance(value, ast.Dict):
            return None
        rules: Dict[str, Optional[str]] = {}
        for k, v in zip(value.keys, value.values):
            if not (
                isinstance(k, ast.Constant) and isinstance(k.value, str)
                and isinstance(v, ast.Constant)
                and (v.value is None or isinstance(v.value, str))
            ):
                return None  # computed entry: degrade, never guess
            rules[k.value] = v.value
        return rules
    return None


def parse_mesh_module(path) -> Optional[MeshModel]:
    """MeshModel parsed from a mesh.py-shaped file, or None when the
    expected shapes (Mesh axis tuple) are missing — callers degrade."""
    path = pathlib.Path(path)
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except (OSError, SyntaxError):
        return None
    axes = _mesh_axes_from_tree(tree)
    if not axes:
        return None
    return MeshModel(
        axes=axes,
        logical_rules=_logical_rules_from_tree(tree),
        source=str(path).replace("\\", "/"),
    )


def from_repo(root) -> Optional[MeshModel]:
    p = pathlib.Path(root) / MESH_MODULE
    return parse_mesh_module(p) if p.is_file() else None
