"""arealint — the repo's JAX/TPU-aware static-analysis framework.

A whole-program static analyzer (stdlib-only, never imports repo code)
that keeps the async-RL stack's performance and correctness invariants
enforceable in tier-1 CI. Two rule layers share one driver:

- **file rules** — per-file AST checks (async hygiene, intra-file
  host-sync/retrace/donation, env-knob / counter / fault-point
  catalogs, await-in-lock);
- **project rules** — whole-program checks over a cross-module,
  name-qualified call graph (``project.py`` + ``callgraph.py``):
  cross-module host-sync, thread/asyncio race rules, donation dataflow
  across call boundaries, jit weak-type drift.

See docs/static_analysis.md for the rule catalog, call-graph semantics,
and severity policy.

Usage::

    python -m tools.arealint [paths...] [--format json|sarif] [--jobs N]
    from tools.arealint import scan_paths, scan_source, scan_sources
"""

from tools.arealint.core import (  # noqa: F401
    Config,
    Finding,
    PROJECT_RULES,
    RULES,
    Rule,
    SEVERITY_ERROR,
    SEVERITY_WARN,
    all_rules,
    default_config,
    default_repo_root,
    excluded_rules_for_path,
    has_errors,
    is_test_path,
    project_rule,
    rule,
    scan_paths,
    scan_project_files,
    scan_source,
    scan_sources,
)
from tools.arealint.meshmodel import (  # noqa: F401
    MeshModel,
    parse_mesh_module,
)
from tools.arealint.resources import (  # noqa: F401
    DEFAULT_RESOURCE_DEFS,
    ResourceCatalog,
    ResourceSpec,
    parse_resources,
)
from tools.arealint.wiremodel import (  # noqa: F401
    DEFAULT_WIRE_DEFS,
    ClientCall,
    Endpoint,
    WireDefs,
    WireModel,
    WireSpec,
    build_model,
    parse_client_modules,
    parse_server_module,
    verify_defs,
)
from tools.arealint.project import Project  # noqa: F401
from tools.arealint.callgraph import (  # noqa: F401
    CallGraph,
    build_call_graph,
    thread_context,
)

# Importing the rule modules registers their rules.
from tools.arealint import rules_async  # noqa: E402,F401
from tools.arealint import rules_jax  # noqa: E402,F401
from tools.arealint import rules_hygiene  # noqa: E402,F401
from tools.arealint import rules_concurrency  # noqa: E402,F401
from tools.arealint import rules_dataflow  # noqa: E402,F401
from tools.arealint import rules_spmd  # noqa: E402,F401
from tools.arealint import rules_lifecycle  # noqa: E402,F401
from tools.arealint import rules_wire  # noqa: E402,F401

from tools.arealint.baseline import (  # noqa: F401
    DEFAULT_BASELINE,
    BaselineError,
    apply_baseline,
    load_baseline,
    norm_path,
)

LEGACY_ASYNC_RULES = (
    "bare-gather",
    "discarded-task",
    "live-checkpoint-rmtree",
    "sleep-in-async",
)
