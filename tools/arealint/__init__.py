"""arealint — the repo's JAX/TPU-aware static-analysis framework.

A rule-registry AST linter (stdlib-only, never imports repo code) that
keeps the async-RL stack's performance and correctness invariants
enforceable in tier-1 CI: async hygiene, host-sync-free hot paths,
retrace/donation discipline, and the env-knob / counter / fault-point
catalogs. See docs/static_analysis.md for the rule catalog and policies.

Usage::

    python -m tools.arealint [paths...] [--format json]
    from tools.arealint import scan_paths, scan_source, RULES
"""

from tools.arealint.core import (  # noqa: F401
    Config,
    Finding,
    RULES,
    Rule,
    SEVERITY_ERROR,
    SEVERITY_WARN,
    default_config,
    default_repo_root,
    has_errors,
    rule,
    scan_paths,
    scan_source,
)

# Importing the rule modules registers their rules.
from tools.arealint import rules_async  # noqa: E402,F401
from tools.arealint import rules_jax  # noqa: E402,F401
from tools.arealint import rules_hygiene  # noqa: E402,F401

from tools.arealint.baseline import (  # noqa: F401
    DEFAULT_BASELINE,
    BaselineError,
    apply_baseline,
    load_baseline,
    norm_path,
)

LEGACY_ASYNC_RULES = (
    "bare-gather",
    "discarded-task",
    "live-checkpoint-rmtree",
    "sleep-in-async",
)
