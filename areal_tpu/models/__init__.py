"""TPU-native model layer: packed-varlen transformer + HF family converters.

Counterpart of the reference's ``realhf/impl/model/nn`` (ReaLModel) and
``realhf/api/from_hf`` converter registry (SURVEY.md §2.4-§2.5) — redesigned
as functional JAX: parameters are plain pytrees with stacked layer axes
(``lax.scan`` over layers), sharding is declarative logical-axis metadata
consumed by ``areal_tpu.parallel``.
"""

from areal_tpu.models.config import ModelConfig  # noqa: F401
