"""HF ↔ areal_tpu checkpoint converters for all supported model families.

TPU-native counterpart of the reference's ``realhf/api/from_hf/*`` registry
(llama/qwen2/qwen3/gpt2/gemma/mistral/mixtral, ~1390 LoC) consumed by
``ReaLModel.from_/to_{family}`` (``realhf/impl/model/nn/real_llm_api.py:898``).

Design: converters are pure functions over ``Dict[str, np.ndarray]`` (flat HF
state dicts) ↔ our stacked-layer pytrees. IO helpers read/write safetensors +
config.json. torch never appears on this path — HF tensors arrive as numpy
(the safetensors reader yields numpy directly).

Note the torch/HF ``nn.Linear`` convention stores weights ``[out, in]``; ours
are ``[in, out]`` (right-multiplication ``x @ w``), so linear weights are
transposed on the way through. GPT-2's ``Conv1D`` is already ``[in, out]``.
"""

import dataclasses
import json
import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from areal_tpu.models.config import ModelConfig, MoEConfig

HFState = Dict[str, np.ndarray]


@dataclasses.dataclass
class HFFamily:
    name: str
    hf_model_type: str
    config_from_hf: Callable[[Dict[str, Any]], ModelConfig]
    config_to_hf: Callable[[ModelConfig], Dict[str, Any]]
    params_from_hf: Callable[[HFState, ModelConfig], Dict[str, Any]]
    params_to_hf: Callable[[Dict[str, Any], ModelConfig], HFState]


HF_FAMILIES: Dict[str, HFFamily] = {}


def register_hf_family(family: HFFamily):
    HF_FAMILIES[family.name] = family


# --------------------------------------------------------------------------- #
# Llama-like families (llama, mistral, qwen2, qwen3, gemma)
# --------------------------------------------------------------------------- #


def _rope_fields(hf: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    rs = hf.get("rope_scaling") or None
    if rs:
        typ = rs.get("rope_type", rs.get("type"))
        if typ in ("default", None):
            return out
        out["rotary_scaling_type"] = typ
        out["rotary_scaling_factor"] = rs.get("factor", 1.0)
        if typ == "llama3":
            out["rotary_low_freq_factor"] = rs.get("low_freq_factor", 1.0)
            out["rotary_high_freq_factor"] = rs.get("high_freq_factor", 4.0)
            out["rotary_original_max_position"] = rs.get(
                "original_max_position_embeddings", 8192
            )
    return out


def _llama_like_config_from_hf(
    hf: Dict[str, Any],
    *,
    qkv_bias: bool = False,
    qk_layernorm: bool = False,
    gemma: bool = False,
    sliding_window: bool = False,
) -> ModelConfig:
    n_q = hf["num_attention_heads"]
    head_dim = hf.get("head_dim") or hf["hidden_size"] // n_q
    return ModelConfig(
        n_layers=hf["num_hidden_layers"],
        n_q_heads=n_q,
        n_kv_heads=hf.get("num_key_value_heads") or n_q,
        head_dim=head_dim,
        hidden_dim=hf["hidden_size"],
        intermediate_dim=hf["intermediate_size"],
        vocab_size=hf["vocab_size"],
        n_positions=hf.get("max_position_embeddings", 32768),
        layer_norm_type="gemma" if gemma else "rms",
        layer_norm_epsilon=hf.get("rms_norm_eps", 1e-6),
        use_attention_bias=qkv_bias or bool(hf.get("attention_bias", False)),
        qk_layernorm=qk_layernorm,
        sliding_window=(hf.get("sliding_window") if sliding_window else None),
        rotary_base=hf.get("rope_theta", 10000.0),
        activation_function={"gelu_pytorch_tanh": "gelu_pytorch_tanh"}.get(
            hf.get("hidden_act", "silu"), hf.get("hidden_act", "silu")
        ),
        tied_embedding=bool(hf.get("tie_word_embeddings", False)) or gemma,
        normalize_embed=gemma,
        **_rope_fields(hf),
    )


def _llama_like_config_to_hf(cfg: ModelConfig, model_type: str) -> Dict[str, Any]:
    hf: Dict[str, Any] = {
        "model_type": model_type,
        "architectures": [_ARCH_NAMES.get(model_type, "LlamaForCausalLM")],
        "hidden_size": cfg.hidden_dim,
        "intermediate_size": cfg.intermediate_dim,
        "num_hidden_layers": cfg.n_layers,
        "num_attention_heads": cfg.n_q_heads,
        "num_key_value_heads": cfg.n_kv_heads,
        "head_dim": cfg.head_dim,
        "vocab_size": cfg.vocab_size,
        "max_position_embeddings": cfg.n_positions,
        "rms_norm_eps": cfg.layer_norm_epsilon,
        "rope_theta": cfg.rotary_base,
        "hidden_act": cfg.activation_function,
        "tie_word_embeddings": cfg.tied_embedding,
        "attention_bias": cfg.use_attention_bias,
    }
    if cfg.sliding_window is not None:
        hf["sliding_window"] = cfg.sliding_window
    if cfg.rotary_scaling_type is not None:
        rs = {"rope_type": cfg.rotary_scaling_type, "factor": cfg.rotary_scaling_factor}
        if cfg.rotary_scaling_type == "llama3":
            rs.update(
                low_freq_factor=cfg.rotary_low_freq_factor,
                high_freq_factor=cfg.rotary_high_freq_factor,
                original_max_position_embeddings=cfg.rotary_original_max_position,
            )
        hf["rope_scaling"] = rs
    return hf


_ARCH_NAMES = {
    "llama": "LlamaForCausalLM",
    "mistral": "MistralForCausalLM",
    "qwen2": "Qwen2ForCausalLM",
    "qwen3": "Qwen3ForCausalLM",
    "gemma": "GemmaForCausalLM",
    "gpt2": "GPT2LMHeadModel",
    "mixtral": "MixtralForCausalLM",
}


def _stack(sd: HFState, pattern: str, n_layers: int, transpose: bool = False):
    mats = []
    for i in range(n_layers):
        m = np.asarray(sd[pattern.format(i=i)])
        mats.append(m.T if transpose else m)
    return np.stack(mats)


def _llama_like_params_from_hf(sd: HFState, cfg: ModelConfig) -> Dict[str, Any]:
    L = cfg.n_layers
    p = "model.layers.{i}."
    attn: Dict[str, Any] = {
        "wq": _stack(sd, p + "self_attn.q_proj.weight", L, True),
        "wk": _stack(sd, p + "self_attn.k_proj.weight", L, True),
        "wv": _stack(sd, p + "self_attn.v_proj.weight", L, True),
        "wo": _stack(sd, p + "self_attn.o_proj.weight", L, True),
    }
    if cfg.use_attention_bias:
        attn["bq"] = _stack(sd, p + "self_attn.q_proj.bias", L)
        attn["bk"] = _stack(sd, p + "self_attn.k_proj.bias", L)
        attn["bv"] = _stack(sd, p + "self_attn.v_proj.bias", L)
    if cfg.qk_layernorm:
        attn["q_norm"] = _stack(sd, p + "self_attn.q_norm.weight", L)
        attn["k_norm"] = _stack(sd, p + "self_attn.k_norm.weight", L)
    if cfg.mlp_type == "moe":
        X = cfg.moe.num_experts
        mlp = {
            "router": _stack(sd, p + "block_sparse_moe.gate.weight", L, True),
            "w_gate": np.stack(
                [
                    np.stack(
                        [
                            np.asarray(
                                sd[f"model.layers.{i}.block_sparse_moe.experts.{j}.w1.weight"]
                            ).T
                            for j in range(X)
                        ]
                    )
                    for i in range(L)
                ]
            ),
            "w_down": np.stack(
                [
                    np.stack(
                        [
                            np.asarray(
                                sd[f"model.layers.{i}.block_sparse_moe.experts.{j}.w2.weight"]
                            ).T
                            for j in range(X)
                        ]
                    )
                    for i in range(L)
                ]
            ),
            "w_up": np.stack(
                [
                    np.stack(
                        [
                            np.asarray(
                                sd[f"model.layers.{i}.block_sparse_moe.experts.{j}.w3.weight"]
                            ).T
                            for j in range(X)
                        ]
                    )
                    for i in range(L)
                ]
            ),
        }
    else:
        mlp = {
            "w_gate": _stack(sd, p + "mlp.gate_proj.weight", L, True),
            "w_up": _stack(sd, p + "mlp.up_proj.weight", L, True),
            "w_down": _stack(sd, p + "mlp.down_proj.weight", L, True),
        }
    params: Dict[str, Any] = {
        "embed": {"weight": np.asarray(sd["model.embed_tokens.weight"])},
        "layers": {
            "ln1": {"weight": _stack(sd, p + "input_layernorm.weight", L)},
            "attn": attn,
            "ln2": {"weight": _stack(sd, p + "post_attention_layernorm.weight", L)},
            "mlp": mlp,
        },
        "final_ln": {"weight": np.asarray(sd["model.norm.weight"])},
    }
    if cfg.is_critic:
        pass  # critic head is never loaded from a CausalLM checkpoint
    elif not cfg.tied_embedding:
        params["head"] = {"weight": np.asarray(sd["lm_head.weight"]).T}
    return params


def _llama_like_params_to_hf(params: Dict[str, Any], cfg: ModelConfig) -> HFState:
    sd: HFState = {"model.embed_tokens.weight": np.asarray(params["embed"]["weight"])}
    lp = params["layers"]
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        sd[p + "input_layernorm.weight"] = np.asarray(lp["ln1"]["weight"][i])
        sd[p + "post_attention_layernorm.weight"] = np.asarray(lp["ln2"]["weight"][i])
        a = lp["attn"]
        sd[p + "self_attn.q_proj.weight"] = np.asarray(a["wq"][i]).T
        sd[p + "self_attn.k_proj.weight"] = np.asarray(a["wk"][i]).T
        sd[p + "self_attn.v_proj.weight"] = np.asarray(a["wv"][i]).T
        sd[p + "self_attn.o_proj.weight"] = np.asarray(a["wo"][i]).T
        if cfg.use_attention_bias:
            sd[p + "self_attn.q_proj.bias"] = np.asarray(a["bq"][i])
            sd[p + "self_attn.k_proj.bias"] = np.asarray(a["bk"][i])
            sd[p + "self_attn.v_proj.bias"] = np.asarray(a["bv"][i])
        if cfg.qk_layernorm:
            sd[p + "self_attn.q_norm.weight"] = np.asarray(a["q_norm"][i])
            sd[p + "self_attn.k_norm.weight"] = np.asarray(a["k_norm"][i])
        m = lp["mlp"]
        if cfg.mlp_type == "moe":
            sd[p + "block_sparse_moe.gate.weight"] = np.asarray(m["router"][i]).T
            for j in range(cfg.moe.num_experts):
                e = p + f"block_sparse_moe.experts.{j}."
                sd[e + "w1.weight"] = np.asarray(m["w_gate"][i, j]).T
                sd[e + "w2.weight"] = np.asarray(m["w_down"][i, j]).T
                sd[e + "w3.weight"] = np.asarray(m["w_up"][i, j]).T
        else:
            sd[p + "mlp.gate_proj.weight"] = np.asarray(m["w_gate"][i]).T
            sd[p + "mlp.up_proj.weight"] = np.asarray(m["w_up"][i]).T
            sd[p + "mlp.down_proj.weight"] = np.asarray(m["w_down"][i]).T
    sd["model.norm.weight"] = np.asarray(params["final_ln"]["weight"])
    if cfg.is_critic:
        pass
    elif not cfg.tied_embedding:
        sd["lm_head.weight"] = np.asarray(params["head"]["weight"]).T
    return sd


def _register_llama_like(name: str, **cfg_kwargs):
    register_hf_family(
        HFFamily(
            name=name,
            hf_model_type=name,
            config_from_hf=lambda hf, kw=cfg_kwargs: _llama_like_config_from_hf(
                hf, **kw
            ),
            config_to_hf=lambda cfg, n=name: _llama_like_config_to_hf(cfg, n),
            params_from_hf=_llama_like_params_from_hf,
            params_to_hf=_llama_like_params_to_hf,
        )
    )


_register_llama_like("llama")
_register_llama_like("mistral", sliding_window=True)
_register_llama_like("qwen2", qkv_bias=True)
_register_llama_like("qwen3", qk_layernorm=True)
_register_llama_like("gemma", gemma=True)


# --------------------------------------------------------------------------- #
# Mixtral (llama-like + MoE)
# --------------------------------------------------------------------------- #


def _mixtral_config_from_hf(hf: Dict[str, Any]) -> ModelConfig:
    base = _llama_like_config_from_hf(hf, sliding_window=True)
    return dataclasses.replace(
        base,
        mlp_type="moe",
        moe=MoEConfig(
            num_experts=hf["num_local_experts"],
            top_k=hf["num_experts_per_tok"],
            aux_loss_coeff=hf.get("router_aux_loss_coef", 0.0),
            norm_topk_prob=True,
        ),
    )


def _mixtral_config_to_hf(cfg: ModelConfig) -> Dict[str, Any]:
    hf = _llama_like_config_to_hf(cfg, "mixtral")
    hf["num_local_experts"] = cfg.moe.num_experts
    hf["num_experts_per_tok"] = cfg.moe.top_k
    return hf


register_hf_family(
    HFFamily(
        name="mixtral",
        hf_model_type="mixtral",
        config_from_hf=_mixtral_config_from_hf,
        config_to_hf=_mixtral_config_to_hf,
        params_from_hf=_llama_like_params_from_hf,
        params_to_hf=_llama_like_params_to_hf,
    )
)


# --------------------------------------------------------------------------- #
# GPT-2
# --------------------------------------------------------------------------- #


def _gpt2_config_from_hf(hf: Dict[str, Any]) -> ModelConfig:
    n_head = hf["n_head"]
    return ModelConfig(
        n_layers=hf["n_layer"],
        n_q_heads=n_head,
        n_kv_heads=n_head,
        head_dim=hf["n_embd"] // n_head,
        hidden_dim=hf["n_embd"],
        intermediate_dim=hf.get("n_inner") or 4 * hf["n_embd"],
        vocab_size=hf["vocab_size"],
        n_positions=hf["n_positions"],
        layer_norm_type="layer",
        layer_norm_epsilon=hf.get("layer_norm_epsilon", 1e-5),
        use_attention_bias=True,
        use_attn_proj_bias=True,
        apply_rotary=False,
        abs_position_embedding=True,
        activation_function="gelu_new",
        mlp_type="fc",
        use_mlp_bias=True,
        tied_embedding=True,
    )


def _gpt2_config_to_hf(cfg: ModelConfig) -> Dict[str, Any]:
    return {
        "model_type": "gpt2",
        "architectures": ["GPT2LMHeadModel"],
        "n_layer": cfg.n_layers,
        "n_head": cfg.n_q_heads,
        "n_embd": cfg.hidden_dim,
        "n_inner": cfg.intermediate_dim,
        "vocab_size": cfg.vocab_size,
        "n_positions": cfg.n_positions,
        "layer_norm_epsilon": cfg.layer_norm_epsilon,
        "activation_function": "gelu_new",
    }


def _gpt2_params_from_hf(sd: HFState, cfg: ModelConfig) -> Dict[str, Any]:
    L, E = cfg.n_layers, cfg.hidden_dim
    # strip HF's "transformer." prefix if present
    if any(k.startswith("transformer.") for k in sd):
        sd = {
            k[len("transformer."):]: v
            for k, v in sd.items()
            if k.startswith("transformer.")
        }
    # c_attn is fused qkv with Conv1D layout [in, 3E]
    wq, wk, wv, bq, bk, bv = [], [], [], [], [], []
    for i in range(L):
        w = np.asarray(sd[f"h.{i}.attn.c_attn.weight"])
        b = np.asarray(sd[f"h.{i}.attn.c_attn.bias"])
        wq.append(w[:, :E]); wk.append(w[:, E : 2 * E]); wv.append(w[:, 2 * E :])
        bq.append(b[:E]); bk.append(b[E : 2 * E]); bv.append(b[2 * E :])
    p = "h.{i}."
    return {
        "embed": {"weight": np.asarray(sd["wte.weight"])},
        "pos_embed": {"weight": np.asarray(sd["wpe.weight"])},
        "layers": {
            "ln1": {
                "weight": _stack(sd, p + "ln_1.weight", L),
                "bias": _stack(sd, p + "ln_1.bias", L),
            },
            "attn": {
                "wq": np.stack(wq), "wk": np.stack(wk), "wv": np.stack(wv),
                "bq": np.stack(bq), "bk": np.stack(bk), "bv": np.stack(bv),
                "wo": _stack(sd, p + "attn.c_proj.weight", L),
                "bo": _stack(sd, p + "attn.c_proj.bias", L),
            },
            "ln2": {
                "weight": _stack(sd, p + "ln_2.weight", L),
                "bias": _stack(sd, p + "ln_2.bias", L),
            },
            "mlp": {
                "w_fc": _stack(sd, p + "mlp.c_fc.weight", L),
                "b_fc": _stack(sd, p + "mlp.c_fc.bias", L),
                "w_proj": _stack(sd, p + "mlp.c_proj.weight", L),
                "b_proj": _stack(sd, p + "mlp.c_proj.bias", L),
            },
        },
        "final_ln": {
            "weight": np.asarray(sd["ln_f.weight"]),
            "bias": np.asarray(sd["ln_f.bias"]),
        },
    }


def _gpt2_params_to_hf(params: Dict[str, Any], cfg: ModelConfig) -> HFState:
    sd: HFState = {
        "transformer.wte.weight": np.asarray(params["embed"]["weight"]),
        "transformer.wpe.weight": np.asarray(params["pos_embed"]["weight"]),
        "transformer.ln_f.weight": np.asarray(params["final_ln"]["weight"]),
        "transformer.ln_f.bias": np.asarray(params["final_ln"]["bias"]),
    }
    lp = params["layers"]
    for i in range(cfg.n_layers):
        p = f"transformer.h.{i}."
        a = lp["attn"]
        sd[p + "ln_1.weight"] = np.asarray(lp["ln1"]["weight"][i])
        sd[p + "ln_1.bias"] = np.asarray(lp["ln1"]["bias"][i])
        sd[p + "ln_2.weight"] = np.asarray(lp["ln2"]["weight"][i])
        sd[p + "ln_2.bias"] = np.asarray(lp["ln2"]["bias"][i])
        sd[p + "attn.c_attn.weight"] = np.concatenate(
            [np.asarray(a["wq"][i]), np.asarray(a["wk"][i]), np.asarray(a["wv"][i])],
            axis=1,
        )
        sd[p + "attn.c_attn.bias"] = np.concatenate(
            [np.asarray(a["bq"][i]), np.asarray(a["bk"][i]), np.asarray(a["bv"][i])]
        )
        sd[p + "attn.c_proj.weight"] = np.asarray(a["wo"][i])
        sd[p + "attn.c_proj.bias"] = np.asarray(a["bo"][i])
        m = lp["mlp"]
        sd[p + "mlp.c_fc.weight"] = np.asarray(m["w_fc"][i])
        sd[p + "mlp.c_fc.bias"] = np.asarray(m["b_fc"][i])
        sd[p + "mlp.c_proj.weight"] = np.asarray(m["w_proj"][i])
        sd[p + "mlp.c_proj.bias"] = np.asarray(m["b_proj"][i])
    return sd


register_hf_family(
    HFFamily(
        name="gpt2",
        hf_model_type="gpt2",
        config_from_hf=_gpt2_config_from_hf,
        config_to_hf=_gpt2_config_to_hf,
        params_from_hf=_gpt2_params_from_hf,
        params_to_hf=_gpt2_params_to_hf,
    )
)


# --------------------------------------------------------------------------- #
# Checkpoint IO (safetensors + config.json)
# --------------------------------------------------------------------------- #


def family_for_model_type(model_type: str) -> HFFamily:
    for fam in HF_FAMILIES.values():
        if fam.hf_model_type == model_type:
            return fam
    raise KeyError(f"No converter registered for HF model_type={model_type!r}")


def load_hf_checkpoint(path: str):
    """Read an HF checkpoint dir -> (ModelConfig, params pytree of numpy)."""
    from safetensors.numpy import load_file

    with open(os.path.join(path, "config.json")) as f:
        hf_cfg = json.load(f)
    fam = family_for_model_type(hf_cfg["model_type"])
    cfg = fam.config_from_hf(hf_cfg)
    sd: HFState = {}
    shards = sorted(
        f for f in os.listdir(path) if f.endswith(".safetensors")
    )
    if not shards:
        raise FileNotFoundError(f"No .safetensors shards under {path}")
    for shard in shards:
        sd.update(load_file(os.path.join(path, shard)))
    # critic/reward checkpoints: the scalar value head rides as
    # ``score.weight [1, E]`` (the HF SequenceClassification convention)
    # plus an ``is_critic`` marker in config.json — family converters only
    # handle the CausalLM surface
    if hf_cfg.get("is_critic"):
        import dataclasses as _dc

        cfg = _dc.replace(cfg, is_critic=True)
    params = fam.params_from_hf(sd, cfg)
    if cfg.is_critic and "score.weight" in sd:
        params["head"] = {"weight": np.asarray(sd["score.weight"]).T}
    return cfg, params


def save_hf_checkpoint(params, cfg: ModelConfig, family: str, path: str):
    """Write params as an HF checkpoint dir (model.safetensors + config.json)."""
    from safetensors.numpy import save_file

    fam = HF_FAMILIES[family]
    os.makedirs(path, exist_ok=True)
    host_params = jax_to_numpy(params)
    sd = fam.params_to_hf(host_params, cfg)
    hf_cfg = fam.config_to_hf(cfg)
    if cfg.is_critic:
        # value head [E, 1] -> score.weight [1, E]; marker for the loader
        sd["score.weight"] = np.asarray(host_params["head"]["weight"]).T
        hf_cfg["is_critic"] = True
    # safetensors writes the *raw buffer*, silently corrupting non-contiguous
    # views (our converters emit transposed views of the stacked params).
    sd = {k: np.ascontiguousarray(v) for k, v in sd.items()}
    save_file(sd, os.path.join(path, "model.safetensors"))
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(hf_cfg, f, indent=2)


def jax_to_numpy(params):
    import jax

    return jax.tree.map(lambda x: np.asarray(x), params)
