"""Model architecture config.

TPU-native counterpart of ``ReaLModelConfig`` (``realhf/api/core/model_api.py:340``)
and ``ReaLMoEConfig`` (``:294``). One dataclass covers every supported HF
family (llama, qwen2, qwen3, mistral, gemma, gpt2, mixtral) via feature
switches, exactly like the reference's single in-house architecture.
"""

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    """Mixture-of-experts settings (≈ ``ReaLMoEConfig``)."""

    num_experts: int = 8
    top_k: int = 2
    routed_scaling_factor: float = 1.0
    aux_loss_coeff: float = 0.0
    z_loss_coeff: float = 0.0
    input_jitter_eps: Optional[float] = None
    norm_topk_prob: bool = True
    # "dense": every expert for every token (XLA-fused; correct under any
    # sharding of the expert axis). "ragged": sort-by-expert grouped GEMM via
    # ``lax.ragged_dot`` (megablox-style) — the TPU fast path when experts are
    # replicated or fit per-device; GSPMD may all-gather expert weights if the
    # expert axis is sharded. With nonzero aux coefficients the two modes
    # optimize slightly different load-balance estimators under the packed
    # training path (per-row mean vs whole-batch; see ``ops/moe.py``).
    dispatch: str = "dense"


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    n_layers: int
    n_q_heads: int
    n_kv_heads: int
    head_dim: int
    hidden_dim: int
    intermediate_dim: int
    vocab_size: int
    n_positions: int = 32768

    # Norms
    layer_norm_type: str = "rms"      # "rms" | "gemma" (=(1+w) rms) | "layer" (gpt2)
    layer_norm_epsilon: float = 1e-5

    # Attention
    use_attention_bias: bool = False       # qkv projection bias (qwen2, gpt2)
    use_attn_proj_bias: bool = False       # output projection bias (gpt2)
    qk_layernorm: bool = False             # per-head q/k RMSNorm (qwen3)
    sliding_window: Optional[int] = None
    attn_logits_soft_cap: Optional[float] = None
    softmax_scale: Optional[float] = None  # default head_dim ** -0.5

    # Rotary (apply_rotary False => learned absolute positions, gpt2)
    apply_rotary: bool = True
    rotary_base: float = 10000.0
    rotary_dim: Optional[int] = None       # default head_dim
    rotary_scaling_type: Optional[str] = None
    rotary_scaling_factor: float = 1.0
    rotary_low_freq_factor: float = 1.0
    rotary_high_freq_factor: float = 4.0
    rotary_original_max_position: int = 8192

    # MLP
    activation_function: str = "silu"
    mlp_type: str = "gated"                # "gated" (swiglu) | "fc" (gpt2) | "moe"
    use_mlp_bias: bool = False             # gpt2
    moe: Optional[MoEConfig] = None

    # Embeddings / head
    tied_embedding: bool = False
    normalize_embed: bool = False          # gemma: scale embeds by sqrt(hidden)
    final_logits_soft_cap: Optional[float] = None
    abs_position_embedding: bool = False   # gpt2 learned positions

    # Dropout (SFT only; PPO runs with 0 like the reference)
    embd_pdrop: float = 0.0
    resid_pdrop: float = 0.0
    attn_pdrop: float = 0.0

    # Head
    is_critic: bool = False                # scalar value head instead of LM head

    # Compute dtype for activations (params kept fp32 master in the optimizer)
    dtype: str = "bfloat16"

    # Paged-KV pool storage dtype for generation engines (docs/performance.md
    # "KV quantization"): None = serving ``dtype`` (raw bf16 pages — the
    # chip-verified default until the gen_kvq bench proves int8 on hardware);
    # "int8" stores quantized pages with per-(page-slot, kv-head) scales in a
    # parallel scales array, halving decode's HBM KV traffic and doubling
    # resident pages at fixed pool HBM. The AREAL_KV_DTYPE env knob
    # (base/constants.py) overrides a None here; an explicit engine argument
    # overrides both.
    kv_dtype: Optional[str] = None

    # Attention backend: None = auto (Pallas flash on TPU, XLA dense on CPU,
    # where pallas only runs interpreted); True/False force it.
    use_flash_attention: Optional[bool] = None

    # STATIC upper bound on any packed segment's length (e.g. max prompt +
    # max new tokens). When set, the flash kernels iterate a statically
    # narrowed block band instead of the full causal rectangle — a multi-x
    # attention win when packing many short sequences. The train engine
    # rejects batches that violate the bound.
    attn_max_seqlen: Optional[int] = None

    # Flash-attention block size override (None = auto: 1024 at T >= 8192,
    # else 512). Bigger score tiles amortize the kernels' VPU mask/softmax
    # passes at very long context; may need more VMEM.
    flash_block_size: Optional[int] = None
    # Separate K-block size (None = same as flash_block_size). Rectangular
    # tiles trade VPU-pass shape against MXU dot shapes at long context.
    flash_block_size_k: Optional[int] = None

    # Cross-entropy in token blocks of this size (None = dense): the LM
    # head + log-softmax + label gather run per block under remat, so the
    # [T, vocab] logits (4 GB f32 at the 32k protocol shape) never
    # materialize. Trades one extra head matmul in the backward for ~8 GB
    # of HBM round trips per step.
    loss_chunk_size: Optional[int] = None

    # Layer-stack execution: 1 = lax.scan over stacked layers (one trace,
    # fast compiles — the default); an int N or True unrolls the scan (full
    # unroll removes the per-layer dynamic-update-slice bookkeeping XLA
    # emits for scan carries/residuals — measured ~20% step-time win on a
    # 12-layer model at 4k tokens — at the cost of layer-count-proportional
    # compile time; prefer it for models up to a few dozen layers).
    layer_scan_unroll: int = 1

    # Rematerialization policy for the training backward pass:
    #   "full" — checkpoint whole layers (max memory savings, ~1/3 extra
    #            FLOPs; the 32k-context default),
    #   "dots" — save matmul outputs, recompute elementwise (small memory
    #            cost, near-zero recompute on MXU),
    #   "dots_attn" — "dots" for the projections/MLP but the attention
    #            kernel stays un-rematted (its q/k/v/out/lse residuals are
    #            saved): a whole-layer checkpoint re-runs the flash forward
    #            inside the backward, ~25% of a long-context step. Costs
    #            ~4 packed activations per layer of extra HBM.
    #   "none" — save everything (fastest when activations fit HBM; right
    #            for small models / short contexts).
    remat_policy: str = "full"

    def flash_enabled(self) -> bool:
        if self.use_flash_attention is None:
            import jax

            return jax.devices()[0].platform == "tpu"
        return self.use_flash_attention

    @property
    def n_rep(self) -> int:
        return self.n_q_heads // self.n_kv_heads

    @property
    def rot_dim(self) -> int:
        return self.rotary_dim if self.rotary_dim is not None else self.head_dim

    def __post_init__(self):
        if self.n_q_heads % self.n_kv_heads != 0:
            raise ValueError("n_q_heads must be divisible by n_kv_heads")
        if self.mlp_type == "moe" and self.moe is None:
            object.__setattr__(self, "moe", MoEConfig())
