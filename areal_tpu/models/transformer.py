"""The flagship model: a packed-varlen transformer as functional JAX.

TPU-native counterpart of ``ReaLModel`` (``realhf/impl/model/nn/real_llm_api.py:100``)
and its blocks (``real_llm_base.py:111-403``). Key departures from the
reference, all deliberate TPU-first choices:

- **No pipeline stages, no TP modules.** Parameters are one pytree with layer
  params *stacked* on a leading axis; the forward is a single ``lax.scan``
  over layers. Parallelism is declarative: ``param_logical_axes`` returns
  logical sharding axes per leaf, and ``areal_tpu.parallel`` maps them onto a
  device mesh for pjit. This replaces the reference's ``parallelism/`` +
  ``pipe_runner`` (~3k LoC) with metadata.
- **Packed data plane.** The training/inference forward consumes a padded
  packed token axis ``[T]`` with ``segment_ids`` (0 = pad), mirroring the
  reference's cu_seqlens varlen batches with static shapes for XLA.
- **Decode path** keeps a per-layer KV cache ``[L, B, S, Hkv, D]`` carried
  through the same layer scan (continuous-batching generation engine builds
  on this; ≈ ``real_llm_generate.py``).

Params are stored fp32 (optimizer master copy) and cast to ``cfg.dtype``
(default bf16) inside the forward — standard mixed precision; the MXU eats
bf16.
"""

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from areal_tpu.models.config import ModelConfig
from areal_tpu.ops import attention as attn_ops
from areal_tpu.ops import norms
from areal_tpu.ops.activations import ACT2FN
from areal_tpu.ops.rotary import RotaryConfig, apply_rotary, rotary_cos_sin

Params = Dict[str, Any]


# --------------------------------------------------------------------------- #
# Initialization & sharding metadata
# --------------------------------------------------------------------------- #


def _split(rng, n):
    return list(jax.random.split(rng, n))


def init_params(cfg: ModelConfig, rng: jax.Array, dtype=jnp.float32) -> Params:
    """Random init (normal(0.02), zeros for biases/norm-offsets, ones for
    norm gains — gemma stores gains as deltas so they init to 0 there)."""
    E, D = cfg.hidden_dim, cfg.head_dim
    Hq, Hkv, F, V, L = (
        cfg.n_q_heads,
        cfg.n_kv_heads,
        cfg.intermediate_dim,
        cfg.vocab_size,
        cfg.n_layers,
    )
    std = 0.02
    rngs = iter(_split(rng, 64))

    def w(shape):
        return (jax.random.normal(next(rngs), shape, jnp.float32) * std).astype(dtype)

    ln_gain = jnp.zeros if cfg.layer_norm_type == "gemma" else jnp.ones

    def ln(extra_bias: bool):
        p = {"weight": ln_gain((L, E), dtype)}
        if extra_bias:
            p["bias"] = jnp.zeros((L, E), dtype)
        return p

    has_ln_bias = cfg.layer_norm_type == "layer"
    attn: Dict[str, Any] = {
        "wq": w((L, E, Hq * D)),
        "wk": w((L, E, Hkv * D)),
        "wv": w((L, E, Hkv * D)),
        "wo": w((L, Hq * D, E)),
    }
    if cfg.use_attention_bias:
        attn["bq"] = jnp.zeros((L, Hq * D), dtype)
        attn["bk"] = jnp.zeros((L, Hkv * D), dtype)
        attn["bv"] = jnp.zeros((L, Hkv * D), dtype)
    if cfg.use_attn_proj_bias:
        attn["bo"] = jnp.zeros((L, E), dtype)
    if cfg.qk_layernorm:
        attn["q_norm"] = jnp.ones((L, D), dtype)
        attn["k_norm"] = jnp.ones((L, D), dtype)

    if cfg.mlp_type == "gated":
        mlp: Dict[str, Any] = {
            "w_gate": w((L, E, F)),
            "w_up": w((L, E, F)),
            "w_down": w((L, F, E)),
        }
    elif cfg.mlp_type == "fc":
        mlp = {"w_fc": w((L, E, F)), "w_proj": w((L, F, E))}
        if cfg.use_mlp_bias:
            mlp["b_fc"] = jnp.zeros((L, F), dtype)
            mlp["b_proj"] = jnp.zeros((L, E), dtype)
    elif cfg.mlp_type == "moe":
        X = cfg.moe.num_experts
        mlp = {
            "router": w((L, E, X)),
            "w_gate": w((L, X, E, F)),
            "w_up": w((L, X, E, F)),
            "w_down": w((L, X, F, E)),
        }
    else:
        raise ValueError(cfg.mlp_type)

    params: Params = {
        "embed": {"weight": w((V, E))},
        "layers": {
            "ln1": ln(has_ln_bias),
            "attn": attn,
            "ln2": ln(has_ln_bias),
            "mlp": mlp,
        },
        "final_ln": {
            "weight": (ln_gain((E,), dtype)),
            **({"bias": jnp.zeros((E,), dtype)} if has_ln_bias else {}),
        },
    }
    if cfg.abs_position_embedding:
        params["pos_embed"] = {"weight": w((cfg.n_positions, E))}
    if cfg.is_critic:
        params["head"] = {"weight": w((E, 1))}
    elif not cfg.tied_embedding:
        params["head"] = {"weight": w((E, V))}
    return params


def param_logical_axes(cfg: ModelConfig) -> Params:
    """Logical sharding axes per parameter leaf (same tree structure as
    ``init_params``). ``None`` entries are replicated. ``areal_tpu.parallel``
    maps logical names → mesh axes (e.g. ``embed→fsdp``, ``heads/mlp/vocab→model``)."""
    has_ln_bias = cfg.layer_norm_type == "layer"

    def ln():
        p = {"weight": ("layer", "embed")}
        if has_ln_bias:
            p["bias"] = ("layer", "embed")
        return p

    attn: Dict[str, Any] = {
        "wq": ("layer", "embed", "heads"),
        "wk": ("layer", "embed", "heads"),
        "wv": ("layer", "embed", "heads"),
        "wo": ("layer", "heads", "embed"),
    }
    if cfg.use_attention_bias:
        attn["bq"] = ("layer", "heads")
        attn["bk"] = ("layer", "heads")
        attn["bv"] = ("layer", "heads")
    if cfg.use_attn_proj_bias:
        attn["bo"] = ("layer", "embed")
    if cfg.qk_layernorm:
        attn["q_norm"] = ("layer", None)
        attn["k_norm"] = ("layer", None)

    if cfg.mlp_type == "gated":
        mlp: Dict[str, Any] = {
            "w_gate": ("layer", "embed", "mlp"),
            "w_up": ("layer", "embed", "mlp"),
            "w_down": ("layer", "mlp", "embed"),
        }
    elif cfg.mlp_type == "fc":
        mlp = {"w_fc": ("layer", "embed", "mlp"), "w_proj": ("layer", "mlp", "embed")}
        if cfg.use_mlp_bias:
            mlp["b_fc"] = ("layer", "mlp")
            mlp["b_proj"] = ("layer", "embed")
    else:  # moe
        # Expert parallelism: the expert dim takes the `model` mesh axis, so
        # the per-expert F dim must stay unsharded (one mesh axis can map to
        # at most one dim of a param). Dense dispatch contracts over the
        # sharded expert dim (one psum); ragged dispatch runs with experts
        # gathered per device — see ``ops/moe.py``.
        mlp = {
            "router": ("layer", "embed", None),
            "w_gate": ("layer", "expert", "embed", None),
            "w_up": ("layer", "expert", "embed", None),
            "w_down": ("layer", "expert", None, "embed"),
        }

    axes: Params = {
        "embed": {"weight": ("vocab", "embed")},
        "layers": {"ln1": ln(), "attn": attn, "ln2": ln(), "mlp": mlp},
        "final_ln": {
            "weight": ("embed",),
            **({"bias": ("embed",)} if has_ln_bias else {}),
        },
    }
    if cfg.abs_position_embedding:
        axes["pos_embed"] = {"weight": (None, "embed")}
    if cfg.is_critic:
        axes["head"] = {"weight": ("embed", None)}
    elif not cfg.tied_embedding:
        axes["head"] = {"weight": ("embed", "vocab")}
    return axes


# --------------------------------------------------------------------------- #
# Layer forward pieces (shared by packed / batched / decode paths)
# --------------------------------------------------------------------------- #


def _norm(cfg: ModelConfig, p, x):
    if cfg.layer_norm_type == "layer":
        return norms.layer_norm(x, p["weight"], p.get("bias"), cfg.layer_norm_epsilon)
    return norms.rms_norm(
        x, p["weight"], cfg.layer_norm_epsilon, plus_one=cfg.layer_norm_type == "gemma"
    )


def _cast(cfg: ModelConfig, p):
    dt = jnp.dtype(cfg.dtype)
    return jax.tree.map(lambda x: x.astype(dt), p)


def _qkv(cfg: ModelConfig, p, x):
    """x: [..., E] -> q [..., Hq, D], k/v [..., Hkv, D] (rope NOT yet applied)."""
    D = cfg.head_dim

    def proj(w, b, h):
        y = x @ w
        if b is not None:
            y = y + b
        return y.reshape(*x.shape[:-1], h, D)

    q = proj(p["wq"], p.get("bq"), cfg.n_q_heads)
    k = proj(p["wk"], p.get("bk"), cfg.n_kv_heads)
    v = proj(p["wv"], p.get("bv"), cfg.n_kv_heads)
    if cfg.qk_layernorm:
        q = norms.rms_norm(q, p["q_norm"], cfg.layer_norm_epsilon)
        k = norms.rms_norm(k, p["k_norm"], cfg.layer_norm_epsilon)
    return q, k, v


def _rotary_cfg(cfg: ModelConfig) -> RotaryConfig:
    return RotaryConfig(
        dim=cfg.rot_dim,
        base=cfg.rotary_base,
        scaling_type=cfg.rotary_scaling_type,
        scaling_factor=cfg.rotary_scaling_factor,
        low_freq_factor=cfg.rotary_low_freq_factor,
        high_freq_factor=cfg.rotary_high_freq_factor,
        original_max_position=cfg.rotary_original_max_position,
        max_position=cfg.n_positions,
    )


def _mlp(cfg: ModelConfig, p, x):
    """Returns (out, aux_loss) — aux is the MoE load-balancing/z loss
    (``jnp`` scalar, 0 for dense MLPs)."""
    act = ACT2FN[cfg.activation_function]
    if cfg.mlp_type == "gated":
        return (act(x @ p["w_gate"]) * (x @ p["w_up"])) @ p["w_down"], jnp.float32(0.0)
    if cfg.mlp_type == "fc":
        h = x @ p["w_fc"]
        if "b_fc" in p:
            h = h + p["b_fc"]
        h = act(h)
        h = h @ p["w_proj"]
        if "b_proj" in p:
            h = h + p["b_proj"]
        return h, jnp.float32(0.0)
    # moe
    from areal_tpu.ops.moe import moe_mlp

    return moe_mlp(cfg, p, x)


def _attn_out(p, ctx):
    """ctx: [..., H, D] -> [..., E]."""
    y = ctx.reshape(*ctx.shape[:-2], -1) @ p["wo"]
    if "bo" in p:
        y = y + p["bo"]
    return y


# --------------------------------------------------------------------------- #
# Packed forward (training / logprob inference)
# --------------------------------------------------------------------------- #


def _embed(cfg: ModelConfig, params: Params, input_ids, positions):
    x = _cast(cfg, params["embed"]["weight"])[input_ids]
    if cfg.normalize_embed:
        x = x * jnp.asarray(cfg.hidden_dim**0.5, x.dtype)
    if cfg.abs_position_embedding:
        x = x + _cast(cfg, params["pos_embed"]["weight"])[positions]
    return x


def head_weight(cfg: ModelConfig, params: Params):
    """The LM-head weight ``[E, V]`` in serving dtype (tied embeddings
    transpose on the fly — a lazy view XLA fuses into the consumer). The
    fused sampling epilogue streams this over vocab blocks instead of
    calling :func:`_head`; the soft cap, when configured, must be applied
    by the consumer (``ops/fused_sample.py`` takes it as an argument)."""
    if cfg.tied_embedding:
        return _cast(cfg, params["embed"]["weight"]).T
    return _cast(cfg, params["head"]["weight"])


def _head(cfg: ModelConfig, params: Params, x):
    if cfg.is_critic:
        return (x @ _cast(cfg, params["head"]["weight"])).astype(jnp.float32)
    logits = (x @ head_weight(cfg, params)).astype(jnp.float32)
    if cfg.final_logits_soft_cap is not None:
        c = cfg.final_logits_soft_cap
        logits = c * jnp.tanh(logits / c)
    return logits


def apply_head(cfg: ModelConfig, params: Params, x):
    """Public :func:`_head`: full logits from final-norm hidden states —
    the engine's sorted-fallback rows (top-p slots under the fused
    epilogue) materialize ONLY their own rows' logits through this."""
    return _head(cfg, params, x)


def forward_packed(
    params: Params,
    cfg: ModelConfig,
    input_ids: jnp.ndarray,     # [T] int32
    segment_ids: jnp.ndarray,   # [T] int32, 0 = padding
    positions: jnp.ndarray,     # [T] int32, restart per segment
    *,
    remat: bool = True,
    with_aux: bool = False,
    with_head: bool = True,
) -> jnp.ndarray:
    """Full forward over a packed token axis. Returns ``[T, vocab]`` logits
    (fp32) or ``[T, 1]`` values for critics; with ``with_aux`` returns
    ``(out, aux_loss)`` where aux is the summed MoE router loss over layers.
    ``with_head=False`` returns the final-norm HIDDEN states ``[T, E]``
    instead — the chunked-loss path applies the head per token block so the
    ``[T, vocab]`` logits (4 GB f32 at 32k x 32k) never materialize.
    Padding rows are garbage — mask downstream with ``segment_ids > 0``."""
    x = _embed(cfg, params, input_ids, positions)
    if cfg.apply_rotary:
        cos, sin = rotary_cos_sin(_rotary_cfg(cfg), positions, jnp.float32)
    else:
        cos = sin = None

    def _attend(q, k, v):
        return attn_ops.packed_attention(
            q,
            k,
            v,
            segment_ids,
            softmax_scale=cfg.softmax_scale,
            soft_cap=cfg.attn_logits_soft_cap,
            sliding_window=cfg.sliding_window,
            use_flash=cfg.flash_enabled(),
            flash_block_size=cfg.flash_block_size,
            flash_block_size_k=cfg.flash_block_size_k,
            max_seqlen=cfg.attn_max_seqlen,
        )

    def _pre(x, lp):
        h = _norm(cfg, lp["ln1"], x)
        q, k, v = _qkv(cfg, lp["attn"], h)
        if cfg.apply_rotary:
            q = apply_rotary(q, cos, sin)
            k = apply_rotary(k, cos, sin)
        return q, k, v

    def _post(x, ctx, lp):
        x = x + _attn_out(lp["attn"], ctx)
        h = _norm(cfg, lp["ln2"], x)
        m, aux = _mlp(cfg, lp["mlp"], h)
        return x + m, aux

    policy = cfg.remat_policy if remat else "none"
    dots = jax.checkpoint_policies.dots_with_no_batch_dims_saveable

    if policy == "dots_attn":
        # Split checkpointing that leaves the attention kernel OUTSIDE the
        # remat region: jax.checkpoint cannot save a custom_vjp's residuals,
        # so a whole-layer checkpoint re-runs the full flash forward inside
        # the backward just to regenerate (out, lse) — ~25% of a long-context
        # step. Here attention residuals (q, k, v, out, lse) are saved
        # (~180 MB/layer at 32k for a 768-wide model) and only the cheap
        # projection/MLP matmul inputs are recomputed. The bf16 param cast
        # stays INSIDE each region — hoisting it would turn every layer's
        # cast param tree into saved residuals.
        pre = jax.checkpoint(
            lambda x, lp: _pre(x, _cast(cfg, lp)),
            policy=dots, prevent_cse=False,
        )
        post = jax.checkpoint(
            lambda x, ctx, lp: _post(x, ctx, _cast(cfg, lp)),
            policy=dots, prevent_cse=False,
        )

        def layer(x, lp):
            q, k, v = pre(x, lp)
            ctx = _attend(q, k, v)
            return post(x, ctx, lp)

    else:

        def layer(x, lp):
            lp = _cast(cfg, lp)
            q, k, v = _pre(x, lp)
            ctx = _attend(q, k, v)
            return _post(x, ctx, lp)

        if policy == "full":
            layer = jax.checkpoint(layer, prevent_cse=False)
        elif policy == "dots":
            layer = jax.checkpoint(layer, policy=dots, prevent_cse=False)
        elif policy != "none":
            raise ValueError(f"unknown remat_policy {policy!r}")
    x, auxes = jax.lax.scan(
        layer, x, params["layers"], unroll=cfg.layer_scan_unroll or 1
    )
    x = _norm(cfg, _cast(cfg, params["final_ln"]), x)
    out = _head(cfg, params, x) if with_head else x
    if with_aux:
        return out, jnp.sum(auxes)
    return out


def chunked_next_token_logprobs(
    params: Params,
    cfg: ModelConfig,
    hidden: jnp.ndarray,       # [T, E] final-norm hidden (with_head=False)
    input_ids: jnp.ndarray,    # [T]
    segment_ids: jnp.ndarray,  # [T]
    chunk: int = 4096,
) -> jnp.ndarray:
    """Next-token logprobs ``[T]`` without ever materializing ``[T, vocab]``
    logits: a remat'd ``lax.scan`` over token blocks applies the LM head,
    log-softmaxes, and gathers the label per block — forward peak memory
    ``[chunk, vocab]``, and the backward recomputes each block's logits
    instead of keeping 4 GB of f32 logits alive at the 32k protocol shape
    (the head matmul recompute is ~2 TFLOP vs ~8 GB of HBM round trips).
    Semantics match ``ops.ppo.gather_packed_shifted_log_probs``."""
    from areal_tpu.ops import ppo as ppo_ops

    T = hidden.shape[0]
    if T % chunk:
        # round DOWN to a divisor of T — falling back to one [T, vocab]
        # block would re-materialize exactly the logits this path exists
        # to avoid
        chunk = next(c for c in range(min(chunk, T), 0, -1) if T % c == 0)
    nc = T // chunk
    nxt = jnp.concatenate([input_ids[1:], jnp.zeros((1,), input_ids.dtype)])

    def block(_, blk):
        h_c, ids_c = blk
        logits = _head(cfg, params, h_c)              # [chunk, V] f32
        logp = jax.nn.log_softmax(logits, axis=-1)
        lp = jnp.take_along_axis(logp, ids_c[:, None], axis=-1)[:, 0]
        return None, lp

    _, lps = jax.lax.scan(
        jax.checkpoint(block, prevent_cse=False),
        None,
        (hidden.reshape(nc, chunk, -1), nxt.reshape(nc, chunk)),
    )
    lp = lps.reshape(T)
    has_next = (segment_ids > 0) & ~ppo_ops.is_segment_end(segment_ids)
    return jnp.where(has_next, lp, 0.0)


# --------------------------------------------------------------------------- #
# KV-cache decode path (generation engine)
# --------------------------------------------------------------------------- #


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    """Per-layer KV cache: ``k, v: [L, B, S, Hkv, D]``; ``lens: [B]`` counts
    valid entries per slot (0 = free slot)."""

    k: jnp.ndarray
    v: jnp.ndarray
    lens: jnp.ndarray

    @classmethod
    def empty(cls, cfg: ModelConfig, batch: int, capacity: int) -> "KVCache":
        shape = (cfg.n_layers, batch, capacity, cfg.n_kv_heads, cfg.head_dim)
        dt = jnp.dtype(cfg.dtype)
        return cls(
            k=jnp.zeros(shape, dt),
            v=jnp.zeros(shape, dt),
            lens=jnp.zeros((batch,), jnp.int32),
        )


def prefill(
    params: Params,
    cfg: ModelConfig,
    cache: KVCache,
    input_ids: jnp.ndarray,   # [B, S] right-padded prompts
    prompt_lens: jnp.ndarray, # [B]
) -> Tuple[jnp.ndarray, KVCache]:
    """Batched prompt processing; fills the cache at positions [0, len) and
    returns fp32 logits of the *last* prompt token per slot: ``[B, vocab]``.

    Attention dispatch: with flash enabled (TPU), rows flatten onto one
    packed ``[B*S]`` token axis with one segment per row and run through the
    varlen flash kernel — O(S) memory per row, so protocol-length (32k)
    prompts prefill without ever materializing the ``[B, H, S, S]`` score
    tensor the dense path below builds (that path stays: it is the right
    tool for small-S CPU tests and autodiff checks)."""
    B, S = input_ids.shape
    positions = jnp.broadcast_to(jnp.arange(S)[None], (B, S))
    valid = positions < prompt_lens[:, None]
    x = _embed(cfg, params, input_ids, positions)
    if cfg.apply_rotary:
        cos, sin = rotary_cos_sin(_rotary_cfg(cfg), positions, jnp.float32)
    else:
        cos = sin = None
    idx = jnp.arange(S)
    use_flash = cfg.flash_enabled()
    if use_flash:
        # one segment per row, padding tail INCLUDED in the segment: a valid
        # q (pos < len) never attends the tail anyway (causal, tail is
        # later), and padded q rows produce finite garbage that the `keep`
        # mask + last-token gather below never read.
        flat_seg = jnp.broadcast_to(
            (jnp.arange(B, dtype=jnp.int32) + 1)[:, None], (B, S)
        ).reshape(B * S)
        mask = None
    else:
        # causal & in-prompt mask, [B, S, S]
        mask = (idx[None, :, None] >= idx[None, None, :]) & valid[:, None, :]
        if cfg.sliding_window is not None:
            mask &= idx[None, :, None] - idx[None, None, :] < cfg.sliding_window
    scale = cfg.softmax_scale or cfg.head_dim**-0.5

    def layer(x, lp):
        lp = _cast(cfg, lp)
        h = _norm(cfg, lp["ln1"], x)
        q, k, v = _qkv(cfg, lp["attn"], h)  # [B, S, H, D]
        if cfg.apply_rotary:
            q = apply_rotary(q, cos, sin)
            k = apply_rotary(k, cos, sin)
        if use_flash:
            H, D = q.shape[-2:]
            ctx = attn_ops.packed_attention(
                q.reshape(B * S, H, D),
                k.reshape(B * S, -1, D),
                v.reshape(B * S, -1, D),
                flat_seg,
                softmax_scale=scale,
                soft_cap=cfg.attn_logits_soft_cap,
                sliding_window=cfg.sliding_window,
                use_flash=True,
                max_seqlen=S,
            ).reshape(B, S, H, D)
        else:
            kk = jnp.repeat(k, cfg.n_rep, axis=2)
            vv = jnp.repeat(v, cfg.n_rep, axis=2)
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, kk, preferred_element_type=jnp.float32) * scale
            if cfg.attn_logits_soft_cap is not None:
                c = cfg.attn_logits_soft_cap
                scores = c * jnp.tanh(scores / c)
            scores = jnp.where(mask[:, None], scores, attn_ops._NEG_INF)
            probs = jax.nn.softmax(scores, axis=-1).astype(vv.dtype)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, vv)
        x = x + _attn_out(lp["attn"], ctx.astype(x.dtype))
        h = _norm(cfg, lp["ln2"], x)
        x = x + _mlp(cfg, lp["mlp"], h)[0]
        return x, (k, v)

    x, (ks, vs) = jax.lax.scan(layer, x, params["layers"])
    cap = cache.k.shape[2]
    pad = cap - S
    if pad < 0:
        raise ValueError("prompt longer than cache capacity")
    ks = jnp.pad(ks, ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
    vs = jnp.pad(vs, ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0)))
    keep = (jnp.arange(cap)[None, :] < prompt_lens[:, None])[None, :, :, None, None]
    cache = KVCache(
        k=jnp.where(keep, ks.astype(cache.k.dtype), cache.k),
        v=jnp.where(keep, vs.astype(cache.v.dtype), cache.v),
        lens=prompt_lens.astype(jnp.int32),
    )
    x = _norm(cfg, _cast(cfg, params["final_ln"]), x)
    last = jnp.take_along_axis(
        x, jnp.maximum(prompt_lens - 1, 0)[:, None, None], axis=1
    )[:, 0]
    return _head(cfg, params, last), cache


def decode_step(
    params: Params,
    cfg: ModelConfig,
    cache: KVCache,
    tokens: jnp.ndarray,       # [B] current tokens
    active: Optional[jnp.ndarray] = None,  # [B] bool; inactive slots untouched
) -> Tuple[jnp.ndarray, KVCache]:
    """One decode step for every cache slot. Returns fp32 logits ``[B, vocab]``
    and the updated cache (lens incremented where ``active``)."""
    B = tokens.shape[0]
    if active is None:
        active = jnp.ones((B,), bool)
    positions = cache.lens  # position of the new token
    x = _embed(cfg, params, tokens, positions)  # [B, E]
    if cfg.apply_rotary:
        cos, sin = rotary_cos_sin(_rotary_cfg(cfg), positions, jnp.float32)
    else:
        cos = sin = None
    write_at = cache.lens  # [B]
    new_lens = jnp.where(active, cache.lens + 1, cache.lens)

    def layer(x, inputs):
        lp, kc, vc = inputs
        lp = _cast(cfg, lp)
        h = _norm(cfg, lp["ln1"], x)
        q, k, v = _qkv(cfg, lp["attn"], h)  # q: [B, Hq, D]; k/v: [B, Hkv, D]
        if cfg.apply_rotary:
            q = apply_rotary(q, cos, sin)
            k = apply_rotary(k, cos, sin)
        # write new K/V at write_at (only for active slots)
        slot = jnp.arange(kc.shape[1])[None, :, None, None]  # [1, S, 1, 1]
        put = (slot == write_at[:, None, None, None]) & active[:, None, None, None]
        kc = jnp.where(put, k[:, None].astype(kc.dtype), kc)
        vc = jnp.where(put, v[:, None].astype(vc.dtype), vc)
        ctx = attn_ops.decode_attention(
            q,
            kc,
            vc,
            new_lens,
            softmax_scale=cfg.softmax_scale,
            soft_cap=cfg.attn_logits_soft_cap,
            sliding_window=cfg.sliding_window,
        )
        x = x + _attn_out(lp["attn"], ctx.astype(x.dtype))
        h = _norm(cfg, lp["ln2"], x)
        x = x + _mlp(cfg, lp["mlp"], h)[0]
        return x, (kc, vc)

    x, (ks, vs) = jax.lax.scan(layer, x, (params["layers"], cache.k, cache.v))
    cache = KVCache(k=ks, v=vs, lens=new_lens)
    x = _norm(cfg, _cast(cfg, params["final_ln"]), x)
    return _head(cfg, params, x), cache


# --------------------------------------------------------------------------- #
# Paged KV generation (page-pool cache; see areal_tpu/gen/pages.py)
# --------------------------------------------------------------------------- #


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PagedKVCache:
    """KV page pool: ``pages [L, P, 2, Hkv, page, D]`` — K and V INTERLEAVED
    per page (index 0 = K, 1 = V), so one page is ONE contiguous block and
    the decode kernel fetches a page's K and V with a single DMA, and the
    HEAD dim comes before the token dim so pages DMA straight into the
    kernel's ``[Hkv, S, D]`` compute layout with NO in-VMEM transpose
    (per-body relayouts of the KV block, not bandwidth or DMA count,
    bounded scattered-page decode — measured round 3). Slot state (page
    tables, lengths) lives with the generation engine — the pool itself
    has no per-sequence structure, which is exactly what lets prompts
    share pages (counterpart of SGLang's radix-cache memory, SURVEY
    §2.1).

    ``scales`` (int8 mode, docs/performance.md "KV quantization"): pages
    store int8 values and a parallel ``[L, P, 2, Hkv, page]`` f32 array
    carries one dequant scale per (page slot, kv head) — page-structured
    exactly like the pool, so page tables, TP's kv-head sharding, and
    radix prefix sharing address both arrays with the same indices and
    shared pages share their scales for free. Quantization happens at the
    post-scan scatter (:func:`_scatter_chunk_kv`); dequant is fused into
    every paged-attention entry point so int8 pages are read straight from
    HBM and widened in-register — a bf16 copy of the pool never exists.
    ``scales is None`` = raw serving-dtype pages (the default)."""

    pages: jnp.ndarray
    scales: Optional[jnp.ndarray] = None

    @property
    def quantized(self) -> bool:
        return self.scales is not None

    @classmethod
    def empty(
        cls,
        cfg: ModelConfig,
        n_pages: int,
        page_size: int,
        kv_dtype: Optional[str] = None,
    ) -> "PagedKVCache":
        """``kv_dtype``: normalized pool storage dtype — ``"int8"`` builds
        the quantized pool + scales pair, anything else (None) stores raw
        ``cfg.dtype`` pages."""
        shape = (
            cfg.n_layers, n_pages, 2, cfg.n_kv_heads, page_size, cfg.head_dim
        )
        if kv_dtype == "int8":
            return cls(
                pages=jnp.zeros(shape, jnp.int8),
                scales=jnp.zeros(shape[:-1], jnp.float32),
            )
        return cls(pages=jnp.zeros(shape, jnp.dtype(cfg.dtype)))


def _scatter_chunk_kv(cache: PagedKVCache, ks, vs, table, positions, valid):
    """ONE scatter of every layer's fresh K/V into the pool.

    ks/vs ``[L, B, C, Hkv, D]``; positions/valid ``[B, C]``. Runs AFTER the
    layer scan — the pool never rides the scan carry (which streamed the
    whole multi-GB pool through stacked scan outputs every step; measured
    ~30 ms/step at a 1.5B/64-slot decode, round-3 xprof).

    The scatter runs on a FLAT ``[L*P*2*Hkv*page, D]`` row view: flattening
    every dim but the minor one is a layout-preserving bitcast, and a 2D
    row scatter keeps the default layout — the earlier multi-dim scatter
    was assigned a PERMUTED pool layout by XLA, forcing two full-pool
    relayout copies per decode step around the (default-layout) attention
    kernel (~11 ms/step at a 1.5B/64-slot profile; HLO ``copy.14/.27``).

    Int8 mode (``cache.scales`` present): each token's K/V row quantizes
    symmetrically over its head_dim (scale = amax/127 per (token, kv head,
    K|V)) and the scale lands in the parallel scales array through the
    SAME flat row indices — one extra [rows] scatter of scalars, no
    second index computation. Per-row scales make incremental page fills
    exact: a new token never forces requantizing its page's earlier
    residents."""
    L, B, C, Hkv, D = ks.shape
    P, _, _, page = cache.pages.shape[1:5]
    M = table.shape[1]
    page_idx = jnp.take_along_axis(
        table, jnp.clip(positions // page, 0, M - 1), axis=1
    )                                                   # [B, C]
    off = positions % page                              # [B, C]
    dt = cache.pages.dtype
    if cache.scales is not None:
        kf = ks.astype(jnp.float32)
        vf = vs.astype(jnp.float32)
        amax = jnp.stack(
            [jnp.max(jnp.abs(kf), axis=-1), jnp.max(jnp.abs(vf), axis=-1)],
            axis=3,
        )                                               # [L, B, C, 2, Hkv]
        scale = jnp.where(amax > 0.0, amax / 127.0, 1.0)
        kv = jnp.clip(
            jnp.round(
                jnp.stack([kf, vf], axis=3) / scale[..., None]
            ),
            -127.0, 127.0,
        ).astype(jnp.int8)                              # [L, B, C, 2, Hkv, D]
    else:
        scale = None
        kv = jnp.stack([ks, vs], axis=3).astype(dt)     # [L, B, C, 2, Hkv, D]
    # flat row = (((l*P + p)*2 + kv)*Hkv + h)*page + off
    n_rows = L * P * 2 * Hkv * page
    base = page_idx[None] + P * jnp.arange(L)[:, None, None]     # [L, B, C]
    kvi = jnp.arange(2)[None, None, None, :, None]
    hi = jnp.arange(Hkv)[None, None, None, None, :]
    rows = ((base[..., None, None] * 2 + kvi) * Hkv + hi) * page \
        + off[None, :, :, None, None]                   # [L, B, C, 2, Hkv]
    rows = jnp.where(valid[None, :, :, None, None], rows, n_rows)  # => drop
    flat = cache.pages.reshape(n_rows, D)
    flat = flat.at[rows].set(kv, mode="drop")
    if scale is None:
        return PagedKVCache(pages=flat.reshape(cache.pages.shape))
    flat_s = cache.scales.reshape(n_rows)
    flat_s = flat_s.at[rows].set(scale, mode="drop")
    return PagedKVCache(
        pages=flat.reshape(cache.pages.shape),
        scales=flat_s.reshape(cache.scales.shape),
    )


def _extend_layers(
    params: Params,
    cfg: ModelConfig,
    cache: PagedKVCache,
    tokens: jnp.ndarray,     # [B, C]
    table: jnp.ndarray,      # [B, M]
    start: jnp.ndarray,      # [B]
    n_new: jnp.ndarray,      # [B]
    skip_pool: bool = False,
    verify: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Shared multi-token layer scan over the page pool (chunked prefill
    AND the spec-decode verify pass — one implementation, two attention
    entry points). Returns ``(x [B, C, E] pre-final-norm hidden, ks, vs,
    positions, valid)``; the caller scatters KV and (for verify) applies
    the head."""
    from areal_tpu.ops import paged_attention as paged_ops

    B, C = tokens.shape
    positions = start[:, None] + jnp.arange(C)[None, :]
    valid = jnp.arange(C)[None, :] < n_new[:, None]
    x = _embed(cfg, params, tokens, positions)
    if cfg.apply_rotary:
        cos, sin = rotary_cos_sin(_rotary_cfg(cfg), positions, jnp.float32)
    else:
        cos = sin = None

    def _attend(q, k, v, li):
        kw = dict(
            softmax_scale=cfg.softmax_scale,
            soft_cap=cfg.attn_logits_soft_cap,
            sliding_window=cfg.sliding_window,
            scales=cache.scales,
        )
        if verify:
            return paged_ops.paged_verify_attention(
                q, k, v, cache.pages, li, table, start, n_new, **kw
            )
        return paged_ops.paged_extend_attention(
            q, k, v, cache.pages, li, table, start, n_new,
            skip_pool=skip_pool, **kw,
        )

    def layer(carry, lp):
        x, li = carry                                 # pool NOT in the scan
        lp = _cast(cfg, lp)
        h = _norm(cfg, lp["ln1"], x)
        q, k, v = _qkv(cfg, lp["attn"], h)            # [B, C, H(kv), D]
        if cfg.apply_rotary:
            q = apply_rotary(q, cos, sin)
            k = apply_rotary(k, cos, sin)
        ctx = _attend(q, k, v, li)
        x = x + _attn_out(lp["attn"], ctx.astype(x.dtype))
        h = _norm(cfg, lp["ln2"], x)
        x = x + _mlp(cfg, lp["mlp"], h)[0]
        return (x, li + 1), (k, v)

    (x, _), (ks, vs) = jax.lax.scan(
        layer, (x, jnp.int32(0)), params["layers"]
    )
    return x, ks, vs, positions, valid


def extend_paged(
    params: Params,
    cfg: ModelConfig,
    cache: PagedKVCache,
    tokens: jnp.ndarray,     # [B, C] chunk of prompt tokens
    table: jnp.ndarray,      # [B, M] page table
    start: jnp.ndarray,      # [B] tokens already resident per slot
    n_new: jnp.ndarray,      # [B] valid tokens in this chunk (<= C)
    skip_pool: bool = False,
) -> PagedKVCache:
    """Chunked prefill: attend the chunk causally over everything resident
    (pool part + intra-chunk part, merged inside the op) and scatter the
    chunk's KV into the pages once after the layer scan. Logits are not
    computed — admission feeds the last prompt token to the first decode
    step instead. ``skip_pool`` (STATIC): every row starts at position 0,
    so the pool scan is dead weight (see ``paged_extend_attention``)."""
    _, ks, vs, positions, valid = _extend_layers(
        params, cfg, cache, tokens, table, start, n_new, skip_pool=skip_pool
    )
    return _scatter_chunk_kv(cache, ks, vs, table, positions, valid)


def verify_step_paged(
    params: Params,
    cfg: ModelConfig,
    cache: PagedKVCache,
    tokens: jnp.ndarray,       # [B, C] verify chunk: [last_token, d_1..d_K]
    table: jnp.ndarray,        # [B, M]
    lens: jnp.ndarray,         # [B] resident tokens (chunk starts here)
    n_new: jnp.ndarray,        # [B] C where the slot is active, 0 otherwise
    write_mask: jnp.ndarray,   # [B, C] which chunk positions' KV may land
    return_hidden: bool = False,
) -> Tuple[jnp.ndarray, PagedKVCache]:
    """Speculative-decode VERIFY: ``decode_step_paged`` generalized to C =
    K+1 query tokens per slot in ONE pass — one params read and one pool
    sweep score the whole draft, where vanilla decode pays both per token.
    Returns fp32 logits ``[B, C, V]`` (position ``i`` is the distribution
    for the token following ``tokens[:, i]``) and the cache with the
    chunk's KV scattered where ``write_mask`` allows.

    ``return_hidden=True`` (STATIC) returns the final-NORM hidden states
    ``[B, C, E]`` instead of logits: the fused sampling epilogue
    (``ops/fused_sample.py``) streams the head over vocab blocks itself,
    so the ``[B, C, V]`` logits never materialize.

    ``write_mask`` is the acceptance-agnostic residency bound the engine
    computes (``active & (n_gen + i < max_gen)``): rejected drafts' KV
    lands in pool positions beyond the post-acceptance ``lens``, which
    attention never reads (``pos < lens``) and later steps overwrite
    before ``lens`` reaches them — so the scatter can run BEFORE the
    accept/reject decision, keeping the whole spec step inside one jitted
    chunk with no host sync. The mask only exists to keep writes inside
    the slot's allocated pages (a position past ``max_gen`` could fall off
    the page table and alias page 0)."""
    x, ks, vs, positions, _ = _extend_layers(
        params, cfg, cache, tokens, table, lens, n_new, verify=True
    )
    cache = _scatter_chunk_kv(cache, ks, vs, table, positions, write_mask)
    x = _norm(cfg, _cast(cfg, params["final_ln"]), x)
    if return_hidden:
        return x, cache
    return _head(cfg, params, x), cache


def decode_step_paged(
    params: Params,
    cfg: ModelConfig,
    cache: PagedKVCache,
    tokens: jnp.ndarray,       # [B] current tokens
    table: jnp.ndarray,        # [B, M]
    lens: jnp.ndarray,         # [B] resident tokens (write position)
    active: jnp.ndarray,       # [B] bool
    use_pallas: Optional[bool] = None,
    mesh=None,
    with_head: bool = True,
    return_hidden: bool = False,
) -> Tuple[Optional[jnp.ndarray], PagedKVCache, jnp.ndarray]:
    """One decode step over the page pool. Returns (fp32 logits ``[B, V]``,
    cache, new lens — incremented where active). The pool is read-only in
    the layer scan; each layer's fresh K/V merges into attention as the
    self token and lands in the pool via one post-scan scatter.

    ``use_pallas`` threads through to the attention dispatch. ``mesh``
    (TP serving) routes the kernel through ``shard_map`` over the kv-head
    axis — each model shard runs Pallas on its local pool slice —
    because bare ``pallas_call`` has no GSPMD partitioning rule and would
    otherwise force a full-pool all-gather.

    ``with_head=False`` (STATIC) skips the final norm + LM head and
    returns ``None`` logits: the cache-maintenance step the engine's
    vanilla chunk runs for a configured draft model only needs the KV
    writes — the head matmul (the biggest single matmul of a small
    model's step at a 152k vocab) would be dead weight.

    ``return_hidden=True`` (STATIC) returns the final-norm HIDDEN states
    ``[B, E]`` in place of logits for the fused sampling epilogue
    (``ops/fused_sample.py``), which streams the head itself — the
    ``[B, V]`` logits never materialize."""
    from areal_tpu.ops import paged_attention as paged_ops

    positions = lens
    x = _embed(cfg, params, tokens, positions)        # [B, E]
    if cfg.apply_rotary:
        cos, sin = rotary_cos_sin(_rotary_cfg(cfg), positions, jnp.float32)
    else:
        cos = sin = None
    new_lens = jnp.where(active, lens + 1, lens)

    def layer(carry, lp):
        x, li = carry                                 # pool NOT in the scan
        lp = _cast(cfg, lp)
        h = _norm(cfg, lp["ln1"], x)
        q, k, v = _qkv(cfg, lp["attn"], h)            # q [B, H, D]
        if cfg.apply_rotary:
            q = apply_rotary(q, cos, sin)
            k = apply_rotary(k, cos, sin)
        ctx = paged_ops.paged_decode_attention(
            q, k, v, cache.pages, li, table, lens,
            softmax_scale=cfg.softmax_scale,
            soft_cap=cfg.attn_logits_soft_cap,
            sliding_window=cfg.sliding_window,
            use_pallas=use_pallas,
            mesh=mesh,
            scales=cache.scales,
        )
        x = x + _attn_out(lp["attn"], ctx.astype(x.dtype))
        h = _norm(cfg, lp["ln2"], x)
        x = x + _mlp(cfg, lp["mlp"], h)[0]
        return (x, li + 1), (k, v)

    (x, _), (ks, vs) = jax.lax.scan(
        layer, (x, jnp.int32(0)), params["layers"]
    )
    cache = _scatter_chunk_kv(
        cache, ks[:, :, None], vs[:, :, None], table,
        positions[:, None], active[:, None],
    )
    if not with_head:
        return None, cache, new_lens
    x = _norm(cfg, _cast(cfg, params["final_ln"]), x)
    if return_hidden:
        return x, cache, new_lens
    return _head(cfg, params, x), cache, new_lens
