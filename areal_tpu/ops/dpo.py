"""Direct-preference-optimization loss.

Counterpart of ``realhf/impl/model/utils/dpo_functional.py`` (the reference
ships the functional only — no DPO interface/experiment — and so do we).
Sequence logprobs arrive interleaved (win, lose) pairs, exactly like the
paired-RW dataset emits them.
"""

from typing import Tuple

import jax
import jax.numpy as jnp


def dpo_loss(
    pi_logps: jnp.ndarray,
    ref_logps: jnp.ndarray,
    beta: float,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Returns (loss, pos_score, neg_score, kl).

    ``pi_logps`` / ``ref_logps``: [2N] per-sequence logprobs, rows
    alternating (win, lose) — ``dpo_loss`` in the reference ``:11-34``.
    """
    assert pi_logps.ndim == 1 and pi_logps.shape[0] % 2 == 0, pi_logps.shape
    assert ref_logps.shape == pi_logps.shape, (pi_logps.shape, ref_logps.shape)
    pi = pi_logps.reshape(-1, 2)
    ref = ref_logps.reshape(-1, 2)
    pi_logratios = pi[:, 0] - pi[:, 1]
    ref_logratios = ref[:, 0] - ref[:, 1]
    loss = -jnp.mean(jax.nn.log_sigmoid(beta * (pi_logratios - ref_logratios)))
    pos_score = jax.lax.stop_gradient(beta * jnp.sum(pi[:, 0] - ref[:, 0]))
    neg_score = jax.lax.stop_gradient(beta * jnp.sum(pi[:, 1] - ref[:, 1]))
    kl = jax.lax.stop_gradient(-jnp.sum(pi_logps - ref_logps))
    return loss, pos_score, neg_score, kl
