"""TPU-native compute ops (JAX/XLA, with Pallas fast paths on TPU).

Replaces the reference's ``csrc/`` CUDA kernels and ``realhf/impl/model/modules``
torch modules with functional JAX equivalents (SURVEY.md §2.1).
"""
