"""Activation functions (counterpart of ``realhf/impl/model/modules/activations.py``)."""

import jax
import jax.numpy as jnp

ACT2FN = {
    "silu": jax.nn.silu,
    # jax.nn.gelu defaults to the tanh approximation; HF "gelu" is exact erf
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "gelu_new": lambda x: jax.nn.gelu(x, approximate=True),
    "gelu_pytorch_tanh": lambda x: jax.nn.gelu(x, approximate=True),
    "relu": jax.nn.relu,
    "tanh": jnp.tanh,
}
