"""Mixture-of-experts MLP (top-k router + experts).

TPU-native counterpart of ``realhf/impl/model/modules/moe/`` (router.py,
experts.py, token_dispatcher.py, layer.py — ~700 LoC). The reference
permutes tokens per expert and runs grouped GEMMs; here we use the
dense-dispatch formulation (every expert computed for every token, combined
with the routing weights). That is the correctness-first XLA path — fine for
tests and small expert counts; a ``lax.ragged_dot`` (megablox-style) dispatch
is the later TPU optimization documented in SURVEY.md §2.1.

Router runs in fp32 (matches the reference's fp32 router,
``moe/router.py``).
"""

from typing import Tuple

import jax
import jax.numpy as jnp

from areal_tpu.ops.activations import ACT2FN


def router_probs(cfg, p, x) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (combine_weights [T, X], router_logits [T, X])."""
    moe = cfg.moe
    logits = (x.astype(jnp.float32) @ p["router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    top_vals, top_idx = jax.lax.top_k(probs, moe.top_k)
    if moe.norm_topk_prob:
        top_vals = top_vals / jnp.sum(top_vals, axis=-1, keepdims=True)
    combine = jnp.zeros_like(probs)
    combine = jnp.put_along_axis(  # scatter top-k weights back to [T, X]
        combine, top_idx, top_vals, axis=-1, inplace=False
    )
    return combine * moe.routed_scaling_factor, logits


def load_balancing_aux_loss(cfg, combine: jnp.ndarray, logits: jnp.ndarray):
    """Switch-style aux loss (≈ ``moe/router.py`` aux loss) in fp32."""
    moe = cfg.moe
    probs = jax.nn.softmax(logits, axis=-1)
    frac_tokens = jnp.mean((combine > 0).astype(jnp.float32), axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    aux = moe.num_experts * jnp.sum(frac_tokens * frac_probs)
    z = jnp.mean(jnp.square(jax.nn.logsumexp(logits, axis=-1)))
    return moe.aux_loss_coeff * aux + moe.z_loss_coeff * z


def moe_mlp(cfg, p, x):
    """x: [..., E] -> ([..., E], aux_loss). Dense dispatch over all experts.

    The aux loss includes padding tokens (the layer has no mask); with packed
    batches the padding fraction is small and its router logits are the
    uniform x=0 output, so the bias is negligible.
    """
    act = ACT2FN[cfg.activation_function]
    lead = x.shape[:-1]
    xt = x.reshape(-1, x.shape[-1])
    combine, logits = router_probs(cfg, p, xt)
    h = act(jnp.einsum("te,xef->txf", xt, p["w_gate"])) * jnp.einsum(
        "te,xef->txf", xt, p["w_up"]
    )
    y = jnp.einsum("txf,xfe->txe", h, p["w_down"])
    out = jnp.einsum("txe,tx->te", y, combine.astype(y.dtype))
    aux = load_balancing_aux_loss(cfg, combine, logits)
    return out.reshape(*lead, -1), aux
