"""Mixture-of-experts MLP (top-k router + experts).

TPU-native counterpart of ``realhf/impl/model/modules/moe/`` (router.py,
experts.py, token_dispatcher.py, layer.py — ~700 LoC). Two dispatch modes,
selected by ``MoEConfig.dispatch``:

- ``"dense"``: every expert computed for every token, combined with the
  routing weights. The correctness-first XLA path; also the right path when
  the expert axis is sharded (the combine einsum contracts over the sharded
  expert dim, giving expert parallelism via one psum).
- ``"ragged"``: the reference's permute-tokens-per-expert grouped-GEMM scheme
  (``token_dispatcher.py``), TPU-native: sort token copies by expert id and
  run ``lax.ragged_dot`` (megablox-style) over contiguous expert groups.
  O(T·K) expert FLOPs instead of O(T·X) — the fast path when experts are
  replicated per device.

Router runs in fp32 (matches the reference's fp32 router,
``moe/router.py``).
"""

from typing import Tuple

import jax
import jax.numpy as jnp

from areal_tpu.ops.activations import ACT2FN


def _route(cfg, router_w, x):
    """fp32 router shared by both dispatch paths.

    Returns (top_vals [T, K] — normalized+scaled combine weights,
    top_idx [T, K], probs [T, X], logits [T, X]).
    """
    moe = cfg.moe
    logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    top_vals, top_idx = jax.lax.top_k(probs, moe.top_k)
    if moe.norm_topk_prob:
        top_vals = top_vals / jnp.sum(top_vals, axis=-1, keepdims=True)
    return top_vals * moe.routed_scaling_factor, top_idx, probs, logits


def router_probs(cfg, p, x) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (combine_weights [T, X], router_logits [T, X])."""
    top_vals, top_idx, probs, logits = _route(cfg, p["router"], x)
    combine = jnp.zeros_like(probs)
    combine = jnp.put_along_axis(  # scatter top-k weights back to [T, X]
        combine, top_idx, top_vals, axis=-1, inplace=False
    )
    return combine, logits


def _aux_tail(cfg, frac_tokens, probs, logits):
    """Switch-style load-balance + z loss from precomputed routing stats."""
    moe = cfg.moe
    frac_probs = jnp.mean(probs, axis=0)
    aux = moe.num_experts * jnp.sum(frac_tokens * frac_probs)
    z = jnp.mean(jnp.square(jax.nn.logsumexp(logits, axis=-1)))
    return moe.aux_loss_coeff * aux + moe.z_loss_coeff * z


def load_balancing_aux_loss(cfg, combine: jnp.ndarray, logits: jnp.ndarray):
    """Switch-style aux loss (≈ ``moe/router.py`` aux loss) in fp32."""
    probs = jax.nn.softmax(logits, axis=-1)
    frac_tokens = jnp.mean((combine > 0).astype(jnp.float32), axis=0)
    return _aux_tail(cfg, frac_tokens, probs, logits)


def moe_mlp(cfg, p, x):
    """x: [..., E] -> ([..., E], aux_loss). Dispatch per ``cfg.moe.dispatch``.

    The aux loss includes padding tokens (the layer has no mask); with packed
    batches the padding fraction is small and its router logits are the
    uniform x=0 output, so the bias is negligible.
    """
    if cfg.moe.dispatch == "ragged":
        return _moe_mlp_ragged(cfg, p, x)
    if cfg.moe.dispatch != "dense":
        raise ValueError(
            f"MoEConfig.dispatch must be 'dense' or 'ragged', "
            f"got {cfg.moe.dispatch!r}"
        )
    return _moe_mlp_dense(cfg, p, x)


def _moe_mlp_dense(cfg, p, x):
    act = ACT2FN[cfg.activation_function]
    lead = x.shape[:-1]
    xt = x.reshape(-1, x.shape[-1])
    combine, logits = router_probs(cfg, p, xt)
    h = act(jnp.einsum("te,xef->txf", xt, p["w_gate"])) * jnp.einsum(
        "te,xef->txf", xt, p["w_up"]
    )
    y = jnp.einsum("txf,xfe->txe", h, p["w_down"])
    out = jnp.einsum("txe,tx->te", y, combine.astype(y.dtype))
    aux = load_balancing_aux_loss(cfg, combine, logits)
    return out.reshape(*lead, -1), aux


def _moe_mlp_ragged(cfg, p, x):
    """Grouped-GEMM dispatch: sort the T·K (token, expert) copies by expert id
    so each expert's tokens are a contiguous row block, then three
    ``lax.ragged_dot`` calls (gate/up/down) run one GEMM per expert without
    materializing the [T, X, F] dense activations.

    ``lax.ragged_dot`` has no batching rule, so under ``vmap`` over packed
    rows (the train engine's ``vmapped_forward``) a ``custom_vmap`` rule
    folds the row dim into the token dim — expert grouping is row-agnostic —
    and broadcasts the globally-computed aux loss back to the rows (the
    engine means it, recovering the global value).

    Known corner: reverse-mode AD of an *un-vmapped* ragged call is
    unsupported (``custom_vmap``'s unbatched application does not linearize
    in current JAX). Every framework training path differentiates under
    ``vmap`` (``vmapped_forward``), where the rule expands away before AD;
    un-vmapped *forward* calls (generation) also work.
    """
    lead = x.shape[:-1]
    xt = x.reshape(-1, x.shape[-1])
    out, aux = _ragged_dispatch(
        cfg, xt, p["router"], p["w_gate"], p["w_up"], p["w_down"]
    )
    return out.reshape(*lead, -1), aux


def _ragged_dispatch(cfg, xt, router, w_gate, w_up, w_down):
    @jax.custom_batching.custom_vmap
    def core(xt, router, w_gate, w_up, w_down):
        return _ragged_core(cfg, xt, router, w_gate, w_up, w_down)

    @core.def_vmap
    def core_vmap(axis_size, in_batched, xt, router, w_gate, w_up, w_down):
        if any(in_batched[1:]):
            raise NotImplementedError(
                "ragged MoE dispatch: only activations may carry a vmap axis"
            )
        B, T, E = xt.shape
        # Bottom out in the plain core: leaving a custom_vmap call in the
        # expanded jaxpr breaks linearization. One vmap level is folded per
        # rule application; a second enclosing vmap is unsupported.
        out, aux = _ragged_core(
            cfg, xt.reshape(B * T, E), router, w_gate, w_up, w_down
        )
        return (out.reshape(B, T, E), jnp.broadcast_to(aux, (B,))), (True, True)

    return core(xt, router, w_gate, w_up, w_down)


def _ragged_core(cfg, xt, router, w_gate, w_up, w_down):
    """xt: [T, E] -> (out [T, E], aux scalar). Static shapes throughout
    (argsort + bincount, no dynamic slicing), so the whole thing jits once
    regardless of the routing realized at runtime."""
    moe = cfg.moe
    act = ACT2FN[cfg.activation_function]
    T, K, X = xt.shape[0], moe.top_k, moe.num_experts

    top_vals, top_idx, probs, logits = _route(cfg, router, xt)
    flat_expert = top_idx.reshape(-1)  # [T*K]
    order = jnp.argsort(flat_expert, stable=True)
    tok_sorted = jnp.repeat(jnp.arange(T, dtype=jnp.int32), K)[order]
    group_sizes = jnp.bincount(flat_expert, length=X).astype(jnp.int32)

    xs = xt[tok_sorted]  # [T*K, E], expert-contiguous
    dot = lambda a, w: jax.lax.ragged_dot(
        a, w, group_sizes, preferred_element_type=jnp.float32
    ).astype(xt.dtype)
    h = act(dot(xs, w_gate)) * dot(xs, w_up)
    y = dot(h, w_down)  # [T*K, E]
    w = top_vals.reshape(-1)[order].astype(y.dtype)
    out = jax.ops.segment_sum(y * w[:, None], tok_sorted, num_segments=T)

    # Aux loss from the same quantities the dense path derives from `combine`:
    # frac_tokens[x] = fraction of (token, slot) selections hitting expert x.
    frac_tokens = group_sizes.astype(jnp.float32) / float(T)
    aux = _aux_tail(cfg, frac_tokens, probs, logits)
    return out.astype(xt.dtype), aux
