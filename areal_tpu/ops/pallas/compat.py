"""Pallas/SPMD API drift shims.

``pltpu.CompilerParams`` is the current spelling of the TPU pallas_call
compiler-params struct; older jax builds (<= 0.4.x) ship the same struct
as ``pltpu.TPUCompilerParams``, and very old ones have neither. Mirrors
the ``jax.profiler.ProfileData`` treatment in ``base/trace_analyzer.py``:
resolve whichever spelling the installed jax has, degrade to "unavailable"
instead of crashing with AttributeError deep inside a kernel build, and
let tests skip via :func:`compiler_params_available`.

The kernels only ever pass ``dimension_semantics`` and
``vmem_limit_bytes`` — both present in every spelling this shim accepts.

Same drift class for the SPMD entry point the kernel wrappers shard
through: ``jax.shard_map`` (kwarg ``check_vma``) is the current
spelling; 0.4.x ships ``jax.experimental.shard_map.shard_map`` (kwarg
``check_rep``, same meaning: disable the replication/varying-axes
check). :func:`shard_map` resolves whichever exists and translates the
kwarg.
"""

from typing import Optional

import jax
from jax.experimental.pallas import tpu as pltpu

# The struct under its current name, else the legacy name, else None.
CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)

# Same drift for the memory-space enum: ``pltpu.MemorySpace`` today,
# ``pltpu.TPUMemorySpace`` on 0.4.x. Both spell the member we use ``ANY``.
MemorySpace = getattr(
    pltpu, "MemorySpace", getattr(pltpu, "TPUMemorySpace", None)
)
ANY_MEMORY_SPACE = getattr(MemorySpace, "ANY", None)


def compiler_params_available() -> bool:
    """True when the installed jax exposes the compiler-params struct
    under either spelling."""
    return CompilerParams is not None


def memory_space_available() -> bool:
    """True when the installed jax exposes the memory-space enum (with
    an ``ANY`` member) under either spelling — required by kernels that
    keep a ref in HBM via ``BlockSpec(memory_space=...)``."""
    return ANY_MEMORY_SPACE is not None


if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _legacy_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )


def compiler_params(**kwargs) -> Optional[object]:
    """A compiler-params instance for ``pallas_call(compiler_params=...)``,
    or None (= pallas defaults) when the struct is unavailable. Passing
    no kwargs also returns None — an empty params struct is equivalent
    and None keeps old-jax behavior identical."""
    if CompilerParams is None or not kwargs:
        return None
    return CompilerParams(**kwargs)
