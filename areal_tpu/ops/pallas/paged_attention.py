"""Paged decode attention (Pallas TPU kernel).

One new token per slot attends to its KV pages IN PLACE — the page table
rides in as a scalar-prefetch operand and feeds the BlockSpec index map, so
pages stream straight from the pool with no materialized per-slot gather
(the XLA fallback in ``ops/paged_attention.py`` gathers ``[B, M*page]``
every step). TPU counterpart of vLLM/SGLang's paged-attention CUDA kernels,
which the reference inherits (SURVEY §2.1).

Grid ``(B, M)``: slot-major, pages innermost. Online-softmax state (m, l,
acc) lives in VMEM scratch across the page axis. Out-of-range pages
(``j*page >= lens[b]``) clamp their index-map output to the previous page —
Pallas skips the DMA when the block index repeats — and ``pl.when`` skips
the compute, so a slot pays only for its resident pages. GQA runs without
materializing the K/V head repeat: scores are batched ``dot_general`` over
the kv-head axis.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -2.3819763e38
LANES = 128


def _interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


def _n_used(lens_b, page):
    """Pages resident for a slot (at least 1 so index maps stay in range)."""
    return jnp.maximum(pl.cdiv(lens_b, page), 1)


def _decode_kernel(
    layer_ref,   # [1] int32 scalar-prefetch: which layer of the pool
    table_ref,   # [B, M] int32 scalar-prefetch
    lens_ref,    # [B] int32 scalar-prefetch (pool-resident, EXCL. self)
    q_ref,       # [1, Hq, D]
    ks_ref,      # [1, Hkv, D] the current token's K (not in the pool)
    vs_ref,      # [1, Hkv, D]
    k_ref,       # [1, 1, page, Hkv*D]
    v_ref,       # [1, 1, page, Hkv*D]
    o_ref,       # [1, Hq, D]
    m_scr,       # [HqP, LANES] f32
    l_scr,       # [HqP, LANES] f32
    acc_scr,     # [HqP, D] f32
    *,
    scale: float,
    page: int,
    n_kv: int,
    n_rep: int,
    soft_cap: Optional[float],
    sliding_window: Optional[int],
):
    b = pl.program_id(0)
    j = pl.program_id(1)
    M = pl.num_programs(1)
    Hq = q_ref.shape[1]
    lens_b = lens_ref[b]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when((j * page < lens_b) & (lens_b > 0))
    def _body():
        D = q_ref.shape[2]
        q = q_ref[0].reshape(n_kv, n_rep, D)                  # [Hkv, r, D]
        k = k_ref[0, 0].reshape(page, n_kv, D).transpose(1, 0, 2)  # [Hkv,p,D]
        v = v_ref[0, 0].reshape(page, n_kv, D).transpose(1, 0, 2)
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale                                             # [Hkv, r, p]
        if soft_cap is not None:
            s = soft_cap * jnp.tanh(s / soft_cap)
        s = s.reshape(Hq, page)
        kpos = j * page + jax.lax.broadcasted_iota(jnp.int32, (Hq, page), 1)
        mask = kpos < lens_b
        if sliding_window is not None:
            # the query sits at position lens_b
            mask &= kpos > lens_b - sliding_window
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:Hq, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)          # [Hq, p]
        corr = jnp.exp(m_prev - m_new)
        l_new = corr * l_scr[:Hq, 0:1] + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.reshape(n_kv, n_rep, page).astype(v.dtype), v,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ).reshape(Hq, D)
        acc_scr[:Hq, :D] = acc_scr[:Hq, :D] * corr + pv
        m_scr[:Hq] = jnp.broadcast_to(m_new, (Hq, LANES))
        l_scr[:Hq] = jnp.broadcast_to(l_new, (Hq, LANES))

    @pl.when(j == M - 1)
    def _done():
        D = q_ref.shape[2]
        # fold the current token's self-attention (always attended; its KV
        # is scattered into the pool by the caller AFTER the layer scan)
        q = q_ref[0].reshape(n_kv, n_rep, D)
        ks = ks_ref[0].astype(q.dtype)                        # [Hkv, D]
        vs = vs_ref[0]
        s_self = jnp.sum(
            q.astype(jnp.float32) * ks[:, None].astype(jnp.float32), axis=2
        ) * scale                                             # [Hkv, r]
        if soft_cap is not None:
            s_self = soft_cap * jnp.tanh(s_self / soft_cap)
        s_self = s_self.reshape(Hq, 1)
        m_prev = m_scr[:Hq, 0:1]
        m_new = jnp.maximum(m_prev, s_self)
        corr = jnp.exp(jnp.where(m_prev > NEG_INF / 2, m_prev - m_new, 0.0))
        p_self = jnp.exp(s_self - m_new)                      # [Hq, 1]
        l = corr * l_scr[:Hq, 0:1] + p_self
        v_rep = jnp.broadcast_to(
            vs[:, None].astype(jnp.float32), (n_kv, n_rep, D)
        ).reshape(Hq, D)
        acc = acc_scr[:Hq, :D] * corr + p_self * v_rep
        o_ref[0] = (acc / l).astype(o_ref.dtype)


def decode(
    q: jnp.ndarray,          # [B, Hq, D]
    k_self: jnp.ndarray,     # [B, Hkv, D] current token's K (not in pool)
    v_self: jnp.ndarray,     # [B, Hkv, D]
    k_pages: jnp.ndarray,    # [L, P, page, Hkv, D] the WHOLE pool
    v_pages: jnp.ndarray,
    layer: jnp.ndarray,      # scalar i32 layer index
    table: jnp.ndarray,      # [B, M] i32
    lens: jnp.ndarray,       # [B] tokens resident in the pool (excl. self)
    *,
    softmax_scale: Optional[float] = None,
    soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
) -> jnp.ndarray:
    """The pool rides in whole; the LAYER index is a scalar-prefetch operand
    feeding the BlockSpec index map, so only the addressed layer's resident
    pages are ever DMA'd — the caller's layer scan never slices the pool."""
    B, Hq, D = q.shape
    L, P, page, Hkv, _ = k_pages.shape
    M = table.shape[1]
    n_rep = Hq // Hkv
    if not _interpret() and (D % 128 != 0 or page % 8 != 0):
        raise ValueError(
            f"paged kernel needs head_dim%128==0 and page%8==0 on TPU; got "
            f"D={D}, page={page} — use the XLA gather path"
        )
    if softmax_scale is None:
        softmax_scale = D ** -0.5
    hq_pad = max(8, Hq)
    kv_flat = k_pages.reshape(L, P, page, Hkv * D)
    vv_flat = v_pages.reshape(L, P, page, Hkv * D)

    def page_map(b, j, layer, table, lens):
        # clamp to the last resident page: repeats skip the DMA
        jj = jnp.minimum(j, _n_used(lens[b], page) - 1)
        return (layer[0], table[b, jj], 0, 0)

    kernel = functools.partial(
        _decode_kernel,
        scale=softmax_scale,
        page=page,
        n_kv=Hkv,
        n_rep=n_rep,
        soft_cap=soft_cap,
        sliding_window=sliding_window,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(B, M),
            in_specs=[
                pl.BlockSpec((1, Hq, D), lambda b, j, ly, t, l: (b, 0, 0)),
                pl.BlockSpec((1, Hkv, D), lambda b, j, ly, t, l: (b, 0, 0)),
                pl.BlockSpec((1, Hkv, D), lambda b, j, ly, t, l: (b, 0, 0)),
                pl.BlockSpec((1, 1, page, Hkv * D), page_map),
                pl.BlockSpec((1, 1, page, Hkv * D), page_map),
            ],
            out_specs=pl.BlockSpec(
                (1, Hq, D), lambda b, j, ly, t, l: (b, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((hq_pad, LANES), jnp.float32),
                pltpu.VMEM((hq_pad, LANES), jnp.float32),
                # lanes padded to a full tile; the kernel uses [:, :D]
                pltpu.VMEM((hq_pad, max(D, LANES)), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        interpret=_interpret(),
    )(
        jnp.asarray(layer, jnp.int32).reshape(1), table, lens,
        q, k_self, v_self, kv_flat, vv_flat,
    )
