"""Paged decode attention (Pallas TPU kernel).

One new token per slot attends to its KV pages IN PLACE — the page table
rides in as a scalar-prefetch operand and the kernel issues its own async
DMAs from the pool (which stays in HBM/ANY memory, full
``[L, P, 2, Hkv, page, D]`` — K and V interleaved head-major, so each
page is ONE DMA landing directly in the ``[Hkv, S, D]`` compute layout;
no flat reshape, no per-layer slice, no in-VMEM transpose). TPU
counterpart of vLLM/SGLang's paged-attention CUDA kernels, which the
reference inherits (SURVEY §2.1).

Grid ``(ceil(B/SB), ceil(M/KP))``: SB slots x KP pages per step. Grid-step
LATENCY (DMA round trips + fixed step cost, ~5.7 µs) — not bandwidth or
FLOPs — dominates decode at serving batch sizes, and it pays per step per
layer; batching SB slots per step amortizes it 8x (measured: one-page
one-slot steps cost 14 ms per 1.5B/64-slot decode step; 368 µs per
64-slot kernel call before slot batching). Every slot's page DMAs for a
step start together and overlap; out-of-range pages skip the DMA and
zero-fill (masked probabilities multiply NaN otherwise). GQA runs without
materializing the K/V head repeat: scores are batched ``dot_general``
over the kv-head axis.

The CURRENT token's K/V ride as separate operands and fold into the
online softmax at the last grid step (the pool is read-only during the
caller's layer scan; the model scatters all layers' new KV afterwards).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from areal_tpu.ops.pallas import compat
from areal_tpu.ops.pallas.compat import ANY_MEMORY_SPACE as _ANY_MEMORY_SPACE
from areal_tpu.ops.pallas.compat import compiler_params as _compiler_params

NEG_INF = -2.3819763e38
LANES = 128


def _interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


def _decode_kernel(
    *refs,
    scale: float,
    page: int,
    kp: int,
    sb: int,
    n_kv: int,
    n_rep: int,
    soft_cap: Optional[float],
    sliding_window: Optional[int],
    quantized: bool,
):
    # Ref order (inputs, outputs, scratch); the int8 pool adds a scales
    # input + a scales scratch/semaphore pair right after their KV twins:
    #   layer_ref  [1] int32 scalar-prefetch: which layer of the pool
    #   table_ref  [B, M] int32 scalar-prefetch
    #   lens_ref   [B] int32 scalar-prefetch (pool-resident, EXCL. self)
    #   q_ref      [SB, Hq, D]
    #   ks_ref     [SB, Hkv, D] the current tokens' K (not in the pool)
    #   vs_ref     [SB, Hkv, D]
    #   kv_hbm     [L, P, 2, Hkv, page, D] whole pool, ANY/HBM
    #   sc_hbm     [L, P, 2, Hkv, page] f32 scales, ANY/HBM   (quantized)
    #   o_ref      [SB, Hq, D]
    #   kv_scr     [2, SB, 2, Hkv, KP*page, D] DOUBLE-buffered page scratch
    #              — pages DMA straight into the compute layout while the
    #              previous grid step's buffer is being consumed
    #   sc_scr     [2, SB, 2, Hkv, KP*page] f32 scale scratch (quantized)
    #   m_scr      [SB, HqP, LANES] f32
    #   l_scr      [SB, HqP, LANES] f32
    #   acc_scr    [SB, HqP, Dp] f32
    #   sems       DMA semaphores [2, SB, KP]
    #   sc_sems    DMA semaphores [2, SB, KP]                 (quantized)
    if quantized:
        (layer_ref, table_ref, lens_ref, q_ref, ks_ref, vs_ref, kv_hbm,
         sc_hbm, o_ref, kv_scr, sc_scr, m_scr, l_scr, acc_scr, sems,
         sc_sems) = refs
    else:
        (layer_ref, table_ref, lens_ref, q_ref, ks_ref, vs_ref, kv_hbm,
         o_ref, kv_scr, m_scr, l_scr, acc_scr, sems) = refs
        sc_hbm = sc_scr = sc_sems = None
    bb = pl.program_id(0)
    j = pl.program_id(1)
    nblk = pl.num_programs(1)
    total = pl.num_programs(0) * nblk
    g = bb * nblk + j         # linearized grid step
    Hq = q_ref.shape[1]
    D = q_ref.shape[2]
    layer = layer_ref[0]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _issue(g_t, buf):
        """Start every resident-page DMA (and zero un-DMA'd tail blocks the
        body will read) for linear grid step ``g_t`` into buffer ``buf``."""
        bb_t = g_t // nblk
        j_t = g_t % nblk
        # the batched body reads EVERY slot's stripe whenever any slot of
        # the block is active, so un-DMA'd pages of shorter slots must be
        # zeroed up to the block the longest slot reaches (masked
        # probabilities are 0, but 0 * NaN = NaN in the PV dot)
        max_lens_t = functools.reduce(
            jnp.maximum, [lens_ref[bb_t * sb + s] for s in range(sb)]
        )
        for s in range(sb):
            slot = bb_t * sb + s
            n_used = pl.cdiv(lens_ref[slot], page)
            for i in range(kp):
                @pl.when(j_t * kp + i < n_used)
                def _start(s=s, i=i, slot=slot, j_t=j_t):
                    pidx = table_ref[slot, j_t * kp + i]
                    # K and V are interleaved per page: ONE DMA per page,
                    # landing in the [2, Hkv, i*page:(i+1)*page, D] stripe
                    # of the compute-layout scratch
                    pltpu.make_async_copy(
                        kv_hbm.at[layer, pidx],
                        kv_scr.at[buf, s, :, :, pl.ds(i * page, page), :],
                        sems.at[buf, s, i],
                    ).start()
                    if quantized:
                        # the page's scale stripe rides a second (tiny —
                        # 1/D of the page bytes) DMA into the parallel
                        # scale scratch; dequant happens in-register at
                        # the dots, never as a widened pool copy
                        pltpu.make_async_copy(
                            sc_hbm.at[layer, pidx],
                            sc_scr.at[buf, s, :, :, pl.ds(i * page, page)],
                            sc_sems.at[buf, s, i],
                        ).start()

                @pl.when(
                    (j_t * kp + i >= n_used)
                    & (j_t * kp * page < max_lens_t)
                )
                def _zero(s=s, i=i, j_t=j_t):
                    kv_scr[buf, s, :, :, pl.ds(i * page, page), :] = (
                        jnp.zeros((2, n_kv, page, D), kv_scr.dtype)
                    )
                    if quantized:
                        sc_scr[buf, s, :, :, pl.ds(i * page, page)] = (
                            jnp.zeros((2, n_kv, page), sc_scr.dtype)
                        )

    # Software pipeline over the (sequential) linearized grid: step g's
    # pages were prefetched at step g-1; here we kick off g+1's DMAs BEFORE
    # consuming g's, so the HBM reads for the next block overlap this
    # block's dots. Un-overlapped DMA cost drops from every grid step to
    # one per kernel call (measured r4: the serial issue->wait->compute
    # loop held the kernel at ~0.42 of HBM bandwidth).
    buf = jax.lax.rem(g, 2)

    @pl.when(g == 0)
    def _prologue():
        _issue(0, 0)

    @pl.when(g + 1 < total)
    def _prefetch():
        _issue(g + 1, jax.lax.rem(g + 1, 2))

    for s in range(sb):
        slot = bb * sb + s
        n_used = pl.cdiv(lens_ref[slot], page)
        for i in range(kp):
            @pl.when(j * kp + i < n_used)
            def _wait(s=s, i=i, slot=slot):
                pidx = table_ref[slot, j * kp + i]
                pltpu.make_async_copy(
                    kv_hbm.at[layer, pidx],
                    kv_scr.at[buf, s, :, :, pl.ds(i * page, page), :],
                    sems.at[buf, s, i],
                ).wait()
                if quantized:
                    pltpu.make_async_copy(
                        sc_hbm.at[layer, pidx],
                        sc_scr.at[buf, s, :, :, pl.ds(i * page, page)],
                        sc_sems.at[buf, s, i],
                    ).wait()

    S = kp * page
    # per-slot resident lengths as an [SB, 1, S] operand built from stacked
    # scalar SPLATS (Mosaic rejects 1D->3D vector reshapes); the whole
    # block body is BATCHED over slots — one slot-folded-batch dot pair
    # instead of SB sequential small-dot bodies, which left the MXU idle
    # between per-slot dots and made the (now DMA-overlapped) kernel
    # compute-bound
    lens_v = jnp.stack(
        [jnp.full((1, S), lens_ref[bb * sb + s], jnp.int32)
         for s in range(sb)]
    )                                                          # [SB, 1, S]
    max_lens = functools.reduce(
        jnp.maximum, [lens_ref[bb * sb + s] for s in range(sb)]
    )

    @pl.when((j * S < max_lens) & (max_lens > 0))
    def _body():
        # (SB, Hkv) folds into ONE batch dim (Mosaic's tpu.matmul supports
        # a single batch dim); the reshape is layout-free
        q = q_ref[...].reshape(sb * n_kv, n_rep, D)
        k = kv_scr[buf, :, 0].reshape(sb * n_kv, S, D)
        v = kv_scr[buf, :, 1].reshape(sb * n_kv, S, D)
        if quantized:
            # in-register widening: int8 in [-127, 127] is exact in bf16
            # (8 mantissa bits cover 256), so casting to q's dtype loses
            # nothing, and the per-(head, position) K scale folds into the
            # SCORES after the dot — it is constant over D, so
            # q·(k_int*s) == (q·k_int)*s with one [*, S] multiply instead
            # of rescaling the whole [*, S, D] block
            k = k.astype(q.dtype)
        sc = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale                                             # [SB*Hkv,r,S]
        if quantized:
            k_sc = sc_scr[buf, :, 0].reshape(sb * n_kv, S)
            sc = sc * k_sc[:, None, :]
        if soft_cap is not None:
            sc = soft_cap * jnp.tanh(sc / soft_cap)
        sc = sc.reshape(sb, Hq, S)
        kpos = j * S + jax.lax.broadcasted_iota(jnp.int32, (sb, Hq, S), 2)
        mask = kpos < lens_v
        if sliding_window is not None:
            # each query sits at position lens of its slot
            mask &= kpos > lens_v - sliding_window
        sc = jnp.where(mask, sc, NEG_INF)

        m_prev = m_scr[:, :Hq, 0:1]                           # [SB,Hq,1]
        m_new = jnp.maximum(m_prev, jnp.max(sc, axis=2, keepdims=True))
        p = jnp.where(mask, jnp.exp(sc - m_new), 0.0)         # [SB,Hq,S]
        corr = jnp.exp(
            jnp.where(m_prev > NEG_INF / 2, m_prev - m_new, 0.0)
        )
        l_new = corr * l_scr[:, :Hq, 0:1] + jnp.sum(
            p, axis=2, keepdims=True
        )
        pq = p.reshape(sb * n_kv, n_rep, S)
        if quantized:
            # the V scale folds into the probabilities (constant over D):
            # Σ_s p[s]·(v_int[s]·vs[s]) == Σ_s (p[s]·vs[s])·v_int[s]
            v_sc = sc_scr[buf, :, 1].reshape(sb * n_kv, S)
            pq = (pq * v_sc[:, None, :]).astype(jnp.float32)
            v = v.astype(jnp.float32)
        else:
            pq = pq.astype(v.dtype)
        pv = jax.lax.dot_general(
            pq, v,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ).reshape(sb, Hq, D)
        acc_scr[:, :Hq, :D] = acc_scr[:, :Hq, :D] * corr + pv
        m_scr[:, :Hq] = jnp.broadcast_to(m_new, (sb, Hq, LANES))
        l_scr[:, :Hq] = jnp.broadcast_to(l_new, (sb, Hq, LANES))

    @pl.when(j == nblk - 1)
    def _done():
        # fold the current tokens' self-attention (always attended; their
        # KV is scattered into the pool by the caller AFTER the layer scan)
        q = q_ref[...].reshape(sb, n_kv, n_rep, D)
        ks = ks_ref[...]                                      # [SB,Hkv,D]
        vs = vs_ref[...]
        s_self = jnp.sum(
            q.astype(jnp.float32) * ks[:, :, None].astype(jnp.float32),
            axis=3,
        ) * scale                                             # [SB,Hkv,r]
        if soft_cap is not None:
            s_self = soft_cap * jnp.tanh(s_self / soft_cap)
        s_self = s_self.reshape(sb, Hq, 1)
        m_prev = m_scr[:, :Hq, 0:1]
        m_new = jnp.maximum(m_prev, s_self)
        corr = jnp.exp(
            jnp.where(m_prev > NEG_INF / 2, m_prev - m_new, 0.0)
        )
        p_self = jnp.exp(s_self - m_new)                      # [SB,Hq,1]
        l = corr * l_scr[:, :Hq, 0:1] + p_self
        v_rep = jnp.broadcast_to(
            vs[:, :, None].astype(jnp.float32), (sb, n_kv, n_rep, D)
        ).reshape(sb, Hq, D)
        acc = acc_scr[:, :Hq, :D] * corr + p_self * v_rep
        o_ref[...] = (acc / l).astype(o_ref.dtype)


def decode(
    q: jnp.ndarray,          # [B, Hq, D]
    k_self: jnp.ndarray,     # [B, Hkv, D] current token's K (not in pool)
    v_self: jnp.ndarray,     # [B, Hkv, D]
    pages: jnp.ndarray,      # [L, P, 2, Hkv, page, D] the WHOLE pool
    layer: jnp.ndarray,      # scalar i32 layer index
    table: jnp.ndarray,      # [B, M] i32
    lens: jnp.ndarray,       # [B] tokens resident in the pool (excl. self)
    *,
    softmax_scale: Optional[float] = None,
    soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
    pages_per_step: int = 8,
    slots_per_step: int = 8,
    scales: Optional[jnp.ndarray] = None,  # [L, P, 2, Hkv, page] f32
) -> jnp.ndarray:
    """The pool rides in whole (ANY memory space); the kernel issues its own
    per-page DMAs keyed by the scalar-prefetched layer index and page table
    — the caller's layer scan never slices or reshapes the pool.

    ``scales`` marks an int8 pool (docs/performance.md "KV quantization"):
    each page's scale stripe DMAs alongside the page into a parallel
    scratch and dequant fuses into the dots — the HBM read stays int8
    (half the KV bytes of bf16 + a 1/D scale overhead), values widen only
    in-register."""
    if _ANY_MEMORY_SPACE is None or not compat.compiler_params_available():
        # fail loudly at the boundary, not deep inside the kernel build:
        # the pool ref must stay in ANY/HBM, and the double-buffered page
        # scratch NEEDS the vmem_limit_bytes raise (silently dropping it
        # would die in the XLA compile with a scoped-vmem error)
        raise RuntimeError(
            "pallas paged decode unavailable: the installed jax lacks "
            "pltpu MemorySpace/TPUMemorySpace or CompilerParams/"
            "TPUCompilerParams — use the XLA gather path "
            "(use_pallas=False)"
        )
    B, Hq, D = q.shape
    L, P, _, Hkv, page, _ = pages.shape
    M = table.shape[1]
    n_rep = Hq // Hkv
    quantized = scales is not None
    page_mult = 32 if quantized else 8  # int8 sublane tile is 32
    if not _interpret() and (D % 128 != 0 or page % page_mult != 0):
        raise ValueError(
            f"paged kernel needs head_dim%128==0 and page%{page_mult}==0 "
            f"on TPU; got D={D}, page={page} — use the XLA gather path"
        )
    if softmax_scale is None:
        softmax_scale = D ** -0.5
    hq_pad = max(8, Hq)
    kp = min(pages_per_step, M)
    nblk = -(-M // kp)
    sb = slots_per_step
    while B % sb:
        sb //= 2

    def _scratch_bytes(sb_):
        # double-buffered KV pages + (quantized) their f32 scale stripes
        b = 2 * 2 * sb_ * kp * page * Hkv * D * pages.dtype.itemsize
        if quantized:
            b += 2 * 2 * sb_ * kp * page * Hkv * 4
        return b

    # VMEM budget: keep the (double-buffered) KV scratch under ~16 MB
    while sb > 1 and _scratch_bytes(sb) > 16 * 1024 * 1024:
        sb //= 2

    kernel = functools.partial(
        _decode_kernel,
        scale=softmax_scale,
        page=page,
        kp=kp,
        sb=sb,
        n_kv=Hkv,
        n_rep=n_rep,
        soft_cap=soft_cap,
        sliding_window=sliding_window,
        quantized=quantized,
    )
    in_specs = [
        pl.BlockSpec((sb, Hq, D), lambda b, j, ly, t, l: (b, 0, 0)),
        pl.BlockSpec((sb, Hkv, D), lambda b, j, ly, t, l: (b, 0, 0)),
        pl.BlockSpec((sb, Hkv, D), lambda b, j, ly, t, l: (b, 0, 0)),
        pl.BlockSpec(memory_space=_ANY_MEMORY_SPACE),
    ]
    scratch_shapes = [
        pltpu.VMEM((2, sb, 2, Hkv, kp * page, D), pages.dtype),
        pltpu.VMEM((sb, hq_pad, LANES), jnp.float32),
        pltpu.VMEM((sb, hq_pad, LANES), jnp.float32),
        # lanes padded to a full tile; the kernel uses [:, :D]
        pltpu.VMEM((sb, hq_pad, max(D, LANES)), jnp.float32),
        pltpu.SemaphoreType.DMA((2, sb, kp)),
    ]
    operands = [
        jnp.asarray(layer, jnp.int32).reshape(1), table, lens,
        q, k_self, v_self, pages,
    ]
    if quantized:
        # scales ride whole in ANY/HBM like the pool; their scratch and
        # semaphores slot in right after their KV twins (kernel ref order)
        in_specs.append(pl.BlockSpec(memory_space=_ANY_MEMORY_SPACE))
        scratch_shapes.insert(
            1, pltpu.VMEM((2, sb, 2, Hkv, kp * page), jnp.float32)
        )
        scratch_shapes.append(pltpu.SemaphoreType.DMA((2, sb, kp)))
        operands.append(scales)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(B // sb, nblk),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (sb, Hq, D), lambda b, j, ly, t, l: (b, 0, 0)
            ),
            scratch_shapes=scratch_shapes,
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        # the double-buffered page scratch alone can exceed the 16 MB
        # default scoped-vmem budget; size the limit from the actual
        # scratch + generous op margin (v5e VMEM is 128 MB)
        compiler_params=_compiler_params(
            vmem_limit_bytes=_scratch_bytes(sb) + 32 * 2**20,
        ),
        interpret=_interpret(),
    )(*operands)

