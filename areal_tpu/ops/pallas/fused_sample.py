"""Fused LM-head + sampling epilogue (Pallas TPU kernel).

One grid step per vocab block: the head block ``W[:, j*BV:(j+1)*BV]``
streams HBM -> VMEM through the pallas pipeline while the hidden states
``x [R, E]`` stay resident, the block's logits come off the MXU in f32,
and the sampling state folds in online — running softmax normalizer
(max + rescaled sum-of-exponentials, the same recurrence as the paged
extend kernel), running raw argmax (greedy slots, token-exact), and a
running Gumbel-top-1 argmax over the temperature-warped logits (the
categorical sample; in-kernel PRNG via ``pltpu.prng_seed`` /
``prng_random_bits``, reseeded per block from the scalar-prefetched seed
so the stream is grid-order independent). The full ``[R, V]`` logits
tensor never exists in HBM: HBM traffic is exactly one read of the head
weight — the decode-epilogue roofline.

Per-row extras for the speculative verify path: an *excluded* token
(masked out of the Gumbel argmax only — the rejection-sampling residual
"p with the rejected token removed") and a *gathered* token whose warped
logit is returned (the draft-token acceptance score).

Top-k slots are NOT handled here (the online top-k buffer lives in the
streamed XLA path of ``ops/fused_sample.py``; the engine routes top-k
rows there or to the sorted fallback). The dispatch in
``ops/fused_sample.py`` enforces this.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from areal_tpu.ops.pallas import compat
from areal_tpu.ops.pallas.compat import compiler_params as _compiler_params

NEG_INF = -2.3819763e38
LANES = 128
_BIG_I32 = 2 ** 30  # python literal: a jnp scalar would be a captured const


def _interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


def _first_max_idx(vals, cols, valid):
    """(max value [R,1], first column index attaining it [R,1]) — the 2D
    formulation of argmax (min column id among the maxima) so the kernel
    never needs a 1-D iota, and tie order matches ``jnp.argmax``."""
    mv = jnp.max(jnp.where(valid, vals, NEG_INF), axis=-1, keepdims=True)
    at_max = valid & (vals == mv)
    mi = jnp.min(jnp.where(at_max, cols, _BIG_I32), axis=-1, keepdims=True)
    return mv, mi


def _kernel(
    seed_ref, x_ref, w_ref, temp_ref, greedy_ref, excl_ref, gid_ref,
    tok_ref, lp_ref, argmax_ref, gat_ref, norm_ref,
    m_scr, l_scr, amv_scr, ami_scr, gp_scr, gw_scr, gi_scr, gat_scr,
    *, nb: int, block_v: int, vocab: int, soft_cap: Optional[float],
):
    j = pl.program_id(0)
    R = x_ref.shape[0]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        amv_scr[...] = jnp.full_like(amv_scr, NEG_INF)
        ami_scr[...] = jnp.zeros_like(ami_scr)
        gp_scr[...] = jnp.full_like(gp_scr, NEG_INF)
        gw_scr[...] = jnp.zeros_like(gw_scr)
        gi_scr[...] = jnp.zeros_like(gi_scr)
        gat_scr[...] = jnp.full_like(gat_scr, NEG_INF)

    logits = jnp.dot(
        x_ref[...], w_ref[...], preferred_element_type=jnp.float32
    )
    if soft_cap is not None and soft_cap > 0:
        logits = jnp.tanh(logits / soft_cap) * soft_cap
    cols = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (R, block_v), 1
    )
    valid = cols < vocab
    t = jnp.maximum(temp_ref[:, :1], 1e-6)
    warped = jnp.where(valid, logits, 0.0) / t

    # online logsumexp of the warped logits
    m_prev = m_scr[:, :1]
    bm = jnp.max(jnp.where(valid, warped, NEG_INF), axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, bm)
    l_new = l_scr[:, :1] * jnp.exp(m_prev - m_new) + jnp.sum(
        jnp.where(valid, jnp.exp(warped - m_new), 0.0),
        axis=-1, keepdims=True,
    )
    m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    # running raw argmax: strict > keeps the earliest maximum across
    # blocks, matching jnp.argmax tie order over the full vocab
    bv, bi = _first_max_idx(logits, cols, valid)
    upd = bv > amv_scr[:, :1]
    amv_new = jnp.where(upd, bv, amv_scr[:, :1])
    ami_new = jnp.where(upd, bi, ami_scr[:, :1])
    amv_scr[...] = jnp.broadcast_to(amv_new, amv_scr.shape)
    ami_scr[...] = jnp.broadcast_to(ami_new, ami_scr.shape)

    # Gumbel-top-1 over warped (+ per-row exclusion): running argmax of
    # warped + G across every block IS a categorical draw. Uniforms come
    # from a counter-based hash of (seed, row, global column) — the
    # murmur3 finalizer over a per-element counter — rather than the
    # stateful pltpu PRNG: identical bits in compiled and interpret mode
    # (the interpret path has no prng_seed lowering), and independent of
    # grid-iteration order by construction.
    rows_i = jax.lax.broadcasted_iota(jnp.int32, (R, block_v), 0)
    h = (cols * -1640531527) ^ (rows_i * -2048144789) ^ seed_ref[0]
    h = jax.lax.bitcast_convert_type(h, jnp.uint32)
    h = h ^ (h >> 16)
    h = h * np.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * np.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    u = ((h >> 8).astype(jnp.float32) + 0.5) * (1.0 / (1 << 24))
    pert = warped - jnp.log(-jnp.log(u))
    pert = jnp.where(cols == excl_ref[:, :1], NEG_INF, pert)
    pbv, pbi = _first_max_idx(pert, cols, valid)
    pw = jnp.sum(
        jnp.where(cols == pbi, warped, 0.0), axis=-1, keepdims=True
    )
    upd2 = pbv > gp_scr[:, :1]
    gp_new = jnp.where(upd2, pbv, gp_scr[:, :1])
    gw_new = jnp.where(upd2, pw, gw_scr[:, :1])
    gi_new = jnp.where(upd2, pbi, gi_scr[:, :1])
    gp_scr[...] = jnp.broadcast_to(gp_new, gp_scr.shape)
    gw_scr[...] = jnp.broadcast_to(gw_new, gw_scr.shape)
    gi_scr[...] = jnp.broadcast_to(gi_new, gi_scr.shape)

    # gathered warped logit (speculative draft score)
    hit = valid & (cols == gid_ref[:, :1])
    any_hit = jnp.max(
        jnp.where(hit, 1.0, 0.0), axis=-1, keepdims=True
    ) > 0.0
    gval = jnp.sum(jnp.where(hit, warped, 0.0), axis=-1, keepdims=True)
    gat_new = jnp.where(any_hit, gval, gat_scr[:, :1])
    gat_scr[...] = jnp.broadcast_to(gat_new, gat_scr.shape)

    @pl.when(j == nb - 1)
    def _emit():
        norm = m_new + jnp.log(l_new)
        is_greedy = greedy_ref[:, :1] > 0
        tok = jnp.where(is_greedy, ami_new, gi_new)
        lp = jnp.where(is_greedy, amv_new / t - norm, gw_new - norm)
        tok_ref[...] = jnp.broadcast_to(tok, tok_ref.shape)
        lp_ref[...] = jnp.broadcast_to(lp, lp_ref.shape)
        argmax_ref[...] = jnp.broadcast_to(ami_new, argmax_ref.shape)
        gat_ref[...] = jnp.broadcast_to(gat_new - norm, gat_ref.shape)
        norm_ref[...] = jnp.broadcast_to(norm, norm_ref.shape)


def fused_sample_pallas(
    rng: jax.Array,
    x: jnp.ndarray,               # [R, E]
    w: jnp.ndarray,               # [E, V]
    temperature: jnp.ndarray,     # [R] f32
    greedy: jnp.ndarray,          # [R] bool
    exclude: Optional[jnp.ndarray] = None,     # [R] i32, -1 = none
    gather_ids: Optional[jnp.ndarray] = None,  # [R] i32
    soft_cap: Optional[float] = None,
    block_v: int = 2048,
    interpret: Optional[bool] = None,
):
    """Kernel wrapper; same result dict as the XLA path of
    ``ops/fused_sample.py`` (minus top-k, which the dispatch never routes
    here). The PRNG seed derives from ``rng`` on device — no host
    round-trip rides the dispatch."""
    if not compat.compiler_params_available():
        raise RuntimeError(
            "pallas fused sample unavailable: the installed jax lacks "
            "CompilerParams/TPUCompilerParams — use the XLA epilogue "
            "(use_pallas=False)"
        )
    R, E = x.shape
    V = w.shape[1]
    block_v = max(LANES, min(block_v, -(-V // LANES) * LANES))
    nb = -(-V // block_v)
    seed = jax.random.randint(
        rng, (1,), jnp.iinfo(jnp.int32).min, jnp.iinfo(jnp.int32).max,
        dtype=jnp.int32,
    )

    def _rows(v, dtype, fill):
        if v is None:
            arr = jnp.full((R, 1), fill, dtype)
        else:
            arr = v.astype(dtype).reshape(R, 1)
        return jnp.broadcast_to(arr, (R, LANES))

    operands = [
        seed,
        x,
        w,
        _rows(temperature, jnp.float32, 1.0),
        _rows(greedy.astype(jnp.int32), jnp.int32, 0),
        _rows(exclude, jnp.int32, -1),
        _rows(gather_ids, jnp.int32, -1),
    ]
    row_spec = pl.BlockSpec((R, LANES), lambda j, s: (0, 0))
    kernel = functools.partial(
        _kernel, nb=nb, block_v=block_v, vocab=V, soft_cap=soft_cap,
    )
    outs = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nb,),
            in_specs=[
                pl.BlockSpec((R, E), lambda j, s: (0, 0)),
                pl.BlockSpec((E, block_v), lambda j, s: (0, j)),
                row_spec, row_spec, row_spec, row_spec,
            ],
            out_specs=[row_spec] * 5,
            scratch_shapes=[
                pltpu.VMEM((R, LANES), jnp.float32),   # m
                pltpu.VMEM((R, LANES), jnp.float32),   # l
                pltpu.VMEM((R, LANES), jnp.float32),   # argmax value
                pltpu.VMEM((R, LANES), jnp.int32),     # argmax index
                pltpu.VMEM((R, LANES), jnp.float32),   # gumbel perturbed max
                pltpu.VMEM((R, LANES), jnp.float32),   # warped @ gumbel idx
                pltpu.VMEM((R, LANES), jnp.int32),     # gumbel index
                pltpu.VMEM((R, LANES), jnp.float32),   # gathered warped
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((R, LANES), jnp.int32),    # tokens
            jax.ShapeDtypeStruct((R, LANES), jnp.float32),  # logprobs
            jax.ShapeDtypeStruct((R, LANES), jnp.int32),    # argmax
            jax.ShapeDtypeStruct((R, LANES), jnp.float32),  # gathered_lp
            jax.ShapeDtypeStruct((R, LANES), jnp.float32),  # norm
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=(
                # resident x + one head block (double-buffered) + row state
                4 * R * E + 2 * 4 * E * block_v + 16 * R * LANES * 4
                + 32 * 2 ** 20
            ),
        ),
        interpret=_interpret() if interpret is None else interpret,
    )(*operands)
    tok, lp, am, gat, norm = (o[:, 0] for o in outs)
    out = {
        "tokens": tok,
        "logprobs": lp,
        "argmax": am,
        "norm": norm,
    }
    if gather_ids is not None:
        out["gathered_lp"] = gat
    return out
