"""Packed varlen flash attention (Pallas TPU kernel).

Block-wise online-softmax attention over one packed token axis with
segment-id masking — the TPU counterpart of the reference's
``flash_attn_varlen_func(cu_seqlens)`` path
(``realhf/impl/model/modules/attn.py:272-289``).

Layout: ``q [H, T, D]``-major inside the kernel (the public wrapper
transposes from the model's ``[T, H, D]``). Grid is
``(heads, q_blocks, k_blocks)`` with the k axis innermost — TPU grids run
sequentially minor-to-major, so the VMEM scratch accumulators carry the
online-softmax state (m, l, acc) across k blocks of one (head, q block).
Causal + segment masking means k blocks strictly above the diagonal are
skipped via ``pl.when`` (no FLOPs, no DMA use of the loaded block).

GQA folds the query-head group into the kv head index via the BlockSpec
index maps (no materialized K/V repeat).

Backward: flash recompute backward is TODO (tracked for the perf pass); the
custom_vjp here recomputes attention with the O(T²) XLA path, which remat
confines to one layer at a time.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -2.3819763e38
LANES = 128


def _flash_kernel(
    seg_q_ref,  # [1, block_q] int32
    seg_k_ref,  # [1, block_k] int32
    q_ref,      # [1, block_q, D]
    k_ref,      # [1, block_k, D]
    v_ref,      # [1, block_k, D]
    o_ref,      # [1, block_q, D]
    m_scr,      # [block_q, LANES] f32
    l_scr,      # [block_q, LANES] f32
    acc_scr,    # [block_q, D] f32
    *,
    scale: float,
    block_q: int,
    block_k: int,
    soft_cap: Optional[float],
    sliding_window: Optional[int],
):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Causal block structure: block contributes iff its first k index can be
    # <= the last q index of this q block.
    diag_ok = ik * block_k <= iq * block_q + block_q - 1
    in_window = True
    if sliding_window is not None:
        # skip blocks entirely left of the window
        in_window = (iq * block_q) - (ik * block_k + block_k - 1) < sliding_window

    @pl.when(diag_ok & in_window)
    def _body():
        q = q_ref[0].astype(jnp.float32)          # [bq, D]
        k = k_ref[0].astype(jnp.float32)          # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                  # [bq, bk]
        if soft_cap is not None:
            s = soft_cap * jnp.tanh(s / soft_cap)
        q_idx = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        k_idx = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        seg_q = seg_q_ref[0][:, None]              # [bq, 1]
        seg_k = seg_k_ref[0][None, :]              # [1, bk]
        mask = (q_idx >= k_idx) & (seg_q == seg_k) & (seg_q > 0)
        if sliding_window is not None:
            mask &= q_idx - k_idx < sliding_window
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scr[:, 0:1]                     # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)  # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        # exp(NEG_INF - m) underflows to 0 for fully-masked rows
        p = jnp.exp(s - m_new)                     # [bq, bk]
        corr = jnp.exp(m_prev - m_new)             # [bq, 1]
        l_new = corr * l_scr[:, 0:1] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == nk - 1)
    def _done():
        l = l_scr[:, 0:1]
        safe_l = jnp.where(l > 0.0, l, 1.0)
        o_ref[0] = (acc_scr[...] / safe_l).astype(o_ref.dtype)


def _flash_forward(
    q, k, v, segment_ids, scale, soft_cap, sliding_window, block_q, block_k
):
    """q: [H, T, D]; k, v: [Hkv, T, D]; segment_ids: [T] -> out [H, T, D]."""
    H, T, D = q.shape
    Hkv = k.shape[0]
    n_rep = H // Hkv
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    assert T % block_q == 0 and T % block_k == 0, (T, block_q, block_k)
    grid = (H, T // block_q, T // block_k)
    seg2d = segment_ids.reshape(1, T)

    kernel = functools.partial(
        _flash_kernel,
        scale=scale,
        block_q=block_q,
        block_k=block_k,
        soft_cap=soft_cap,
        sliding_window=sliding_window,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q), lambda h, i, j: (0, i)),
            pl.BlockSpec((1, block_k), lambda h, i, j: (0, j)),
            pl.BlockSpec((1, block_q, D), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec(
                (1, block_k, D), lambda h, i, j, r=n_rep: (h // r, j, 0)
            ),
            pl.BlockSpec(
                (1, block_k, D), lambda h, i, j, r=n_rep: (h // r, j, 0)
            ),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda h, i, j: (h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((H, T, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        # off-TPU (CPU tests) the kernel runs in the pallas interpreter
        interpret=jax.devices()[0].platform != "tpu",
    )(seg2d, seg2d, q, k, v)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8)
)
def _flash_thd(q, k, v, segment_ids, scale, soft_cap, sliding_window, block_q, block_k):
    """[T, H, D]-layout entry with custom vjp."""
    out = _flash_forward(
        q.swapaxes(0, 1),
        k.swapaxes(0, 1),
        v.swapaxes(0, 1),
        segment_ids,
        scale,
        soft_cap,
        sliding_window,
        block_q,
        block_k,
    )
    return out.swapaxes(0, 1)


def _flash_fwd_rule(q, k, v, segment_ids, scale, soft_cap, sliding_window, block_q, block_k):
    out = _flash_thd(q, k, v, segment_ids, scale, soft_cap, sliding_window, block_q, block_k)
    return out, (q, k, v, segment_ids)


def _flash_bwd_rule(scale, soft_cap, sliding_window, block_q, block_k, res, g):
    # Recompute with the XLA path and differentiate it. Memory-heavy but
    # remat-confined to one layer; the fused flash backward kernel is the
    # planned perf-pass replacement.
    from areal_tpu.ops.attention import _attention_xla

    q, k, v, segment_ids = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _attention_xla(
            q_, k_, v_, segment_ids, scale, soft_cap, sliding_window
        ),
        q,
        k,
        v,
    )
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


_flash_thd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def packed_flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    segment_ids: jnp.ndarray,
    *,
    softmax_scale: float,
    soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
    block_size: int = 512,
) -> jnp.ndarray:
    """Causal packed-varlen flash attention. q ``[T, H, D]``, k/v
    ``[T, Hkv, D]``, segment_ids ``[T]`` (0 = pad) -> ``[T, H, D]``."""
    return _flash_thd(
        q, k, v, segment_ids.astype(jnp.int32), softmax_scale, soft_cap,
        sliding_window, block_size, block_size,
    )
