"""Packed varlen flash attention (Pallas TPU kernels, forward AND backward).

Block-wise online-softmax attention over one packed token axis with
segment-id masking — the TPU counterpart of the reference's
``flash_attn_varlen_func(cu_seqlens)`` path
(``realhf/impl/model/modules/attn.py:272-289``), which trains through fused
varlen flash in both directions.

Layout: ``q [H, T, D]``-major inside the kernels (the public wrapper
transposes from the model's ``[T, H, D]``). TPU grids run sequentially
minor-to-major, so VMEM scratch accumulators carry state across the
innermost grid axis:

- **forward**: grid ``(H, nq, nk)``; online-softmax state (m, l, acc) per
  (head, q block); also emits the logsumexp ``lse [H, T]`` for the backward.
- **bwd (fused)**: grid ``(Hkv, nk, n_rep, nq)``; for one kv-head k block,
  a single (p, ds) recompute feeds ``dv += pᵀ dо``, ``dk += dsᵀ q`` (per-
  block VMEM scratch) AND ``dq += ds k`` (whole-group ``[n_rep, T, D]`` f32
  VMEM scratch, flushed once per kv head) — 5 dots + 1 exp per block pair
  instead of the 7 + 2 of separate dq/dkv sweeps. Falls back to the separate
  ``_dq_kernel``/``_dkv_kernel`` sweeps when the dq scratch exceeds
  ``FUSED_BWD_MAX_DQ_BYTES``. GQA never materializes a K/V repeat: the group
  is a grid axis.

**Band-limited iteration.** Packed rows carry non-decreasing segment ids
(padding 0 at the tail), so the only (q block, k block) pairs with any
unmasked work form a band: causal diagonal on one side, the first k block
containing the q block's minimum segment (`kstart`, narrowed further by a
sliding window) on the other. The band bounds ride in as scalar-prefetch
operands and feed the BlockSpec index maps: out-of-band grid steps clamp to
the previous block index, and Pallas skips the DMA entirely when the index
map output repeats. Inside the band the kernels run unconditionally (the
token-level mask handles block-edge partials), so skipped steps cost neither
FLOPs nor HBM traffic.

**Interior-block specialization.** The kernels are VPU-bound, not MXU-bound:
at D=64 each score element costs ~128 MXU FLOPs but ~10 VPU passes when the
token-level mask is materialized (two iotas, three compares, two ands, a
where, the exp). For a 32k causal row all but the ~3% diagonal/segment-edge
blocks are *interior* — every token pair unmasked — so a per-(q block,
k block) ``needs_mask`` table (computed in XLA, scalar-prefetched) routes
each grid step to either the masked body or a mask-free fast body that runs
just the online-softmax update. Softmax runs in the log2 domain
(``exp2(s·scale·log2e)``) — one fewer VPU multiply per element than ``exp``,
matching how Mosaic lowers transcendentals; the emitted ``lse`` stays in
natural log, so the contract with the backward and with ring attention is
unchanged.

The backward follows the flash-attention-2 recipe: residuals are
``(q, k, v, out, lse)``; ``delta = rowsum(dо * out)`` is computed in XLA
(cheap elementwise reduce), and ``ds = p * (dp - delta)`` inside the kernel.
All matmuls take bf16 operands with f32 accumulation (operand-side f32
casts would quarter MXU throughput).
"""

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -2.3819763e38
LANES = 128
LOG2E = 1.4426950408889634
LN2 = 0.6931471805599453
# Dual-body (masked/interior) kernels pay a small branch overhead per grid
# step; below this token count boundary blocks dominate any realistic packing
# and the single masked body wins.
SPECIALIZE_MIN_T = 8192
# Fused-backward dq scratch + output block budget (v5e has 128 MB VMEM; the
# rest of the kernel needs ~30 MB at block 1024). Above this the backward
# falls back to separate dq/dkv sweeps.
FUSED_BWD_MAX_DQ_BYTES = 48 * 2**20


def _interpret() -> bool:
    # off-TPU (CPU tests) the kernels run in the pallas interpreter
    return jax.devices()[0].platform != "tpu"


def _band_bounds(segment_ids, block_q, block_k, sliding_window, T):
    """Per-block band bounds for the packed row (all int32):

    - ``kstart [nq]``: first k block with any key the q block may attend to
      (segment- and window-derived; can exceed the causal diagonal for
      all-pad q blocks — callers clamp to it).
    - ``qlast [nk]``: last q block with any query attending into the k block
      (-1 when the k block is all padding).
    """
    nq, nk = T // block_q, T // block_k
    BIG = jnp.int32(2**30)
    sq = segment_ids.reshape(nq, block_q)
    sk = segment_ids.reshape(nk, block_k)
    qmin = jnp.where(sq > 0, sq, BIG).min(axis=1).astype(jnp.int32)
    kmax = sk.max(axis=1).astype(jnp.int32)
    # monotone prefix: pad-tail kmax drops to 0, so search on the running max
    kmax_mono = jax.lax.associative_scan(jnp.maximum, kmax)
    kstart = jnp.searchsorted(kmax_mono, qmin, side="left").astype(jnp.int32)
    # qmin is globally non-decreasing (BIG on the pad tail)
    qlast = (
        jnp.searchsorted(qmin, kmax, side="right").astype(jnp.int32) - 1
    )
    qlast = jnp.where(kmax > 0, qlast, -1)
    if sliding_window is not None:
        iq = jnp.arange(nq, dtype=jnp.int32)
        ik = jnp.arange(nk, dtype=jnp.int32)
        kstart = jnp.maximum(
            kstart,
            jnp.maximum(iq * block_q - (sliding_window - 1), 0) // block_k,
        )
        qlast = jnp.minimum(
            qlast, (ik * block_k + block_k - 1 + sliding_window - 1) // block_q
        )
    return kstart, qlast


def _last_k(iq, block_q, block_k):
    """Causal diagonal: last k block with keys not after this q block."""
    return (iq * block_q + block_q - 1) // block_k


def _first_q(ik, block_q, block_k):
    """Causal diagonal: first q block with queries not before this k block."""
    return (ik * block_k) // block_q


def _k_band_blocks(block_q, block_k, max_seqlen, T):
    """Static bound on the k-block band width per q block: a q block's
    earliest needed key starts at most ``max_seqlen - 1`` tokens before the
    block (the segment containing its first token), and its last is the
    causal diagonal — so the span is <= block_q + max_seqlen - 1 tokens."""
    nk = T // block_k
    if max_seqlen is None:
        return nk
    return min(nk, -(-(block_q + max_seqlen - 1) // block_k) + 1)


def _q_band_blocks(block_q, block_k, max_seqlen, T):
    """Static bound on the q-block band width per k block (symmetric)."""
    nq = T // block_q
    if max_seqlen is None:
        return nq
    return min(nq, -(-(block_k + max_seqlen - 1) // block_q) + 1)


def _block_needs_mask(segment_ids, block_q, block_k, sliding_window, T):
    """``[nq*nk] int32``: 0 where the (q block, k block) pair is *interior* —
    every token pair unmasked (block fully below the causal diagonal, one
    shared nonzero segment, fully inside any sliding window) — so the
    kernels skip mask construction entirely; 1 where token-level masking is
    required. Out-of-band pairs never execute a body, so their value is
    irrelevant."""
    nq, nk = T // block_q, T // block_k
    sq = segment_ids.reshape(nq, block_q)
    sk = segment_ids.reshape(nk, block_k)
    q_seg = sq.min(axis=1)
    q_uni = (q_seg == sq.max(axis=1)) & (q_seg > 0)
    k_seg = sk.min(axis=1)
    k_uni = k_seg == sk.max(axis=1)
    same = q_uni[:, None] & k_uni[None, :] & (q_seg[:, None] == k_seg[None, :])
    iq = jnp.arange(nq, dtype=jnp.int32)
    ik = jnp.arange(nk, dtype=jnp.int32)
    causal = (iq * block_q)[:, None] >= (ik * block_k + block_k - 1)[None, :]
    interior = same & causal
    if sliding_window is not None:
        maxdiff = (iq * block_q + block_q - 1)[:, None] - (ik * block_k)[None, :]
        interior &= maxdiff < sliding_window
    return jnp.where(interior, 0, 1).astype(jnp.int32).reshape(-1)


def _scores_log2(q_ref, k_ref, scale, soft_cap):
    """Block scores in the log2 domain: ``(q·kᵀ)·scale·log2e`` (soft-capped
    in the natural domain first when requested). f32 [bq, bk]."""
    s = jax.lax.dot_general(
        q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if soft_cap is None:
        return s * (scale * LOG2E)
    s = soft_cap * jnp.tanh(s * (scale / soft_cap))
    return s * LOG2E


def _token_mask(seg_q_ref, seg_k_ref, iq, ik, block_q, block_k, sliding_window):
    """Token-level mask for a boundary block (causal ∧ same segment ∧ not
    pad ∧ window)."""
    q_idx = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_idx = ik * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    seg_q = seg_q_ref[0][:, None]
    seg_k = seg_k_ref[0][None, :]
    mask = (q_idx >= k_idx) & (seg_q == seg_k) & (seg_q > 0)
    if sliding_window is not None:
        mask &= q_idx - k_idx < sliding_window
    return mask


def _dispatch_masked(active, specialize, needs_scalar, body):
    """Register the masked/interior pl.when branches shared by every kernel:
    ``body(masked)`` runs under ``active``; with ``specialize`` the
    ``needs_scalar`` table value routes to the mask-free interior body."""
    if specialize:

        @pl.when(active & (needs_scalar == 1))
        def _boundary():
            body(masked=True)

        @pl.when(active & (needs_scalar == 0))
        def _interior():
            body(masked=False)

    else:

        @pl.when(active)
        def _body():
            body(masked=True)



# --------------------------------------------------------------------------- #
# forward
# --------------------------------------------------------------------------- #


def _fwd_kernel(
    kstart_ref,  # [nq] int32 scalar-prefetch
    needs_ref,   # [nq*nk] int32 scalar-prefetch (see _block_needs_mask)
    seg_q_ref,   # [1, block_q] int32
    seg_k_ref,   # [1, block_k] int32
    q_ref,       # [1, block_q, D]
    k_ref,       # [1, block_k, D]
    v_ref,       # [1, block_k, D]
    o_ref,       # [1, block_q, D]
    lse_ref,     # [1, 1, block_q, 1] f32 (column layout; see _flash_forward)
    m_scr,       # [block_q, LANES] f32 (running max, log2 domain)
    l_scr,       # [block_q, LANES] f32
    acc_scr,     # [block_q, D] f32
    *,
    scale: float,
    block_q: int,
    block_k: int,
    nk_blocks: int,
    soft_cap: Optional[float],
    sliding_window: Optional[int],
    specialize: bool,
):
    iq = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)
    ik = kstart_ref[iq] + j  # band-relative -> absolute k block

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _update(masked: bool):
        s2 = _scores_log2(q_ref, k_ref, scale, soft_cap)  # [bq, bk] f32
        if masked:
            mask = _token_mask(
                seg_q_ref, seg_k_ref, iq, ik, block_q, block_k, sliding_window
            )
            s2 = jnp.where(mask, s2, NEG_INF)
        m_prev = m_scr[:, 0:1]                     # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s2, axis=1, keepdims=True))
        p = jnp.exp2(s2 - m_new)                   # [bq, bk]
        if masked:
            # NEG_INF is finite, so exp2(s2 - m_new) is 1 (not 0) on
            # fully-masked rows — zero masked entries explicitly so pad rows
            # keep l == 0 and output 0, matching the XLA path.
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp2(m_prev - m_new)            # [bq, 1]
        l_new = corr * l_scr[:, 0:1] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    active = ik <= _last_k(iq, block_q, block_k)
    needs = needs_ref[iq * nk_blocks + jnp.minimum(ik, nk_blocks - 1)]
    _dispatch_masked(active, specialize, needs, _update)

    @pl.when(j == nk - 1)
    def _done():
        l = l_scr[:, 0:1]
        safe_l = jnp.where(l > 0.0, l, 1.0)
        o_ref[0] = (acc_scr[...] / safe_l).astype(o_ref.dtype)
        # natural-log logsumexp residual; NEG_INF on fully-masked (pad) rows
        lse = jnp.where(
            l > 0.0, m_scr[:, 0:1] * LN2 + jnp.log(safe_l), NEG_INF
        )                                          # [bq, 1]
        lse_ref[0, 0] = lse


def _flash_forward(
    q, k, v, segment_ids, scale, soft_cap, sliding_window, block_q, block_k,
    max_seqlen,
):
    """q: [H, T, D]; k, v: [Hkv, T, D]; segment_ids: [T]
    -> (out [H, T, D], lse [H, T] f32).

    The kernel-side lse layout is ``[H, nq, block_q, 1]`` — Mosaic requires
    the last two block dims be (÷8, ÷128) or full, and a trailing size-1 lane
    dim keeps per-q-block logsumexp columns addressable per (head, q block)
    without a 128-lane broadcast buffer. It is compacted to ``[H, T]`` in XLA
    right after the call, so the padded layout never persists as a residual."""
    H, T, D = q.shape
    Hkv = k.shape[0]
    n_rep = H // Hkv
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    assert T % block_q == 0 and T % block_k == 0, (T, block_q, block_k)
    grid = (H, T // block_q, _k_band_blocks(block_q, block_k, max_seqlen, T))
    seg2d = segment_ids.reshape(1, T)
    kstart, _ = _band_bounds(segment_ids, block_q, block_k, sliding_window, T)
    needs = _block_needs_mask(segment_ids, block_q, block_k, sliding_window, T)

    def kmap(h, i, j, ks, nm, r=n_rep):
        return (
            h // r,
            jnp.minimum(ks[i] + j, _last_k(i, block_q, block_k)),
            0,
        )

    kernel = functools.partial(
        _fwd_kernel,
        scale=scale,
        block_q=block_q,
        block_k=block_k,
        nk_blocks=T // block_k,
        soft_cap=soft_cap,
        sliding_window=sliding_window,
        specialize=T >= SPECIALIZE_MIN_T,
    )
    out, lse4 = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, block_q), lambda h, i, j, ks, nm: (0, i)),
                pl.BlockSpec(
                    (1, block_k),
                    lambda h, i, j, ks, nm: (
                        0,
                        jnp.minimum(ks[i] + j, _last_k(i, block_q, block_k)),
                    ),
                ),
                pl.BlockSpec((1, block_q, D), lambda h, i, j, ks, nm: (h, i, 0)),
                pl.BlockSpec((1, block_k, D), kmap),
                pl.BlockSpec((1, block_k, D), kmap),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, D), lambda h, i, j, ks, nm: (h, i, 0)),
                pl.BlockSpec(
                    (1, 1, block_q, 1), lambda h, i, j, ks, nm: (h, i, 0, 0)
                ),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, LANES), jnp.float32),
                pltpu.VMEM((block_q, LANES), jnp.float32),
                pltpu.VMEM((block_q, D), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((H, T, D), q.dtype),
            jax.ShapeDtypeStruct((H, T // block_q, block_q, 1), jnp.float32),
        ],
        # blocks >= 2048 carry a [block_q, block_k] f32 score tile past the
        # default scoped-vmem budget; raise it (v5e VMEM is 128 MB)
        compiler_params=pltpu.CompilerParams(
            **({"vmem_limit_bytes": 100 * 2**20} if block_q >= 2048 else {})
        ),
        interpret=_interpret(),
    )(kstart, needs, seg2d, seg2d, q, k, v)
    return out, lse4.reshape(H, T)


# --------------------------------------------------------------------------- #
# backward
# --------------------------------------------------------------------------- #


def _recompute_p_ds(
    q_ref, k_ref, seg_q_ref, seg_k_ref, lse_ref, delta_ref, do_ref, v_ref,
    iq, ik, *, scale, block_q, block_k, soft_cap, sliding_window,
    masked: bool,
):
    """Shared block math for both backward kernels: returns (p, ds_raw) with
    ds_raw = dL/d(q·kᵀ) BEFORE the `scale` factor (folded in by callers).
    ``masked=False`` is the interior fast path: no mask construction."""
    if soft_cap is not None:
        s_dot = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        t = jnp.tanh(s_dot * (scale / soft_cap))
        s2 = (soft_cap * LOG2E) * t                # log2 domain
    else:
        s2 = _scores_log2(q_ref, k_ref, scale, None)
    # residual lse is natural-log; clamp the log2 conversion so pad rows
    # (lse == NEG_INF) don't overflow to -inf and feed exp2 an inf argument
    lse2 = jnp.maximum(lse_ref[0, 0] * LOG2E, NEG_INF)  # [bq, 1]
    p = jnp.exp2(s2 - lse2)                        # [bq, bk]
    if masked:
        mask = _token_mask(
            seg_q_ref, seg_k_ref, iq, ik, block_q, block_k, sliding_window
        )
        p = jnp.where(mask, p, 0.0)
    dp = jax.lax.dot_general(
        do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                              # [bq, bk] f32
    delta = delta_ref[0, 0]                        # [bq, 1]
    ds = p * (dp - delta)                          # dL/ds
    if soft_cap is not None:
        ds = ds * (1.0 - t * t)                    # through the tanh cap
    return p, ds


def _bwd_kernel(
    qlast_ref,
    needs_ref,
    seg_q_ref, seg_k_ref, lse_ref, delta_ref, q_ref, k_ref, v_ref, do_ref,
    dk_ref, dv_ref,
    dq_ref,     # [n_rep, T, D] — one q-head group, written once per kv head
    dk_scr,     # [block_k, D] f32
    dv_scr,     # [block_k, D] f32
    dq_scr,     # [n_rep, T, D] f32 — whole-group dq accumulator
    *,
    scale, block_q, block_k, nk_blocks, nq_blocks, soft_cap, sliding_window,
    specialize, n_rep,
):
    # Fused flash backward, kv-stationary: grid (Hkv, nk, n_rep, nq) with nq
    # innermost. The (hkv, ik) dk/dv blocks accumulate in VMEM scratch across
    # the inner (r, jq) sweep; dq accumulates across the OUTER ik sweep in a
    # whole-group [n_rep, T, D] f32 scratch (HBM read-modify-write through
    # output aliasing is undefined across non-consecutive revisits, so the
    # running dq must live in VMEM), flushed once per kv head. One (p, ds)
    # recompute feeds all three gradients: 5 dots + 1 exp per block pair,
    # vs 7 dots + 2 exps when dq and dk/dv ran as separate sweeps.
    ik = pl.program_id(1)
    ir = pl.program_id(2)
    jq = pl.program_id(3)
    nq = pl.num_programs(3)
    nk = pl.num_programs(1)
    iq = _first_q(ik, block_q, block_k) + jq

    @pl.when((ir == 0) & (jq == 0))
    def _init_kv():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    @pl.when((ik == 0) & (ir == 0) & (jq == 0))
    def _init_dq():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    def _accum(masked: bool):
        p, ds = _recompute_p_ds(
            q_ref, k_ref, seg_q_ref, seg_k_ref, lse_ref, delta_ref, do_ref,
            v_ref, iq, ik, scale=scale, block_q=block_q, block_k=block_k,
            soft_cap=soft_cap, sliding_window=sliding_window, masked=masked,
        )
        # dv += pᵀ @ do ; dk += dsᵀ @ q  (bf16 operands, f32 accumulate)
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        row = jnp.minimum(iq, nq_blocks - 1) * block_q
        dq_scr[ir, pl.ds(row, block_q), :] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    active = iq <= qlast_ref[ik]
    needs = needs_ref[jnp.minimum(iq, nq_blocks - 1) * nk_blocks + ik]
    _dispatch_masked(active, specialize, needs, _accum)

    @pl.when((ir == pl.num_programs(2) - 1) & (jq == nq - 1))
    def _done_kv():
        dk_ref[0] = (dk_scr[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)

    @pl.when((ik == nk - 1) & (ir == pl.num_programs(2) - 1) & (jq == nq - 1))
    def _done_dq():
        dq_ref[...] = (dq_scr[...] * scale).astype(dq_ref.dtype)


def _dq_kernel(
    kstart_ref,
    needs_ref,
    seg_q_ref, seg_k_ref, lse_ref, delta_ref, q_ref, k_ref, v_ref, do_ref,
    dq_ref,
    dq_scr,     # [block_q, D] f32
    *,
    scale, block_q, block_k, nk_blocks, soft_cap, sliding_window, specialize,
):
    iq = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)
    ik = kstart_ref[iq] + j

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    def _accum(masked: bool):
        _, ds = _recompute_p_ds(
            q_ref, k_ref, seg_q_ref, seg_k_ref, lse_ref, delta_ref, do_ref,
            v_ref, iq, ik, scale=scale, block_q=block_q, block_k=block_k,
            soft_cap=soft_cap, sliding_window=sliding_window, masked=masked,
        )
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    active = ik <= _last_k(iq, block_q, block_k)
    needs = needs_ref[iq * nk_blocks + jnp.minimum(ik, nk_blocks - 1)]
    _dispatch_masked(active, specialize, needs, _accum)

    @pl.when(j == nk - 1)
    def _done():
        dq_ref[0] = (dq_scr[...] * scale).astype(dq_ref.dtype)


def _dkv_kernel(
    qlast_ref,
    needs_ref,
    seg_q_ref, seg_k_ref, lse_ref, delta_ref, q_ref, k_ref, v_ref, do_ref,
    dk_ref, dv_ref,
    dk_scr,     # [block_k, D] f32
    dv_scr,     # [block_k, D] f32
    *,
    scale, block_q, block_k, nk_blocks, nq_blocks, soft_cap, sliding_window,
    specialize, n_rep,
):
    # grid: (Hkv, nk, n_rep, nq) — nq innermost; the (hkv, nk) output block
    # stays resident while every grouped q head and q block accumulates.
    ik = pl.program_id(1)
    ir = pl.program_id(2)
    jq = pl.program_id(3)
    nq = pl.num_programs(3)
    iq = _first_q(ik, block_q, block_k) + jq

    @pl.when((ir == 0) & (jq == 0))
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _accum(masked: bool):
        p, ds = _recompute_p_ds(
            q_ref, k_ref, seg_q_ref, seg_k_ref, lse_ref, delta_ref, do_ref,
            v_ref, iq, ik, scale=scale, block_q=block_q, block_k=block_k,
            soft_cap=soft_cap, sliding_window=sliding_window, masked=masked,
        )
        # dv += pᵀ @ do ; dk += dsᵀ @ q  (bf16 operands, f32 accumulate)
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    active = iq <= qlast_ref[ik]
    needs = needs_ref[jnp.minimum(iq, nq_blocks - 1) * nk_blocks + ik]
    _dispatch_masked(active, specialize, needs, _accum)

    @pl.when((ir == pl.num_programs(2) - 1) & (jq == nq - 1))
    def _done():
        dk_ref[0] = (dk_scr[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_backward(
    q, k, v, segment_ids, out, lse, do,
    scale, soft_cap, sliding_window, block_q, block_k, max_seqlen,
):
    """All [H|Hkv, T, D]-layout. Returns (dq, dk, dv)."""
    H, T, D = q.shape
    Hkv = k.shape[0]
    n_rep = H // Hkv
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    seg2d = segment_ids.reshape(1, T)
    # delta_i = rowsum(do * out) — cheap elementwise reduce, stays in XLA
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # [H, T]
    # kernel-side column layout (see _flash_forward docstring)
    nq = T // block_q
    lse4 = lse.reshape(H, nq, block_q, 1)
    delta4 = delta.reshape(H, nq, block_q, 1)
    kstart, qlast = _band_bounds(
        segment_ids, block_q, block_k, sliding_window, T
    )
    needs = _block_needs_mask(segment_ids, block_q, block_k, sliding_window, T)

    common = dict(
        scale=scale, block_q=block_q, block_k=block_k,
        nk_blocks=T // block_k, soft_cap=soft_cap,
        sliding_window=sliding_window, specialize=T >= SPECIALIZE_MIN_T,
    )

    def dkv_qi(ql, j, i):
        # clip: qlast can be -1 (all-pad k block); the step is inactive then
        return jnp.clip(
            _first_q(j, block_q, block_k) + i, 0, (T // block_q) - 1
        )

    def qi3(h, j, r, i, ql, nm, nr=n_rep):
        return (h * nr + r, dkv_qi(ql, j, i), 0)

    def qi4(h, j, r, i, ql, nm, nr=n_rep):
        return (h * nr + r, dkv_qi(ql, j, i), 0, 0)

    kv_spec = pl.BlockSpec((1, block_k, D), lambda h, j, r, i, ql, nm: (h, j, 0))
    group_in_specs = [
        pl.BlockSpec(
            (1, block_q),
            lambda h, j, r, i, ql, nm: (0, dkv_qi(ql, j, i)),
        ),
        pl.BlockSpec((1, block_k), lambda h, j, r, i, ql, nm: (0, j)),
        pl.BlockSpec((1, 1, block_q, 1), qi4),
        pl.BlockSpec((1, 1, block_q, 1), qi4),
        pl.BlockSpec((1, block_q, D), qi3),
        kv_spec,
        kv_spec,
        pl.BlockSpec((1, block_q, D), qi3),
    ]


    # Whole-group dq scratch [n_rep, T, D] f32 + its output block; fall back
    # to separate dq/dkv sweeps when that won't fit VMEM (very long context
    # or large head groups).
    dq_scr_bytes = n_rep * T * D * 4
    dq_out_bytes = n_rep * T * D * q.dtype.itemsize
    if dq_scr_bytes + dq_out_bytes <= FUSED_BWD_MAX_DQ_BYTES:
        limit = None
        if dq_scr_bytes + dq_out_bytes > 8 * 2**20:
            # leave the compiler's default scoped budget alone for small
            # shapes (raising it measurably hurt short-context throughput)
            limit = dq_scr_bytes + dq_out_bytes + 78 * 2**20
        dk, dv, dq = pl.pallas_call(
            functools.partial(
                _bwd_kernel, **common, nq_blocks=T // block_q, n_rep=n_rep
            ),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(
                    Hkv, T // block_k, n_rep,
                    _q_band_blocks(block_q, block_k, max_seqlen, T),
                ),
                in_specs=group_in_specs,
                out_specs=[
                    kv_spec,
                    kv_spec,
                    pl.BlockSpec(
                        (n_rep, T, D), lambda h, j, r, i, ql, nm: (h, 0, 0)
                    ),
                ],
                scratch_shapes=[
                    pltpu.VMEM((block_k, D), jnp.float32),
                    pltpu.VMEM((block_k, D), jnp.float32),
                    pltpu.VMEM((n_rep, T, D), jnp.float32),
                ],
            ),
            out_shape=[
                jax.ShapeDtypeStruct((Hkv, T, D), k.dtype),
                jax.ShapeDtypeStruct((Hkv, T, D), v.dtype),
                jax.ShapeDtypeStruct((H, T, D), q.dtype),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=(
                    "parallel", "arbitrary", "arbitrary", "arbitrary"
                ),
                **({"vmem_limit_bytes": limit} if limit else {}),
            ),
            interpret=_interpret(),
        )(qlast, needs, seg2d, seg2d, lse4, delta4, q, k, v, do)
        return dq, dk, dv

    def dq_kj(h, i, j, ks, nm, r=n_rep):
        return (
            h // r,
            jnp.minimum(ks[i] + j, _last_k(i, block_q, block_k)),
            0,
        )

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, **common),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(
                H, T // block_q,
                _k_band_blocks(block_q, block_k, max_seqlen, T),
            ),
            in_specs=[
                pl.BlockSpec((1, block_q), lambda h, i, j, ks, nm: (0, i)),
                pl.BlockSpec(
                    (1, block_k),
                    lambda h, i, j, ks, nm: (
                        0,
                        jnp.minimum(ks[i] + j, _last_k(i, block_q, block_k)),
                    ),
                ),
                pl.BlockSpec(
                    (1, 1, block_q, 1), lambda h, i, j, ks, nm: (h, i, 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, block_q, 1), lambda h, i, j, ks, nm: (h, i, 0, 0)
                ),
                pl.BlockSpec((1, block_q, D), lambda h, i, j, ks, nm: (h, i, 0)),
                pl.BlockSpec((1, block_k, D), dq_kj),
                pl.BlockSpec((1, block_k, D), dq_kj),
                pl.BlockSpec((1, block_q, D), lambda h, i, j, ks, nm: (h, i, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, block_q, D), lambda h, i, j, ks, nm: (h, i, 0)
            ),
            scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((H, T, D), q.dtype),
        # split-backward p/ds tiles need the same scoped-vmem raise as the
        # forward at block sizes >= 2048
        compiler_params=pltpu.CompilerParams(
            **({"vmem_limit_bytes": 100 * 2**20} if block_q >= 2048 else {})
        ),
        interpret=_interpret(),
    )(kstart, needs, seg2d, seg2d, lse4, delta4, q, k, v, do)

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, **common, nq_blocks=T // block_q, n_rep=n_rep
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(
                Hkv, T // block_k, n_rep,
                _q_band_blocks(block_q, block_k, max_seqlen, T),
            ),
            in_specs=group_in_specs,
            out_specs=[kv_spec, kv_spec],
            scratch_shapes=[
                pltpu.VMEM((block_k, D), jnp.float32),
                pltpu.VMEM((block_k, D), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((Hkv, T, D), k.dtype),
            jax.ShapeDtypeStruct((Hkv, T, D), v.dtype),
        ],
        compiler_params=pltpu.CompilerParams(
            **({"vmem_limit_bytes": 100 * 2**20} if block_k >= 2048 else {})
        ),
        interpret=_interpret(),
    )(qlast, needs, seg2d, seg2d, lse4, delta4, q, k, v, do)
    return dq, dk, dv


# --------------------------------------------------------------------------- #
# custom-vjp entry ([T, H, D] public layout)
# --------------------------------------------------------------------------- #


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash_thd(q, k, v, segment_ids, scale, soft_cap, sliding_window, block_q,
               block_k, max_seqlen):
    """[T, H, D]-layout entry with custom vjp."""
    out, _ = _flash_forward(
        q.swapaxes(0, 1),
        k.swapaxes(0, 1),
        v.swapaxes(0, 1),
        segment_ids,
        scale,
        soft_cap,
        sliding_window,
        block_q,
        block_k,
        max_seqlen,
    )
    return out.swapaxes(0, 1)


def _flash_fwd_rule(q, k, v, segment_ids, scale, soft_cap, sliding_window,
                    block_q, block_k, max_seqlen):
    out, lse = _flash_forward(
        q.swapaxes(0, 1), k.swapaxes(0, 1), v.swapaxes(0, 1), segment_ids,
        scale, soft_cap, sliding_window, block_q, block_k, max_seqlen,
    )
    return out.swapaxes(0, 1), (q, k, v, segment_ids, out, lse)


def _flash_bwd_rule(scale, soft_cap, sliding_window, block_q, block_k,
                    max_seqlen, res, g):
    q, k, v, segment_ids, out_htd, lse = res
    dq, dk, dv = _flash_backward(
        q.swapaxes(0, 1), k.swapaxes(0, 1), v.swapaxes(0, 1), segment_ids,
        out_htd, lse, g.swapaxes(0, 1),
        scale, soft_cap, sliding_window, block_q, block_k, max_seqlen,
    )
    return dq.swapaxes(0, 1), dk.swapaxes(0, 1), dv.swapaxes(0, 1), None


_flash_thd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def packed_flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    segment_ids: jnp.ndarray,
    *,
    softmax_scale: float,
    soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
    block_size: int = 512,
    max_seqlen: Optional[int] = None,
) -> jnp.ndarray:
    """Causal packed-varlen flash attention. q ``[T, H, D]``, k/v
    ``[T, Hkv, D]``, segment_ids ``[T]`` (0 = pad) -> ``[T, H, D]``.

    ``max_seqlen``: STATIC upper bound on any single segment's length. When
    set, the kernels iterate a statically narrowed (q block, k block) band
    instead of the full causal rectangle — at short-segment packing most
    grid steps are out-of-band no-ops that still cost ~µs each, so this is
    a multi-x win. Segments longer than the bound get silently truncated
    attention: callers must validate (the train engine does; any other
    caller gets a device-side check under ``AREAL_DEBUG_CHECKS=1``). The
    flag is read at TRACE time — set it before the first jit of a calling
    step; flipping it later does not retrace cached programs.
    """
    if max_seqlen is not None and os.environ.get("AREAL_DEBUG_CHECKS") == "1":
        T = segment_ids.shape[0]
        seg_max = jnp.max(
            jnp.bincount(
                jnp.where(segment_ids > 0, segment_ids, 0), length=T + 1
            )[1:]
        )

        def _check(observed, bound=max_seqlen):
            if int(observed) > bound:
                raise ValueError(
                    f"packed_flash_attention: a segment has {int(observed)} "
                    f"tokens but max_seqlen={bound}; attention beyond the "
                    "band would be silently truncated"
                )

        jax.debug.callback(_check, seg_max)
    return _flash_thd(
        q, k, v, segment_ids.astype(jnp.int32), softmax_scale, soft_cap,
        sliding_window, block_size, block_size, max_seqlen,
    )
