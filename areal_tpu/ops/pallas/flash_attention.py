"""Packed varlen flash attention (Pallas TPU kernels, forward AND backward).

Block-wise online-softmax attention over one packed token axis with
segment-id masking — the TPU counterpart of the reference's
``flash_attn_varlen_func(cu_seqlens)`` path
(``realhf/impl/model/modules/attn.py:272-289``), which trains through fused
varlen flash in both directions.

Layout: ``q [H, T, D]``-major inside the kernels (the public wrapper
transposes from the model's ``[T, H, D]``). TPU grids run sequentially
minor-to-major, so VMEM scratch accumulators carry state across the
innermost grid axis:

- **forward**: grid ``(H, nq, nk)``; online-softmax state (m, l, acc) per
  (head, q block); also emits the logsumexp ``lse [H, T]`` for the backward.
- **bwd (fused)**: grid ``(Hkv, nk, n_rep, nq)``; for one kv-head k block,
  a single (p, ds) recompute feeds ``dv += pᵀ dо``, ``dk += dsᵀ q`` (per-
  block VMEM scratch) AND ``dq += ds k`` (whole-group ``[n_rep, T, D]`` f32
  VMEM scratch, flushed once per kv head) — 5 dots + 1 exp per block pair
  instead of the 7 + 2 of separate dq/dkv sweeps. Falls back to the separate
  ``_dq_kernel``/``_dkv_kernel`` sweeps when the dq scratch exceeds
  ``FUSED_BWD_MAX_DQ_BYTES``. GQA never materializes a K/V repeat: the group
  is a grid axis.

**Band-limited iteration.** Packed rows carry non-decreasing segment ids
(padding 0 at the tail), so the only (q block, k block) pairs with any
unmasked work form a band: causal diagonal on one side, the first k block
containing the q block's minimum segment (`kstart`, narrowed further by a
sliding window) on the other. The band bounds ride in as scalar-prefetch
operands and feed the BlockSpec index maps: out-of-band grid steps clamp to
the previous block index, and Pallas skips the DMA entirely when the index
map output repeats. Inside the band the kernels run unconditionally (the
token-level mask handles block-edge partials), so skipped steps cost neither
FLOPs nor HBM traffic.

**Interior-block specialization.** The kernels are VPU-bound, not MXU-bound:
at D=64 each score element costs ~128 MXU FLOPs but ~10 VPU passes when the
token-level mask is materialized (two iotas, three compares, two ands, a
where, the exp). For a 32k causal row all but the ~3% diagonal/segment-edge
blocks are *interior* — every token pair unmasked — so a per-(q block,
k block) ``needs_mask`` table (computed in XLA, scalar-prefetched) routes
each grid step to either the masked body or a mask-free fast body that runs
just the online-softmax update. Softmax runs in the log2 domain
(``exp2(s·scale·log2e)``) — one fewer VPU multiply per element than ``exp``,
matching how Mosaic lowers transcendentals; the emitted ``lse`` stays in
natural log, so the contract with the backward and with ring attention is
unchanged.

The backward follows the flash-attention-2 recipe: residuals are
``(q, k, v, out, lse)``; ``delta = rowsum(dо * out)`` is computed in XLA
(cheap elementwise reduce), and ``ds = p * (dp - delta)`` inside the kernel.
All matmuls take bf16 operands with f32 accumulation (operand-side f32
casts would quarter MXU throughput).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from areal_tpu.ops.pallas.compat import compiler_params as _compiler_params

NEG_INF = -2.3819763e38
LANES = 128
LOG2E = 1.4426950408889634
LN2 = 0.6931471805599453
# Dual-body (masked/interior) kernels pay a small branch overhead per grid
# step; below this token count boundary blocks dominate any realistic packing
# and the single masked body wins.
SPECIALIZE_MIN_T = 8192
# Fused-backward dq scratch + output block budget (v5e has 128 MB VMEM; the
# rest of the kernel needs ~30 MB at block 1024). Above this the backward
# falls back to separate dq/dkv sweeps.
FUSED_BWD_MAX_DQ_BYTES = 48 * 2**20


def _bwd_pipeline() -> bool:
    # cross-block software pipelining in the fused backward (VERDICT r4
    # #4): park (p, ds) one step and issue their gradient dots alongside
    # the next block's VPU work. Numerics identical (parking dtype = the
    # dots' operand dtype). Default OFF until chip-measured — the bench
    # A/Bs both settings and the winner becomes the default.
    from areal_tpu.base import constants

    return constants.flash_bwd_pipeline_enabled()


def _interpret() -> bool:
    # off-TPU (CPU tests) the kernels run in the pallas interpreter
    return jax.devices()[0].platform != "tpu"


def _band_bounds(segment_ids, block_q, block_k, sliding_window, T):
    """Per-block band bounds for the packed row (all int32):

    - ``kstart [nq]``: first k block with any key the q block may attend to
      (segment- and window-derived; can exceed the causal diagonal for
      all-pad q blocks — callers clamp to it).
    - ``qlast [nk]``: last q block with any query attending into the k block
      (-1 when the k block is all padding).
    """
    nq, nk = T // block_q, T // block_k
    BIG = jnp.int32(2**30)
    sq = segment_ids.reshape(nq, block_q)
    sk = segment_ids.reshape(nk, block_k)
    qmin = jnp.where(sq > 0, sq, BIG).min(axis=1).astype(jnp.int32)
    kmax = sk.max(axis=1).astype(jnp.int32)
    # monotone prefix: pad-tail kmax drops to 0, so search on the running max
    kmax_mono = jax.lax.associative_scan(jnp.maximum, kmax)
    kstart = jnp.searchsorted(kmax_mono, qmin, side="left").astype(jnp.int32)
    # qmin is globally non-decreasing (BIG on the pad tail)
    qlast = (
        jnp.searchsorted(qmin, kmax, side="right").astype(jnp.int32) - 1
    )
    qlast = jnp.where(kmax > 0, qlast, -1)
    if sliding_window is not None:
        iq = jnp.arange(nq, dtype=jnp.int32)
        ik = jnp.arange(nk, dtype=jnp.int32)
        kstart = jnp.maximum(
            kstart,
            jnp.maximum(iq * block_q - (sliding_window - 1), 0) // block_k,
        )
        qlast = jnp.minimum(
            qlast, (ik * block_k + block_k - 1 + sliding_window - 1) // block_q
        )
    return kstart, qlast


def _last_k(iq, block_q, block_k):
    """Causal diagonal: last k block with keys not after this q block."""
    return (iq * block_q + block_q - 1) // block_k


def _first_q(ik, block_q, block_k):
    """Causal diagonal: first q block with queries not before this k block."""
    return (ik * block_k) // block_q


def _k_band_blocks(block_q, block_k, max_seqlen, T):
    """Static bound on the k-block band width per q block: a q block's
    earliest needed key starts at most ``max_seqlen - 1`` tokens before the
    block (the segment containing its first token), and its last is the
    causal diagonal — so the span is <= block_q + max_seqlen - 1 tokens."""
    nk = T // block_k
    if max_seqlen is None:
        return nk
    return min(nk, -(-(block_q + max_seqlen - 1) // block_k) + 1)


def _q_band_blocks(block_q, block_k, max_seqlen, T):
    """Static bound on the q-block band width per k block (symmetric)."""
    nq = T // block_q
    if max_seqlen is None:
        return nq
    return min(nq, -(-(block_k + max_seqlen - 1) // block_q) + 1)


def _block_needs_mask(segment_ids, block_q, block_k, sliding_window, T):
    """``[nq*nk] int32``: 0 where the (q block, k block) pair is *interior* —
    every token pair unmasked (block fully below the causal diagonal, one
    shared nonzero segment, fully inside any sliding window) — so the
    kernels skip mask construction entirely; 1 where token-level masking is
    required. Out-of-band pairs never execute a body, so their value is
    irrelevant."""
    nq, nk = T // block_q, T // block_k
    sq = segment_ids.reshape(nq, block_q)
    sk = segment_ids.reshape(nk, block_k)
    q_seg = sq.min(axis=1)
    q_uni = (q_seg == sq.max(axis=1)) & (q_seg > 0)
    k_seg = sk.min(axis=1)
    k_uni = k_seg == sk.max(axis=1)
    same = q_uni[:, None] & k_uni[None, :] & (q_seg[:, None] == k_seg[None, :])
    iq = jnp.arange(nq, dtype=jnp.int32)
    ik = jnp.arange(nk, dtype=jnp.int32)
    causal = (iq * block_q)[:, None] >= (ik * block_k + block_k - 1)[None, :]
    interior = same & causal
    if sliding_window is not None:
        maxdiff = (iq * block_q + block_q - 1)[:, None] - (ik * block_k)[None, :]
        interior &= maxdiff < sliding_window
    return jnp.where(interior, 0, 1).astype(jnp.int32).reshape(-1)


def _scores_log2(q2d, k_ref, scale, soft_cap):
    """Block scores in the log2 domain: ``(q·kᵀ)·scale·log2e`` (soft-capped
    in the natural domain first when requested). ``q2d`` is the (possibly
    rep-folded) ``[rows, D]`` q block; result f32 [rows, bk].

    Without a cap, the scale folds into the q BLOCK before the dot — a
    [rows, D] multiply instead of a full [rows, bk] VPU pass over the
    scores (D=64 models are VPU-bound at long context; one pass of ~5 is
    free). The extra bf16 rounding on q is below the dot's own bf16
    noise."""
    if soft_cap is None:
        qs = q2d * jnp.asarray(scale * LOG2E, q2d.dtype)
        return jax.lax.dot_general(
            qs, k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    s = jax.lax.dot_general(
        q2d, k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    s = soft_cap * jnp.tanh(s * (scale / soft_cap))
    return s * LOG2E


def _token_mask(seg_q_ref, seg_k_ref, iq, ik, block_q, block_k, sliding_window,
                n_rep: int = 1):
    """Token-level mask for a boundary block (causal ∧ same segment ∧ not
    pad ∧ window). With ``n_rep > 1`` the q rows are ``n_rep`` grouped
    heads' blocks stacked (GQA head folding): row r*block_q + t is token
    ``iq*block_q + t`` of rep r, so positions repeat with period block_q."""
    rows = n_rep * block_q
    row = jax.lax.broadcasted_iota(jnp.int32, (rows, block_k), 0)
    q_idx = iq * block_q + (
        jax.lax.rem(row, block_q) if n_rep > 1 else row
    )
    k_idx = ik * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (rows, block_k), 1
    )
    seg_q = seg_q_ref[0][:, None]
    if n_rep > 1:
        seg_q = jnp.concatenate([seg_q] * n_rep, axis=0)
    seg_k = seg_k_ref[0][None, :]
    mask = (q_idx >= k_idx) & (seg_q == seg_k) & (seg_q > 0)
    if sliding_window is not None:
        mask &= q_idx - k_idx < sliding_window
    return mask


def _dispatch_masked(active, specialize, needs_scalar, body):
    """Register the masked/interior pl.when branches shared by every kernel:
    ``body(masked)`` runs under ``active``; with ``specialize`` the
    ``needs_scalar`` table value routes to the mask-free interior body."""
    if specialize:

        @pl.when(active & (needs_scalar == 1))
        def _boundary():
            body(masked=True)

        @pl.when(active & (needs_scalar == 0))
        def _interior():
            body(masked=False)

    else:

        @pl.when(active)
        def _body():
            body(masked=True)



# --------------------------------------------------------------------------- #
# forward
# --------------------------------------------------------------------------- #


def _fwd_step(
    iq, ik, is_first, is_last, active,
    needs_ref, seg_q_ref, seg_k_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
    m_scr, l_scr, acc_scr,
    *, scale, block_q, block_k, nk_blocks, soft_cap, sliding_window,
    specialize, n_rep,
):
    """One forward grid step (shared by the band and triangle kernels):
    block indices + first/last/active arrive as traced values.

    GQA head folding: the grid's head dim walks KV heads; the q/o blocks
    carry ALL ``n_rep`` grouped q heads stacked ``[n_rep, block_q, D]``
    and fold to ``[n_rep*block_q, D]`` rows for ONE score/PV dot pair per
    step — n_rep x fewer grid steps, n_rep x fewer k/v block fetches, and
    n_rep x taller dots (better MXU occupancy at D=64)."""
    rows = n_rep * block_q

    @pl.when(is_first)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _update(masked: bool):
        q2d = q_ref[...].reshape(rows, q_ref.shape[-1])
        s2 = _scores_log2(q2d, k_ref, scale, soft_cap)  # [rows, bk] f32
        if masked:
            mask = _token_mask(
                seg_q_ref, seg_k_ref, iq, ik, block_q, block_k,
                sliding_window, n_rep,
            )
            s2 = jnp.where(mask, s2, NEG_INF)
        m_prev = m_scr[:, 0:1]                     # [rows, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s2, axis=1, keepdims=True))
        p = jnp.exp2(s2 - m_new)                   # [rows, bk]
        if masked:
            # NEG_INF is finite, so exp2(s2 - m_new) is 1 (not 0) on
            # fully-masked rows — zero masked entries explicitly so pad rows
            # keep l == 0 and output 0, matching the XLA path.
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp2(m_prev - m_new)            # [rows, 1]
        l_new = corr * l_scr[:, 0:1] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    needs = needs_ref[iq * nk_blocks + jnp.minimum(ik, nk_blocks - 1)]
    _dispatch_masked(active, specialize, needs, _update)

    @pl.when(is_last)
    def _done():
        l = l_scr[:, 0:1]
        safe_l = jnp.where(l > 0.0, l, 1.0)
        D = o_ref.shape[-1]
        o_ref[...] = (
            (acc_scr[...] / safe_l).reshape(n_rep, block_q, D)
        ).astype(o_ref.dtype)
        # natural-log logsumexp residual; NEG_INF on fully-masked (pad) rows
        lse = jnp.where(
            l > 0.0, m_scr[:, 0:1] * LN2 + jnp.log(safe_l), NEG_INF
        )                                          # [rows, 1]
        lse_ref[...] = lse.reshape(n_rep, 1, block_q, 1)


def _fwd_kernel(
    kstart_ref,  # [nq] int32 scalar-prefetch
    needs_ref,   # [nq*nk] int32 scalar-prefetch (see _block_needs_mask)
    seg_q_ref,   # [1, block_q] int32
    seg_k_ref,   # [1, block_k] int32
    q_ref,       # [n_rep, block_q, D] — the kv head's whole q group
    k_ref,       # [1, block_k, D]
    v_ref,       # [1, block_k, D]
    o_ref,       # [n_rep, block_q, D]
    lse_ref,     # [n_rep, 1, block_q, 1] f32 (column layout; see _flash_forward)
    m_scr,       # [n_rep*block_q, LANES] f32 (running max, log2 domain)
    l_scr,       # [n_rep*block_q, LANES] f32
    acc_scr,     # [n_rep*block_q, D] f32
    *,
    scale: float,
    block_q: int,
    block_k: int,
    nk_blocks: int,
    soft_cap: Optional[float],
    sliding_window: Optional[int],
    specialize: bool,
    n_rep: int,
):
    iq = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)
    ik = kstart_ref[iq] + j  # band-relative -> absolute k block
    _fwd_step(
        iq, ik, j == 0, j == nk - 1, ik <= _last_k(iq, block_q, block_k),
        needs_ref, seg_q_ref, seg_k_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
        m_scr, l_scr, acc_scr,
        scale=scale, block_q=block_q, block_k=block_k, nk_blocks=nk_blocks,
        soft_cap=soft_cap, sliding_window=sliding_window,
        specialize=specialize, n_rep=n_rep,
    )


def _fwd_kernel_tri(
    kstart_ref,  # [nq] int32 scalar-prefetch (runtime segment/window start)
    needs_ref,   # [nq*nk] int32 scalar-prefetch
    iq_tab,      # [L] int32 STATIC: q-block of linear step l
    ik_tab,      # [L] int32 STATIC: k-block of linear step l
    first_tab,   # [L] int32 STATIC: 1 = first step of its q block
    last_tab,    # [L] int32 STATIC: 1 = last step of its q block
    seg_q_ref, seg_k_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
    m_scr, l_scr, acc_scr,
    *,
    scale, block_q, block_k, nk_blocks, soft_cap, sliding_window, specialize,
    n_rep,
):
    """Triangle-enumerated forward: the grid's second dim walks ONLY the
    causally-possible (iq, ik) block pairs (static tables), instead of the
    nq x nk rectangle whose upper half is no-op steps at full-causal long
    context (~half the grid at 32k single-sequence; each no-op still costs
    a grid-step latency). Runtime segment starts prune further via
    ``active = ik >= kstart[iq]``."""
    l = pl.program_id(1)
    iq = iq_tab[l]
    ik = ik_tab[l]
    _fwd_step(
        iq, ik, first_tab[l] == 1, last_tab[l] == 1, ik >= kstart_ref[iq],
        needs_ref, seg_q_ref, seg_k_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
        m_scr, l_scr, acc_scr,
        scale=scale, block_q=block_q, block_k=block_k, nk_blocks=nk_blocks,
        soft_cap=soft_cap, sliding_window=sliding_window,
        specialize=specialize, n_rep=n_rep,
    )


@functools.lru_cache(maxsize=None)
def _tri_tables(nq, nk, block_q, block_k):
    """Static (iq, ik) enumeration of the causal triangle's block pairs,
    with first/last flags per q-block sweep. At full-causal context this
    halves the grid vs the nq x nk rectangle (the pruned steps are
    impossible under causality, not merely masked)."""
    import numpy as np

    iqs, iks, firsts, lasts = [], [], [], []
    for iq in range(nq):
        lk = min((iq * block_q + block_q - 1) // block_k, nk - 1)
        for ik in range(lk + 1):
            iqs.append(iq)
            iks.append(ik)
            firsts.append(1 if ik == 0 else 0)
            lasts.append(1 if ik == lk else 0)
    return (
        np.asarray(iqs, np.int32), np.asarray(iks, np.int32),
        np.asarray(firsts, np.int32), np.asarray(lasts, np.int32),
    )


def _flash_forward(
    q, k, v, segment_ids, scale, soft_cap, sliding_window, block_q, block_k,
    max_seqlen,
):
    """q: [H, T, D]; k, v: [Hkv, T, D]; segment_ids: [T]
    -> (out [H, T, D], lse [H, T] f32).

    The kernel-side lse layout is ``[H, nq, block_q, 1]`` — Mosaic requires
    the last two block dims be (÷8, ÷128) or full, and a trailing size-1 lane
    dim keeps per-q-block logsumexp columns addressable per (head, q block)
    without a 128-lane broadcast buffer. It is compacted to ``[H, T]`` in XLA
    right after the call, so the padded layout never persists as a residual."""
    H, T, D = q.shape
    Hkv = k.shape[0]
    n_rep = H // Hkv
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    # rep folding multiplies the q-side tile rows by n_rep: halve block_q
    # only when the folded [n_rep*block_q, block_k] f32 score tiles would
    # overflow the maximum scoped-vmem budget (~114 MB) — big-tile configs
    # like n_rep=8 x flash_block_size=2048 previously compiled unfolded
    while 2 * n_rep * block_q * block_k * 4 > 90 * 2**20 and block_q > 512:
        block_q //= 2
    assert T % block_q == 0 and T % block_k == 0, (T, block_q, block_k)
    seg2d = segment_ids.reshape(1, T)
    kstart, _ = _band_bounds(segment_ids, block_q, block_k, sliding_window, T)
    needs = _block_needs_mask(segment_ids, block_q, block_k, sliding_window, T)

    common = dict(
        scale=scale,
        block_q=block_q,
        block_k=block_k,
        nk_blocks=T // block_k,
        soft_cap=soft_cap,
        sliding_window=sliding_window,
        specialize=T >= SPECIALIZE_MIN_T,
        n_rep=n_rep,
    )
    # GQA head folding: the grid walks KV heads; each step carries the
    # whole q-head group [n_rep, block_q, D]
    scratch_shapes = [
        pltpu.VMEM((n_rep * block_q, LANES), jnp.float32),
        pltpu.VMEM((n_rep * block_q, LANES), jnp.float32),
        pltpu.VMEM((n_rep * block_q, D), jnp.float32),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((H, T, D), q.dtype),
        jax.ShapeDtypeStruct((H, T // block_q, block_q, 1), jnp.float32),
    ]
    # big score tiles ([n_rep*block_q, block_k] f32) can exceed the default
    # scoped-vmem budget; raise it (v5e VMEM is 128 MB)
    tile_bytes = (
        2 * n_rep * block_q * block_k * 4
        + sum(4 * s.shape[0] * s.shape[1] for s in scratch_shapes)
    )
    compiler_params = _compiler_params(
        **({"vmem_limit_bytes": min(tile_bytes + 48 * 2**20, 114 * 2**20)}
           if tile_bytes > 24 * 2**20 or block_q >= 2048 else {})
    )

    if max_seqlen is None:
        # no static band: enumerate the causal triangle's block pairs
        # instead of the nq x nk rectangle (half of which is no-op steps at
        # full-causal context)
        iq_t, ik_t, first_t, last_t = _tri_tables(
            T // block_q, T // block_k, block_q, block_k
        )

        def qmap(h, l, ks, nm, iqt, ikt, ft, lt):
            return (h, iqt[l], 0)

        def kvmap(h, l, ks, nm, iqt, ikt, ft, lt):
            return (h, ikt[l], 0)

        out, lse4 = pl.pallas_call(
            functools.partial(_fwd_kernel_tri, **common),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=6,
                grid=(Hkv, len(iq_t)),
                in_specs=[
                    pl.BlockSpec(
                        (1, block_q),
                        lambda h, l, ks, nm, iqt, ikt, ft, lt: (0, iqt[l]),
                    ),
                    pl.BlockSpec(
                        (1, block_k),
                        lambda h, l, ks, nm, iqt, ikt, ft, lt: (0, ikt[l]),
                    ),
                    pl.BlockSpec((n_rep, block_q, D), qmap),
                    pl.BlockSpec((1, block_k, D), kvmap),
                    pl.BlockSpec((1, block_k, D), kvmap),
                ],
                out_specs=[
                    pl.BlockSpec((n_rep, block_q, D), qmap),
                    pl.BlockSpec(
                        (n_rep, 1, block_q, 1),
                        lambda h, l, ks, nm, iqt, ikt, ft, lt: (h, iqt[l], 0, 0),
                    ),
                ],
                scratch_shapes=scratch_shapes,
            ),
            out_shape=out_shape,
            compiler_params=compiler_params,
            interpret=_interpret(),
        )(
            kstart, needs, jnp.asarray(iq_t), jnp.asarray(ik_t),
            jnp.asarray(first_t), jnp.asarray(last_t), seg2d, seg2d, q, k, v,
        )
        return out, lse4.reshape(H, T)

    grid = (Hkv, T // block_q, _k_band_blocks(block_q, block_k, max_seqlen, T))

    def kmap(h, i, j, ks, nm):
        return (
            h,
            jnp.minimum(ks[i] + j, _last_k(i, block_q, block_k)),
            0,
        )

    out, lse4 = pl.pallas_call(
        functools.partial(_fwd_kernel, **common),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, block_q), lambda h, i, j, ks, nm: (0, i)),
                pl.BlockSpec(
                    (1, block_k),
                    lambda h, i, j, ks, nm: (
                        0,
                        jnp.minimum(ks[i] + j, _last_k(i, block_q, block_k)),
                    ),
                ),
                pl.BlockSpec(
                    (n_rep, block_q, D), lambda h, i, j, ks, nm: (h, i, 0)
                ),
                pl.BlockSpec((1, block_k, D), kmap),
                pl.BlockSpec((1, block_k, D), kmap),
            ],
            out_specs=[
                pl.BlockSpec(
                    (n_rep, block_q, D), lambda h, i, j, ks, nm: (h, i, 0)
                ),
                pl.BlockSpec(
                    (n_rep, 1, block_q, 1),
                    lambda h, i, j, ks, nm: (h, i, 0, 0),
                ),
            ],
            scratch_shapes=scratch_shapes,
        ),
        out_shape=out_shape,
        compiler_params=compiler_params,
        interpret=_interpret(),
    )(kstart, needs, seg2d, seg2d, q, k, v)
    return out, lse4.reshape(H, T)


# --------------------------------------------------------------------------- #
# backward
# --------------------------------------------------------------------------- #


def _recompute_p_ds(
    q_ref, k_ref, seg_q_ref, seg_k_ref, lse_ref, delta_ref, do_ref, v_ref,
    iq, ik, *, scale, block_q, block_k, soft_cap, sliding_window,
    masked: bool, n_rep: int = 1,
):
    """Shared block math for both backward kernels: returns (p, ds_raw) with
    ds_raw = dL/d(q·kᵀ) BEFORE the `scale` factor (folded in by callers).
    ``masked=False`` is the interior fast path: no mask construction.
    With ``n_rep > 1`` the q-side refs carry the whole grouped head stack
    ``[n_rep, block_q, ...]`` and everything runs rep-folded ``[rows, bk]``
    (see ``_fwd_step``)."""
    rows = n_rep * block_q
    D = q_ref.shape[-1]
    q2d = q_ref[...].reshape(rows, D)
    if soft_cap is not None:
        s_dot = jax.lax.dot_general(
            q2d, k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        t = jnp.tanh(s_dot * (scale / soft_cap))
        s2 = (soft_cap * LOG2E) * t                # log2 domain
    else:
        s2 = _scores_log2(q2d, k_ref, scale, None)
    # residual lse is natural-log; clamp the log2 conversion so pad rows
    # (lse == NEG_INF) don't overflow to -inf and feed exp2 an inf argument
    lse2 = jnp.maximum(
        lse_ref[...].reshape(rows, 1) * LOG2E, NEG_INF
    )                                              # [rows, 1]
    p = jnp.exp2(s2 - lse2)                        # [rows, bk]
    if masked:
        mask = _token_mask(
            seg_q_ref, seg_k_ref, iq, ik, block_q, block_k, sliding_window,
            n_rep,
        )
        p = jnp.where(mask, p, 0.0)
    dp = jax.lax.dot_general(
        do_ref[...].reshape(rows, D), v_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                              # [rows, bk] f32
    delta = delta_ref[...].reshape(rows, 1)        # [rows, 1]
    ds = p * (dp - delta)                          # dL/ds
    if soft_cap is not None:
        ds = ds * (1.0 - t * t)                    # through the tanh cap
    return p, ds


def _bwd_kernel(
    kstart_ref,  # [nq] int32 scalar-prefetch (runtime segment/window start)
    needs_ref,
    seg_q_ref, seg_k_ref, lse_ref, delta_ref, q_ref, k_ref, v_ref, do_ref,
    dk_ref, dv_ref,
    dq_ref,     # [n_rep, block_q, D] — one q-head group's block
    dk_scr,     # [T, D] f32 — whole-T accumulator, flushed per kv head
    dv_scr,     # [T, D] f32
    dq_scr,     # [n_rep*block_q, D] f32 — one q sweep's accumulator
    *pipe,      # optional (p, ds, kprev, meta) parking scratch (pipelined)
    scale, block_q, block_k, nk_blocks, nq_blocks, soft_cap, sliding_window,
    specialize, n_rep,
):
    # Fused flash backward, Q-STATIONARY + rep-folded: grid (Hkv, nq, nk)
    # with nk innermost; every step carries the WHOLE q-head group
    # [n_rep, block_q, ...] folded to [n_rep*block_q, bk] (one dot set per
    # group — see _fwd_step). dq accumulates across the inner ik sweep in a
    # [rows, D] scratch and flushes into its (consecutively-revisited)
    # output window at the end of each q sweep; dk/dv accumulate into
    # WHOLE-T [T, D] f32 scratches (16.8 MB at 32k/D=64 — independent of
    # n_rep, unlike the previous kv-stationary whole-group dq scratch whose
    # rep-folded tiles blew the 128 MB VMEM budget) and flush once per kv
    # head. One (p, ds) recompute feeds all three gradients: 5 dots + 1
    # exp per group-block pair.
    iq = pl.program_id(1)
    j = pl.program_id(2)
    nq = pl.num_programs(1)
    nkb = pl.num_programs(2)
    ik = kstart_ref[iq] + j
    _bwd_step(
        ik, iq,
        j == 0,
        (iq == 0) & (j == 0),
        j == nkb - 1,
        (iq == nq - 1) & (j == nkb - 1),
        ik <= _last_k(iq, block_q, block_k),
        needs_ref, seg_q_ref, seg_k_ref, lse_ref, delta_ref, q_ref, k_ref,
        v_ref, do_ref, dk_ref, dv_ref, dq_ref, dk_scr, dv_scr, dq_scr,
        tuple(pipe) if pipe else None,
        scale=scale, block_q=block_q, block_k=block_k, nk_blocks=nk_blocks,
        nq_blocks=nq_blocks, soft_cap=soft_cap, sliding_window=sliding_window,
        specialize=specialize, n_rep=n_rep,
    )


def _bwd_step(
    ik, iq, init_dq, init_kv, done_dq, done_kv, active,
    needs_ref, seg_q_ref, seg_k_ref, lse_ref, delta_ref, q_ref, k_ref,
    v_ref, do_ref, dk_ref, dv_ref, dq_ref, dk_scr, dv_scr, dq_scr,
    pipe_scr,
    *, scale, block_q, block_k, nk_blocks, nq_blocks, soft_cap,
    sliding_window, specialize, n_rep,
):
    """One fused-backward grid step (shared by band and triangle kernels);
    q-side refs carry the whole rep group ``[n_rep, block_q, ...]``.
    ``init_dq``/``done_dq`` bound one q block's k sweep; ``init_kv``/
    ``done_kv`` bound one kv head's whole traversal.

    With ``pipe_scr`` (cross-block software pipelining, VERDICT r4 #4):
    the three gradient dots consuming (p, ds) are DEFERRED one grid step —
    step j issues step j-1's ``dv += pᵀdo``, ``dk += dsᵀq``, ``dq += ds·k``
    from VMEM scratch between j's score/dp dots and j's exp/mask VPU work,
    so the MXU chews the previous block's gradients while the VPU builds
    the current block's probabilities instead of serializing p→dv, ds→dk/dq
    every step (~7.7 µs/step vs ~4.4 ideal, the round-4 limiter). do/q/
    delta/lse are q-stationary across the inner k sweep, so only the k
    block (for dq) and the dv/dk column offset need carrying in scratch;
    the deferred dots flush inside ``done_dq`` before q/do move on."""
    rows = n_rep * block_q
    D = q_ref.shape[-1]
    pipeline = pipe_scr is not None
    if pipeline:
        p_scr, ds_scr, kprev_scr, meta_scr = pipe_scr

    @pl.when(init_dq)
    def _init_dq():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    @pl.when(init_kv)
    def _init_kv():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)
        if pipeline:
            meta_scr[1] = 0  # no pending block

    def _grad_dots(p, ds, col, kblk):
        # dv += pᵀ @ do ; dk += dsᵀ @ q over the FOLDED rows — summing the
        # group's per-head contributions inside the dot itself
        dv_scr[pl.ds(col, block_k), :] += jax.lax.dot_general(
            p, do_ref[...].reshape(rows, D),
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_scr[pl.ds(col, block_k), :] += jax.lax.dot_general(
            ds, q_ref[...].reshape(rows, D),
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dq_scr[...] += jax.lax.dot_general(
            ds, kblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    def _issue_pending():
        @pl.when(meta_scr[1] == 1)
        def _():
            _grad_dots(
                p_scr[...], ds_scr[...],
                meta_scr[0], kprev_scr[...],
            )
        meta_scr[1] = 0

    def _accum(masked: bool):
        p, ds = _recompute_p_ds(
            q_ref, k_ref, seg_q_ref, seg_k_ref, lse_ref, delta_ref, do_ref,
            v_ref, iq, ik, scale=scale, block_q=block_q, block_k=block_k,
            soft_cap=soft_cap, sliding_window=sliding_window, masked=masked,
            n_rep=n_rep,
        )
        col = jnp.minimum(ik, nk_blocks - 1) * block_k
        if pipeline:
            # park this block's (p, ds, k, col); consumed next step (or in
            # the done_dq flush below). bf16 parking matches the dots'
            # operand dtype, so numerics are unchanged.
            p_scr[...] = p.astype(do_ref.dtype)
            ds_scr[...] = ds.astype(q_ref.dtype)
            kprev_scr[...] = k_ref[0]
            meta_scr[0] = col
            meta_scr[1] = 1
        else:
            _grad_dots(
                p.astype(do_ref.dtype), ds.astype(q_ref.dtype), col,
                k_ref[0],
            )

    # clamp BOTH indices: the band wrapper's ik = kstart[iq]+j can pass
    # nk_blocks for all-pad q blocks (inactive, but the scalar read must
    # stay in bounds)
    needs = needs_ref[
        jnp.minimum(iq, nq_blocks - 1) * nk_blocks
        + jnp.minimum(ik, nk_blocks - 1)
    ]
    if pipeline:
        # previous block's gradient dots FIRST: no data dependency on this
        # step's VPU work, so Mosaic can overlap them with _accum's
        # exp/mask while this step's own dots queue behind
        _issue_pending()
    _dispatch_masked(active, specialize, needs, _accum)

    @pl.when(done_dq)
    def _done_dq():
        if pipeline:
            _issue_pending()  # the sweep's last block, parked just above
        dq_ref[...] = (
            (dq_scr[...] * scale).reshape(n_rep, block_q, D)
        ).astype(dq_ref.dtype)

    @pl.when(done_kv)
    def _done_kv():
        dk_ref[0] = (dk_scr[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_kernel_tri(
    kstart_ref,  # [nq] int32 scalar-prefetch (runtime segment/window start)
    needs_ref,   # [nq*nk] int32 scalar-prefetch
    iq_tab,      # [L] int32 STATIC: q-block of linear step l
    ik_tab,      # [L] int32 STATIC: k-block of linear step l
    first_tab,   # [L] int32 STATIC: 1 = first step of its q block's sweep
    last_tab,    # [L] int32 STATIC: 1 = last step of its q block's sweep
    seg_q_ref, seg_k_ref, lse_ref, delta_ref, q_ref, k_ref, v_ref, do_ref,
    dk_ref, dv_ref, dq_ref, dk_scr, dv_scr, dq_scr,
    *pipe,
    scale, block_q, block_k, nk_blocks, nq_blocks, soft_cap, sliding_window,
    specialize, n_rep,
):
    """Triangle-enumerated fused backward: the linear grid dim walks only
    the causally-possible (iq, ik) group pairs (the forward's static
    tables) instead of the nq x nk rectangle (~half no-op steps at
    full-causal long context). Runtime segment starts prune via
    ``ik >= kstart[iq]``."""
    l = pl.program_id(1)
    L = pl.num_programs(1)
    iq = iq_tab[l]
    _bwd_step(
        ik_tab[l], iq,
        first_tab[l] == 1,
        l == 0,
        last_tab[l] == 1,
        l == L - 1,
        ik_tab[l] >= kstart_ref[iq],
        needs_ref, seg_q_ref, seg_k_ref, lse_ref, delta_ref, q_ref, k_ref,
        v_ref, do_ref, dk_ref, dv_ref, dq_ref, dk_scr, dv_scr, dq_scr,
        tuple(pipe) if pipe else None,
        scale=scale, block_q=block_q, block_k=block_k, nk_blocks=nk_blocks,
        nq_blocks=nq_blocks, soft_cap=soft_cap, sliding_window=sliding_window,
        specialize=specialize, n_rep=n_rep,
    )


def _dq_kernel(
    kstart_ref,
    needs_ref,
    seg_q_ref, seg_k_ref, lse_ref, delta_ref, q_ref, k_ref, v_ref, do_ref,
    dq_ref,
    dq_scr,     # [n_rep*block_q, D] f32
    *,
    scale, block_q, block_k, nk_blocks, soft_cap, sliding_window, specialize,
    n_rep,
):
    # grid (Hkv, nq, k_band): reps folded into the q block (see _fwd_step)
    rows = n_rep * block_q
    D = q_ref.shape[-1]
    iq = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)
    ik = kstart_ref[iq] + j

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    def _accum(masked: bool):
        _, ds = _recompute_p_ds(
            q_ref, k_ref, seg_q_ref, seg_k_ref, lse_ref, delta_ref, do_ref,
            v_ref, iq, ik, scale=scale, block_q=block_q, block_k=block_k,
            soft_cap=soft_cap, sliding_window=sliding_window, masked=masked,
            n_rep=n_rep,
        )
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    active = ik <= _last_k(iq, block_q, block_k)
    needs = needs_ref[iq * nk_blocks + jnp.minimum(ik, nk_blocks - 1)]
    _dispatch_masked(active, specialize, needs, _accum)

    @pl.when(j == nk - 1)
    def _done():
        dq_ref[...] = (
            (dq_scr[...] * scale).reshape(n_rep, block_q, D)
        ).astype(dq_ref.dtype)


def _dkv_kernel(
    qlast_ref,
    needs_ref,
    seg_q_ref, seg_k_ref, lse_ref, delta_ref, q_ref, k_ref, v_ref, do_ref,
    dk_ref, dv_ref,
    dk_scr,     # [block_k, D] f32
    dv_scr,     # [block_k, D] f32
    *,
    scale, block_q, block_k, nk_blocks, nq_blocks, soft_cap, sliding_window,
    specialize, n_rep,
):
    # grid: (Hkv, nk, nq) — nq innermost, reps folded into the q block;
    # the (hkv, nk) output block stays resident while every q block of the
    # whole head group accumulates.
    rows = n_rep * block_q
    D = q_ref.shape[-1]
    ik = pl.program_id(1)
    jq = pl.program_id(2)
    nq = pl.num_programs(2)
    iq = _first_q(ik, block_q, block_k) + jq

    @pl.when(jq == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _accum(masked: bool):
        p, ds = _recompute_p_ds(
            q_ref, k_ref, seg_q_ref, seg_k_ref, lse_ref, delta_ref, do_ref,
            v_ref, iq, ik, scale=scale, block_q=block_q, block_k=block_k,
            soft_cap=soft_cap, sliding_window=sliding_window, masked=masked,
            n_rep=n_rep,
        )
        # dv += pᵀ @ do ; dk += dsᵀ @ q over the folded rows (bf16
        # operands, f32 accumulate) — the group's heads sum inside the dot
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[...].reshape(rows, D),
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[...].reshape(rows, D),
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    active = iq <= qlast_ref[ik]
    needs = needs_ref[jnp.minimum(iq, nq_blocks - 1) * nk_blocks + ik]
    _dispatch_masked(active, specialize, needs, _accum)

    @pl.when(jq == nq - 1)
    def _done():
        dk_ref[0] = (dk_scr[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_backward(
    q, k, v, segment_ids, out, lse, do,
    scale, soft_cap, sliding_window, block_q, block_k, max_seqlen,
):
    """All [H|Hkv, T, D]-layout. Returns (dq, dk, dv)."""
    H, T, D = q.shape
    Hkv = k.shape[0]
    n_rep = H // Hkv
    block_q = min(block_q, T)
    block_k = min(block_k, T)
    # rep folding multiplies the q-side tile rows by n_rep: cap the folded
    # [n_rep*block_q, block_k] f32 score/ds tiles so the fused kernel's
    # VMEM (tiles + whole-T dk/dv scratch) stays inside the 128 MB budget
    # (block_k-aware like the forward's cap: n_rep=8 x 2048 blocks would
    # otherwise request ~190 MB)
    while n_rep * block_q > 2048 and block_q > 512:
        block_q //= 2
    while 2 * n_rep * block_q * block_k * 4 > 64 * 2**20 and block_k > 512:
        block_k //= 2
    seg2d = segment_ids.reshape(1, T)
    # delta_i = rowsum(do * out) — cheap elementwise reduce, stays in XLA
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # [H, T]
    # kernel-side column layout (see _flash_forward docstring)
    nq = T // block_q
    lse4 = lse.reshape(H, nq, block_q, 1)
    delta4 = delta.reshape(H, nq, block_q, 1)
    kstart, qlast = _band_bounds(
        segment_ids, block_q, block_k, sliding_window, T
    )
    needs = _block_needs_mask(segment_ids, block_q, block_k, sliding_window, T)

    common = dict(
        scale=scale, block_q=block_q, block_k=block_k,
        nk_blocks=T // block_k, soft_cap=soft_cap,
        sliding_window=sliding_window, specialize=T >= SPECIALIZE_MIN_T,
    )

    # Fused q-stationary backward: dq flushes per q sweep into its
    # (consecutively-revisited) output window; dk/dv accumulate in WHOLE-T
    # [T, D] f32 scratches (n_rep-independent) flushed once per kv head.
    # Fall back to separate dq/dkv sweeps only when the whole-T scratch
    # itself won't fit VMEM (extreme context lengths).
    dkv_scr_bytes = 2 * T * D * 4
    if dkv_scr_bytes <= FUSED_BWD_MAX_DQ_BYTES:
        # estimated scoped need: whole-T dk/dv scratch + the rep-folded
        # f32 score/ds tiles (x4: s2, p, ds + slack). Leave the compiler's
        # default budget alone for small shapes (raising it measurably
        # hurt short-context throughput).
        # raise only when the default 16 MB budget cannot fit (raising it
        # when unnecessary measurably hurt short-context throughput —
        # ~7% on the 1B/512-packed shape, chip-measured r3+r4)
        pipeline = _bwd_pipeline()
        rows = n_rep * block_q
        est = dkv_scr_bytes + 4 * n_rep * block_q * block_k * 4
        if pipeline:  # parked p/ds tiles + k block copy
            est += rows * block_k * (
                do.dtype.itemsize + q.dtype.itemsize
            ) + block_k * D * k.dtype.itemsize
        limit = (
            min(est + 40 * 2**20, 114 * 2**20)  # 114 MB = max scoped limit
            if est > 14 * 2**20 else None
        )
        out_shapes = [
            jax.ShapeDtypeStruct((Hkv, T, D), k.dtype),
            jax.ShapeDtypeStruct((Hkv, T, D), v.dtype),
            jax.ShapeDtypeStruct((H, T, D), q.dtype),
        ]
        scratch_shapes = [
            pltpu.VMEM((T, D), jnp.float32),
            pltpu.VMEM((T, D), jnp.float32),
            pltpu.VMEM((n_rep * block_q, D), jnp.float32),
        ]
        if pipeline:
            scratch_shapes += [
                pltpu.VMEM((rows, block_k), do.dtype),   # parked p
                pltpu.VMEM((rows, block_k), q.dtype),    # parked ds
                pltpu.VMEM((block_k, D), k.dtype),       # parked k block
                pltpu.SMEM((2,), jnp.int32),             # [col, valid]
            ]
        kv_whole = pl.BlockSpec(
            (1, T, D), lambda *idx: (idx[0], 0, 0)
        )
        if max_seqlen is None:
            # no static band: walk only the causal triangle's (iq, ik)
            # group pairs — the forward's own static tables
            iq_t, ik_t, first_t, last_t = _tri_tables(
                T // block_q, T // block_k, block_q, block_k
            )

            def t_kv(h, l, ks, nm, iqt, ikt, ft, lt):
                return (h, ikt[l], 0)

            def t_q3(h, l, ks, nm, iqt, ikt, ft, lt):
                return (h, iqt[l], 0)

            def t_q4(h, l, ks, nm, iqt, ikt, ft, lt):
                return (h, iqt[l], 0, 0)

            dk, dv, dq = pl.pallas_call(
                functools.partial(
                    _bwd_kernel_tri, **common, nq_blocks=T // block_q,
                    n_rep=n_rep,
                ),
                grid_spec=pltpu.PrefetchScalarGridSpec(
                    num_scalar_prefetch=6,
                    grid=(Hkv, len(iq_t)),
                    in_specs=[
                        pl.BlockSpec(
                            (1, block_q),
                            lambda h, l, ks, nm, iqt, ikt, ft, lt:
                                (0, iqt[l]),
                        ),
                        pl.BlockSpec(
                            (1, block_k),
                            lambda h, l, ks, nm, iqt, ikt, ft, lt:
                                (0, ikt[l]),
                        ),
                        pl.BlockSpec((n_rep, 1, block_q, 1), t_q4),
                        pl.BlockSpec((n_rep, 1, block_q, 1), t_q4),
                        pl.BlockSpec((n_rep, block_q, D), t_q3),
                        pl.BlockSpec((1, block_k, D), t_kv),
                        pl.BlockSpec((1, block_k, D), t_kv),
                        pl.BlockSpec((n_rep, block_q, D), t_q3),
                    ],
                    out_specs=[
                        kv_whole,
                        kv_whole,
                        pl.BlockSpec((n_rep, block_q, D), t_q3),
                    ],
                    scratch_shapes=scratch_shapes,
                ),
                out_shape=out_shapes,
                compiler_params=_compiler_params(
                    dimension_semantics=("parallel", "arbitrary"),
                    **({"vmem_limit_bytes": limit} if limit else {}),
                ),
                interpret=_interpret(),
            )(
                kstart, needs, jnp.asarray(iq_t), jnp.asarray(ik_t),
                jnp.asarray(first_t), jnp.asarray(last_t),
                seg2d, seg2d, lse4, delta4, q, k, v, do,
            )
            return dq, dk, dv

        def b_kv(h, i, j, ks, nm):
            return (
                h,
                jnp.minimum(ks[i] + j, _last_k(i, block_q, block_k)),
                0,
            )

        def b_q3(h, i, j, ks, nm):
            return (h, i, 0)

        def b_q4(h, i, j, ks, nm):
            return (h, i, 0, 0)

        dk, dv, dq = pl.pallas_call(
            functools.partial(
                _bwd_kernel, **common, nq_blocks=T // block_q, n_rep=n_rep
            ),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(
                    Hkv, T // block_q,
                    _k_band_blocks(block_q, block_k, max_seqlen, T),
                ),
                in_specs=[
                    pl.BlockSpec((1, block_q), lambda h, i, j, ks, nm: (0, i)),
                    pl.BlockSpec(
                        (1, block_k),
                        lambda h, i, j, ks, nm: (
                            0,
                            jnp.minimum(
                                ks[i] + j, _last_k(i, block_q, block_k)
                            ),
                        ),
                    ),
                    pl.BlockSpec((n_rep, 1, block_q, 1), b_q4),
                    pl.BlockSpec((n_rep, 1, block_q, 1), b_q4),
                    pl.BlockSpec((n_rep, block_q, D), b_q3),
                    pl.BlockSpec((1, block_k, D), b_kv),
                    pl.BlockSpec((1, block_k, D), b_kv),
                    pl.BlockSpec((n_rep, block_q, D), b_q3),
                ],
                out_specs=[
                    kv_whole,
                    kv_whole,
                    pl.BlockSpec((n_rep, block_q, D), b_q3),
                ],
                scratch_shapes=scratch_shapes,
            ),
            out_shape=out_shapes,
            compiler_params=_compiler_params(
                dimension_semantics=("parallel", "arbitrary", "arbitrary"),
                **({"vmem_limit_bytes": limit} if limit else {}),
            ),
            interpret=_interpret(),
        )(kstart, needs, seg2d, seg2d, lse4, delta4, q, k, v, do)
        return dq, dk, dv

    def dq_kj(h, i, j, ks, nm):
        return (
            h,
            jnp.minimum(ks[i] + j, _last_k(i, block_q, block_k)),
            0,
        )

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, **common, n_rep=n_rep),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(
                Hkv, T // block_q,
                _k_band_blocks(block_q, block_k, max_seqlen, T),
            ),
            in_specs=[
                pl.BlockSpec((1, block_q), lambda h, i, j, ks, nm: (0, i)),
                pl.BlockSpec(
                    (1, block_k),
                    lambda h, i, j, ks, nm: (
                        0,
                        jnp.minimum(ks[i] + j, _last_k(i, block_q, block_k)),
                    ),
                ),
                pl.BlockSpec(
                    (n_rep, 1, block_q, 1), lambda h, i, j, ks, nm: (h, i, 0, 0)
                ),
                pl.BlockSpec(
                    (n_rep, 1, block_q, 1), lambda h, i, j, ks, nm: (h, i, 0, 0)
                ),
                pl.BlockSpec(
                    (n_rep, block_q, D), lambda h, i, j, ks, nm: (h, i, 0)
                ),
                pl.BlockSpec((1, block_k, D), dq_kj),
                pl.BlockSpec((1, block_k, D), dq_kj),
                pl.BlockSpec(
                    (n_rep, block_q, D), lambda h, i, j, ks, nm: (h, i, 0)
                ),
            ],
            out_specs=pl.BlockSpec(
                (n_rep, block_q, D), lambda h, i, j, ks, nm: (h, i, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((n_rep * block_q, D), jnp.float32)
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((H, T, D), q.dtype),
        # split-backward p/ds tiles need the same scoped-vmem raise as the
        # forward at big (rep-folded) blocks
        compiler_params=_compiler_params(
            **({"vmem_limit_bytes": 100 * 2**20}
               if n_rep * block_q >= 2048 else {})
        ),
        interpret=_interpret(),
    )(kstart, needs, seg2d, seg2d, lse4, delta4, q, k, v, do)

    def dkv_qi(ql, j, i):
        # clip: qlast can be -1 (all-pad k block); the step is inactive then
        return jnp.clip(
            _first_q(j, block_q, block_k) + i, 0, (T // block_q) - 1
        )

    def qi3(h, j, i, ql, nm):
        return (h, dkv_qi(ql, j, i), 0)

    def qi4(h, j, i, ql, nm):
        return (h, dkv_qi(ql, j, i), 0, 0)

    kv_spec = pl.BlockSpec((1, block_k, D), lambda h, j, i, ql, nm: (h, j, 0))
    group_in_specs = [
        pl.BlockSpec(
            (1, block_q),
            lambda h, j, i, ql, nm: (0, dkv_qi(ql, j, i)),
        ),
        pl.BlockSpec((1, block_k), lambda h, j, i, ql, nm: (0, j)),
        pl.BlockSpec((n_rep, 1, block_q, 1), qi4),
        pl.BlockSpec((n_rep, 1, block_q, 1), qi4),
        pl.BlockSpec((n_rep, block_q, D), qi3),
        kv_spec,
        kv_spec,
        pl.BlockSpec((n_rep, block_q, D), qi3),
    ]
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, **common, nq_blocks=T // block_q, n_rep=n_rep
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(
                Hkv, T // block_k,
                _q_band_blocks(block_q, block_k, max_seqlen, T),
            ),
            in_specs=group_in_specs,
            out_specs=[kv_spec, kv_spec],
            scratch_shapes=[
                pltpu.VMEM((block_k, D), jnp.float32),
                pltpu.VMEM((block_k, D), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((Hkv, T, D), k.dtype),
            jax.ShapeDtypeStruct((Hkv, T, D), v.dtype),
        ],
        compiler_params=_compiler_params(
            **({"vmem_limit_bytes": 100 * 2**20}
               if block_k >= 2048 or n_rep * block_q >= 2048 else {})
        ),
        interpret=_interpret(),
    )(qlast, needs, seg2d, seg2d, lse4, delta4, q, k, v, do)
    return dq, dk, dv


# --------------------------------------------------------------------------- #
# custom-vjp entry ([T, H, D] public layout)
# --------------------------------------------------------------------------- #


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash_thd(q, k, v, segment_ids, scale, soft_cap, sliding_window, block_q,
               block_k, max_seqlen):
    """[T, H, D]-layout entry with custom vjp."""
    out, _ = _flash_forward(
        q.swapaxes(0, 1),
        k.swapaxes(0, 1),
        v.swapaxes(0, 1),
        segment_ids,
        scale,
        soft_cap,
        sliding_window,
        block_q,
        block_k,
        max_seqlen,
    )
    return out.swapaxes(0, 1)


def _flash_fwd_rule(q, k, v, segment_ids, scale, soft_cap, sliding_window,
                    block_q, block_k, max_seqlen):
    out, lse = _flash_forward(
        q.swapaxes(0, 1), k.swapaxes(0, 1), v.swapaxes(0, 1), segment_ids,
        scale, soft_cap, sliding_window, block_q, block_k, max_seqlen,
    )
    return out.swapaxes(0, 1), (q, k, v, segment_ids, out, lse)


def _flash_bwd_rule(scale, soft_cap, sliding_window, block_q, block_k,
                    max_seqlen, res, g):
    q, k, v, segment_ids, out_htd, lse = res
    dq, dk, dv = _flash_backward(
        q.swapaxes(0, 1), k.swapaxes(0, 1), v.swapaxes(0, 1), segment_ids,
        out_htd, lse, g.swapaxes(0, 1),
        scale, soft_cap, sliding_window, block_q, block_k, max_seqlen,
    )
    return dq.swapaxes(0, 1), dk.swapaxes(0, 1), dv.swapaxes(0, 1), None


_flash_thd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def packed_flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    segment_ids: jnp.ndarray,
    *,
    softmax_scale: float,
    soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
    block_size: int = 512,
    block_size_k: Optional[int] = None,
    max_seqlen: Optional[int] = None,
) -> jnp.ndarray:
    """Causal packed-varlen flash attention. q ``[T, H, D]``, k/v
    ``[T, Hkv, D]``, segment_ids ``[T]`` (0 = pad) -> ``[T, H, D]``.

    ``max_seqlen``: STATIC upper bound on any single segment's length. When
    set, the kernels iterate a statically narrowed (q block, k block) band
    instead of the full causal rectangle — at short-segment packing most
    grid steps are out-of-band no-ops that still cost ~µs each, so this is
    a multi-x win. Segments longer than the bound get silently truncated
    attention: callers must validate (the train engine does; any other
    caller gets a device-side check under ``AREAL_DEBUG_CHECKS=1``). The
    flag is read at TRACE time — set it before the first jit of a calling
    step; flipping it later does not retrace cached programs.
    """
    from areal_tpu.base import constants

    if max_seqlen is not None and constants.debug_checks_enabled():
        T = segment_ids.shape[0]
        seg_max = jnp.max(
            jnp.bincount(
                jnp.where(segment_ids > 0, segment_ids, 0), length=T + 1
            )[1:]
        )

        def _check(observed, bound=max_seqlen):
            if int(observed) > bound:
                raise ValueError(
                    f"packed_flash_attention: a segment has {int(observed)} "
                    f"tokens but max_seqlen={bound}; attention beyond the "
                    "band would be silently truncated"
                )

        jax.debug.callback(_check, seg_max)
    return _flash_thd(
        q, k, v, segment_ids.astype(jnp.int32), softmax_scale, soft_cap,
        sliding_window, block_size, block_size_k or block_size, max_seqlen,
    )
