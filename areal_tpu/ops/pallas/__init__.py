"""Pallas TPU kernels for the hot ops.

TPU-native replacement for the reference's CUDA kernels and flash-attn
dependency (SURVEY.md §2.1): a block-wise flash attention over packed varlen
batches with segment-id masking (≈ ``flash_attn_varlen_func`` at
``realhf/impl/model/modules/attn.py:289``).
"""
