"""Rotary position embeddings with scaling variants.

TPU-native counterpart of ``realhf/impl/model/modules/rotary.py`` (281 LoC in
the reference). Functional: frequencies are computed on the fly from positions
(no cached cos/sin buffers — XLA constant-folds or fuses them), which also
makes packed varlen batches trivial: each token carries its own position.

Supports the HF ``rope_scaling`` variants used by the reference model
families: none, "linear", "dynamic" (NTK), and "llama3".
"""

import dataclasses
import math
from typing import Optional

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class RotaryConfig:
    dim: int                      # rotary dimension (usually head_dim)
    base: float = 10000.0
    scaling_type: Optional[str] = None   # None | "linear" | "dynamic" | "llama3"
    scaling_factor: float = 1.0
    # llama3-specific:
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position: int = 8192
    # dynamic-NTK-specific:
    max_position: int = 2048


def _inv_freq(cfg: RotaryConfig) -> jnp.ndarray:
    base = cfg.base
    if cfg.scaling_type == "dynamic":
        # NTK-aware base rescale, fixed at the configured max length (the
        # sequence-length-adaptive variant is not jit-friendly; families used
        # for RL here ship with static rope configs anyway).
        base = base * cfg.scaling_factor ** (cfg.dim / (cfg.dim - 2))
    inv = 1.0 / (
        base ** (jnp.arange(0, cfg.dim, 2, dtype=jnp.float32) / cfg.dim)
    )
    if cfg.scaling_type == "linear":
        inv = inv / cfg.scaling_factor
    elif cfg.scaling_type == "llama3":
        # Frequency-dependent interpolation (HF Llama-3.1 convention).
        low_wl = cfg.original_max_position / cfg.low_freq_factor
        high_wl = cfg.original_max_position / cfg.high_freq_factor
        wl = 2 * math.pi / inv
        smooth = (cfg.original_max_position / wl - cfg.low_freq_factor) / (
            cfg.high_freq_factor - cfg.low_freq_factor
        )
        smooth = jnp.clip(smooth, 0.0, 1.0)
        scaled = (1 - smooth) * inv / cfg.scaling_factor + smooth * inv
        inv = jnp.where(wl > low_wl, inv / cfg.scaling_factor, inv)
        inv = jnp.where((wl <= low_wl) & (wl >= high_wl), scaled, inv)
    return inv


def rotary_cos_sin(cfg: RotaryConfig, positions: jnp.ndarray, dtype=jnp.float32):
    """cos/sin tables for given integer positions. Shapes ``[..., dim/2]``."""
    inv = _inv_freq(cfg)
    freqs = positions.astype(jnp.float32)[..., None] * inv[None]
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rotary(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray):
    """Apply rotary embedding.

    ``x``: ``[..., heads, head_dim]``; ``cos/sin``: ``[..., head_dim/2]``
    (broadcast over the heads axis). Uses the HF "half-split" layout
    (first half / second half), matching all supported families.
    """
    d2 = cos.shape[-1]
    x1 = x[..., :d2]
    x2 = x[..., d2 : 2 * d2]
    c = cos[..., None, :].astype(jnp.float32)
    s = sin[..., None, :].astype(jnp.float32)
    x1f = x1.astype(jnp.float32)
    x2f = x2.astype(jnp.float32)
    o1 = x1f * c - x2f * s
    o2 = x2f * c + x1f * s
    out = jnp.concatenate([o1, o2], axis=-1)
    if 2 * d2 < x.shape[-1]:  # partial rotary (gpt-neox style)
        out = jnp.concatenate([out, x[..., 2 * d2 :].astype(jnp.float32)], axis=-1)
    return out.astype(x.dtype)
