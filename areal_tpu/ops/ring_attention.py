"""Ring attention: context parallelism for long packed sequences.

The first-class long-context story (SURVEY §2.2 "SP"): the packed token axis
shards over a ``ctx`` mesh axis; each device holds a T/cp chunk of q/k/v and
K/V chunks ROTATE around the ring (``lax.ppermute``) while every device
accumulates online-softmax partials against its resident queries — attention
memory and FLOPs per device scale with T/cp, and the K/V traffic rides ICI
(the reference reaches long context through Megatron's sequence parallelism
+ flash-attn varlen kernels; the ring is the TPU-native equivalent of its
context-parallel decomposition).

Implementation notes:
- Pure JAX inside ``shard_map``: ``ppermute`` is differentiable (its
  transpose is the reverse rotation), so the BACKWARD ring — dq locally,
  dk/dv accumulated while rotating back — falls out of autodiff instead of
  a second hand-written protocol.
- Each (q-chunk, kv-chunk) pair runs blockwise online-softmax over k
  sub-chunks (``lax.scan``) under ``jax.checkpoint``: nothing quadratic in
  T is ever materialized, forward or backward.
- Masks use GLOBAL positions (chunk offset = ring index * chunk length):
  causal + packed segment ids + optional sliding window, matching
  ``ops/pallas/flash_attention.py`` semantics (pad rows output 0).
- Per-pair skip: a kv chunk strictly after the q chunk (causal) contributes
  nothing and is skipped with ``lax.cond``, so the causal ring costs ~half.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

NEG_INF = -2.3819763e38


def _pair_partial(
    q, k, v, seg_q, seg_k, q_off, k_off, m, l, acc,
    *, scale, soft_cap, sliding_window, block_k,
):
    """Fold one kv chunk into the (m, l, acc) online-softmax state.

    q [T, H, D]; k/v [T, Hkv, D]; seg_* [T]; offsets are global token
    positions of each chunk's first token. State: m, l [H, T, 1] f32;
    acc [H, T, D] f32.
    """
    T, H, Dh = q.shape
    Hkv = k.shape[1]
    n_rep = H // Hkv
    nb = T // block_k
    qg = q_off + jnp.arange(T)
    qT = q.swapaxes(0, 1)                        # [H, T, D]

    def body(state, inputs):
        m, l, acc = state
        kb, vb, seg_kb, kg = inputs              # [bk, Hkv, D], ..., [bk]
        kb = jnp.repeat(kb, n_rep, axis=1)       # [bk, H, D]
        vb = jnp.repeat(vb, n_rep, axis=1)
        s = jnp.einsum(
            "htd,bhd->htb", qT, kb, preferred_element_type=jnp.float32
        ) * scale                                # [H, T, bk]
        if soft_cap is not None:
            s = soft_cap * jnp.tanh(s / soft_cap)
        mask = (
            (qg[:, None] >= kg[None, :])
            & (seg_q[:, None] == seg_kb[None, :])
            & (seg_q[:, None] > 0)
        )
        if sliding_window is not None:
            mask &= qg[:, None] - kg[None, :] < sliding_window
        s = jnp.where(mask[None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(mask[None], jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m - m_new)
        l = corr * l + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum(
            "htb,bhd->htd", p.astype(vb.dtype), vb,
            preferred_element_type=jnp.float32,
        )
        return (m_new, l, acc), None

    kb = k.reshape(nb, block_k, Hkv, Dh)
    vb = v.reshape(nb, block_k, Hkv, Dh)
    segb = seg_k.reshape(nb, block_k)
    kg = (k_off + jnp.arange(T)).reshape(nb, block_k)
    (m, l, acc), _ = jax.lax.scan(body, (m, l, acc), (kb, vb, segb, kg))
    return m, l, acc


def _ring_shard(
    q, k, v, seg,
    *, axis_name, scale, soft_cap, sliding_window, block_k, cp,
):
    """Per-shard body (inside shard_map): q/k/v [T, H(kv), D], seg [T]."""
    T, H, Dh = q.shape
    idx = jax.lax.axis_index(axis_name)
    m = jnp.full((H, T, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((H, T, 1), jnp.float32)
    acc = jnp.zeros((H, T, Dh), jnp.float32)
    q_off = idx * T

    kv = (k, v, seg)
    perm = [(i, (i + 1) % cp) for i in range(cp)]
    pair = functools.partial(
        _pair_partial, scale=scale, soft_cap=soft_cap,
        sliding_window=sliding_window, block_k=block_k,
    )
    for s in range(cp):
        src = (idx - s) % cp
        k_s, v_s, seg_s = kv
        k_off = src * T

        def with_chunk(state):
            return jax.checkpoint(
                lambda st: pair(
                    q, k_s, v_s, seg, seg_s, q_off, k_off, *st
                )
            )(state)

        # causal skip: a kv chunk strictly after the q chunk is all-masked
        m, l, acc = jax.lax.cond(
            k_off <= q_off, with_chunk, lambda st: st, (m, l, acc)
        )
        if s != cp - 1:
            kv = jax.lax.ppermute(kv, axis_name, perm)
    safe_l = jnp.where(l > 0.0, l, 1.0)
    out = (acc / safe_l).swapaxes(0, 1).astype(q.dtype)  # [T, H, D]
    return out


def ring_attention(
    q: jnp.ndarray,          # [T, H, D] global (token axis sharded over ctx)
    k: jnp.ndarray,          # [T, Hkv, D]
    v: jnp.ndarray,
    segment_ids: jnp.ndarray,  # [T] int32, 0 = padding
    mesh,
    axis_name: str = "ctx",
    *,
    softmax_scale: Optional[float] = None,
    soft_cap: Optional[float] = None,
    sliding_window: Optional[float] = None,
    block_k: int = 512,
) -> jnp.ndarray:
    """Causal packed-varlen attention with the token axis ring-sharded.

    Call from inside (or outside) jit with GLOBAL arrays; the internal
    shard_map re-partitions over ``axis_name``. Differentiable end-to-end
    (the backward ring is autodiff through ppermute).
    """
    from jax.experimental.shard_map import shard_map

    T, H, Dh = q.shape
    cp = mesh.shape[axis_name]
    if softmax_scale is None:
        softmax_scale = Dh ** -0.5
    if T % cp != 0:
        raise ValueError(f"token axis {T} not divisible by ctx={cp}")
    chunk = T // cp
    bk = min(block_k, chunk)
    if chunk % bk != 0:
        import math

        bk = math.gcd(chunk, bk)  # largest workable sub-chunk
    # NOT named `body`: _ring_shard's internal scan body def shares that
    # name, and the shadowing made the wrapped callable ambiguous to
    # read (and to arealint's shard_map arity resolution)
    ring_body = functools.partial(
        _ring_shard,
        axis_name=axis_name,
        scale=softmax_scale,
        soft_cap=soft_cap,
        sliding_window=sliding_window,
        block_k=bk,
        cp=cp,
    )
    # keep TP: heads stay split over `model` inside the ring when both q and
    # kv head counts divide it (they must split together or the GQA group
    # ratio breaks); otherwise heads replicate across model for this op
    m = mesh.shape.get("model", 1)
    head_ax = "model" if (m > 1 and H % m == 0 and k.shape[1] % m == 0) else None
    spec_t = P(axis_name)
    spec_qkv = P(axis_name, head_ax, None)
    return shard_map(
        ring_body,
        mesh=mesh,
        in_specs=(spec_qkv, spec_qkv, spec_qkv, spec_t),
        out_specs=spec_qkv,
        check_rep=False,
    )(q, k, v, segment_ids)
