"""Normalization layers (functional).

TPU-native counterpart of ``realhf/impl/model/modules/rms.py`` and the
LayerNorm variants in ``realhf/impl/model/modules/mlp.py``. Plain jnp — XLA
fuses these into surrounding ops; no Pallas needed.
"""

import jax.numpy as jnp


def rms_norm(x, weight, eps: float = 1e-6, *, plus_one: bool = False):
    """RMSNorm. ``plus_one`` selects the Gemma convention ``(1 + w) * x_hat``.

    Computation runs in float32 regardless of input dtype (matches the
    reference's fp32 norm accumulation) and casts back at the end.
    """
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    x = x / jnp.sqrt(var + eps)
    w = weight.astype(jnp.float32)
    if plus_one:
        w = 1.0 + w
    return (x * w).astype(dtype)


def layer_norm(x, weight, bias, eps: float = 1e-5):
    """Standard LayerNorm (GPT-2 family)."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    x = (x - mean) / jnp.sqrt(var + eps)
    out = x * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(dtype)
