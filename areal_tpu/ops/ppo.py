"""PPO math over packed sequences.

TPU-native counterpart of ``realhf/impl/model/utils/ppo_functional.py`` (394
LoC) and the ``csrc/cugae`` CUDA kernel (``csrc/cugae/gae.cu:10``). Semantics
match the reference exactly (tests compare against a numpy port of
``pygae1d_nolp_misalign``); the layout is redesigned for XLA:

- The reference packs with ``cu_seqlens`` and a *misaligned* values array
  (one extra bootstrap slot per sequence). Here every array lives on the same
  padded packed token axis ``[T]`` with ``segment_ids`` (0 = pad), and the
  bootstrap is an explicit per-token ``next_values`` array — static shapes,
  no host-side offsets.
- GAE is a first-order linear recurrence ``A_t = delta_t + (gamma*lam)*A_{t+1}``
  solved with ``jax.lax.associative_scan`` (log-depth on the VPU) instead of a
  one-thread-per-sequence CUDA kernel; segment boundaries reset the carry via
  the decay coefficient, so one scan covers the whole packed batch.

All loss math runs in float32 (reference asserts fp32 inputs).
"""

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp


# --------------------------------------------------------------------------- #
# KL controllers (host-side Python state, ≈ ppo_functional.py:14-48)
# --------------------------------------------------------------------------- #


class FixedKLController:
    def __init__(self, kl_coef: float):
        self.value = kl_coef

    def update(self, current: float, n_steps: int):
        pass


class AdaptiveKLController:
    """Adaptive KL controller (arXiv:1909.08593)."""

    def __init__(self, init_kl_coef: float, target: float, horizon: float):
        self.value = init_kl_coef
        self.target = target
        self.horizon = horizon

    def update(self, current: float, n_steps: int):
        proportional_error = float(
            jnp.clip(current / self.target - 1, -0.2, 0.2)
        )
        self.value *= 1 + proportional_error * n_steps / self.horizon


# --------------------------------------------------------------------------- #
# Losses
# --------------------------------------------------------------------------- #


def actor_loss_fn(
    logprobs: jnp.ndarray,
    old_logprobs: jnp.ndarray,
    advantages: jnp.ndarray,
    eps_clip: float,
    loss_mask: jnp.ndarray,
    c_clip: Optional[float] = None,
    proximal_logprobs: Optional[jnp.ndarray] = None,
    behav_imp_weight_cap: Optional[float] = None,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Decoupled-PPO actor loss (≈ ``ppo_functional.actor_loss_fn:51``).

    ``proximal_logprobs`` activates the decoupled objective: the clip ratio is
    taken w.r.t. the proximal (recomputed) policy while the behavioral policy
    contributes an importance weight ``exp(proximal - behav)``; optionally
    capped. ``c_clip`` activates dual clipping (arXiv:1912.09729).
    """
    logprobs = logprobs.astype(jnp.float32)
    old_logprobs = old_logprobs.astype(jnp.float32)
    advantages = advantages.astype(jnp.float32)
    loss_mask = loss_mask.astype(bool)
    denorm_logprobs = (
        proximal_logprobs.astype(jnp.float32)
        if proximal_logprobs is not None
        else old_logprobs
    )
    n_valid = jnp.maximum(jnp.sum(loss_mask), 1)

    ratio = jnp.where(loss_mask, jnp.exp(logprobs - denorm_logprobs), 0.0)
    clipped_ratio = jnp.clip(ratio, 1.0 - eps_clip, 1.0 + eps_clip)
    pg_loss1 = -advantages * ratio
    pg_loss2 = -advantages * clipped_ratio
    clip_mask = jax.lax.stop_gradient(pg_loss1 < pg_loss2)
    pg_loss = jnp.maximum(pg_loss1, pg_loss2)
    if c_clip is not None:
        assert c_clip > 1.0, c_clip
        pg_loss3 = jnp.sign(advantages) * c_clip * advantages
        dual_clip_mask = jax.lax.stop_gradient(pg_loss3 < pg_loss)
        pg_loss = jnp.minimum(pg_loss, pg_loss3)
    else:
        dual_clip_mask = jnp.zeros_like(clip_mask)

    stat: Dict[str, jnp.ndarray] = {}
    if proximal_logprobs is not None:
        behav_kl = proximal_logprobs - old_logprobs
        behav_imp_weight = jnp.exp(behav_kl)
        if behav_imp_weight_cap is not None:
            behav_mask = (behav_imp_weight <= behav_imp_weight_cap) & loss_mask
        else:
            behav_mask = loss_mask
        behav_kl = jnp.where(behav_mask, behav_kl, 0.0)
        behav_imp_weight = jnp.where(behav_mask, behav_imp_weight, 0.0)
        pg_loss = pg_loss * jax.lax.stop_gradient(behav_imp_weight)
        stat.update(
            behave_imp_weight=behav_imp_weight,
            behave_approx_kl=behav_kl,
            behave_mask=behav_mask,
        )

    logging_loss = jax.lax.stop_gradient(pg_loss)
    loss = jnp.sum(jnp.where(loss_mask, pg_loss, 0.0)) / n_valid
    stat.update(
        loss=logging_loss,
        importance_weight=jax.lax.stop_gradient(ratio),
        approx_kl=jax.lax.stop_gradient(logprobs - denorm_logprobs),
        clip_mask=clip_mask & loss_mask,
        dual_clip_mask=dual_clip_mask & loss_mask,
    )
    return loss, stat


def _huber(x, y, delta: float = 10.0):
    diff = jnp.abs(x - y)
    return jnp.where(diff < delta, 0.5 * diff**2, delta * (diff - 0.5 * delta))


def _mse(x, y):
    return 0.5 * (x - y) ** 2


def critic_loss_fn(
    value: jnp.ndarray,
    old_value: jnp.ndarray,
    target_value: jnp.ndarray,
    value_eps_clip: float,
    loss_mask: jnp.ndarray,
    loss_fn_type: str = "mse",
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Clipped value loss (≈ ``ppo_functional.critic_loss_fn:161``)."""
    value = value.astype(jnp.float32)
    old_value = old_value.astype(jnp.float32)
    target_value = target_value.astype(jnp.float32)
    loss_mask = loss_mask.astype(bool)
    loss_fn = {"huber": _huber, "mse": _mse}[loss_fn_type]

    loss_original = loss_fn(value, target_value)
    value_clipped = old_value + jnp.clip(
        value - old_value, -value_eps_clip, value_eps_clip
    )
    loss_clipped = loss_fn(value_clipped, target_value)
    value_loss = jnp.maximum(loss_original, loss_clipped)
    clip_mask = jax.lax.stop_gradient(loss_clipped > loss_original) & loss_mask
    n_valid = jnp.maximum(jnp.sum(loss_mask), 1)
    loss = jnp.sum(jnp.where(loss_mask, value_loss, 0.0)) / n_valid
    return loss, {"clip_mask": clip_mask, "loss": jax.lax.stop_gradient(value_loss)}


# --------------------------------------------------------------------------- #
# Rewards & GAE on the packed segment layout
# --------------------------------------------------------------------------- #


def is_segment_end(segment_ids: jnp.ndarray) -> jnp.ndarray:
    """True at the last token of each segment (padding rows are False)."""
    nxt = jnp.concatenate([segment_ids[1:], jnp.zeros((1,), segment_ids.dtype)])
    return (segment_ids > 0) & (nxt != segment_ids)


def get_packed_rewards(
    kl_ctl: float,
    clip_reward_value: float,
    log_probs: jnp.ndarray,       # [T] behavior logprobs at action tokens
    ref_log_probs: jnp.ndarray,   # [T]
    reward_score: jnp.ndarray,    # [T]: per-token; the interface scatters the
                                  # sequence-level score onto segment ends
    segment_ids: jnp.ndarray,     # [T]
    seq_no_eos_mask: jnp.ndarray, # [T] broadcast per token (True = truncated)
    mask_no_eos_with_zero: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """≈ ``ppo_functional.get_packed_rewards:229``: KL penalty everywhere plus
    the (clipped) task reward on the final action token of each sequence."""
    mask = segment_ids > 0
    kl_rewards = jnp.where(mask, -kl_ctl * (log_probs - ref_log_probs), 0.0)
    score = jnp.clip(reward_score, -clip_reward_value, clip_reward_value)
    at_end = is_segment_end(segment_ids)
    if mask_no_eos_with_zero:
        score = jnp.where(seq_no_eos_mask, 0.0, score)
    tot_rewards = kl_rewards + jnp.where(at_end, score, 0.0)
    return kl_rewards, tot_rewards


def segment_next_values(
    values: jnp.ndarray, segment_ids: jnp.ndarray, bootstrap: jnp.ndarray
) -> jnp.ndarray:
    """next_values[t] = values[t+1] within a segment; at the segment's last
    token, ``bootstrap[t]`` (e.g. the value of the EOS/truncation token, or 0)."""
    shifted = jnp.concatenate([values[1:], jnp.zeros((1,), values.dtype)])
    return jnp.where(is_segment_end(segment_ids), bootstrap, shifted)


def segment_gae(
    rewards: jnp.ndarray,      # [T] fp32
    values: jnp.ndarray,       # [T] fp32
    next_values: jnp.ndarray,  # [T] fp32 (see segment_next_values)
    segment_ids: jnp.ndarray,  # [T]
    gamma: float,
    lam: float,
    mask: Optional[jnp.ndarray] = None,     # valid action positions
    not_end: Optional[jnp.ndarray] = None,  # t+1 continues the trajectory
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """GAE over every segment at once via associative scan.

    Replaces ``cugae1d_nolp_misalign`` / ``pygae1d_nolp_misalign``
    (``csrc/cugae/gae.cu:10``, ``ppo_functional.py:292``): advantages and
    returns, zero outside ``mask``. By default a trajectory is a whole
    segment; PPO passes an action ``mask`` (generated tokens only) and a
    matching ``not_end`` so trajectories span only the action positions.
    """
    if mask is None:
        mask = segment_ids > 0
    mask = mask.astype(jnp.float32)
    rewards = rewards.astype(jnp.float32) * mask
    values = values.astype(jnp.float32)
    next_values = next_values.astype(jnp.float32)
    delta = (rewards + gamma * next_values - values) * mask
    # Recurrence (in reverse token order): A_t = delta_t + c_t * A_{t+1},
    # where c_t = gamma*lam if t+1 continues the same trajectory else 0.
    if not_end is None:
        not_end = ~is_segment_end(segment_ids)
    c = gamma * lam * not_end.astype(jnp.float32) * mask

    def combine(right, left):
        # Elements are (a, b) representing x -> a*x + b, composed right-to-left
        # on the reversed axis.
        a1, b1 = right
        a2, b2 = left
        return a2 * a1, a2 * b1 + b2

    a_rev = jnp.flip(c, axis=0)
    b_rev = jnp.flip(delta, axis=0)
    _, adv_rev = jax.lax.associative_scan(combine, (a_rev, b_rev), axis=0)
    advantages = jnp.flip(adv_rev, axis=0) * mask
    returns = (advantages + values) * mask
    return advantages, returns


# --------------------------------------------------------------------------- #
# Packed logprob / normalization helpers (≈ impl/model/utils/functional.py)
# --------------------------------------------------------------------------- #


def gather_logprobs(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Log p(labels[t] | logits[t]) for each packed position, fp32. [T]"""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]


def gather_packed_shifted_log_probs(
    logits: jnp.ndarray, input_ids: jnp.ndarray, segment_ids: jnp.ndarray
) -> jnp.ndarray:
    """Logprob of the *next* token at each position (zero where the next token
    leaves the segment). ≈ ``gather_packed_shifted_log_probs`` in the
    reference's ``utils/functional.py`` but with static shapes: the output
    stays [T]; positions without a successor are 0 and masked downstream."""
    nxt_ids = jnp.concatenate([input_ids[1:], jnp.zeros((1,), input_ids.dtype)])
    lp = gather_logprobs(logits, nxt_ids)
    has_next = (segment_ids > 0) & ~is_segment_end(segment_ids)
    return jnp.where(has_next, lp, 0.0)


def entropy_from_logits(logits: jnp.ndarray) -> jnp.ndarray:
    """Per-position categorical entropy, fp32. [T]"""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.sum(jnp.exp(logp) * logp, axis=-1)


def masked_normalization(
    x: jnp.ndarray,
    mask: jnp.ndarray,
    eps: float = 1e-5,
    unbiased: bool = False,
) -> jnp.ndarray:
    """Normalize to zero mean / unit std over masked entries (fp32).

    ≈ ``masked_normalization`` in the reference's ``utils/functional.py``;
    the reference all-reduces across DP — here the caller runs this inside
    pjit on the global batch, so the mean/std are already global.
    """
    x = x.astype(jnp.float32)
    mask = mask.astype(jnp.float32)
    n = jnp.maximum(jnp.sum(mask), 1.0)
    mean = jnp.sum(x * mask) / n
    var = jnp.sum(jnp.square(x - mean) * mask) / jnp.maximum(
        n - (1.0 if unbiased else 0.0), 1.0
    )
    return jnp.where(mask > 0, (x - mean) / jnp.sqrt(var + eps), x)


def group_normalization(
    x: jnp.ndarray,
    mask: jnp.ndarray,
    group_ids: jnp.ndarray,
    num_groups: int,
    eps: float = 1e-5,
    std_norm: bool = True,
) -> jnp.ndarray:
    """GRPO-style per-group advantage normalization
    (≈ ``ppo_interface.py:648-680`` group_adv_norm): subtract the group mean
    (and optionally divide by group std) where groups share a prompt."""
    x = x.astype(jnp.float32)
    m = mask.astype(jnp.float32)
    gsum = jax.ops.segment_sum(x * m, group_ids, num_segments=num_groups)
    gcnt = jnp.maximum(
        jax.ops.segment_sum(m, group_ids, num_segments=num_groups), 1.0
    )
    gmean = (gsum / gcnt)[group_ids]
    out = x - gmean
    if std_norm:
        gvar = jax.ops.segment_sum(jnp.square(out) * m, group_ids, num_segments=num_groups)
        gstd = jnp.sqrt(gvar / gcnt + eps)[group_ids]
        out = out / gstd
    return jnp.where(m > 0, out, x)
