"""Paged KV attention: decode + chunked-extend over a page pool.

TPU-native counterpart of the paged attention the reference inherits from
SGLang/vLLM CUDA kernels. KV lives in a pool ``[L, P, 2, Hkv, page, D]``
(K and V interleaved per page — one page, one contiguous block, one DMA,
heads before tokens so the decode kernel needs no in-VMEM transpose);
each slot owns a page TABLE ``[M]`` instead of a dense slab, so HBM scales
with resident tokens and identical prompts share pages.

DESIGN: the pool is READ-ONLY inside these ops. The caller's layer scan
passes the whole pool plus a layer index and the CURRENT tokens' K/V as
separate operands; attention folds the fresh tokens in analytically
(online-softmax merge of the pool part and the self/intra-chunk part), and
the model writes all layers' new KV into the pool in ONE scatter after the
scan. The previous formulation updated the pool inside the layer scan,
which forced XLA to stream the whole multi-GB pool through the scan's
stacked outputs every decode step (dynamic-update-slice + copy ≈ 30 ms/step
at a 1.5B/64-slot profile — measured, round-3 xprof).

Two implementations:
- XLA gather path (here): one fused gather of the slot's pages into a
  contiguous view — correct everywhere (CPU tests); callers pass
  width-limited tables so the gather reads O(resident) pages.
- Pallas kernel (``ops/pallas/paged_attention.py``): reads pages in place
  via kernel-issued DMAs on TPU — no materialized gather.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

_NEG_INF = -2.3819763e38


def gather_pages(
    pages: jnp.ndarray, table: jnp.ndarray, layer
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``[L, P, 2, Hkv, page, D]`` + table ``[B, M]`` + layer index ->
    ``(k, v)`` each ``[B, M*page, Hkv, D]`` (contiguous per-slot views;
    garbage beyond the slot's length, masked by the caller's ``lens``).
    ONE gather serves K and V, and the layer index fuses into it — no
    materialized per-layer slice."""
    B, M = table.shape
    g = pages[layer, table]                # [B, M, 2, Hkv, page, D]
    Hkv, page, D = g.shape[3:]
    g = jnp.swapaxes(g, 3, 4)              # [B, M, 2, page, Hkv, D]
    k = g[:, :, 0].reshape(B, M * page, Hkv, D)
    v = g[:, :, 1].reshape(B, M * page, Hkv, D)
    return k, v


def gather_dequant_pages(
    pages: jnp.ndarray,
    table: jnp.ndarray,
    layer,
    scales: Optional[jnp.ndarray],
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """:func:`gather_pages` with int8 dequant fused behind the same gather:
    when ``scales`` (``[L, P, 2, Hkv, page]`` f32, parallel to the pool) is
    present, the gathered int8 views widen to f32 against their per-(token,
    head) scales. Only the O(resident) per-slot VIEW is ever widened — the
    HBM read is int8 and a full-size higher-precision pool copy never
    exists (that is the whole point of the quantized pool)."""
    k, v = gather_pages(pages, table, layer)
    if scales is None:
        return k, v
    B, M = table.shape
    g = scales[layer, table]               # [B, M, 2, Hkv, page]
    Hkv, page = g.shape[3:]
    g = jnp.swapaxes(g, 3, 4)              # [B, M, 2, page, Hkv]
    k_s = g[:, :, 0].reshape(B, M * page, Hkv)
    v_s = g[:, :, 1].reshape(B, M * page, Hkv)
    k = k.astype(jnp.float32) * k_s[..., None]
    v = v.astype(jnp.float32) * v_s[..., None]
    return k, v


def paged_decode_attention(
    q: jnp.ndarray,          # [B, H, D] one new token per slot
    k_self: jnp.ndarray,     # [B, Hkv, D] the new token's K (not in pool)
    v_self: jnp.ndarray,     # [B, Hkv, D]
    pages: jnp.ndarray,      # [L, P, 2, Hkv, page, D] the WHOLE pool
    layer: jnp.ndarray,      # scalar i32 layer index
    table: jnp.ndarray,      # [B, M] i32
    lens: jnp.ndarray,       # [B] tokens RESIDENT IN THE POOL (excl. self)
    *,
    softmax_scale: Optional[float] = None,
    soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
    use_pallas: Optional[bool] = None,
    mesh=None,
    scales: Optional[jnp.ndarray] = None,  # [L, P, 2, Hkv, page] int8 pools
) -> jnp.ndarray:
    """Single-token attention against paged KV plus the token itself.
    The pool holds positions ``[0, lens)``; the query sits at position
    ``lens`` and always attends itself via ``k_self``/``v_self`` (its KV is
    scattered into the pool by the caller AFTER the layer scan). Returns
    ``[B, H, D]``.

    ``scales`` marks an int8-quantized pool (docs/performance.md "KV
    quantization"): dequant fuses into both implementations — the Pallas
    kernel DMAs int8 pages + their scale stripes and widens in-register;
    the XLA path folds the scales into the gathered per-slot view. The
    self token's K/V stay full precision (they have not been quantized
    yet — they land in the pool at the caller's post-scan scatter).

    With ``mesh`` carrying a >1-way ``model`` axis, the Pallas kernel runs
    under ``shard_map`` over the kv-head axis (VERDICT r4 weak #7 / #5):
    attention is per-head independent and the head groups align with the
    pool's kv-head sharding, so each model shard runs the kernel on its
    LOCAL pool slice — no all-gather, no XLA-gather fallback on the TP
    serving hot path. The scales array shards on the same kv-head axis.

    This is the attention half of the decode-step roofline; the OTHER
    half — the LM head + sampling epilogue — streams through
    ``ops/fused_sample.py`` under ``AREAL_FUSED_SAMPLE`` (same
    auto-detect-then-fallback dispatch shape as ``use_pallas`` here)."""
    B, H, D = q.shape
    Hkv = pages.shape[3]
    n_rep = H // Hkv
    if softmax_scale is None:
        softmax_scale = D ** -0.5
    tp = mesh.shape.get("model", 1) if mesh is not None else 1
    if use_pallas is None:
        # the kernel's in-VMEM reshapes need a full-lane head_dim; smaller
        # heads (and sub-tile pages) take the XLA gather path. int8 pages
        # need a (32, 128)-tileable stripe — page % 32 instead of % 8
        page_mult = 32 if pages.dtype == jnp.int8 else 8
        use_pallas = (
            jax.devices()[0].platform == "tpu"
            and q.shape[-1] % 128 == 0
            and pages.shape[4] % page_mult == 0
            and Hkv % tp == 0
        )
    elif use_pallas and tp > 1 and Hkv % tp != 0:
        # explicit use_pallas=True with an incompatible mesh: the shard_map
        # below splits the kv-head axis over the model axis and cannot
        # split a head — fail here with the real constraint instead of an
        # opaque sharding error from inside the shard_map trace
        raise ValueError(
            f"paged_attention(use_pallas=True): {Hkv} kv heads are not "
            f"divisible by the mesh's model axis ({tp}); the Pallas decode "
            "kernel shards whole kv-head groups. Use a model axis that "
            "divides n_kv_heads, or pass use_pallas=False for the XLA "
            "gather path."
        )
    if use_pallas:
        from areal_tpu.ops.pallas import paged_attention as pl_paged

        def _kernel(q_, k_, v_, pages_, layer_, table_, lens_, *scales_):
            return pl_paged.decode(
                q_, k_, v_, pages_, layer_, table_, lens_,
                softmax_scale=softmax_scale, soft_cap=soft_cap,
                sliding_window=sliding_window,
                scales=scales_[0] if scales_ else None,
            )

        operands = (q, k_self, v_self, pages, layer, table, lens)
        if scales is not None:
            operands += (scales,)
        if tp > 1:
            from jax.sharding import PartitionSpec as P

            # contiguous q-head chunks of H/tp cover whole GQA groups
            # (H/tp = n_rep * Hkv/tp), so per-shard n_rep is unchanged
            from areal_tpu.ops.pallas.compat import shard_map

            in_specs = (
                P(None, "model", None),                    # q
                P(None, "model", None),                    # k_self
                P(None, "model", None),                    # v_self
                P(None, None, None, "model", None, None),  # pool
                P(),                                       # layer
                P(None, None),                             # table
                P(None),                                   # lens
            )
            if scales is not None:
                # the scales pytree rides the pool's kv-head sharding
                in_specs += (P(None, None, None, "model", None),)
            return shard_map(
                _kernel, mesh=mesh,
                in_specs=in_specs,
                out_specs=P(None, "model", None),
                check_vma=False,
            )(*operands)
        return _kernel(*operands)
    k, v = gather_dequant_pages(pages, table, layer, scales)  # [B, S, Hkv, D]
    S = k.shape[1]
    qg = q.reshape(B, Hkv, n_rep, D)
    s_pool = jnp.einsum(
        "bgrd,bsgd->bgrs", qg, k, preferred_element_type=jnp.float32
    ) * softmax_scale                       # [B, Hkv, r, S]
    s_self = jnp.einsum(
        "bgrd,bgd->bgr", qg, k_self.astype(q.dtype),
        preferred_element_type=jnp.float32,
    ) * softmax_scale                       # [B, Hkv, r]
    if soft_cap is not None:
        s_pool = soft_cap * jnp.tanh(s_pool / soft_cap)
        s_self = soft_cap * jnp.tanh(s_self / soft_cap)
    pos = jnp.arange(S)[None, :]
    mask = pos < lens[:, None]              # [B, S]
    if sliding_window is not None:
        # the query sits at position lens
        mask &= pos > lens[:, None] - sliding_window
    s_pool = jnp.where(mask[:, None, None], s_pool, _NEG_INF)
    # online-softmax merge of pool part and the always-attended self token
    m = jnp.maximum(s_pool.max(-1), s_self)            # [B, Hkv, r]
    p_pool = jnp.exp(s_pool - m[..., None])
    p_pool = jnp.where(mask[:, None, None], p_pool, 0.0)
    p_self = jnp.exp(s_self - m)
    denom = p_pool.sum(-1) + p_self
    acc = jnp.einsum(
        "bgrs,bsgd->bgrd", p_pool.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    ) + p_self[..., None] * v_self[:, :, None].astype(jnp.float32)
    out = acc / denom[..., None]
    return out.reshape(B, H, D).astype(q.dtype)


def paged_verify_attention(
    q: jnp.ndarray,          # [B, C, H, D] verify chunk (C = K+1, small)
    k_chunk: jnp.ndarray,    # [B, C, Hkv, D]
    v_chunk: jnp.ndarray,
    pages: jnp.ndarray,      # [L, P, 2, Hkv, page, D]
    layer: jnp.ndarray,
    table: jnp.ndarray,      # [B, M]
    lens: jnp.ndarray,       # [B] tokens resident in the pool
    n_new: jnp.ndarray,      # [B] valid chunk tokens (C where active, 0 else)
    *,
    softmax_scale: Optional[float] = None,
    soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
    scales: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Multi-token VERIFY attention for speculative decoding: the chunk is
    ``[last_token, d_1..d_K]`` sitting at positions ``[lens, lens+K]``;
    token ``i`` attends every pool position ``< lens`` plus chunk tokens
    ``<= i`` — exactly the chunked-prefill contract with ``start = lens``,
    so this delegates to :func:`paged_extend_attention` (ONE pass scores
    all K+1 positions; the per-position decode kernel would re-read params
    and pool K+1 times, which is the cost spec decode exists to amortize).

    A dedicated kernel entry point, not an engine-side call into the
    extend op, for the same reason decode has XLA + Pallas variants behind
    one name: a fused verify kernel (C is tiny and static — the pool scan
    could prefetch across positions) can land here later without touching
    the model or engine layers.

    The chunk's K/V ride as operands; the CALLER scatters them into the
    pool after its layer scan, masking positions that can never become
    resident (rejected drafts are overwritten before ``lens`` reaches
    them)."""
    if q.ndim != 4 or q.shape[1] != k_chunk.shape[1]:
        raise ValueError(
            f"verify chunk shapes disagree: q {q.shape} vs k {k_chunk.shape}"
        )
    return paged_extend_attention(
        q, k_chunk, v_chunk, pages, layer, table, lens, n_new,
        softmax_scale=softmax_scale, soft_cap=soft_cap,
        sliding_window=sliding_window, scales=scales,
    )


def paged_extend_attention(
    q: jnp.ndarray,          # [B, C, H, D] chunk of new tokens
    k_chunk: jnp.ndarray,    # [B, C, Hkv, D] the chunk's K (not in pool)
    v_chunk: jnp.ndarray,
    pages: jnp.ndarray,      # [L, P, 2, Hkv, page, D] the WHOLE pool
    layer: jnp.ndarray,      # scalar i32 layer index
    table: jnp.ndarray,      # [B, M]
    start: jnp.ndarray,      # [B] tokens RESIDENT IN THE POOL (chunk start)
    n_new: jnp.ndarray,      # [B] valid new tokens in the chunk (<= C)
    *,
    softmax_scale: Optional[float] = None,
    soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
    kv_block: int = 1024,
    skip_pool: bool = False,
    scales: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Chunked-prefill attention: chunk token i (global position start+i)
    attends every pool position < start plus chunk tokens <= i (intra-chunk
    causal). The chunk's K/V ride as operands — the caller scatters them
    into the pool after its layer scan. Returns ``[B, C, H, D]``.

    ``skip_pool`` (STATIC): the caller knows every row starts at position 0
    (cold-prompt first chunks), so the pool holds nothing visible — skip
    the page gather + blockwise pool scan entirely. At short-prompt
    admission the pool part costs as much as the intra-chunk part while
    contributing only masked-out zeros.

    The pool part runs as a blockwise online softmax over KV blocks (a
    ``lax.scan``): the naive formulation materializes ``[B, H, C, S]`` f32
    scores — 12.9 GB for a 4-slot x 2048-chunk x 32k-context extend — while
    this peaks at ``[B, H, C, max(kv_block, C)]``. GQA never materializes a
    K/V repeat: the query's group axis rides the einsum."""
    B, C, H, D = q.shape
    Hkv = pages.shape[3]
    n_rep = H // Hkv
    if softmax_scale is None:
        softmax_scale = D ** -0.5
    qg = q.reshape(B, C, Hkv, n_rep, D)
    qpos_in_chunk = jnp.arange(C)
    valid_q = qpos_in_chunk[None, :] < n_new[:, None]        # [B, C]

    # ---- intra-chunk causal part (always: every token attends itself) ---
    s_in = jnp.einsum(
        "bcgrd,bsgd->bgrcs", qg, k_chunk.astype(q.dtype),
        preferred_element_type=jnp.float32,
    ) * softmax_scale                                        # [B,g,r,C,C]
    if soft_cap is not None:
        s_in = soft_cap * jnp.tanh(s_in / soft_cap)
    causal = qpos_in_chunk[:, None] >= qpos_in_chunk[None, :]  # [C, C]
    in_mask = causal[None] & valid_q[:, None, :]             # [B, C, C]
    if sliding_window is not None:
        in_mask &= (
            qpos_in_chunk[:, None] - qpos_in_chunk[None, :] < sliding_window
        )[None]
    s_in = jnp.where(in_mask[:, None, None], s_in, _NEG_INF)
    m = s_in.max(-1)                                         # [B,g,r,C]
    p_in = jnp.exp(s_in - m[..., None])
    p_in = jnp.where(in_mask[:, None, None], p_in, 0.0)
    l = p_in.sum(-1)
    acc = jnp.einsum(
        "bgrcs,bsgd->bgrcd", p_in.astype(v_chunk.dtype), v_chunk,
        preferred_element_type=jnp.float32,
    )

    if skip_pool:
        out = acc / jnp.maximum(l, 1e-30)[..., None]
        out = jnp.moveaxis(out, 3, 1).reshape(B, C, H, D)
        return jnp.where(
            valid_q[:, :, None, None], out, 0.0
        ).astype(q.dtype)

    # ---- pool part: blockwise online softmax over resident KV ----------
    # (int8 pools dequant behind the gather — the per-slot view widens,
    # never the pool; the intra-chunk part above is untouched: the chunk's
    # own K/V ride as full-precision operands)
    k, v = gather_dequant_pages(pages, table, layer, scales)  # [B, S, Hkv, D]
    S = k.shape[1]
    Sb = kv_block if S % kv_block == 0 else S
    nb = S // Sb
    kb = jnp.moveaxis(k.reshape(B, nb, Sb, Hkv, D), 1, 0)
    vb = jnp.moveaxis(v.reshape(B, nb, Sb, Hkv, D), 1, 0)
    offs = jnp.arange(nb) * Sb
    qpos = start[:, None] + qpos_in_chunk[None, :]           # [B, C]

    def body(carry, blk):
        m, l, acc = carry
        k_blk, v_blk, off = blk
        s = jnp.einsum(
            "bcgrd,bsgd->bgrcs", qg, k_blk,
            preferred_element_type=jnp.float32,
        ) * softmax_scale
        if soft_cap is not None:
            s = soft_cap * jnp.tanh(s / soft_cap)
        kpos = off + jnp.arange(Sb)                          # [Sb]
        # every pool position < start is causally visible to every chunk
        # token; the per-token bound only matters for the sliding window
        mask = kpos[None, None, :] < start[:, None, None]    # [B, 1|C, Sb]
        mask = jnp.broadcast_to(mask, (B, C, Sb))
        if sliding_window is not None:
            mask &= kpos[None, None, :] > qpos[:, :, None] - sliding_window
        s = jnp.where(mask[:, None, None], s, _NEG_INF)      # [B,g,r,C,Sb]
        m_new = jnp.maximum(m, s.max(-1))
        # m can be -inf while everything so far is masked; keep the
        # rescale finite
        alpha = jnp.exp(jnp.where(m > _NEG_INF / 2, m - m_new, 0.0))
        p = jnp.exp(s - m_new[..., None])
        p = jnp.where(mask[:, None, None], p, 0.0)
        l = l * alpha + p.sum(-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bgrcs,bsgd->bgrcd", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32,
        )
        return (m_new, l, acc), None

    (m, l, acc), _ = jax.lax.scan(body, (m, l, acc), (kb, vb, offs))
    out = acc / jnp.maximum(l, 1e-30)[..., None]             # [B,g,r,C,D]
    out = jnp.moveaxis(out, 3, 1).reshape(B, C, H, D)
    # fully-masked (invalid) rows carry garbage; zero them
    out = jnp.where(valid_q[:, :, None, None], out, 0.0)
    return out.astype(q.dtype)
