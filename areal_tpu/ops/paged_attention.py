"""Paged KV attention: decode + chunked-extend over a page pool.

TPU-native counterpart of the paged attention the reference inherits from
SGLang/vLLM CUDA kernels. KV lives in a pool ``[n_pages, page, Hkv, D]``
(per layer); each slot owns a page TABLE ``[M]`` instead of a dense slab, so
HBM scales with resident tokens and identical prompts share pages.

Two implementations:
- XLA gather path (here): gather the slot's pages into a contiguous view and
  reuse the dense attention math — correct everywhere (CPU tests), with a
  per-step gather the compiler fuses reasonably;
- Pallas kernel (``ops/pallas/paged_attention.py``): reads pages in place
  via scalar-prefetch table indices on TPU — no materialized gather.
"""

from typing import Optional

import jax
import jax.numpy as jnp

from areal_tpu.ops import attention as attn_ops

_NEG_INF = -2.3819763e38


def gather_pages(pages: jnp.ndarray, table: jnp.ndarray) -> jnp.ndarray:
    """``[P, page, Hkv, D]`` + table ``[B, M]`` -> ``[B, M*page, Hkv, D]``
    (a contiguous per-slot view; garbage beyond the slot's length, masked by
    the caller's ``lens``)."""
    B, M = table.shape
    g = pages[table]                       # [B, M, page, Hkv, D]
    return g.reshape(B, M * pages.shape[1], *pages.shape[2:])


def paged_decode_attention(
    q: jnp.ndarray,          # [B, H, D] one new token per slot
    k_pages: jnp.ndarray,    # [P, page, Hkv, D]
    v_pages: jnp.ndarray,
    table: jnp.ndarray,      # [B, M] i32
    lens: jnp.ndarray,       # [B] valid tokens INCLUDING the current one
    *,
    softmax_scale: Optional[float] = None,
    soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
    use_pallas: Optional[bool] = None,
) -> jnp.ndarray:
    """Single-token attention against paged KV. The new token's K/V must
    already be written at position ``lens - 1``. Returns ``[B, H, D]``."""
    if use_pallas is None:
        # the kernel's in-VMEM reshapes need a full-lane head_dim; smaller
        # heads (and sub-tile pages) take the XLA gather path
        use_pallas = (
            jax.devices()[0].platform == "tpu"
            and q.shape[-1] % 128 == 0
            and k_pages.shape[1] % 8 == 0
        )
    if use_pallas:
        from areal_tpu.ops.pallas import paged_attention as pl_paged

        return pl_paged.decode(
            q, k_pages, v_pages, table, lens,
            softmax_scale=softmax_scale, soft_cap=soft_cap,
            sliding_window=sliding_window,
        )
    k = gather_pages(k_pages, table)
    v = gather_pages(v_pages, table)
    return attn_ops.decode_attention(
        q, k, v, lens,
        softmax_scale=softmax_scale, soft_cap=soft_cap,
        sliding_window=sliding_window,
    )


def paged_extend_attention(
    q: jnp.ndarray,          # [B, C, H, D] chunk of new tokens
    k_pages: jnp.ndarray,    # [P, page, Hkv, D]
    v_pages: jnp.ndarray,
    table: jnp.ndarray,      # [B, M]
    start: jnp.ndarray,      # [B] chunk start position (tokens already resident)
    n_new: jnp.ndarray,      # [B] valid new tokens in the chunk (<= C)
    *,
    softmax_scale: Optional[float] = None,
    soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
) -> jnp.ndarray:
    """Chunked-prefill attention: chunk token i (global position start+i)
    attends to every resident position <= its own. The chunk's K/V must
    already be written into the pages. Returns ``[B, C, H, D]``."""
    B, C, H, D = q.shape
    if softmax_scale is None:
        softmax_scale = D ** -0.5
    k = gather_pages(k_pages, table)      # [B, S, Hkv, D]
    v = gather_pages(v_pages, table)
    S = k.shape[1]
    n_rep = H // k.shape[2]
    if n_rep > 1:
        k = jnp.repeat(k, n_rep, axis=2)
        v = jnp.repeat(v, n_rep, axis=2)
    scores = jnp.einsum(
        "bchd,bshd->bhcs", q, k, preferred_element_type=jnp.float32
    ) * softmax_scale
    if soft_cap is not None:
        scores = soft_cap * jnp.tanh(scores / soft_cap)
    qpos = start[:, None] + jnp.arange(C)[None, :]          # [B, C]
    kpos = jnp.arange(S)[None, :]                           # [1, S]
    mask = kpos[:, None, :] <= qpos[:, :, None]             # [B, C, S] causal
    if sliding_window is not None:
        mask &= kpos[:, None, :] > qpos[:, :, None] - sliding_window
    valid_q = jnp.arange(C)[None, :] < n_new[:, None]       # [B, C]
    mask &= valid_q[:, :, None]
    scores = jnp.where(mask[:, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    # fully-masked (invalid) rows produce uniform probs; zero them
    probs = jnp.where(valid_q[:, None, :, None], probs, 0.0)
    return jnp.einsum("bhcs,bshd->bchd", probs, v)
