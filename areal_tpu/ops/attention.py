"""Packed varlen causal attention.

TPU-native counterpart of the reference's flash-attn varlen path
(``realhf/impl/model/modules/attn.py:272-289``). Where the reference carries
``cu_seqlens`` into ``flash_attn_varlen_func`` (CUDA), we pack sequences into
one token axis and carry integer ``segment_ids`` (0 = padding, real segments
start at 1). A token attends to a key iff they share a segment id and the key
does not come later in the packed order. Positions restart per segment, so
causality within a segment coincides with packed-order causality.

Two implementations behind one entry point:
- ``_attention_xla``: plain einsum + mask. Reference semantics; used on CPU
  (tests) and as the autodiff-friendly fallback.
- Pallas flash attention (``areal_tpu.ops.pallas.flash_attention``) on TPU for
  long contexts — selected by ``use_flash`` when available.

All shapes static: ``q,k,v`` are ``[T, H, D]`` / ``[T, Hkv, D]`` where T is
the padded packed-token budget, so one compiled program serves every batch.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -2.3819763e38  # ~ -float32 max; matches common flash-attn masks


def _repeat_kv(k: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """[T, Hkv, D] -> [T, Hkv*n_rep, D] (GQA key/value head expansion)."""
    if n_rep == 1:
        return k
    t, hkv, d = k.shape
    return jnp.broadcast_to(k[:, :, None, :], (t, hkv, n_rep, d)).reshape(
        t, hkv * n_rep, d
    )


def _attention_xla(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    segment_ids: jnp.ndarray,
    softmax_scale: float,
    soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
) -> jnp.ndarray:
    t, h, d = q.shape
    n_rep = h // k.shape[1]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    scores = jnp.einsum(
        "qhd,khd->hqk", q, k, preferred_element_type=jnp.float32
    ) * softmax_scale
    if soft_cap is not None:
        scores = soft_cap * jnp.tanh(scores / soft_cap)
    idx = jnp.arange(t)
    same_seg = (segment_ids[:, None] == segment_ids[None, :]) & (
        segment_ids[:, None] > 0
    )
    causal = idx[:, None] >= idx[None, :]
    mask = same_seg & causal
    if sliding_window is not None:
        mask &= idx[:, None] - idx[None, :] < sliding_window
    scores = jnp.where(mask[None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    # Fully-masked (padding) rows: softmax over all -inf gives garbage; zero them.
    probs = jnp.where(mask.any(axis=-1)[None, :, None], probs, 0.0)
    return jnp.einsum("hqk,khd->qhd", probs.astype(v.dtype), v)


def packed_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    segment_ids: jnp.ndarray,
    *,
    softmax_scale: Optional[float] = None,
    soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
    use_flash: bool = False,
    flash_block_size: Optional[int] = None,
    flash_block_size_k: Optional[int] = None,
    max_seqlen: Optional[int] = None,
) -> jnp.ndarray:
    """Causal self-attention over a packed token axis.

    Args:
      q: ``[T, H, D]``; k, v: ``[T, Hkv, D]`` (``H % Hkv == 0``).
      segment_ids: ``[T]`` int32, 0 marks padding tokens.
      flash_block_size: None = auto — 1024 at long context (T >= 8192), where
        bigger score tiles roughly double measured kernel throughput; 512
        otherwise (short packed segments straddle fewer block boundaries).
      max_seqlen: STATIC upper bound on any segment length; narrows the
        flash kernels' block band (see ``packed_flash_attention``).
    Returns ``[T, H, D]``.
    """
    if softmax_scale is None:
        softmax_scale = q.shape[-1] ** -0.5
    if _RING_CTX is not None:
        from areal_tpu.ops.ring_attention import ring_attention

        mesh, axis = _RING_CTX
        return ring_attention(
            q, k, v, segment_ids, mesh, axis,
            softmax_scale=softmax_scale,
            soft_cap=soft_cap,
            sliding_window=sliding_window,
        )
    if use_flash:
        from areal_tpu.ops.pallas import flash_attention as _fa

        T = q.shape[0]
        bs = flash_block_size or (
            1024 if T >= 8192 and T % 1024 == 0 else 512
        )
        while T % bs:
            # an override that does not divide T would silently truncate
            # the kernel grid; fall back to the largest dividing block
            bs //= 2
        bsk = flash_block_size_k or bs
        while T % bsk:
            bsk //= 2
        return _fa.packed_flash_attention(
            q,
            k,
            v,
            segment_ids,
            softmax_scale=softmax_scale,
            soft_cap=soft_cap,
            sliding_window=sliding_window,
            block_size=bs,
            block_size_k=bsk,
            max_seqlen=max_seqlen,
        )
    return _attention_xla(
        q, k, v, segment_ids, softmax_scale, soft_cap, sliding_window
    )


# Context-parallel override: when set, packed training attention rings the
# token axis over the given mesh axis (engines with ParallelConfig.ctx > 1
# set this at init; the trace picks it up wherever the forward runs).
_RING_CTX = None


def set_context_parallel(mesh, axis_name: str = "ctx"):
    global _RING_CTX
    if _RING_CTX is not None:
        old_mesh, old_axis = _RING_CTX
        if old_axis != axis_name or dict(old_mesh.shape) != dict(mesh.shape):
            raise ValueError(
                "conflicting context-parallel topologies in one process: "
                f"{dict(old_mesh.shape)} vs {dict(mesh.shape)} — every train "
                "engine in a CP experiment must share the same mesh shape"
            )
    _RING_CTX = (mesh, axis_name)


def get_context_parallel():
    return _RING_CTX


def clear_context_parallel():
    global _RING_CTX
    _RING_CTX = None


def decode_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    cache_lens: jnp.ndarray,
    *,
    softmax_scale: Optional[float] = None,
    soft_cap: Optional[float] = None,
    sliding_window: Optional[int] = None,
) -> jnp.ndarray:
    """Single-token decode attention against a per-sequence KV cache.

    Args:
      q: ``[B, H, D]`` — one new token per sequence.
      k_cache, v_cache: ``[B, S, Hkv, D]`` — S is the static cache capacity;
        the new token's K/V must already be written at ``cache_lens - 1``.
      cache_lens: ``[B]`` int32 — number of valid cache entries per sequence
        (including the current token).
    Returns ``[B, H, D]``.
    """
    if softmax_scale is None:
        softmax_scale = q.shape[-1] ** -0.5
    b, s = k_cache.shape[0], k_cache.shape[1]
    n_rep = q.shape[1] // k_cache.shape[2]
    k = k_cache
    v = v_cache
    if n_rep > 1:
        k = jnp.repeat(k, n_rep, axis=2)
        v = jnp.repeat(v, n_rep, axis=2)
    scores = jnp.einsum(
        "bhd,bshd->bhs", q, k, preferred_element_type=jnp.float32
    ) * softmax_scale
    if soft_cap is not None:
        scores = soft_cap * jnp.tanh(scores / soft_cap)
    pos = jnp.arange(s)[None, :]
    mask = pos < cache_lens[:, None]
    if sliding_window is not None:
        mask &= pos >= cache_lens[:, None] - sliding_window
    scores = jnp.where(mask[:, None, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    probs = jnp.where(cache_lens[:, None, None] > 0, probs, 0.0)
    return jnp.einsum("bhs,bshd->bhd", probs.astype(v.dtype), v)
