"""Fused LM-head + sampling epilogue (docs/performance.md "Fused sampling
epilogue").

A decode step's epilogue in the reference path is: materialize the full
``[B, V]`` logits (``x @ W_head``), then sort / log-softmax / categorical
over them (``gen/sampling.py``). At a 152k vocab the logits tensor and its
descending sort dominate the per-token cost — they are one of the two
residuals between measured decode and the HBM roofline (ROADMAP item 2).

This module streams the head over vocab blocks instead: per block it
computes ``logits_blk = x @ W[:, v0:v1]`` and folds the block into online
per-row state —

- running max ``m`` and rescaled sum-of-exponentials ``l`` (the standard
  online-softmax recurrence, same as ``ops/paged_attention.py``'s extend
  kernel) give the exact log-normalizer ``m + log l``;
- a running raw-logits argmax (value, index) makes greedy slots
  *token-exact* vs ``jnp.argmax`` over the full array (strictly-greater
  updates keep the first maximum, matching ``jnp.argmax`` tie order);
- a running **Gumbel-top-1** argmax over ``warped + G`` (``G`` iid Gumbel,
  derived per block from the PRNG key) IS a categorical sample from
  ``softmax(warped)`` — distribution-exact, no ``[B, V]`` materialization,
  with an optional per-row *excluded* token (the speculative residual
  "p with the rejected token removed, renormalized");
- an optional running top-``TOPK_MAX`` (value, index) buffer merged per
  block via ``lax.top_k`` serves top-k slots exactly (for ``k <=
  TOPK_MAX``): the final sample is a cheap ``[R, TOPK_MAX]`` categorical
  over the masked buffer;
- a per-row gathered warped logit (the speculative draft-token score).

Top-p slots are NOT handled here — they keep the sorted reference path
via the engine's warp-row bucket machinery (PR 9), so only those rows pay
the ``[W, V]`` sort.

Exactness contract (pinned by tests/test_fused_sample.py): greedy slots
are token-exact and logprob-exact (up to float associativity) vs
``sample_tokens``; temperature and top-k slots are distribution-exact —
same marginal, different RNG stream, so individual draws differ from
``jax.random.categorical``. Top-k keeps *exactly k* tokens; the sorted
reference keeps ties at the k-th value (a measure-zero difference for
continuous logits).

Dispatch mirrors ``ops/paged_attention.py``: ``use_pallas=None``
auto-detects (TPU, no top-k buffer, no mesh); the XLA path is itself
streamed (peak extra memory ``[R, block]``, not ``[R, V]``) and serves
CPU/interpret parity, meshes (GSPMD partitions the block matmuls), and
top-k slots. Explicitly requesting the kernel somewhere it cannot run
raises with the real constraint instead of silently degrading.
"""

from typing import Dict, Optional

import jax
import jax.numpy as jnp

# Matches gen/sampling.py: masked-out entries of a distribution.
NEG_INF = -1e10
# Initializer/mask for online state: below any representable warped logit
# (greedy rows divide by the 1e-6 temperature floor, so real warped values
# reach ~1e8 magnitude; -1e10 would be ambiguous there).
_MASK = -2.3819763e38
# Top-k buffer width: slots with top_k <= TOPK_MAX sample exactly from the
# online buffer; larger top_k falls back to the sorted reference path.
TOPK_MAX = 64


def _update_block(
    c: Dict[str, jnp.ndarray],
    logits: jnp.ndarray,           # [R, Bk] f32 (soft cap already applied)
    col0,                          # scalar (may be traced): first column id
    key_blk: jax.Array,
    t: jnp.ndarray,                # [R] f32 temperature (floored)
    exclude: Optional[jnp.ndarray],
    gather_ids: Optional[jnp.ndarray],
    kmax: int,
) -> Dict[str, jnp.ndarray]:
    """Fold one vocab block into the online per-row state."""
    Bk = logits.shape[1]
    cols = col0 + jnp.arange(Bk, dtype=jnp.int32)
    warped = logits / t[:, None]
    out = dict(c)

    # online logsumexp of the warped logits
    m_new = jnp.maximum(c["m"], jnp.max(warped, axis=-1))
    out["m"] = m_new
    out["l"] = c["l"] * jnp.exp(c["m"] - m_new) + jnp.sum(
        jnp.exp(warped - m_new[:, None]), axis=-1
    )

    # running raw argmax: strict > keeps the earliest maximum, matching
    # jnp.argmax tie order over the full array
    bi = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    bv = jnp.take_along_axis(logits, bi[:, None], axis=-1)[:, 0]
    upd = bv > c["am_v"]
    out["am_v"] = jnp.where(upd, bv, c["am_v"])
    out["am_i"] = jnp.where(upd, col0 + bi, c["am_i"]).astype(jnp.int32)

    # Gumbel-top-1: argmax over warped + G across all blocks is a
    # categorical draw from softmax(warped) (ties are measure-zero)
    u = jax.random.uniform(
        key_blk, warped.shape, minval=jnp.finfo(jnp.float32).tiny, maxval=1.0
    )
    pert = warped - jnp.log(-jnp.log(u))
    if exclude is not None:
        pert = jnp.where(cols[None, :] == exclude[:, None], _MASK, pert)
    pbi = jnp.argmax(pert, axis=-1)
    pbv = jnp.take_along_axis(pert, pbi[:, None], axis=-1)[:, 0]
    pwv = jnp.take_along_axis(warped, pbi[:, None], axis=-1)[:, 0]
    upd2 = pbv > c["g_p"]
    out["g_p"] = jnp.where(upd2, pbv, c["g_p"])
    out["g_w"] = jnp.where(upd2, pwv, c["g_w"])
    out["g_i"] = jnp.where(
        upd2, (col0 + pbi).astype(jnp.int32), c["g_i"]
    ).astype(jnp.int32)

    if gather_ids is not None:
        hit = cols[None, :] == gather_ids[:, None]
        out["gat"] = jnp.where(
            hit.any(axis=-1),
            jnp.sum(jnp.where(hit, warped, 0.0), axis=-1),
            c["gat"],
        )

    if "topv" in c:
        cat_v = jnp.concatenate([c["topv"], warped], axis=-1)
        cat_i = jnp.concatenate(
            [c["topi"], jnp.broadcast_to(cols, warped.shape)], axis=-1
        )
        tv, sel = jax.lax.top_k(cat_v, kmax)
        out["topv"] = tv
        out["topi"] = jnp.take_along_axis(cat_i, sel, axis=-1)
    return out


def _fused_sample_xla(
    rng, x, w, temperature, greedy, soft_cap, topk, exclude, gather_ids,
    block_size, kmax,
) -> Dict[str, jnp.ndarray]:
    R, E = x.shape
    V = w.shape[1]
    block = max(1, min(int(block_size), V))
    nbf, tail = divmod(V, block)
    t = jnp.maximum(temperature.astype(jnp.float32), 1e-6)

    carry = {
        "m": jnp.full((R,), _MASK, jnp.float32),
        "l": jnp.zeros((R,), jnp.float32),
        "am_v": jnp.full((R,), _MASK, jnp.float32),
        "am_i": jnp.zeros((R,), jnp.int32),
        "g_p": jnp.full((R,), _MASK, jnp.float32),
        "g_w": jnp.zeros((R,), jnp.float32),
        "g_i": jnp.zeros((R,), jnp.int32),
    }
    if gather_ids is not None:
        carry["gat"] = jnp.full((R,), _MASK, jnp.float32)
    if topk is not None:
        carry["topv"] = jnp.full((R, kmax), _MASK, jnp.float32)
        carry["topi"] = jnp.zeros((R, kmax), jnp.int32)

    def _logits(w_blk):
        out = jnp.dot(x, w_blk, preferred_element_type=jnp.float32)
        if soft_cap is not None and soft_cap > 0:
            out = jnp.tanh(out / soft_cap) * soft_cap
        return out

    if nbf > 0:
        def body(c, j):
            w_blk = jax.lax.dynamic_slice(w, (0, j * block), (E, block))
            c = _update_block(
                c, _logits(w_blk), j * block, jax.random.fold_in(rng, j),
                t, exclude, gather_ids, kmax,
            )
            return c, None

        carry, _ = jax.lax.scan(body, carry, jnp.arange(nbf))
    if tail:
        w_blk = jax.lax.slice(w, (0, nbf * block), (E, V))
        carry = _update_block(
            carry, _logits(w_blk), nbf * block,
            jax.random.fold_in(rng, nbf), t, exclude, gather_ids, kmax,
        )

    norm = carry["m"] + jnp.log(carry["l"])
    tokens = jnp.where(greedy, carry["am_i"], carry["g_i"])
    lp = jnp.where(
        greedy, carry["am_v"] / t - norm, carry["g_w"] - norm
    )
    if topk is not None:
        kk = jnp.clip(topk, 1, kmax)[:, None]
        pos = jnp.arange(kmax)[None, :]
        masked = jnp.where(pos < kk, carry["topv"], NEG_INF)
        choice = jax.random.categorical(
            jax.random.fold_in(rng, nbf + 1), masked, axis=-1
        )
        tok_k = jnp.take_along_axis(
            carry["topi"], choice[:, None], axis=-1
        )[:, 0]
        lp_k = jnp.take_along_axis(masked, choice[:, None], axis=-1)[:, 0] \
            - jax.scipy.special.logsumexp(masked, axis=-1)
        use_k = (topk <= kmax) & ~greedy
        tokens = jnp.where(use_k, tok_k, tokens)
        lp = jnp.where(use_k, lp_k, lp)
    out = {
        "tokens": tokens.astype(jnp.int32),
        "logprobs": lp.astype(jnp.float32),
        "argmax": carry["am_i"],
        "norm": norm,
    }
    if gather_ids is not None:
        out["gathered_lp"] = carry["gat"] - norm
    return out


def fused_sample(
    rng: jax.Array,
    x: jnp.ndarray,                # [R, E] final-norm hidden states
    w: jnp.ndarray,                # [E, V] head weight (serving dtype)
    temperature: jnp.ndarray,      # [R] f32 (0 => greedy slot)
    greedy: jnp.ndarray,           # [R] bool
    soft_cap: Optional[float] = None,
    topk: Optional[jnp.ndarray] = None,    # [R] i32; > TOPK_MAX => inactive
    exclude: Optional[jnp.ndarray] = None,  # [R] i32 token to mask (-1 none)
    gather_ids: Optional[jnp.ndarray] = None,  # [R] i32 token to score
    block_size: int = 2048,
    use_pallas: Optional[bool] = None,
    mesh=None,
    interpret: Optional[bool] = None,
) -> Dict[str, jnp.ndarray]:
    """Sample one token per row without materializing ``[R, V]`` logits.

    Returns a dict: ``tokens`` [R] i32 (greedy rows: exact raw argmax;
    rows with active ``topk``: exact top-k sample; others: Gumbel-top-1
    categorical over the temperature-warped head, minus the optional
    ``exclude`` token), ``logprobs`` [R] f32 w.r.t. the warped (and, for
    top-k rows, top-k-restricted) distribution — the same semantics
    ``sample_tokens`` reports — plus ``argmax`` [R] i32 (raw argmax),
    ``norm`` [R] f32 (warped log-normalizer) and, when ``gather_ids`` is
    given, ``gathered_lp`` [R] f32 (warped logprob of the gathered token,
    the speculative draft score).

    ``use_pallas=None`` auto-detects: the TPU kernel runs when there is no
    top-k buffer and no mesh; everywhere else the streamed XLA path runs
    (same math, same memory shape — peak ``[R, block]``). Explicit
    ``use_pallas=True`` raises when the kernel cannot serve the request.
    """
    R, E = x.shape
    V = w.shape[1]
    if w.shape[0] != E:
        raise ValueError(f"head weight {w.shape} does not match hidden {x.shape}")
    platform = jax.devices()[0].platform
    if use_pallas is None:
        use_pallas = (
            platform == "tpu"
            and mesh is None
            and topk is None
            and V >= 128
        )
    if use_pallas:
        if topk is not None:
            raise ValueError(
                "fused_sample pallas kernel does not maintain the top-k "
                "buffer; leave use_pallas unset so top-k rows take the "
                "streamed XLA epilogue"
            )
        if mesh is not None:
            raise ValueError(
                "fused_sample pallas kernel has no TP shard_map wiring; "
                "use the XLA epilogue under a mesh (GSPMD partitions the "
                "block matmuls)"
            )
        from areal_tpu.ops.pallas import fused_sample as _pk

        return _pk.fused_sample_pallas(
            rng, x, w, temperature, greedy,
            exclude=exclude, gather_ids=gather_ids, soft_cap=soft_cap,
            block_v=block_size, interpret=interpret,
        )
    return _fused_sample_xla(
        rng, x, w, temperature, greedy, soft_cap, topk, exclude,
        gather_ids, block_size, TOPK_MAX,
    )


def fused_spec_rejection(
    rng: jax.Array,
    hidden: jnp.ndarray,           # [B, C, E] final-norm verify hidden
    w: jnp.ndarray,                # [E, V]
    draft: jnp.ndarray,            # [B, K] proposed tokens
    sp,                            # SamplingParams
    greedy: Optional[jnp.ndarray] = None,
    soft_cap: Optional[float] = None,
    block_size: int = 2048,
    use_pallas: Optional[bool] = None,
    mesh=None,
):
    """Speculative rejection sampling from the streamed head — the fused
    counterpart of ``gen/sampling.py::spec_rejection_sample`` for
    DETERMINISTIC (one-hot) drafters, fed final-norm verify hidden states
    instead of materialized ``[B, C, V]`` logits.

    One fused pass over the ``B * C`` rows yields, per position: the
    draft token's warped target logprob (the acceptance threshold), the
    raw argmax (greedy acceptance + residual), and a pre-sampled residual
    candidate — Gumbel-top-1 with the position's draft token excluded
    (positions ``< K``; exclusion only binds where a rejection can occur)
    which IS a draw from "p with the rejected token removed, renormalized";
    the bonus position ``K`` samples the plain warped target. Acceptance
    then picks the boundary row. Returns exactly
    ``(accept_len, tokens [B, C], logprobs [B, C], boundary_argmax)`` with
    the reference's semantics: token-exact for greedy slots,
    distribution-exact otherwise. Warping slots (top-p / top-k) are NOT
    handled here — the engine routes them through the sorted reference
    path via the warp-row bucket.
    """
    B, C, E = hidden.shape
    K = C - 1
    r_acc, r_res = jax.random.split(rng)
    if greedy is None:
        greedy = sp.temperature <= 0.0
    flat = hidden.reshape(B * C, E)
    temp = jnp.repeat(sp.temperature, C)
    greedy_flat = jnp.repeat(greedy, C)
    neg1 = jnp.full((B, 1), -1, jnp.int32)
    excl = jnp.concatenate([draft.astype(jnp.int32), neg1], axis=1)
    gids = jnp.concatenate(
        [draft.astype(jnp.int32), jnp.zeros((B, 1), jnp.int32)], axis=1
    )
    res = fused_sample(
        r_res, flat, w, temp, greedy_flat, soft_cap=soft_cap,
        exclude=excl.reshape(-1), gather_ids=gids.reshape(-1),
        block_size=block_size, use_pallas=use_pallas, mesh=mesh,
    )
    cand = res["tokens"].reshape(B, C)
    cand_lp = res["logprobs"].reshape(B, C)
    argmax = res["argmax"].reshape(B, C)
    draft_lp = res["gathered_lp"].reshape(B, C)[:, :K]

    u = jax.random.uniform(r_acc, draft.shape, minval=1e-20)
    accept = jnp.where(
        greedy[:, None], draft == argmax[:, :K], jnp.log(u) < draft_lp
    )
    a = jnp.cumprod(accept.astype(jnp.int32), axis=1).sum(axis=1)

    res_tok = jnp.take_along_axis(cand, a[:, None], axis=1)[:, 0]
    res_lp = jnp.take_along_axis(cand_lp, a[:, None], axis=1)[:, 0]
    boundary_argmax = jnp.take_along_axis(argmax, a[:, None], axis=1)[:, 0]

    pos = jnp.arange(C)[None, :]
    draft_pad = jnp.concatenate([draft, draft[:, -1:]], axis=1)
    dlp_pad = jnp.concatenate([draft_lp, draft_lp[:, -1:]], axis=1)
    tokens = jnp.where(
        pos < a[:, None], draft_pad, res_tok[:, None]
    ).astype(jnp.int32)
    lps = jnp.where(pos < a[:, None], dlp_pad, res_lp[:, None])
    return a.astype(jnp.int32), tokens, lps, boundary_argmax.astype(jnp.int32)
