"""Single-step math/code verification environment.

Counterpart of ``realhf/impl/environment/math_code_single_step_env.py:41``:
one step takes ``(qid, answers)`` and returns per-answer binary success,
dispatching to the local verifier or the remote sandbox
(``AREAL_ENABLE_FUNCTION_CALL``). Task metadata (ground-truth solutions /
test cases) comes from the dataset's id→metadata map.
"""

import asyncio
from typing import Dict, List, Tuple

from areal_tpu.api.env import EnvironmentService
from areal_tpu.rewards import math_verify, code_verify, remote


class MathCodeSingleStepEnv(EnvironmentService):
    def __init__(self, dataset_metadata: Dict[str, dict], timeout: float = 100.0):
        # qid -> {"task": "math"|"code", "solutions": [...] | "input_output": {...}}
        self.metadata = dataset_metadata
        self.timeout = timeout

    async def reset(self, seed=None, options=None):
        return None, {}

    async def step(self, action: Tuple) -> Tuple:
        qid, answers = action
        meta = self.metadata[str(qid)]
        task = meta.get("task", "math")
        if task == "tool_use":
            # graded (not binary): token-F1/EM of the extracted answer tool
            # call + format bonus, normalized into [0, 1] so downstream
            # binary-success consumers (filter band with ub=1.0, the
            # (s-0.5)*2 reward transform) stay well-defined; pure host math,
            # never remoted
            from areal_tpu.rewards import tool_use

            cw, fw = 1.0, 0.2
            scores = [
                tool_use.tool_use_reward(
                    a,
                    str(meta.get("answer", "")),
                    correctness_weight=cw,
                    format_weight=fw,
                    scoring_method=meta.get("scoring_method", "f1"),
                )
                / (cw + fw)
                for a in answers
            ]
            return None, scores, True, False, {}
        if task == "gpqa":
            # multiple-choice grading is pure host string matching — never
            # remoted, and it must not fall through to the code branch
            # (which KeyErrors on meta['input_output'] for gpqa rows)
            from areal_tpu.evaluation.grading import grade_gpqa_answer

            golds = meta.get("solutions") or []
            scores = [grade_gpqa_answer(a, golds) for a in answers]
            return None, scores, True, False, {}
        if remote.ENABLED and remote.service_domain():
            if task == "math":
                success = await remote.math_verify_remote(
                    answers, [meta["solutions"]] * len(answers),
                    [str(qid)] * len(answers),
                )
            else:
                success = await remote.code_verify_remote(
                    answers, [str(qid)] * len(answers)
                )
        else:
            loop = asyncio.get_event_loop()
            # return_exceptions: a verifier crashing on one pathological
            # answer must not abort the whole group's rewards — the broken
            # answer grades False below, its siblings keep their scores
            if task == "math":
                success = await asyncio.gather(
                    *(
                        loop.run_in_executor(
                            None, math_verify.verify_math_solution,
                            a, meta["solutions"],
                        )
                        for a in answers
                    ),
                    return_exceptions=True,
                )
            else:
                success = await asyncio.gather(
                    *(
                        loop.run_in_executor(
                            None, code_verify.verify_code_solution,
                            a, meta["input_output"],
                        )
                        for a in answers
                    ),
                    return_exceptions=True,
                )
        return None, [
            bool(s) and not isinstance(s, BaseException) for s in success
        ], True, False, {}
