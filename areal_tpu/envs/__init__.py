"""Environment services (≈ ``realhf/impl/environment/``)."""

from areal_tpu.api.env import register_environment
from areal_tpu.envs.math_code_single_step import MathCodeSingleStepEnv

register_environment("math-code-single-step", MathCodeSingleStepEnv)
