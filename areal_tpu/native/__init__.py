"""Build-on-demand loader for the native host runtime (ctypes ABI).

The compute path is JAX/XLA/Pallas; this package holds the HOST-side native
code (counterpart of the reference's ``csrc/`` CPU helpers): the packed-
buffer fill kernels behind ``train/batching.pack_sequences``.

The shared object compiles lazily with g++ into the package directory the
first time it is needed (no pybind11/setuptools dance; plain C ABI +
ctypes). Everything degrades gracefully: if no compiler is available or the
build fails, callers fall back to the pure-numpy implementations —
``available()`` says which path is live. Set ``AREAL_DISABLE_NATIVE=1`` to
force the fallback (parity tests exercise both).
"""

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

logger = logging.getLogger("areal_tpu.native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "packer.cpp")
_SO = os.path.join(_DIR, "_packer.so")

_lib = None
_tried = False
_lock = threading.Lock()


def _build() -> bool:
    # per-process temp name: concurrent first-use builds (trainer +
    # evaluator child, multiple Slurm tasks on one FS) must not interleave
    # writes into one .tmp; os.replace is atomic, last writer wins
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, _SRC]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, text=True, timeout=120
        )
        os.replace(tmp, _SO)
        return True
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            FileNotFoundError, OSError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        logger.warning("native packer build failed (%s); using numpy fallback",
                       detail.strip()[:500])
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        from areal_tpu.base import constants

        if constants.native_disabled():
            return None
        try:
            stale = not os.path.exists(_SO) or (
                os.path.getmtime(_SO) < os.path.getmtime(_SRC)
            )
        except OSError:
            stale = True  # source missing/unreadable: try a build, then fail soft
        if stale and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            # a stale/corrupt .so (e.g. from an interrupted build on a
            # previous run): rebuild once before giving up
            if not _build():
                return None
            try:
                lib = ctypes.CDLL(_SO)
            except OSError as e:
                logger.warning("native packer load failed (%s)", e)
                return None
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        u8p = ctypes.c_void_p
        lib.plan_rows_lpt.argtypes = [i64p, ctypes.c_int64, ctypes.c_int64, i64p]
        lib.pack_copy.argtypes = [u8p, u8p, i64p, i64p, i64p, i64p,
                                  ctypes.c_int64, ctypes.c_int64, ctypes.c_int64]
        lib.pack_broadcast.argtypes = list(lib.pack_copy.argtypes)
        lib.pack_meta.argtypes = [i32p, i32p, i32p, i64p, i64p, i64p, i64p,
                                  i64p, ctypes.c_int64, ctypes.c_int64]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def plan_rows_lpt(lengths: np.ndarray, n_rows: int) -> np.ndarray:
    lib = _load()
    assert lib is not None
    lengths = np.ascontiguousarray(lengths, np.int64)
    out = np.empty(len(lengths), np.int64)
    lib.plan_rows_lpt(lengths, len(lengths), n_rows, out)
    return out


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


def pack_copy(dst, src, rows, starts, lengths, src_offsets):
    """dst [n_rows, capacity(, trailing...)] C-contiguous; src flat packed.
    `capacity` counts ELEMENTS of the trailing-item type (trailing dims fold
    into itemsize)."""
    lib = _load()
    assert lib is not None
    n_rows, capacity = dst.shape[0], dst.shape[1]
    itemsize = dst.dtype.itemsize * int(np.prod(dst.shape[2:], dtype=np.int64))
    lib.pack_copy(
        _ptr(dst), _ptr(src),
        np.ascontiguousarray(rows, np.int64),
        np.ascontiguousarray(starts, np.int64),
        np.ascontiguousarray(lengths, np.int64),
        np.ascontiguousarray(src_offsets, np.int64),
        len(rows), capacity, itemsize,
    )


def pack_broadcast(dst, src, rows, starts, lengths, src_idx):
    lib = _load()
    assert lib is not None
    capacity = dst.shape[1]
    itemsize = dst.dtype.itemsize * int(np.prod(dst.shape[2:], dtype=np.int64))
    lib.pack_broadcast(
        _ptr(dst), _ptr(src),
        np.ascontiguousarray(rows, np.int64),
        np.ascontiguousarray(starts, np.int64),
        np.ascontiguousarray(lengths, np.int64),
        np.ascontiguousarray(src_idx, np.int64),
        len(rows), capacity, itemsize,
    )


def pack_meta(segment_ids, positions, item_ids, rows, starts, lengths,
              segments, items):
    lib = _load()
    assert lib is not None
    capacity = segment_ids.shape[1]
    lib.pack_meta(
        segment_ids, positions, item_ids,
        np.ascontiguousarray(rows, np.int64),
        np.ascontiguousarray(starts, np.int64),
        np.ascontiguousarray(lengths, np.int64),
        np.ascontiguousarray(segments, np.int64),
        np.ascontiguousarray(items, np.int64),
        len(rows), capacity,
    )
