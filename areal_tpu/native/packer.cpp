// Native host-side packing kernels (counterpart of the reference's csrc/
// CPU helpers: the data-plumbing between Python bookkeeping and device
// buffers). The TPU compute path is JAX/XLA/Pallas; THIS is the host
// runtime's hot loop — filling [n_rows, capacity] packed buffers from
// per-sequence slices runs once per micro-batch per key, and at
// 512-prompt x 16-sample batches the Python slice-assignment loop it
// replaces costs tens of milliseconds per step.
//
// Exposed via a plain C ABI for ctypes (no pybind11 in this image; see
// areal_tpu/native/__init__.py for the build-on-demand loader).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <queue>
#include <utility>
#include <vector>

extern "C" {

// LPT (longest-processing-time) row planning: assign each sequence (by
// descending length) to the least-loaded row. Ties break on row index so
// results are deterministic and IDENTICAL to the Python planner.
void plan_rows_lpt(const int64_t* lengths, int64_t n, int64_t n_rows,
                   int64_t* rows_out) {
  std::vector<int64_t> order(n);
  for (int64_t i = 0; i < n; ++i) order[i] = i;
  std::stable_sort(order.begin(), order.end(),
                   [&](int64_t a, int64_t b) { return lengths[a] > lengths[b]; });
  // min-heap of (load, row)
  using Slot = std::pair<int64_t, int64_t>;
  std::priority_queue<Slot, std::vector<Slot>, std::greater<Slot>> heap;
  for (int64_t r = 0; r < n_rows; ++r) heap.emplace(0, r);
  for (int64_t idx : order) {
    auto [load, r] = heap.top();
    heap.pop();
    rows_out[idx] = r;
    heap.emplace(load + lengths[idx], r);
  }
}

// Token-aligned fill: dst[rows[i], starts[i] : starts[i]+lengths[i]] =
// src[src_offsets[i] : src_offsets[i]+lengths[i]] for every sequence, on
// row-major dst [n_rows, capacity, itemsize bytes/element].
void pack_copy(uint8_t* dst, const uint8_t* src, const int64_t* rows,
               const int64_t* starts, const int64_t* lengths,
               const int64_t* src_offsets, int64_t n_seqs, int64_t capacity,
               int64_t itemsize) {
  for (int64_t i = 0; i < n_seqs; ++i) {
    uint8_t* d = dst + (rows[i] * capacity + starts[i]) * itemsize;
    const uint8_t* s = src + src_offsets[i] * itemsize;
    std::memcpy(d, s, static_cast<size_t>(lengths[i]) * itemsize);
  }
}

// Scalar broadcast fill: dst[rows[i], starts[i] : +lengths[i]] = src[src_idx[i]]
// (one element replicated across the sequence's span).
void pack_broadcast(uint8_t* dst, const uint8_t* src, const int64_t* rows,
                    const int64_t* starts, const int64_t* lengths,
                    const int64_t* src_idx, int64_t n_seqs, int64_t capacity,
                    int64_t itemsize) {
  for (int64_t i = 0; i < n_seqs; ++i) {
    uint8_t* d = dst + (rows[i] * capacity + starts[i]) * itemsize;
    const uint8_t* s = src + src_idx[i] * itemsize;
    for (int64_t t = 0; t < lengths[i]; ++t)
      std::memcpy(d + t * itemsize, s, itemsize);
  }
}

// Segment metadata fill: segment ids, positions (0..len-1), item ids —
// the three bookkeeping buffers every packed batch carries, in one pass.
void pack_meta(int32_t* segment_ids, int32_t* positions, int32_t* item_ids,
               const int64_t* rows, const int64_t* starts,
               const int64_t* lengths, const int64_t* segments,
               const int64_t* items, int64_t n_seqs, int64_t capacity) {
  for (int64_t i = 0; i < n_seqs; ++i) {
    int64_t base = rows[i] * capacity + starts[i];
    for (int64_t t = 0; t < lengths[i]; ++t) {
      segment_ids[base + t] = static_cast<int32_t>(segments[i]);
      positions[base + t] = static_cast<int32_t>(t);
      item_ids[base + t] = static_cast<int32_t>(items[i]);
    }
  }
}

}  // extern "C"
