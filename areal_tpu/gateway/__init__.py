"""Production serving gateway (docs/serving.md).

Fronts the interruptible gen fleet with an OpenAI-compatible HTTP API
backed by continuous batching, per-tenant QoS (token-bucket rate limits +
weighted fair queueing), KV-occupancy admission control, and a
telemetry-driven autoscaler that resizes the routed server set live.

Modules:

- ``qos``        — tenants, token buckets, weighted fair queue (pure)
- ``scheduler``  — continuous-batching dispatch onto gen servers
- ``api``        — /v1/completions + /v1/chat/completions (SSE + buffered)
- ``autoscaler`` — fleet-aggregate -> scale decisions -> routed-set edits
"""

from areal_tpu.gateway.api import (  # noqa: F401
    ByteFallbackCodec,
    GatewayConfig,
    GatewayServer,
    HFTokenizerCodec,
    TokenCodec,
    serve_gateway,
)
from areal_tpu.gateway.autoscaler import (  # noqa: F401
    Autoscaler,
    AutoscalerConfig,
    ScaleDecision,
    ScaleSignals,
    decide,
)
from areal_tpu.gateway.qos import (  # noqa: F401
    TenantSpec,
    TokenBucket,
    WeightedFairQueue,
)
from areal_tpu.gateway.scheduler import (  # noqa: F401
    ContinuousBatchScheduler,
    GatewayRequest,
    RateLimited,
)
