"""Per-tenant QoS primitives for the serving gateway (docs/serving.md).

Pure bookkeeping — no I/O, no asyncio — so the policy layer is trivially
testable and lives in one place (the same split as ``system/fleet.py`` vs
the manager):

- :class:`TenantSpec` — a tenant's weight and rate-limit envelope.
- :class:`TokenBucket` — classic token-bucket rate limiter. Requests are
  charged their *budgeted* cost (prompt tokens + ``max_tokens``) at
  admission and refunded the unused budget at completion, so the bucket
  tracks real token consumption instead of request counts.
- :class:`WeightedFairQueue` — start-time fair queueing across tenants:
  each enqueued item is stamped a virtual finish time
  ``vft = max(vtime, tenant_last_vft) + cost / weight`` and ``pop``
  returns the globally smallest stamp. A heavy tenant's backlog inflates
  only its OWN virtual clock, so a light tenant's next request jumps the
  line — the starvation guarantee ``tests/test_gateway.py`` pins.
"""

import collections
import dataclasses
import math
import time
from typing import Deque, Dict, List, Optional, Tuple


@dataclasses.dataclass
class TenantSpec:
    """One tenant's QoS envelope. ``rate_tokens_per_s <= 0`` means
    unlimited; ``burst_tokens <= 0`` defaults to 4x the rate (or
    unlimited when the rate is unlimited)."""

    name: str
    weight: float = 1.0
    rate_tokens_per_s: float = 0.0
    burst_tokens: float = 0.0
    # default per-request deadline in seconds (<= 0 = none): applied when
    # the client names no deadline of its own; the scheduler sheds queued
    # requests whose remaining budget cannot cover estimated service
    default_deadline_s: float = 0.0

    def resolved_burst(self) -> float:
        if self.rate_tokens_per_s <= 0:
            return math.inf
        if self.burst_tokens > 0:
            return self.burst_tokens
        return 4.0 * self.rate_tokens_per_s


class TokenBucket:
    """Token bucket with an injectable clock (tests drive virtual time)."""

    def __init__(
        self,
        rate_tokens_per_s: float,
        burst_tokens: float,
        clock=time.monotonic,
    ):
        self.rate = max(rate_tokens_per_s, 0.0)
        self.burst = burst_tokens if burst_tokens > 0 else math.inf
        self.unlimited = self.rate <= 0
        self._clock = clock
        self._tokens = self.burst
        self._t = clock()

    def _refill(self) -> None:
        now = self._clock()
        if not self.unlimited and now > self._t:
            self._tokens = min(
                self.burst, self._tokens + (now - self._t) * self.rate
            )
        self._t = now

    def try_acquire(self, cost: float) -> bool:
        if self.unlimited:
            return True
        self._refill()
        if self._tokens >= cost:
            self._tokens -= cost
            return True
        return False

    def refund(self, amount: float) -> None:
        """Return unused budget (actual consumption < the charge)."""
        if not self.unlimited and amount > 0:
            self._tokens = min(self.burst, self._tokens + amount)

    def retry_after_s(self, cost: float) -> float:
        """Seconds until ``cost`` tokens will be available (the 429
        Retry-After hint); 0 when it would succeed now."""
        if self.unlimited:
            return 0.0
        self._refill()
        missing = min(cost, self.burst) - self._tokens
        if missing <= 0:
            return 0.0
        return missing / self.rate

    @property
    def available(self) -> float:
        self._refill()
        return self._tokens


def _service_share(cost: float, weight: float) -> float:
    """One queue entry's virtual-time share. The SINGLE definition:
    ``push`` stamps it, ``rollback`` reverses it — deriving it twice
    would let the rollback amount drift from what the stamp advanced."""
    return max(cost, 1.0) / max(weight, 1e-9)


class WeightedFairQueue:
    """Start-time fair queue: O(tenants) pop, FIFO within a tenant.

    Entries are ``(vft, share, item)`` — ``share`` (= cost/weight) is kept
    so ``drop_where`` can roll the tenant's virtual clock back for work
    that never ran (a cancelled queued request must not deprioritize the
    tenant's future traffic)."""

    def __init__(self):
        self._queues: Dict[str, Deque[Tuple[float, float, object]]] = {}
        self._last_vft: Dict[str, float] = {}
        self._vtime = 0.0
        self._len = 0

    def __len__(self) -> int:
        return self._len

    def depth(self, tenant: str) -> int:
        return len(self._queues.get(tenant, ()))

    def push(self, tenant: str, cost: float, weight: float, item) -> None:
        start = max(self._vtime, self._last_vft.get(tenant, 0.0))
        share = _service_share(cost, weight)
        vft = start + share
        self._last_vft[tenant] = vft
        self._queues.setdefault(tenant, collections.deque()).append(
            (vft, share, item)
        )
        self._len += 1

    def pop(self):
        """Item with the smallest virtual finish time; None when empty."""
        best_tenant: Optional[str] = None
        best_vft = math.inf
        for tenant, q in self._queues.items():
            if q and q[0][0] < best_vft:
                best_vft, best_tenant = q[0][0], tenant
        if best_tenant is None:
            return None
        vft, _, item = self._queues[best_tenant].popleft()
        if not self._queues[best_tenant]:
            del self._queues[best_tenant]
        self._vtime = max(self._vtime, vft)
        self._len -= 1
        return item

    def rollback(self, tenant: str, cost: float, weight: float) -> None:
        """Roll the tenant's virtual clock back for ONE already-popped
        entry that never ran — the popped-entry twin of ``drop_where``'s
        rollback, for the dispatch-side cancel race (the scheduler pops a
        request, then discovers it was cancelled). Later queued entries
        of the tenant (and its ``_last_vft``) shift earlier by the same
        service share, so the cancelled work does not count against the
        tenant's fair share."""
        share = _service_share(cost, weight)
        if tenant in self._last_vft:
            self._last_vft[tenant] -= share
        q = self._queues.get(tenant)
        if q:
            self._queues[tenant] = collections.deque(
                (vft - share, s, it) for vft, s, it in q
            )

    def drop_where(self, pred) -> int:
        """Remove queued items matching ``pred`` (client disconnects while
        still queued); returns how many were dropped. Later entries of the
        same tenant (and its ``_last_vft``) shift earlier by the dropped
        items' service shares — the cancelled work never ran, so it must
        not count against the tenant's fair share."""
        dropped = 0
        for tenant in list(self._queues):
            kept: Deque[Tuple[float, float, object]] = collections.deque()
            shift = 0.0
            for vft, share, it in self._queues[tenant]:
                if pred(it):
                    dropped += 1
                    shift += share
                else:
                    kept.append((vft - shift, share, it))
            if shift and tenant in self._last_vft:
                self._last_vft[tenant] -= shift
            if kept:
                self._queues[tenant] = kept
            else:
                del self._queues[tenant]
        self._len -= dropped
        return dropped


def build_buckets(
    tenants: Dict[str, TenantSpec], clock=time.monotonic
) -> Dict[str, TokenBucket]:
    return {
        name: TokenBucket(
            spec.rate_tokens_per_s, spec.resolved_burst(), clock=clock
        )
        for name, spec in tenants.items()
    }


def request_cost(prompt_len: int, max_new_tokens: int) -> float:
    """The budgeted cost a request is charged at admission (refunded down
    to actual consumption at completion)."""
    return float(prompt_len + max_new_tokens)
