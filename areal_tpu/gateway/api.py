"""OpenAI-compatible HTTP frontend for the gen fleet (docs/serving.md).

An aiohttp app exposing:

- ``POST /v1/completions`` — prompt as a string (via the configured
  tokenizer codec) or a raw token-id array (the OpenAI token-array form;
  what the tests and RL-side tooling use), SSE streaming or buffered.
- ``POST /v1/chat/completions`` — messages rendered through a minimal
  chat template, same streaming surface (``chat.completion.chunk``).
- ``GET /v1/models``, ``GET /health``, ``GET /metrics_json``.

Validation is answered with OpenAI-style 4xx error bodies
(``{"error": {"message", "type", "code"}}``) before anything reaches the
scheduler; rate-limit and queue-full answers are 429 with a
``Retry-After`` hint. Tenancy comes from the ``Authorization: Bearer``
key (mapped through the configured key table) or an ``X-Tenant`` header,
defaulting to the anonymous tenant.

Token<->text conversion goes through a :class:`TokenCodec`. Production
wires an HF tokenizer (``HFTokenizerCodec``); the fallback
:class:`ByteFallbackCodec` keeps the surface usable against random-weight
models (tests, ``make serve`` without a checkpoint) where text is
meaningless anyway.
"""

import asyncio
import dataclasses
import json
import time
from typing import Dict, List, Optional, Tuple

from aiohttp import web

from areal_tpu.base import logging, tracing
from areal_tpu.base import metrics as metrics_mod
from areal_tpu.gateway.scheduler import (
    ContinuousBatchScheduler,
    GatewayRequest,
    RateLimited,
    ServiceUnavailable,
)

logger = logging.getLogger("areal_tpu.gateway.api")


# --------------------------------------------------------------------- #
# token <-> text codecs
# --------------------------------------------------------------------- #


class TokenCodec:
    """encode/decode between user-facing text and engine token ids."""

    def encode(self, text: str) -> List[int]:
        raise NotImplementedError

    def decode(self, ids: List[int]) -> str:
        raise NotImplementedError


class ByteFallbackCodec(TokenCodec):
    """UTF-8 bytes clamped into the model vocab. Deterministic and
    reversible for ids < 256 — a placeholder codec for random-weight
    serving, NOT a tokenizer (documented in docs/serving.md)."""

    def __init__(self, vocab_size: int):
        self.vocab_size = vocab_size

    def encode(self, text: str) -> List[int]:
        return [b % self.vocab_size for b in text.encode("utf-8")]

    def decode(self, ids: List[int]) -> str:
        return bytes(int(t) % 256 for t in ids).decode(
            "latin-1", errors="replace"
        )


class HFTokenizerCodec(TokenCodec):
    """Wraps a HuggingFace tokenizer (lazy transformers import)."""

    def __init__(self, path: str):
        import transformers

        self.tok = transformers.AutoTokenizer.from_pretrained(path)

    def encode(self, text: str) -> List[int]:
        return list(self.tok.encode(text, add_special_tokens=False))

    def decode(self, ids: List[int]) -> str:
        return self.tok.decode(ids)


# --------------------------------------------------------------------- #
# config + validation
# --------------------------------------------------------------------- #


@dataclasses.dataclass
class GatewayConfig:
    model_id: str = "areal-tpu"
    default_tenant: str = "anonymous"
    # API key -> tenant name; empty + require_api_key=False means every
    # unauthenticated request rides the default (anonymous) tenant
    api_keys: Dict[str, str] = dataclasses.field(default_factory=dict)
    require_api_key: bool = False
    max_tokens_cap: int = 2048
    default_max_tokens: int = 256
    # brownout level >= 1 (gateway/brownout.py): a live clamp applied on
    # top of the validated request — None when the ladder is at level 0
    brownout_max_tokens: Optional[int] = None


class BadRequest(Exception):
    def __init__(self, message: str, status: int = 400, code: str = ""):
        super().__init__(message)
        self.status = status
        self.code = code or "invalid_request_error"


def _error_response(message: str, status: int, code: str, **headers):
    if status == 429:
        metrics_mod.counters.add(metrics_mod.GW_REJECTED_429)
    elif status < 500:
        metrics_mod.counters.add(metrics_mod.GW_REJECTED_4XX)
    return web.json_response(
        {
            "error": {
                "message": message,
                "type": "invalid_request_error" if status < 500 else
                "server_error",
                "code": code,
            }
        },
        status=status,
        headers={k.replace("_", "-"): str(v) for k, v in headers.items()},
    )


def _require(cond: bool, message: str):
    if not cond:
        raise BadRequest(message)


def parse_sampling(d: dict, cfg: GatewayConfig) -> Tuple[Dict, bool]:
    """Shared OpenAI sampling-surface validation -> (engine
    sampling_params, stream flag)."""
    try:
        max_tokens = int(d.get("max_tokens", cfg.default_max_tokens))
        temperature = float(d.get("temperature", 1.0))
        top_p = float(d.get("top_p", 1.0))
        n = int(d.get("n", 1))
        stream = bool(d.get("stream", False))
    except (TypeError, ValueError) as e:
        raise BadRequest(f"malformed sampling parameter: {e}")
    _require(1 <= max_tokens <= cfg.max_tokens_cap,
             f"max_tokens must be in [1, {cfg.max_tokens_cap}]")
    _require(temperature >= 0.0, "temperature must be >= 0")
    _require(0.0 < top_p <= 1.0, "top_p must be in (0, 1]")
    _require(n == 1, "n > 1 is not supported")
    if cfg.brownout_max_tokens is not None:
        # degraded-but-correct: shorter answers for everyone beats 429s
        # for some (the clamp is removed when the ladder steps back down)
        max_tokens = min(max_tokens, max(int(cfg.brownout_max_tokens), 1))
    sp = {
        "max_new_tokens": max_tokens,
        "temperature": temperature,
        "top_p": top_p,
        "greedy": temperature == 0.0,
    }
    return sp, stream


def parse_deadline(d: dict, request: web.Request) -> float:
    """Per-request deadline intake: the body's ``timeout`` field (seconds,
    OpenAI-client idiom) wins over an ``X-Request-Deadline`` header
    (relative seconds); 0 = none (the tenant/fleet default applies)."""
    raw = d.get("timeout")
    if raw is None:
        raw = request.headers.get("X-Request-Deadline")
    if raw is None:
        return 0.0
    try:
        deadline = float(raw)
    except (TypeError, ValueError):
        raise BadRequest("'timeout' must be a number of seconds")
    _require(
        deadline > 0 and deadline == deadline and deadline != float("inf"),
        "'timeout' must be a positive finite number of seconds",
    )
    return deadline


def encode_stop(stop, codec: TokenCodec) -> List[int]:
    """OpenAI ``stop`` strings -> engine stop token ids. Only stops that
    encode to exactly one token are expressible at the engine level; a
    multi-token stop is a clear 400, not a silent ignore."""
    if stop is None:
        return []
    if isinstance(stop, str):
        stop = [stop]
    _require(isinstance(stop, list) and len(stop) <= 4,
             "stop must be a string or a list of up to 4 strings")
    out = []
    for s in stop:
        if isinstance(s, int):
            out.append(s)
            continue
        _require(isinstance(s, str), "stop entries must be strings")
        ids = codec.encode(s)
        _require(
            len(ids) == 1,
            f"stop sequence {s!r} does not map to a single token; pass "
            "stop token ids directly via 'stop_token_ids'",
        )
        out.append(ids[0])
    return out


# --------------------------------------------------------------------- #
# server
# --------------------------------------------------------------------- #


class GatewayServer:
    def __init__(
        self,
        scheduler: ContinuousBatchScheduler,
        codec: TokenCodec,
        config: Optional[GatewayConfig] = None,
    ):
        self.scheduler = scheduler
        self.codec = codec
        self.config = config or GatewayConfig()
        # tenants that may be named via the UNAUTHENTICATED X-Tenant
        # header: the INITIAL configured set MINUS key-mapped tenants —
        # an arbitrary header must neither mint a fresh full token bucket
        # per unseen name (rate-limit bypass + unbounded state) nor
        # impersonate a tenant whose identity is an API key (draining its
        # budget/weight would be a cross-tenant denial of service)
        self._known_tenants = set(scheduler.tenants) - set(
            self.config.api_keys.values()
        )
        self._start_t = time.time()
        self.app = web.Application()
        self._bind_routes(self.app)

    def _bind_routes(self, app: web.Application) -> None:
        """The route table in one place: the wire-contract catalog test
        registers these on a bare Application (no scheduler construction)
        and diffs them against the statically parsed endpoint table."""
        app.router.add_post("/v1/completions", self._completions)
        app.router.add_post(
            "/v1/chat/completions", self._chat_completions
        )
        app.router.add_get("/v1/models", self._models)
        app.router.add_get("/health", self._health)
        app.router.add_get("/metrics_json", self._metrics)

    # ---------------------------- tenancy ----------------------------- #

    def _tenant_of(self, request: web.Request) -> str:
        auth = request.headers.get("Authorization", "")
        key = auth[7:].strip() if auth.startswith("Bearer ") else ""
        if key:
            tenant = self.config.api_keys.get(key)
            if tenant is None and self.config.require_api_key:
                raise BadRequest("invalid API key", status=401,
                                 code="invalid_api_key")
            if tenant is not None:
                return tenant
        if self.config.require_api_key:
            raise BadRequest("missing API key", status=401,
                             code="invalid_api_key")
        header = request.headers.get("X-Tenant", "")
        if header and header in self._known_tenants:
            return header
        # unknown names collapse into the default tenant (shared bucket
        # and fair-queue lane) instead of minting unbounded tenant state
        return self.config.default_tenant

    # --------------------------- handlers ----------------------------- #

    async def _json_body(self, request: web.Request) -> dict:
        try:
            d = await request.json()
        except (ValueError, TypeError):
            raise BadRequest("body is not valid JSON")
        _require(isinstance(d, dict), "body must be a JSON object")
        return d

    def _prompt_ids(self, prompt) -> List[int]:
        if isinstance(prompt, str):
            _require(len(prompt) > 0, "prompt must be non-empty")
            return self.codec.encode(prompt)
        if isinstance(prompt, list) and prompt and all(
            isinstance(t, int) and not isinstance(t, bool) for t in prompt
        ):
            return list(prompt)
        raise BadRequest(
            "prompt must be a non-empty string or a non-empty array of "
            "token ids"
        )

    def _check_capacity(self, input_ids: List[int], sp: Dict) -> None:
        """Reject prompts the backend engines cannot hold — a 400 HERE,
        not a 502 when the dispatch hits the gen server's validator (the
        request would also have burned queue + rate budget)."""
        cap = self.scheduler.min_slot_capacity()
        if cap and len(input_ids) - 1 + sp["max_new_tokens"] > cap:
            raise BadRequest(
                f"prompt ({len(input_ids)} tokens) + max_tokens "
                f"({sp['max_new_tokens']}) exceeds the backend per-slot "
                f"capacity {cap}"
            )

    async def _completions(self, request: web.Request) -> web.StreamResponse:
        # trace intake: honor an inbound W3C ``traceparent`` header (an
        # external caller continuing its own trace), else root a fresh
        # trace here — the gateway is the serving plane's trace origin
        with tracing.activate(
            request.headers.get("traceparent")
        ), tracing.span("gw/request", endpoint="/v1/completions") as sa:
            return await self._completions_body(request, sa)

    async def _completions_body(
        self, request: web.Request, span_attrs: Dict
    ) -> web.StreamResponse:
        try:
            tenant = self._tenant_of(request)
            d = await self._json_body(request)
            _require("prompt" in d, "missing required field 'prompt'")
            input_ids = self._prompt_ids(d["prompt"])
            sp, stream = parse_sampling(d, self.config)
            stops = encode_stop(d.get("stop"), self.codec)
            extra = d.get("stop_token_ids", [])
            _require(
                isinstance(extra, list)
                and all(isinstance(t, int) for t in extra),
                "stop_token_ids must be a list of integers",
            )
            stops.extend(extra)
            if stops:
                sp["stop_token_ids"] = stops
            self._check_capacity(input_ids, sp)
            req = GatewayRequest.build(
                tenant, input_ids, sp,
                deadline_s=parse_deadline(d, request),
            )
            span_attrs["rid"] = req.rid
            span_attrs["tenant"] = tenant
            self.scheduler.submit(req)
        except BadRequest as e:
            return _error_response(str(e), e.status, e.code)
        except RateLimited as e:
            if e.permanent:  # can never be admitted: a client error
                return _error_response(str(e), 400, "invalid_request_error")
            return _error_response(
                str(e), 429, "rate_limit_exceeded",
                Retry_After=max(1, int(e.retry_after_s + 0.999)),
            )
        except ServiceUnavailable as e:
            return _error_response(
                str(e), 503, "service_unavailable",
                Retry_After=max(1, int(e.retry_after_s + 0.999)),
            )
        if stream:
            return await self._stream_out(
                request, req, object_name="text_completion",
                make_delta=lambda text, first: {"text": text},
            )
        return await self._buffered_out(request, req, chat=False)

    async def _chat_completions(
        self, request: web.Request
    ) -> web.StreamResponse:
        with tracing.activate(
            request.headers.get("traceparent")
        ), tracing.span("gw/request", endpoint="/v1/chat/completions") as sa:
            return await self._chat_completions_body(request, sa)

    async def _chat_completions_body(
        self, request: web.Request, span_attrs: Dict
    ) -> web.StreamResponse:
        try:
            tenant = self._tenant_of(request)
            d = await self._json_body(request)
            msgs = d.get("messages")
            _require(
                isinstance(msgs, list) and len(msgs) > 0,
                "messages must be a non-empty list",
            )
            for m in msgs:
                _require(
                    isinstance(m, dict)
                    and isinstance(m.get("role"), str)
                    and isinstance(m.get("content"), str),
                    "each message needs string 'role' and 'content'",
                )
            input_ids = self.codec.encode(render_chat(msgs))
            _require(len(input_ids) > 0, "messages rendered to an empty prompt")
            sp, stream = parse_sampling(d, self.config)
            stops = encode_stop(d.get("stop"), self.codec)
            if stops:
                sp["stop_token_ids"] = stops
            self._check_capacity(input_ids, sp)
            req = GatewayRequest.build(
                tenant, input_ids, sp,
                deadline_s=parse_deadline(d, request),
            )
            span_attrs["rid"] = req.rid
            span_attrs["tenant"] = tenant
            self.scheduler.submit(req)
        except BadRequest as e:
            return _error_response(str(e), e.status, e.code)
        except RateLimited as e:
            if e.permanent:  # can never be admitted: a client error
                return _error_response(str(e), 400, "invalid_request_error")
            return _error_response(
                str(e), 429, "rate_limit_exceeded",
                Retry_After=max(1, int(e.retry_after_s + 0.999)),
            )
        except ServiceUnavailable as e:
            return _error_response(
                str(e), 503, "service_unavailable",
                Retry_After=max(1, int(e.retry_after_s + 0.999)),
            )
        if stream:
            return await self._stream_out(
                request, req, object_name="chat.completion.chunk",
                make_delta=lambda text, first: {
                    "delta": (
                        {"role": "assistant", "content": text}
                        if first else {"content": text}
                    )
                },
            )
        return await self._buffered_out(request, req, chat=True)

    # ------------------------- output shaping ------------------------- #

    def _envelope(self, req: GatewayRequest, object_name: str) -> dict:
        return {
            "id": f"cmpl-{req.rid}",
            "object": object_name,
            "created": int(self._start_t),
            "model": self.config.model_id,
        }

    @staticmethod
    def _finish(reason: Optional[str]) -> str:
        # "deadline" passes through so streaming clients can tell a
        # budget-truncated answer from a natural stop
        if reason in ("length", "deadline"):
            return reason
        return "stop"

    async def _next_event(self, request: web.Request, req: GatewayRequest):
        """Next scheduler event, polling the transport while waiting: a
        client that hangs up while its request is still QUEUED must
        release the queue slot + token-bucket charge now, not after the
        request ran to completion against a dead socket."""
        while True:
            try:
                return await asyncio.wait_for(req.events.get(), timeout=0.5)
            except asyncio.TimeoutError:
                tr = request.transport
                if tr is None or tr.is_closing():
                    raise ConnectionResetError("client went away")

    async def _stream_out(self, request, req, object_name, make_delta):
        resp = web.StreamResponse(
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
            }
        )
        await resp.prepare(request)
        first = True
        # incremental detokenization: decode the FULL accumulated ids and
        # emit the text suffix — per-chunk decode garbles graphemes whose
        # tokens straddle a chunk boundary under a real (BPE) codec
        all_ids: List[int] = []
        emitted = 0
        try:
            while True:
                ev = await self._next_event(request, req)
                if "error" in ev:
                    frame = {
                        **self._envelope(req, object_name),
                        "choices": [],
                        "error": {"message": ev["error"],
                                  "type": "server_error"},
                    }
                    await resp.write(
                        b"data: " + json.dumps(frame).encode() + b"\n\n"
                    )
                    break
                all_ids.extend(ev.get("token_ids", []))
                full = self.codec.decode(all_ids)
                text, emitted = full[emitted:], len(full)
                reason = ev.get("finish_reason")
                choice = {
                    "index": 0,
                    "finish_reason": self._finish(reason) if reason else None,
                    **make_delta(text, first),
                }
                first = False
                frame = {
                    **self._envelope(req, object_name),
                    "choices": [choice],
                }
                await resp.write(
                    b"data: " + json.dumps(frame).encode() + b"\n\n"
                )
                if reason:
                    break
            await resp.write(b"data: [DONE]\n\n")
        except (ConnectionResetError, asyncio.CancelledError):
            self.scheduler.cancel(req)
            raise
        return resp

    async def _buffered_out(
        self, request: web.Request, req: GatewayRequest, chat: bool
    ) -> web.Response:
        tokens: List[int] = []
        logprobs: List[float] = []
        reason = None
        try:
            while reason is None:
                ev = await self._next_event(request, req)
                if "error" in ev:
                    if ev.get("finish_reason") == "deadline":
                        return _error_response(
                            ev["error"], 504, "deadline_exceeded"
                        )
                    return web.json_response(
                        {"error": {"message": ev["error"],
                                   "type": "server_error"}},
                        status=502,
                    )
                tokens.extend(ev.get("token_ids", []))
                logprobs.extend(ev.get("logprobs", []))
                reason = ev.get("finish_reason")
        except (ConnectionResetError, asyncio.CancelledError):
            self.scheduler.cancel(req)
            raise
        if reason == "deadline" and not tokens:
            # expired before the first token: nothing useful to return
            return _error_response(
                "request deadline exceeded", 504, "deadline_exceeded"
            )
        text = self.codec.decode(tokens)
        if chat:
            choice = {
                "index": 0,
                "message": {"role": "assistant", "content": text},
                "finish_reason": self._finish(reason),
            }
            obj = "chat.completion"
        else:
            choice = {
                "index": 0,
                "text": text,
                "logprobs": None,
                "finish_reason": self._finish(reason),
            }
            obj = "text_completion"
        return web.json_response(
            {
                **self._envelope(req, obj),
                "object": obj,
                "choices": [choice],
                "usage": {
                    "prompt_tokens": len(req.input_ids),
                    "completion_tokens": len(tokens),
                    "total_tokens": len(req.input_ids) + len(tokens),
                },
            }
        )

    # ------------------------- control plane -------------------------- #

    async def _models(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "object": "list",
                "data": [
                    {
                        "id": self.config.model_id,
                        "object": "model",
                        "created": int(self._start_t),
                        "owned_by": "areal_tpu",
                    }
                ],
            }
        )

    async def _health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    async def _metrics(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "uptime_s": round(time.time() - self._start_t, 3),
                # arealint: wire(/metrics_json, scheduler gauges are built in gateway/scheduler.py)
                **self.scheduler.metrics_dict(),
            }
        )


def render_chat(messages: List[dict]) -> str:
    """Minimal chat template (an HF codec could template instead; this
    keeps the wire format stable across codecs)."""
    parts = [f"{m['role']}: {m['content']}" for m in messages]
    return "\n".join(parts) + "\nassistant:"


async def serve_gateway(
    server: GatewayServer, host: str, port: int
) -> web.AppRunner:
    runner = web.AppRunner(server.app)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    logger.info("serving gateway on %s:%d", host, port)
    return runner
