"""Brownout ladder for the serving gateway (docs/serving.md
"Survivability").

When the fleet is saturated — KV demand occupancy pinned, queue-wait p95
climbing, breakers opening — uniform queueing turns every request into a
timeout. Graceful degradation sheds LOAD before it sheds CORRECTNESS,
through an ordered ladder of reversible levels:

1. **clamp** — cap ``max_tokens`` fleet-wide (shorter answers for
   everyone beats failures for some).
2. **no speculation** — disable speculative decoding via each backend's
   ``/spec_decode`` toggle: draft work competes with target-model decode
   for the same chips, so under saturation speculation costs throughput.
3. **shed best-effort** — 429 tenants whose weight is below the
   configured floor, with an honest ``Retry-After`` (the ladder's
   soonest possible de-escalation), keeping capacity for paying lanes.
4. **admit nothing** — every new request answers 429; in-flight streams
   run to completion. The last rung before falling over.

The split mirrors ``gateway/autoscaler.py``: :func:`decide` is a PURE
function over :class:`~areal_tpu.gateway.autoscaler.ScaleSignals` (tests
drive it with synthetic inputs), :class:`BrownoutController` is the
actuation loop. Escalation is immediate — saturation compounds — while
de-escalation steps down ONE level at a time, only after every signal
drops below the current level's entry thresholds times the hysteresis
factor AND the level has been held ``min_hold_s`` (no flapping between
adjacent rungs on a noisy signal). Transitions are counted
(``gw/brownout_transitions``) and the current level is a live gauge
(``gw/brownout_level``).
"""

import asyncio
import dataclasses
import time
from typing import Awaitable, Callable, Dict, List, Optional

from areal_tpu.base import logging
from areal_tpu.base import metrics as metrics_mod
from areal_tpu.gateway.autoscaler import ScaleSignals

logger = logging.getLogger("areal_tpu.gateway.brownout")


@dataclasses.dataclass
class LevelThresholds:
    """Entry thresholds for ONE ladder rung: the rung trips when ANY of
    the three signals reaches its bound (pressure has many shapes)."""

    kv_occupancy: float
    queue_wait_p95_s: float
    breaker_open_frac: float  # open breakers / routed servers


@dataclasses.dataclass
class BrownoutConfig:
    # rung i of the ladder = levels[i-1]; level 0 is healthy
    levels: List[LevelThresholds] = dataclasses.field(
        default_factory=lambda: [
            LevelThresholds(0.90, 5.0, 0.25),   # 1: clamp max_tokens
            LevelThresholds(0.95, 15.0, 0.50),  # 2: disable spec decode
            LevelThresholds(0.97, 30.0, 0.75),  # 3: shed light tenants
            LevelThresholds(0.99, 60.0, 1.00),  # 4: admit nothing new
        ]
    )
    # de-escalate only when every signal < entry threshold * hysteresis
    hysteresis: float = 0.8
    min_hold_s: float = 30.0   # dwell before any step DOWN
    interval_s: float = 5.0    # controller loop cadence
    clamp_max_tokens: int = 256   # the level-1 cap
    weight_floor: float = 1.0     # level-3: shed tenants below this weight


def decide(cfg: BrownoutConfig, sig: ScaleSignals, current: int) -> int:
    """Pure ladder step: the target level given the signals and the
    current rung. Escalates straight to the worst tripped rung;
    de-escalates one rung only when every signal is below the CURRENT
    rung's entry thresholds times the hysteresis factor. (The dwell-time
    gate lives in the controller — time is side effect, not policy.)"""
    frac = sig.breaker_open / max(sig.routed, 1)

    def trips(lvl: LevelThresholds) -> bool:
        return (
            sig.kv_occupancy >= lvl.kv_occupancy
            or sig.queue_wait_p95_s >= lvl.queue_wait_p95_s
            or frac >= lvl.breaker_open_frac
        )

    worst = 0
    for i, lvl in enumerate(cfg.levels, start=1):
        if trips(lvl):
            worst = i
    if worst > current:
        return worst
    if worst < current:
        entry = cfg.levels[current - 1]
        h = cfg.hysteresis
        if (
            sig.kv_occupancy < entry.kv_occupancy * h
            and sig.queue_wait_p95_s < entry.queue_wait_p95_s * h
            and frac < entry.breaker_open_frac * h
        ):
            return current - 1
    return current


class BrownoutController:
    """Actuation loop around :func:`decide`.

    The levers are injected callbacks so the controller stays free of
    gateway internals (and tests drive it against plain recorders):

    - ``clamp_cb(max_tokens | None)`` — apply/remove the fleet-wide
      ``max_tokens`` cap (level >= 1).
    - ``spec_cb(enabled)`` — async; toggle speculative decoding across
      the fleet (disabled at level >= 2, restored below).
    - ``shed_cb(weight_floor, retry_after_s)`` — shed tenants below the
      floor (level >= 3; floor 0 disables shedding).
    - ``pause_cb(paused, retry_after_s)`` — stop admitting new requests
      (level >= 4).
    """

    def __init__(
        self,
        cfg: BrownoutConfig,
        fetch_signals: Callable[[], ScaleSignals],
        clamp_cb: Callable[[Optional[int]], None],
        spec_cb: Callable[[bool], Awaitable[None]],
        shed_cb: Callable[[float, float], None],
        pause_cb: Callable[[bool, float], None],
        clock=time.monotonic,
    ):
        self.cfg = cfg
        self.fetch_signals = fetch_signals
        self.clamp_cb = clamp_cb
        self.spec_cb = spec_cb
        self.shed_cb = shed_cb
        self.pause_cb = pause_cb
        self._clock = clock
        self.level = 0
        self._last_transition_t = -float("inf")
        metrics_mod.counters.gauge(metrics_mod.GW_BROWNOUT_LEVEL, 0.0)

    def retry_after_s(self) -> float:
        """The honest recovery estimate a shed request is handed: the
        soonest the ladder can step down (remaining dwell), floored at
        one loop interval — never a constant pulled from thin air."""
        held = self._clock() - self._last_transition_t
        return max(self.cfg.min_hold_s - held, self.cfg.interval_s)

    async def step_once(self) -> int:
        """One fetch->decide->apply pass (tests call it directly with
        fake clocks/signals). Returns the level after the pass."""
        sig = self.fetch_signals()
        target = decide(self.cfg, sig, self.level)
        if target < self.level and (
            self._clock() - self._last_transition_t < self.cfg.min_hold_s
        ):
            return self.level  # dwell; escalation is never delayed
        if target != self.level:
            await self._apply(target, sig)
        return self.level

    async def _apply(self, target: int, sig: ScaleSignals) -> None:
        prev, self.level = self.level, target
        self._last_transition_t = self._clock()
        retry_after = self.retry_after_s()
        self.clamp_cb(self.cfg.clamp_max_tokens if target >= 1 else None)
        if (target >= 2) != (prev >= 2):
            await self.spec_cb(target < 2)
        self.shed_cb(
            self.cfg.weight_floor if target >= 3 else 0.0, retry_after
        )
        self.pause_cb(target >= 4, retry_after)
        metrics_mod.counters.gauge(
            metrics_mod.GW_BROWNOUT_LEVEL, float(target)
        )
        metrics_mod.counters.add(metrics_mod.GW_BROWNOUT_TRANSITIONS)
        logger.warning(
            "brownout level %d -> %d (kv %.2f, wait p95 %.1fs, "
            "breakers %d/%d)",
            prev, target, sig.kv_occupancy, sig.queue_wait_p95_s,
            sig.breaker_open, sig.routed,
        )

    async def run(self):
        while True:
            try:
                await self.step_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("brownout pass failed")
            await asyncio.sleep(self.cfg.interval_s)


def wire_brownout(
    cfg: BrownoutConfig,
    scheduler,
    gateway_config,
    client,
    clock=time.monotonic,
) -> BrownoutController:
    """Build a controller actuating a :class:`ContinuousBatchScheduler` +
    :class:`GatewayConfig` pair over a :class:`GenAPIClient`.

    Signals come from the scheduler's live capacity view (mean KV demand
    occupancy + unhealthy count) and the ``gw/queue_wait_s`` histogram.
    The spec-decode lever remembers which backends actually HAD
    speculation on, so restoring the ladder does not switch it on where
    an operator had it disabled."""
    spec_prev: Dict[str, bool] = {}

    def fetch_signals() -> ScaleSignals:
        states = list(scheduler._servers.values())
        routed = len(states)
        unhealthy = sum(1 for s in states if not s.healthy)
        occ = (
            sum(s.kv_occupancy for s in states) / routed if routed else 0.0
        )
        h = metrics_mod.counters.histogram(metrics_mod.GW_QUEUE_WAIT_S)
        p95 = (
            float(h.percentile(95.0))
            if h is not None and h.count else 0.0
        )
        return ScaleSignals(
            routed=routed,
            healthy=routed - unhealthy,
            queue_depth=float(scheduler.queue_depth()),
            kv_occupancy=occ,
            queue_wait_p95_s=p95,
            breaker_open=unhealthy,
        )

    def clamp_cb(max_tokens: Optional[int]) -> None:
        gateway_config.brownout_max_tokens = max_tokens

    async def spec_cb(enabled: bool) -> None:
        if not enabled:
            for url in scheduler.server_urls():
                try:
                    m = await client.metrics(url)
                    spec_prev[url] = bool(m.get("spec_decode", False))
                    if spec_prev[url]:
                        await client.set_spec_decode(url, False)
                except Exception:
                    logger.warning(
                        "brownout: disabling spec decode on %s failed", url
                    )
            return
        for url, was_on in spec_prev.items():
            if not was_on:
                continue
            try:
                await client.set_spec_decode(url, True)
            except Exception:
                logger.warning(
                    "brownout: restoring spec decode on %s failed", url
                )
        spec_prev.clear()

    def shed_cb(weight_floor: float, retry_after_s: float) -> None:
        scheduler.shed_weight_floor = weight_floor
        scheduler.brownout_retry_after_s = retry_after_s

    def pause_cb(paused: bool, retry_after_s: float) -> None:
        scheduler.admit_paused = paused
        scheduler.brownout_retry_after_s = retry_after_s

    return BrownoutController(
        cfg, fetch_signals, clamp_cb, spec_cb, shed_cb, pause_cb,
        clock=clock,
    )
