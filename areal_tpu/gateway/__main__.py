"""Standalone local serving stack: engine + gen server + gateway.

``make serve`` / ``python -m areal_tpu.gateway`` — brings up ONE
generation engine (an HF checkpoint when ``--model-path`` is given, a
tiny random-weight model otherwise), the gen HTTP server around it, and
the OpenAI-compatible gateway in a single process. For local development
and smoke tests; production runs through the launcher
(``apps/launcher.py`` gateway worker), which fronts the whole fleet.

    python -m areal_tpu.gateway [--port 8000] [--model-path /ckpt]
        [--tokenizer-path /tok] [--slots 8] [--rate-tps 0]
"""

import argparse
import asyncio
import sys

from areal_tpu.base import constants, logging, network
from areal_tpu.gateway.api import (
    ByteFallbackCodec,
    GatewayConfig,
    GatewayServer,
    HFTokenizerCodec,
    serve_gateway,
)
from areal_tpu.gateway.qos import TenantSpec
from areal_tpu.gateway.scheduler import ContinuousBatchScheduler

logger = logging.getLogger("areal_tpu.gateway.main")


def _build_engine(args):
    import jax

    from areal_tpu.gen.engine import GenerationEngine
    from areal_tpu.models import transformer as tfm
    from areal_tpu.models.config import ModelConfig

    if args.model_path:
        from areal_tpu.models import hf as hf_conv

        cfg, params = hf_conv.load_hf_checkpoint(args.model_path)
    else:
        logger.warning(
            "no --model-path: serving a tiny RANDOM-weight model "
            "(smoke-test mode; output tokens are meaningless)"
        )
        cfg = ModelConfig(
            n_layers=2, n_q_heads=4, n_kv_heads=2, head_dim=8,
            hidden_dim=32, intermediate_dim=64, vocab_size=128,
            dtype="float32",
        )
        params = tfm.init_params(cfg, jax.random.key(0))
    return GenerationEngine(
        cfg, params, max_slots=args.slots, max_seqlen=args.max_seqlen
    )


async def _amain(args) -> int:
    from areal_tpu.gen.server import serve as serve_gen

    engine = _build_engine(args)
    gen_port = network.find_free_port()
    gen_runner = await serve_gen(engine, "127.0.0.1", gen_port)
    gen_url = f"http://127.0.0.1:{gen_port}"

    scheduler = ContinuousBatchScheduler(
        [gen_url],
        default_tenant=TenantSpec(
            name="anonymous",
            rate_tokens_per_s=args.rate_tps,
            default_deadline_s=args.deadline_s,
        ),
    )
    await scheduler.start()
    codec = (
        HFTokenizerCodec(args.tokenizer_path or args.model_path)
        if (args.tokenizer_path or args.model_path)
        else ByteFallbackCodec(engine.cfg.vocab_size)
    )
    gw = GatewayServer(
        scheduler, codec,
        GatewayConfig(max_tokens_cap=engine.G),
    )
    port = args.port or constants.gateway_port() or network.find_free_port()
    gw_runner = await serve_gateway(gw, "0.0.0.0", port)
    brownout_task = None
    if args.brownout:
        from areal_tpu.gateway.brownout import BrownoutConfig, wire_brownout

        controller = wire_brownout(
            BrownoutConfig(), scheduler, gw.config, scheduler._client
        )
        brownout_task = asyncio.get_event_loop().create_task(
            controller.run()
        )
    print(f"gateway listening on http://127.0.0.1:{port}/v1 "
          f"(backend {gen_url})", flush=True)
    try:
        while True:
            await asyncio.sleep(3600)
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        if brownout_task is not None:
            brownout_task.cancel()
        await scheduler.stop()
        await gw_runner.cleanup()
        await gen_runner.cleanup()
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="areal_tpu.gateway", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--port", type=int, default=0,
                   help="gateway port (default AREAL_GATEWAY_PORT or free)")
    p.add_argument("--model-path", default="", help="HF checkpoint dir")
    p.add_argument("--tokenizer-path", default="",
                   help="tokenizer dir (default: model path)")
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--max-seqlen", type=int, default=2048)
    p.add_argument("--rate-tps", type=float, default=0.0,
                   help="per-tenant token-bucket rate (0 = unlimited)")
    p.add_argument("--deadline-s", type=float, default=0.0,
                   help="default per-request deadline in seconds (0 = none)")
    p.add_argument("--brownout", action="store_true",
                   help="enable the brownout degradation ladder")
    args = p.parse_args(argv)
    try:
        return asyncio.run(_amain(args))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
