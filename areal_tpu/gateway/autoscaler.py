"""Telemetry-driven autoscaler for the serving gateway (docs/serving.md).

Closes the loop the observability plane opened: the per-worker telemetry
exporters publish queue/latency/HBM gauges, ``system/telemetry.py``
merges them into the ``fleet/`` aggregate, and this module turns that
aggregate into scale decisions:

- **signals** (:class:`ScaleSignals`): gateway queue depth per routed
  server, queue-wait p95 from the ``gw/queue_wait_s`` merged histogram,
  mean KV-pool occupancy across gen servers, and open-breaker counts
  from the manager's per-server states.
- **decision table** (:func:`decide`): a PURE function — synthetic
  aggregates drive it directly in tests. Grow when any pressure signal
  trips (or to replace breaker-open servers / reach the floor); shrink
  only when EVERY relax signal agrees; hold otherwise.
- **actuation** (:class:`Autoscaler`): a loop that fetches signals,
  applies cooldown, and grows/shrinks the ROUTED server set through
  callbacks — the gateway scheduler's ``set_servers`` plus the gserver
  manager's ``/add_server`` / ``/remove_server`` control endpoints, so
  sticky RL routing rebalances live alongside user traffic.
"""

import asyncio
import dataclasses
import time
from typing import Callable, Dict, List, Optional

from areal_tpu.base import logging
from areal_tpu.base import metrics as metrics_mod

logger = logging.getLogger("areal_tpu.gateway.autoscaler")


@dataclasses.dataclass
class AutoscalerConfig:
    min_servers: int = 1
    max_servers: int = 8
    interval_s: float = 10.0
    cooldown_s: float = 30.0
    # grow when ANY of these trips
    up_queue_per_server: float = 4.0
    up_kv_occupancy: float = 0.85
    up_queue_wait_p95_s: float = 10.0
    # shrink only when ALL of these hold (hysteresis below the up-gates)
    down_queue_per_server: float = 0.25
    down_kv_occupancy: float = 0.30


@dataclasses.dataclass
class ScaleSignals:
    """The decision table's input, extracted from a ``fleet/`` aggregate."""

    routed: int                    # servers currently routed by the gateway
    healthy: int                   # routed minus breaker-open
    queue_depth: float = 0.0       # gateway fair-queue depth
    kv_occupancy: float = 0.0      # mean pool occupancy across gen servers
    queue_wait_p95_s: float = 0.0  # merged gw/queue_wait_s p95
    breaker_open: int = 0          # manager breaker states: open/half-open

    @classmethod
    def from_fleet_scalars(
        cls,
        scalars: Dict[str, float],
        routed: int,
        n_gen_servers: Optional[int] = None,
    ) -> "ScaleSignals":
        """Pull the autoscaler's inputs out of a flattened ``fleet/``
        scalar dict (``telemetry.FleetAggregate.scalars()``). Gauges are
        fleet SUMS, so per-server means divide by the exporter count."""
        n_gen = n_gen_servers if n_gen_servers is not None else max(
            int(scalars.get("servers_total", routed) or routed), 1
        )
        open_cnt = int(
            scalars.get("servers_open", 0.0)
            + scalars.get("servers_half_open", 0.0)
        )
        occ = scalars.get(
            "kv_pool_demand_occupancy", scalars.get("kv_pool_occupancy", 0.0)
        )
        return cls(
            routed=routed,
            healthy=max(routed - open_cnt, 0),
            queue_depth=float(scalars.get("gw_queue_depth", 0.0)),
            kv_occupancy=float(occ) / max(n_gen, 1),
            queue_wait_p95_s=float(scalars.get("gw/queue_wait_s/p95", 0.0)),
            breaker_open=open_cnt,
        )


@dataclasses.dataclass
class ScaleDecision:
    action: str                 # "grow" | "shrink" | "hold"
    delta: int = 0              # servers to add (grow) or remove (shrink)
    reasons: List[str] = dataclasses.field(default_factory=list)


def decide(cfg: AutoscalerConfig, sig: ScaleSignals) -> ScaleDecision:
    """Pure decision table (tested against synthetic aggregates)."""
    reasons: List[str] = []
    headroom = cfg.max_servers - sig.routed
    # floor / breaker replacement first: these are correctness, not load
    if sig.healthy < cfg.min_servers and headroom > 0:
        want = min(cfg.min_servers - sig.healthy, headroom)
        reasons.append(
            f"healthy {sig.healthy} below floor {cfg.min_servers}"
        )
        return ScaleDecision("grow", want, reasons)
    if sig.breaker_open > 0 and headroom > 0:
        want = min(sig.breaker_open, headroom)
        reasons.append(f"replacing {sig.breaker_open} breaker-open servers")
        return ScaleDecision("grow", want, reasons)
    per_server = sig.queue_depth / max(sig.healthy, 1)
    if headroom > 0:
        if per_server > cfg.up_queue_per_server:
            reasons.append(
                f"queue {per_server:.1f}/server > {cfg.up_queue_per_server}"
            )
        if sig.kv_occupancy > cfg.up_kv_occupancy:
            reasons.append(
                f"kv occupancy {sig.kv_occupancy:.2f} > "
                f"{cfg.up_kv_occupancy}"
            )
        if sig.queue_wait_p95_s > cfg.up_queue_wait_p95_s:
            reasons.append(
                f"queue wait p95 {sig.queue_wait_p95_s:.1f}s > "
                f"{cfg.up_queue_wait_p95_s}s"
            )
        if reasons:
            # deep backlog grows faster than one-at-a-time
            extra = int(per_server // (2 * cfg.up_queue_per_server))
            return ScaleDecision(
                "grow", min(1 + extra, headroom), reasons
            )
    if (
        sig.routed > cfg.min_servers
        and sig.breaker_open == 0
        and per_server < cfg.down_queue_per_server
        and sig.kv_occupancy < cfg.down_kv_occupancy
        and sig.queue_wait_p95_s < cfg.up_queue_wait_p95_s / 2
    ):
        return ScaleDecision(
            "shrink", 1,
            [
                f"idle: queue {per_server:.2f}/server, kv occupancy "
                f"{sig.kv_occupancy:.2f}"
            ],
        )
    return ScaleDecision("hold", 0, reasons)


class Autoscaler:
    """Actuation loop around :func:`decide`.

    ``fetch_signals`` returns the current :class:`ScaleSignals` (built
    from the fleet aggregate); ``grow_cb(n)`` / ``shrink_cb(n)`` apply a
    decision and return how many servers actually changed (the routed
    set is bounded by what the launcher spawned, so a grow can be
    partially satisfied)."""

    def __init__(
        self,
        cfg: AutoscalerConfig,
        fetch_signals: Callable[[], ScaleSignals],
        grow_cb: Callable[[int], int],
        shrink_cb: Callable[[int], int],
        clock=time.monotonic,
    ):
        self.cfg = cfg
        self.fetch_signals = fetch_signals
        self.grow_cb = grow_cb
        self.shrink_cb = shrink_cb
        self._clock = clock
        self._last_action_t = -float("inf")
        self.last_decision: Optional[ScaleDecision] = None

    def step_once(self) -> ScaleDecision:
        """One fetch->decide->apply pass (the loop body; tests call it
        directly with fake clocks/signals)."""
        sig = self.fetch_signals()
        decision = decide(self.cfg, sig)
        self.last_decision = decision
        if decision.action == "hold":
            return decision
        now = self._clock()
        if now - self._last_action_t < self.cfg.cooldown_s:
            return ScaleDecision(
                "hold", 0,
                [f"cooldown ({decision.action} {decision.delta} deferred)"],
            )
        applied = 0
        if decision.action == "grow":
            applied = self.grow_cb(decision.delta)
            if applied:
                metrics_mod.counters.add(metrics_mod.GW_SCALE_UPS, applied)
        elif decision.action == "shrink":
            applied = self.shrink_cb(decision.delta)
            if applied:
                metrics_mod.counters.add(
                    metrics_mod.GW_SCALE_DOWNS, applied
                )
        if applied:
            self._last_action_t = now
            logger.info(
                "autoscaler %s %d server(s): %s",
                decision.action, applied, "; ".join(decision.reasons),
            )
        return decision

    async def run(self):
        while True:
            try:
                self.step_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("autoscaler pass failed")
            await asyncio.sleep(self.cfg.interval_s)
