"""Continuous-batching scheduler: the gateway's dispatch plane.

Bridges the OpenAI-compatible API surface (``gateway/api.py``) to the gen
fleet's chunked engines. Requests are NOT batched at request boundaries:
each one is dispatched into an engine slot the moment one frees up (the
engine's chunked admission protocol, ``gen/engine.py``), subject to three
gates applied in order:

1. **rate limit** — per-tenant token bucket, charged the budgeted cost
   (prompt + ``max_tokens``) at arrival, refunded the unused budget at
   completion. Over-budget requests answer 429 immediately.
2. **queue** — admitted requests wait in a weighted fair queue
   (``gateway/qos.py``): one heavy tenant's backlog cannot starve the
   rest. A full queue answers 429.
3. **admission** — the dispatch loop releases the queue head to a backend
   only when one has a free slot AND its KV-pool occupancy is below the
   admit threshold (the signal ``gen/pages.py`` exposes through
   ``/metrics_json``); otherwise the request waits, keeping deep queues
   in the gateway (visible, fair, cancellable) instead of inside engines.

Generation streams back chunk-by-chunk over ``GenAPIClient.
generate_stream``; a weight-update interruption is resumed transparently
(resubmit with accumulated tokens — the partial-rollout protocol reused
for user traffic). The routed server set is LIVE: the autoscaler
(``gateway/autoscaler.py``) grows/shrinks it between requests.
"""

import asyncio
import dataclasses
import math
import time
import uuid
from typing import AsyncIterator, Dict, List, Optional

import aiohttp

from areal_tpu.base import constants, faults, logging, tracing
from areal_tpu.base import metrics as metrics_mod
from areal_tpu.gateway.qos import (
    TenantSpec,
    TokenBucket,
    WeightedFairQueue,
    build_buckets,
    request_cost,
)
from areal_tpu.gen.client import DeadlineExceeded, GenAPIClient

logger = logging.getLogger("areal_tpu.gateway.scheduler")


@dataclasses.dataclass
class GatewayRequest:
    """One in-flight API request as the scheduler sees it."""

    rid: str
    tenant: str
    input_ids: List[int]
    sampling_params: Dict
    cost: float = 0.0
    enqueue_t: float = 0.0
    events: asyncio.Queue = dataclasses.field(default_factory=asyncio.Queue)
    cancelled: bool = False
    n_generated: int = 0
    finish_reason: Optional[str] = None
    # deadline propagation: the RELATIVE budget the client/tenant named
    # (0 = none) and the ABSOLUTE expiry ``submit`` stamps on the
    # scheduler clock — queue shedding, dispatch and the per-chunk stream
    # all compare against ``deadline_t``
    deadline_s: float = 0.0
    deadline_t: float = math.inf
    # trace propagation: the wire context captured in the API handler's
    # task (``tracing.wire_context()``). The dispatch loop runs requests
    # in tasks it spawns itself, so the handler's contextvars never reach
    # ``_run_request`` — the context must ride the request object.
    trace: Optional[Dict] = None

    @classmethod
    def build(
        cls,
        tenant: str,
        input_ids: List[int],
        sampling_params: Dict,
        deadline_s: float = 0.0,
    ):
        return cls(
            rid=f"gw-{uuid.uuid4().hex[:16]}",
            tenant=tenant,
            input_ids=list(input_ids),
            sampling_params=dict(sampling_params),
            cost=request_cost(
                len(input_ids), int(sampling_params.get("max_new_tokens", 256))
            ),
            enqueue_t=time.monotonic(),
            deadline_s=max(float(deadline_s), 0.0),
            trace=tracing.wire_context(),
        )


class RateLimited(Exception):
    """``permanent`` marks a request that can NEVER be admitted (cost
    above the tenant's burst capacity) — the API answers 400, not a 429
    that would send the client into an infinite retry loop."""

    def __init__(
        self, reason: str, retry_after_s: float = 1.0,
        permanent: bool = False,
    ):
        super().__init__(reason)
        self.retry_after_s = max(retry_after_s, 0.0)
        self.permanent = permanent


class ServiceUnavailable(Exception):
    """Every routed backend is unreachable/breaker-open: the API answers
    503 + Retry-After (the capacity-poll interval — the gateway's
    re-admission probe cadence) instead of queueing the request behind a
    fleet that may be gone for minutes."""

    def __init__(self, reason: str, retry_after_s: float = 1.0):
        super().__init__(reason)
        self.retry_after_s = max(retry_after_s, 0.0)


@dataclasses.dataclass
class ServerState:
    """The scheduler's capacity view of one backend."""

    url: str
    max_slots: int = 1
    inflight: int = 0
    kv_occupancy: float = 0.0
    healthy: bool = True
    slot_capacity: int = 0  # per-slot token capacity (0 = not polled yet)
    # weight-update pause (polled from /metrics_json): a paused backend
    # is never picked as a HEDGE target — the pause stalls the whole
    # fleet the same way, so a hedge doubles load for zero latency win
    paused: bool = False

    def free_slots(self, admit_occupancy: float) -> int:
        if not self.healthy or self.kv_occupancy >= admit_occupancy:
            return 0
        return max(self.max_slots - self.inflight, 0)


class ContinuousBatchScheduler:
    def __init__(
        self,
        server_urls: List[str],
        tenants: Optional[Dict[str, TenantSpec]] = None,
        *,
        default_tenant: Optional[TenantSpec] = None,
        max_queue: Optional[int] = None,
        admit_occupancy: Optional[float] = None,
        metrics_poll_interval: float = 2.0,
        client: Optional[GenAPIClient] = None,
        clock=time.monotonic,
        hedge_enabled: Optional[bool] = None,
        hedge_min_delay_s: float = 0.25,
        hedge_max_fraction: float = 0.1,
        max_stream_resumes: int = 3,
        deadline_sweep_interval_s: float = 0.25,
    ):
        self.tenants = dict(tenants or {})
        self.default_tenant = default_tenant or TenantSpec(
            name="anonymous",
            rate_tokens_per_s=constants.gateway_rate_tps(),
            burst_tokens=constants.gateway_burst(),
        )
        self.max_queue = (
            max_queue if max_queue is not None else constants.gateway_max_queue()
        )
        self.admit_occupancy = (
            admit_occupancy
            if admit_occupancy is not None
            else constants.gateway_admit_occupancy()
        )
        self.metrics_poll_interval = metrics_poll_interval
        # hedged dispatch (docs/serving.md "Survivability"): re-submit a
        # still-unstarted request to a second healthy backend once the
        # primary stalls past the live ttft p95 (floored at min_delay);
        # per-tenant hedge volume is capped at max_fraction of requests
        self.hedge_enabled = (
            constants.gateway_hedge() if hedge_enabled is None
            else hedge_enabled
        )
        self.hedge_min_delay_s = hedge_min_delay_s
        self.hedge_max_fraction = hedge_max_fraction
        # transparent resume cap when a BACKEND dies mid-stream (the
        # weight-update resubmit protocol generalized to server loss)
        self.max_stream_resumes = max_stream_resumes
        self.deadline_sweep_interval_s = deadline_sweep_interval_s
        # brownout actuation (gateway/brownout.py): submit-side levers the
        # controller flips; plain attributes so tests drive them directly
        self.admit_paused = False
        self.shed_weight_floor = 0.0
        self.brownout_retry_after_s = 30.0
        self._tenant_reqs: Dict[str, int] = {}
        self._tenant_hedges: Dict[str, int] = {}
        self._clock = clock
        self._wfq = WeightedFairQueue()
        self._buckets: Dict[str, TokenBucket] = build_buckets(
            self.tenants, clock=clock
        )
        self._servers: Dict[str, ServerState] = {
            u: ServerState(url=u) for u in server_urls
        }
        # servers removed from routing with requests still draining: their
        # state object (the live inflight count) is restored on re-add so
        # a grow right after a shrink cannot over-commit the engine
        self._retired: Dict[str, ServerState] = {}
        self._client = client
        self._owns_client = client is None
        self._wake = asyncio.Event()
        self._tasks: set = set()
        self._loops: List[asyncio.Task] = []
        self._stopped = False
        # completions since start, by finish reason (metrics_json view)
        self.completed: Dict[str, int] = {}

    # ------------------------------------------------------------------ #
    # lifecycle / live server set
    # ------------------------------------------------------------------ #

    async def start(self):
        if self._client is None:
            self._client = GenAPIClient(timeout=3600.0)
            await self._client.__aenter__()
        loop = asyncio.get_event_loop()
        self._loops = [
            loop.create_task(self._dispatch_loop()),
            loop.create_task(self._poll_loop()),
            loop.create_task(self._deadline_loop()),
        ]
        # one eager capacity poll so the first dispatch sees real slot
        # counts instead of the max_slots=1 placeholder
        await self.poll_capacity()
        return self

    async def stop(self):
        self._stopped = True
        for t in [*self._loops, *self._tasks]:
            t.cancel()
        if self._loops or self._tasks:
            await asyncio.gather(
                *self._loops, *self._tasks, return_exceptions=True
            )
        self._loops = []
        if self._owns_client and self._client is not None:
            await self._client.__aexit__(None, None, None)
            self._client = None

    def set_servers(self, urls: List[str]) -> None:
        """Replace the routed server set (autoscaler hook). In-flight
        requests on removed servers drain naturally — only NEW dispatches
        see the new set; a re-added server resumes its draining state's
        inflight count instead of starting a fresh (over-committing) one."""
        for u in urls:
            if u not in self._servers:
                self._servers[u] = self._retired.pop(u, None) or ServerState(
                    url=u
                )
        for u in list(self._servers):
            if u not in urls:
                s = self._servers.pop(u)
                if s.inflight > 0:
                    self._retired[u] = s
        self._wake.set()

    def server_urls(self) -> List[str]:
        return list(self._servers)

    # ------------------------------------------------------------------ #
    # intake
    # ------------------------------------------------------------------ #

    def _tenant_spec(self, tenant: str) -> TenantSpec:
        spec = self.tenants.get(tenant)
        if spec is None:
            spec = dataclasses.replace(self.default_tenant, name=tenant)
            self.tenants[tenant] = spec
        return spec

    def _bucket(self, tenant: str) -> TokenBucket:
        b = self._buckets.get(tenant)
        if b is None:
            spec = self._tenant_spec(tenant)
            b = TokenBucket(
                spec.rate_tokens_per_s, spec.resolved_burst(), clock=self._clock
            )
            self._buckets[tenant] = b
        return b

    def submit(self, req: GatewayRequest) -> GatewayRequest:
        """Admit a request into the fair queue (raises RateLimited /
        ServiceUnavailable — the API layer counts the rejection once, in
        its error response path)."""
        if self._servers and not any(
            s.healthy for s in self._servers.values()
        ):
            raise ServiceUnavailable(
                "no healthy generation backend (all breakers open)",
                retry_after_s=self.metrics_poll_interval,
            )
        if self.admit_paused:
            raise RateLimited(
                "gateway brownout: not admitting new requests",
                retry_after_s=self.brownout_retry_after_s,
            )
        spec = self._tenant_spec(req.tenant)
        if (
            self.shed_weight_floor > 0
            and spec.weight < self.shed_weight_floor
        ):
            raise RateLimited(
                f"gateway brownout: tenant {req.tenant!r} weight "
                f"{spec.weight:g} below the shed floor "
                f"{self.shed_weight_floor:g}",
                retry_after_s=self.brownout_retry_after_s,
            )
        if len(self._wfq) >= self.max_queue:
            raise RateLimited(
                f"gateway queue full ({self.max_queue} waiting)",
                retry_after_s=self._queue_retry_after_s(),
            )
        bucket = self._bucket(req.tenant)
        if not bucket.unlimited and req.cost > bucket.burst:
            raise RateLimited(
                f"request cost {req.cost:.0f} tokens exceeds tenant "
                f"{req.tenant!r} burst capacity {bucket.burst:.0f} — it "
                "can never be admitted; lower max_tokens",
                permanent=True,
            )
        # arealint: owns(gateway.token-bucket, settled out of line — _run_request's finally refunds cost-minus-consumption, cancel() refunds queued drops, _dispatch_loop refunds the cancel-race pops)
        if not bucket.try_acquire(req.cost):
            raise RateLimited(
                f"tenant {req.tenant!r} over its token rate limit",
                retry_after_s=bucket.retry_after_s(req.cost),
            )
        # deadline: client-named budget, else the tenant default, else the
        # fleet-wide env default; stamped absolute on the scheduler clock
        dl = req.deadline_s
        if dl <= 0:
            dl = spec.default_deadline_s
        if dl <= 0:
            dl = constants.gateway_deadline_s()
        if dl > 0:
            req.deadline_s = dl
            req.deadline_t = self._clock() + dl
        self._tenant_reqs[req.tenant] = (
            self._tenant_reqs.get(req.tenant, 0) + 1
        )
        req.enqueue_t = self._clock()
        # arealint: owns(gateway.wfq, drained by _dispatch_loop's pop; cancel() drops queued entries with the clock rollback)
        self._wfq.push(req.tenant, req.cost, spec.weight, req)
        metrics_mod.counters.add(metrics_mod.GW_REQUESTS)
        self._wake.set()
        return req

    def cancel(self, req: GatewayRequest) -> None:
        """Client went away: drop from the queue if still queued (the
        dispatch path checks ``cancelled`` before and during streaming).
        The full-cost refund applies only to still-queued requests — a
        running one settles its real consumption in ``_run_request``'s
        finally (refunding here too would double-credit the bucket)."""
        req.cancelled = True
        if self._wfq.drop_where(lambda it: it is req):
            self._bucket(req.tenant).refund(req.cost)

    def queue_depth(self) -> int:
        return len(self._wfq)

    def inflight(self) -> int:
        return sum(s.inflight for s in self._servers.values())

    # ------------------------------------------------------------------ #
    # capacity view
    # ------------------------------------------------------------------ #

    async def poll_capacity(self):
        """Refresh every backend's slot count + KV occupancy (the
        admission signals) from /metrics_json; unreachable servers are
        marked unhealthy until the next successful poll."""
        servers = list(self._servers.values())
        if not servers:
            return
        results = await asyncio.gather(
            *(self._client.metrics(s.url) for s in servers),
            return_exceptions=True,
        )
        for s, r in zip(servers, results):
            if isinstance(r, BaseException):
                s.healthy = False
                continue
            s.healthy = True
            s.max_slots = int(r.get("max_slots", s.max_slots) or 1)
            # DEMAND occupancy (excludes evictable prefix-cache pages):
            # gating on raw occupancy livelocks against a cache-warm but
            # otherwise idle server (falls back for older backends)
            s.kv_occupancy = float(
                r.get(
                    "kv_pool_demand_occupancy",
                    r.get("kv_pool_occupancy", 0.0),
                )
            )
            s.slot_capacity = int(r.get("slot_capacity", s.slot_capacity))
            s.paused = bool(r.get("paused", False))
        self._wake.set()

    def min_slot_capacity(self) -> int:
        """Smallest per-slot token capacity across polled backends (0 =
        none polled yet) — the gateway's prompt-size validation bound."""
        caps = [s.slot_capacity for s in self._servers.values()
                if s.slot_capacity > 0]
        return min(caps) if caps else 0

    async def _poll_loop(self):
        while not self._stopped:
            await asyncio.sleep(self.metrics_poll_interval)
            try:
                await self.poll_capacity()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("gateway capacity poll failed")

    def _pick_server(self) -> Optional[ServerState]:
        if faults.maybe_trip("gw.deadline_storm"):
            # scripted storm (tools/chaos.py --serve): report zero
            # dispatch capacity so queued requests age out in the fair
            # queue against their deadlines
            return None
        best, best_free = None, 0
        for s in self._servers.values():
            free = s.free_slots(self.admit_occupancy)
            if free > best_free:
                best, best_free = s, free
        return best

    def _hedge_candidate(self, exclude: ServerState) -> Optional[ServerState]:
        """A second backend for a hedge stream: healthy, not the primary,
        not weight-update-paused, with a free slot."""
        best, best_free = None, 0
        for s in self._servers.values():
            if s is exclude or s.paused:
                continue
            free = s.free_slots(self.admit_occupancy)
            if free > best_free:
                best, best_free = s, free
        return best

    # ------------------------------------------------------------------ #
    # live latency estimates (deadline shedding + hedge delay)
    # ------------------------------------------------------------------ #

    def _ttft_p95_s(self) -> float:
        """Live enqueue->first-token p95 (0 when nothing observed yet)."""
        h = metrics_mod.counters.histogram(metrics_mod.GW_TTFT_S)
        if h is None or h.count == 0:
            return 0.0
        return float(h.percentile(95.0))

    def _hedge_delay_s(self) -> float:
        return max(self.hedge_min_delay_s, self._ttft_p95_s())

    def _queue_retry_after_s(self) -> float:
        """Queue-full 429 hint: the live queue-wait p95 (how long the
        queue actually takes to drain to dispatch), clamped to [1, 60] —
        an honest estimate instead of a fixed constant."""
        h = metrics_mod.counters.histogram(metrics_mod.GW_QUEUE_WAIT_S)
        if h is None or h.count == 0:
            return 1.0
        return min(max(float(h.percentile(95.0)), 1.0), 60.0)

    def _hedge_allowed(self, req: GatewayRequest) -> bool:
        if not self.hedge_enabled:
            return False
        hedges = self._tenant_hedges.get(req.tenant, 0)
        reqs = self._tenant_reqs.get(req.tenant, 0)
        return hedges < self.hedge_max_fraction * reqs + 1.0

    # ------------------------------------------------------------------ #
    # deadline shedding
    # ------------------------------------------------------------------ #

    def sweep_deadlines(self) -> int:
        """Shed queued requests whose remaining budget can no longer cover
        estimated service (the live ttft p95): the entry never dispatches,
        its charge is refunded, the fair-queue virtual clock rolls back
        (``drop_where``), and the waiting handler gets a final deadline
        event. Returns how many were shed."""
        now = self._clock()
        est = self._ttft_p95_s()
        victims: List[GatewayRequest] = []

        def expired(it) -> bool:
            if now + est >= it.deadline_t:
                victims.append(it)
                return True
            return False

        self._wfq.drop_where(expired)
        for req in victims:
            self._settle_queue_shed(req, rolled_back=True)
        return len(victims)

    def _settle_queue_shed(
        self, req: GatewayRequest, *, rolled_back: bool
    ) -> None:
        """Settle a deadline-shed QUEUED request: full refund (nothing
        ran), fair-clock rollback unless ``drop_where`` already did it,
        counter + final event for the waiting handler."""
        self._bucket(req.tenant).refund(req.cost)
        if not rolled_back:
            self._wfq.rollback(
                req.tenant, req.cost, self._tenant_spec(req.tenant).weight
            )
        req.finish_reason = "deadline"
        metrics_mod.counters.add(metrics_mod.GW_DEADLINE_SHED)
        self.completed["deadline"] = self.completed.get("deadline", 0) + 1
        req.events.put_nowait(
            {"error": "deadline expired before dispatch",
             "finish_reason": "deadline"}
        )

    async def _deadline_loop(self):
        while not self._stopped:
            await asyncio.sleep(self.deadline_sweep_interval_s)
            try:
                if len(self._wfq) and self.sweep_deadlines():
                    self._wake.set()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("gateway deadline sweep failed")

    # ------------------------------------------------------------------ #
    # dispatch
    # ------------------------------------------------------------------ #

    async def _dispatch_loop(self):
        while not self._stopped:
            await self._wake.wait()
            self._wake.clear()
            while len(self._wfq):
                srv = self._pick_server()
                if srv is None:
                    break  # a completion or capacity poll re-wakes us
                req = self._wfq.pop()
                if req is None:
                    continue
                if req.cancelled:
                    # cancel() raced the pop: its drop_where missed the
                    # request (no longer queued) and no _run_request will
                    # ever settle the charge — refund the full budget
                    # here or the tenant's bucket leaks one request cost
                    # per cancel-while-dispatching race. The fair-queue
                    # virtual clock rolls back too: pop() advanced the
                    # tenant's stamp for work that never ran
                    self._bucket(req.tenant).refund(req.cost)
                    self._wfq.rollback(
                        req.tenant, req.cost,
                        self._tenant_spec(req.tenant).weight,
                    )
                    continue
                if req.deadline_t <= self._clock():
                    # expired between sweep ticks: same settle as the
                    # cancel race above — the pop advanced the fair clock
                    # for work that never dispatches
                    self._settle_queue_shed(req, rolled_back=False)
                    continue
                self._acquire_server(srv)
                t = asyncio.get_event_loop().create_task(
                    self._run_request(req, srv)
                )
                self._tasks.add(t)
                t.add_done_callback(self._tasks.discard)

    def _acquire_server(self, srv: ServerState) -> None:
        srv.inflight += 1

    def _release_server(self, srv: ServerState) -> None:
        srv.inflight = max(srv.inflight - 1, 0)
        if srv.inflight == 0 and self._retired.get(srv.url) is srv:
            del self._retired[srv.url]  # fully drained
        self._wake.set()

    async def _run_request(self, req: GatewayRequest, srv: ServerState):
        # re-activate the wire context the API handler captured onto the
        # request (this task belongs to the dispatch loop, not the
        # handler), so the GenAPIClient hops downstream re-propagate it
        with tracing.activate(req.trace), tracing.span(
            "gw/dispatch", rid=req.rid, tenant=req.tenant
        ) as span_attrs:
            try:
                await self._stream_request(req, srv)
            finally:
                span_attrs["finish"] = req.finish_reason
                span_attrs["tokens"] = req.n_generated

    async def _stream_request(self, req: GatewayRequest, srv: ServerState):
        wait_s = self._clock() - req.enqueue_t
        metrics_mod.counters.add(metrics_mod.GW_ADMITTED)
        metrics_mod.counters.observe(metrics_mod.GW_QUEUE_WAIT_S, wait_s)
        first_token = True
        # the bound server can change mid-request (a hedge win, a resume
        # after backend death): the box keeps the finally settling against
        # the CURRENT binding, never a stale one
        srv_box: List[ServerState] = [srv]
        resumes = 0
        dispatches = 0
        try:
            # transparent resume across weight-update interruptions AND
            # backend death: resubmit prompt+partial with the remaining
            # budget (partial-rollout protocol)
            ids = list(req.input_ids)
            sp = dict(req.sampling_params)
            budget = int(sp.get("max_new_tokens", 256))
            while True:
                finish = None
                died: Optional[BaseException] = None
                deadline_s = None
                if req.deadline_t != math.inf:
                    deadline_s = req.deadline_t - self._clock()
                    if deadline_s <= 0:
                        # expired before this (re)dispatch reached a backend
                        req.finish_reason = "deadline"
                        metrics_mod.counters.add(
                            metrics_mod.GW_DEADLINE_SHED
                        )
                        await req.events.put(
                            {"token_ids": [], "logprobs": [],
                             "finish_reason": "deadline"}
                        )
                        break
                agen = self._hedged_stream(
                    req, srv_box, ids, sp, deadline_s,
                    allow_hedge=dispatches == 0 and req.n_generated == 0,
                )
                dispatches += 1
                try:
                    async for ev in agen:
                        toks = ev.get("token_ids", [])
                        if toks and first_token:
                            first_token = False
                            metrics_mod.counters.observe(
                                metrics_mod.GW_TTFT_S,
                                self._clock() - req.enqueue_t,
                            )
                        req.n_generated += len(toks)
                        ids.extend(toks)
                        finish = ev.get("finish_reason")
                        if req.cancelled:
                            await agen.aclose()  # closes the HTTP stream;
                            # the gen server's disconnect path frees the slot
                            finish = "cancelled"
                            break
                        if not finish and req.deadline_t < self._clock():
                            # budget ran out mid-stream: forward the delta
                            # with a deadline finish; closing the stream
                            # cancels the engine slot (disconnect path)
                            await agen.aclose()
                            finish = "deadline"
                            await req.events.put(
                                {**ev, "finish_reason": "deadline"}
                            )
                            break
                        if finish == "interrupted":
                            # weight update paused the fleet mid-request:
                            # keep the delta, strip the finish — the client
                            # sees one seamless stream across the resubmit
                            if toks:
                                await req.events.put(
                                    {**ev, "finish_reason": None}
                                )
                        elif toks or finish:
                            await req.events.put(ev)
                except DeadlineExceeded:
                    # budget expired during connect backoff: the request
                    # never reached this backend's engine
                    finish = "deadline"
                    await req.events.put(
                        {"token_ids": [], "logprobs": [],
                         "finish_reason": "deadline"}
                    )
                except (
                    aiohttp.ClientError, ConnectionError,
                    asyncio.TimeoutError,
                ) as e:
                    died = e  # backend dropped the stream pre-completion
                if finish == "deadline":
                    metrics_mod.counters.add(metrics_mod.GW_DEADLINE_SHED)
                    req.finish_reason = "deadline"
                    break
                if finish is None and not req.cancelled:
                    # stream died without a final frame: backend loss. The
                    # weight-update resume generalized to server death —
                    # resubmit prompt+partial on a surviving server so the
                    # client sees one seamless (token-exact) stream.
                    cur = srv_box[0]
                    cur.healthy = False  # next successful poll restores it
                    remaining = budget - req.n_generated
                    if remaining <= 0:
                        req.finish_reason = "length"
                        await req.events.put(
                            {"token_ids": [], "logprobs": [],
                             "finish_reason": "length"}
                        )
                        break
                    alt = self._pick_server()
                    if alt is None or resumes >= self.max_stream_resumes:
                        req.finish_reason = "error"
                        await req.events.put(
                            {"error": "stream ended early"
                                      + (f" ({died!r})" if died else ""),
                             "finish_reason": "error"}
                        )
                        break
                    resumes += 1
                    metrics_mod.counters.add(metrics_mod.GW_STREAM_RESUMES)
                    logger.warning(
                        "request %s: backend %s died mid-stream; resuming "
                        "on %s (%d tokens in)",
                        req.rid, cur.url, alt.url, req.n_generated,
                    )
                    self._release_server(cur)
                    self._acquire_server(alt)
                    srv_box[0] = alt
                    sp["max_new_tokens"] = remaining
                    continue
                if finish != "interrupted":
                    req.finish_reason = finish or "error"
                    break
                remaining = budget - req.n_generated
                if remaining <= 0:
                    req.finish_reason = "length"
                    await req.events.put(
                        {"token_ids": [], "logprobs": [],
                         "finish_reason": "length"}
                    )
                    break
                metrics_mod.counters.add(metrics_mod.GW_RESUBMITS)
                sp["max_new_tokens"] = remaining
        except asyncio.CancelledError:
            # scheduler shutdown with traffic in flight: a handler blocked
            # in events() must still wake (best-effort, never blocks)
            req.finish_reason = "cancelled"
            req.events.put_nowait(
                {"error": "request cancelled", "finish_reason": "cancelled"}
            )
            raise
        except Exception as e:
            logger.exception("gateway request %s failed", req.rid)
            req.finish_reason = "error"
            await req.events.put(
                {"error": repr(e), "finish_reason": "error"}
            )
        finally:
            self._release_server(srv_box[0])
            # refund the unused token budget; charge what actually ran
            used = len(req.input_ids) + req.n_generated
            self._bucket(req.tenant).refund(max(req.cost - used, 0.0))
            metrics_mod.counters.add(metrics_mod.GW_COMPLETED)
            metrics_mod.counters.add(
                metrics_mod.GW_STREAMED_TOKENS, req.n_generated
            )
            metrics_mod.counters.add(
                metrics_mod.GW_TENANT_TOKENS_PREFIX + req.tenant, used
            )
            reason = req.finish_reason or "error"
            self.completed[reason] = self.completed.get(reason, 0) + 1
            self._wake.set()

    # ------------------------------------------------------------------ #
    # hedged dispatch
    # ------------------------------------------------------------------ #

    async def _hedged_stream(
        self,
        req: GatewayRequest,
        srv_box: List[ServerState],
        ids: List[int],
        sp: Dict,
        deadline_s: Optional[float],
        allow_hedge: bool,
    ):
        """One dispatch attempt's frame stream, with hedging: when the
        primary's first chunk stalls past the live ttft p95, a second
        healthy backend gets the same request and the first backend to
        produce a chunk wins — the loser's stream is closed (its rid
        cancels through the gen server's disconnect path) and its slot
        hold released. ``srv_box`` is rebound to the winning server so the
        caller's finally settles against the right backend. Hedging only
        applies pre-first-chunk on the first dispatch, and never against a
        weight-update pause (a pause stalls every backend identically)."""
        srv = srv_box[0]
        inner = self._client.generate_stream(
            srv.url, f"{req.rid}-c{req.n_generated}", ids, sp,
            deadline_s=deadline_s,
        )
        first_ev = None
        if allow_hedge and self.hedge_enabled and not srv.paused:
            inner, first_ev = await self._race_hedge(
                req, srv_box, ids, sp, deadline_s, inner
            )
        try:
            if first_ev is not None:
                yield first_ev
            async for ev in inner:
                yield ev
        finally:
            await inner.aclose()

    async def _race_hedge(
        self, req, srv_box, ids, sp, deadline_s, agen,
    ):
        """Race the primary stream's first frame against the hedge delay;
        returns ``(winning stream, its first frame or None)``. When a
        hedge was opened, the losing stream is cancelled and its server
        hold released; when every attempt died pre-first-frame, the
        primary's error propagates (the caller's resume path owns it)."""
        srv = srv_box[0]
        loop = asyncio.get_event_loop()
        first = loop.create_task(agen.__anext__())
        await asyncio.wait({first}, timeout=self._hedge_delay_s())
        hsrv = None
        if not first.done() and self._hedge_allowed(req):
            hsrv = self._hedge_candidate(exclude=srv)
        if hsrv is None:
            try:
                return agen, await first
            except StopAsyncIteration:
                return agen, None
        self._tenant_hedges[req.tenant] = (
            self._tenant_hedges.get(req.tenant, 0) + 1
        )
        metrics_mod.counters.add(metrics_mod.GW_HEDGES)
        self._acquire_server(hsrv)
        hgen = self._client.generate_stream(
            hsrv.url, f"{req.rid}-h{req.n_generated}", ids, sp,
            deadline_s=deadline_s,
        )
        hfirst = loop.create_task(hgen.__anext__())

        def ok(t):
            return t.done() and not t.cancelled() and t.exception() is None

        def dead(t):
            return (
                t.done() and not t.cancelled()
                and t.exception() is not None
            )

        try:
            while not (
                ok(first) or ok(hfirst) or (dead(first) and dead(hfirst))
            ):
                await asyncio.wait(
                    {t for t in (first, hfirst) if not t.done()},
                    return_when=asyncio.FIRST_COMPLETED,
                )
        except asyncio.CancelledError:
            for t in (first, hfirst):
                t.cancel()
            await asyncio.gather(first, hfirst, return_exceptions=True)
            await asyncio.gather(
                agen.aclose(), hgen.aclose(), return_exceptions=True
            )
            self._release_server(hsrv)
            raise
        if ok(first) or dead(hfirst):
            # prefer the primary on a tie; both-dead also lands here so
            # the hedge side settles below and the primary's error/EOF
            # propagates to the caller
            win_t, win_gen, win_srv = first, agen, srv
            lose_t, lose_gen, lose_srv = hfirst, hgen, hsrv
        else:
            win_t, win_gen, win_srv = hfirst, hgen, hsrv
            lose_t, lose_gen, lose_srv = first, agen, srv
            metrics_mod.counters.add(metrics_mod.GW_HEDGE_WINS)
        lose_t.cancel()
        await asyncio.gather(lose_t, return_exceptions=True)
        await lose_gen.aclose()
        self._release_server(lose_srv)
        srv_box[0] = win_srv
        try:
            return win_gen, win_t.result()
        except StopAsyncIteration:
            return win_gen, None

    # ------------------------------------------------------------------ #
    # consumption
    # ------------------------------------------------------------------ #

    async def events(self, req: GatewayRequest) -> AsyncIterator[Dict]:
        """Yield the request's event frames until the final one."""
        while True:
            ev = await req.events.get()
            yield ev
            if ev.get("finish_reason"):
                return

    def metrics_dict(self) -> Dict:
        return {
            "queue_depth": self.queue_depth(),
            "inflight": self.inflight(),
            "servers": {
                u: {
                    "max_slots": s.max_slots,
                    "inflight": s.inflight,
                    "kv_occupancy": round(s.kv_occupancy, 4),
                    "healthy": s.healthy,
                    "paused": s.paused,
                }
                for u, s in self._servers.items()
            },
            "completed": dict(self.completed),
            "tenants": sorted(self.tenants),
            "admit_paused": self.admit_paused,
            "shed_weight_floor": self.shed_weight_floor,
            "hedge_enabled": self.hedge_enabled,
        }
