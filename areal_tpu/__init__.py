"""areal_tpu: a TPU-native asynchronous RL training framework for LLMs.

A from-scratch JAX/XLA/Pallas re-design with the capabilities of AReaL
(reference: /root/reference): decoupled streaming rollout on a TPU inference
fleet + pjit trainer running decoupled PPO, connected by a staleness-controlled
sample queue and a weight-sync channel.
"""

__version__ = "0.1.0"
