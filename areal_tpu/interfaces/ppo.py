"""PPO actor & critic interfaces (decoupled async PPO).

TPU-native counterpart of ``realhf/impl/model/interface/ppo_interface.py``
(1341 LoC). The structure mirrors the reference's train_step
(``ppo_interface.py:527``): reward shaping with KL penalty → GAE →
(group-)advantage normalization over the *whole* batch → minibatch loop with
one optimizer step each, using the decoupled/dual-clip actor loss.

Key layout difference: every per-token quantity is token-aligned on the
packed axis (logprob at position t = log p(token t+1 | ≤ t)), so the action
mask is "has a next token AND the next token is generated". GAE runs as one
associative scan over the flat packed batch (``areal_tpu.ops.ppo``), not a
CUDA kernel.
"""

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.api.model import ModelInterface, PPOHyperparameters
from areal_tpu.ops import ppo as ppo_ops
from areal_tpu.parallel import multihost
from areal_tpu.train import batching
from areal_tpu.train import engine as engine_mod
from areal_tpu.train.engine import vmapped_forward, vmapped_next_token_logprobs


def _action_mask(arrays) -> jnp.ndarray:
    """[D, T] bool: positions whose *label* (next token) is a generated
    token of the same segment."""
    seg = arrays["segment_ids"]
    has_next = (seg > 0) & ~jax.vmap(ppo_ops.is_segment_end)(seg)
    pm = arrays["prompt_mask"].astype(bool)
    label_is_prompt = jnp.concatenate([pm[:, 1:], jnp.zeros_like(pm[:, :1])], 1)
    return has_next & ~label_is_prompt


def logprob_output_fn(params, cfg, arrays):
    """Token-aligned logprobs of the next token — the "inference" MFC that
    recomputes proximal logprobs (≈ ``ppo_interface.py:474``). Honors
    ``cfg.loss_chunk_size`` (no [T, vocab] logits at long context)."""
    return vmapped_next_token_logprobs(params, cfg, arrays)


def value_output_fn(params, cfg, arrays):
    """Per-token critic values [D, T] (zero on padding)."""
    values = vmapped_forward(params, cfg, arrays)[..., 0]
    return jnp.where(arrays["segment_ids"] > 0, values, 0.0)




@dataclasses.dataclass
class PPOActorInterface(ModelInterface):
    hp: PPOHyperparameters = dataclasses.field(default_factory=PPOHyperparameters)
    hf_family: Optional[str] = None

    def __post_init__(self):
        if self.hp.use_adaptive_kl:
            self.kl_ctl = ppo_ops.AdaptiveKLController(
                self.hp.kl_ctl, self.hp.adaptive_kl_target, self.hp.adaptive_kl_horizon
            )
        else:
            self.kl_ctl = ppo_ops.FixedKLController(self.hp.kl_ctl)
        self._last_ref_kl = 0.0
        # Built once so the engine's jit cache hits across train_step calls.
        self._actor_loss_fn = self._build_actor_loss()

    def _build_actor_loss(self):
        hp = self.hp

        def actor_loss(params, cfg, arrays):
            mask = _action_mask(arrays)
            new_lp, aux = vmapped_next_token_logprobs(
                params, cfg, arrays, with_aux=True
            )
            old_lp = arrays["packed_logprobs"].astype(jnp.float32)
            prox = arrays.get("prox_logp")
            if hp.use_decoupled_loss and prox is not None:
                prox = prox.astype(jnp.float32)
            elif hp.recompute_logprob and prox is not None:
                # sync-PPO with recomputed logprobs: use them as "old"
                old_lp, prox = prox.astype(jnp.float32), None
            else:
                prox = None
            loss, stat = ppo_ops.actor_loss_fn(
                new_lp.reshape(-1),
                old_lp.reshape(-1),
                arrays["advantages"].astype(jnp.float32).reshape(-1),
                hp.eps_clip,
                mask.reshape(-1),
                c_clip=hp.c_clip,
                proximal_logprobs=None if prox is None else prox.reshape(-1),
                behav_imp_weight_cap=hp.behav_imp_weight_cap,
            )
            n = jnp.maximum(mask.sum(), 1)
            scalar_stats = {
                "actor_loss": loss,
                "importance_weight": jnp.sum(stat["importance_weight"]) / n,
                "actor_clip_ratio": jnp.sum(stat["clip_mask"]) / n,
                "approx_kl": jnp.sum(jnp.abs(stat["approx_kl"] * mask.reshape(-1))) / n,
            }
            return loss + aux, scalar_stats

        return actor_loss

    # -------------------------------------------------------------- #
    # proximal logprob recompute (actor_inf MFC)
    # -------------------------------------------------------------- #

    def inference(
        self, engine, sample: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample:
        outs = engine.forward(sample, mb_spec, logprob_output_fn)
        main = sample.main_key()
        res = SequenceSample(
            keys={"prox_logp"},
            ids=list(sample.ids),
            seqlens={"prox_logp": [list(l) for l in sample.seqlens[main]]},
            data={"prox_logp": np.concatenate([o.astype(np.float32) for o in outs])},
        )
        return res

    # -------------------------------------------------------------- #
    # advantage computation over the full batch
    # -------------------------------------------------------------- #

    def _prepare(self, sample: SequenceSample) -> SequenceSample:
        """Compute advantages/returns on the whole batch (flat packed layout)
        and attach them as new keys — the analogue of the reference's
        pre-minibatch GAE + normalization block (``ppo_interface.py:527-647``)."""
        hp = self.hp
        pb = batching.pack_sequences(sample, n_rows=1, pad_multiple=128)
        a = {k: jnp.asarray(v[0]) for k, v in pb.arrays.items()}
        seg = a["segment_ids"]
        mask = _action_mask({k: v[None] for k, v in a.items()})[0]

        behav_lp = a["packed_logprobs"].astype(jnp.float32)
        ref_lp = a.get("packed_ref_logprobs")
        if ref_lp is None:
            ref_lp = behav_lp  # zero KL penalty
        values = a.get("values")
        if values is None or hp.disable_value:
            values = jnp.zeros_like(behav_lp)
        values = values.astype(jnp.float32) * mask

        reward_score = (
            a["rewards"].astype(jnp.float32) * hp.reward_output_scaling
            + hp.reward_output_bias
        )
        no_eos = a.get("seq_no_eos_mask")
        if no_eos is None:
            no_eos = jnp.zeros_like(reward_score, dtype=bool)
        no_eos = no_eos.astype(bool)

        # KL-penalized dense rewards + task reward at the *last action* token
        ref_kl = behav_lp - ref_lp.astype(jnp.float32)
        ref_kl_mean = jnp.sum(jnp.where(mask, ref_kl, 0.0)) / jnp.maximum(
            mask.sum(), 1
        )
        kl_rw = jnp.where(mask, -self.kl_ctl.value * ref_kl, 0.0)
        nxt_mask = jnp.concatenate([mask[1:], jnp.zeros((1,), bool)])
        last_action = mask & ~nxt_mask
        score = jnp.clip(reward_score, -hp.max_reward_clip, hp.max_reward_clip)
        if hp.mask_no_eos_with_zero:
            score = jnp.where(no_eos, 0.0, score)
        rewards = kl_rw + jnp.where(last_action, score, 0.0)

        # next values: within the action span values[t+1]; at the last action,
        # bootstrap with the next token's value iff the sequence was truncated
        # (≈ cugae's seq_no_eos bootstrap).
        shifted_v = jnp.concatenate([values[1:], jnp.zeros((1,), values.dtype)])
        raw_v = a.get("values")
        if raw_v is not None and not hp.disable_value:
            shifted_raw = jnp.concatenate(
                [raw_v.astype(jnp.float32)[1:], jnp.zeros((1,), jnp.float32)]
            )
        else:
            shifted_raw = jnp.zeros_like(values)
        next_values = jnp.where(
            nxt_mask, shifted_v, jnp.where(no_eos, shifted_raw, 0.0)
        )

        adv, ret = ppo_ops.segment_gae(
            rewards, values, next_values, seg, hp.discount, hp.gae_lambda,
            mask=mask, not_end=nxt_mask,
        )
        if hp.group_adv_norm:
            adv = ppo_ops.group_normalization(
                adv, mask, a["item_ids"], num_groups=sample.bs
            )
        elif hp.adv_norm:
            adv = ppo_ops.masked_normalization(adv, mask)

        return self._attach(sample, pb, adv, ret, kl_rw, ref_kl_mean)

    def _attach(self, sample, pb, adv, ret, kl_rw, ref_kl_mean):
        # ONE device->host transfer for everything the host needs
        adv, ret, kl_rw, ref_kl_mean = jax.device_get(
            (adv, ret, kl_rw, ref_kl_mean)
        )
        self._last_ref_kl = float(ref_kl_mean)
        main = sample.main_key()
        seqlens = {"advantages": [list(l) for l in sample.seqlens[main]],
                   "returns": [list(l) for l in sample.seqlens[main]],
                   "kl_rewards": [list(l) for l in sample.seqlens[main]]}
        data = {}
        for key, arr in (("advantages", adv), ("returns", ret), ("kl_rewards", kl_rw)):
            per_seq = pb.unpack(np.asarray(arr)[None])
            data[key] = np.concatenate(per_seq).astype(np.float32)
        sample.update_(
            SequenceSample(
                keys=set(seqlens), ids=list(sample.ids), seqlens=seqlens, data=data
            )
        )
        return sample

    # -------------------------------------------------------------- #
    # train step
    # -------------------------------------------------------------- #

    def train_step(
        self, engine, sample: SequenceSample, mb_spec: MicroBatchSpec
    ) -> Dict[str, float]:
        hp = self.hp
        sample = self._prepare(sample)
        # engine.train_batch is collective: the minibatch COUNT must agree
        # across hosts even when per-host batch sizes differ (a starved host
        # with a partial batch must not run fewer collective calls)
        n_mb = int(
            multihost.allreduce_min(np.int64(min(hp.ppo_n_minibatches, sample.bs)))
        )
        mbs = sample.split(max(n_mb, 1))
        # pipelined minibatch loop: pack+put of minibatch n+1 overlaps the
        # in-flight jitted step for minibatch n (serial loop when
        # AREAL_TRAIN_PREFETCH is off). No host collectives may run between
        # these dispatches — ours (the kl_ctl allreduce) sit after the loop.
        all_stats = engine.train_batches_pipelined(
            mbs, mb_spec, self._actor_loss_fn, fetch_stats=False
        )
        engine.version += 1
        # minibatch-mean WITHOUT a device pull (deferred-stats path: the
        # trainer fetches once per logging interval, not per step)
        out = engine_mod.mean_stats_dicts(all_stats)
        # Adaptive KL control tracks policy-vs-reference divergence (the
        # signed masked mean over action tokens), like the reference
        # (ppo_interface.py:973-978) — NOT the PPO update KL. The update is
        # fed the GLOBAL mean so per-host controllers never drift apart.
        tot = multihost.allreduce_sum(
            np.asarray([self._last_ref_kl * sample.bs, sample.bs], np.float64)
        )
        ref_kl_global = float(tot[0] / max(tot[1], 1))
        self.kl_ctl.update(ref_kl_global, int(tot[1]))
        out["kl_ctl"] = self.kl_ctl.value
        out["ref_kl"] = ref_kl_global
        out["n_seqs"] = sample.bs
        if not engine_mod.train_prefetch_enabled():
            # legacy per-step blocking behavior for callers that asked for it
            out = engine_mod.fetch_stats_dict(out)
        return out


@dataclasses.dataclass
class PPOCriticInterface(ModelInterface):
    hp: PPOHyperparameters = dataclasses.field(default_factory=PPOHyperparameters)
    hf_family: Optional[str] = None
    # Share the ACTOR's controller here: with use_adaptive_kl the coefficient
    # adapts every actor step, and the critic's value targets must be shaped
    # with the same coefficient or they diverge from the actor's advantages
    # (the reference shares one kl_adapter, ``ppo_interface.py``).
    kl_ctl: Optional[object] = None

    def __post_init__(self):
        if self.kl_ctl is None:
            # standalone construction: mirror the actor's controller choice,
            # or adaptive-KL critics silently fall back to a fixed coefficient
            if self.hp.use_adaptive_kl:
                self.kl_ctl = ppo_ops.AdaptiveKLController(
                    self.hp.kl_ctl,
                    self.hp.adaptive_kl_target,
                    self.hp.adaptive_kl_horizon,
                )
            else:
                self.kl_ctl = ppo_ops.FixedKLController(self.hp.kl_ctl)
        self._actor_helper = PPOActorInterface(hp=self.hp)
        # the helper only runs _prepare (reward shaping + GAE); its KL
        # coefficient must track the shared controller, and its update()
        # must never fire (the actor owns updates)
        self._actor_helper.kl_ctl = self.kl_ctl
        hp = self.hp

        def critic_loss(params, cfg, arrays):
            mask = _action_mask(arrays)
            values, aux = vmapped_forward(params, cfg, arrays, with_aux=True)
            new_values = jnp.where(
                arrays["segment_ids"] > 0, values[..., 0], 0.0
            )
            loss, stat = ppo_ops.critic_loss_fn(
                new_values.reshape(-1),
                arrays["values"].astype(jnp.float32).reshape(-1),
                arrays["returns"].astype(jnp.float32).reshape(-1),
                hp.value_eps_clip,
                mask.reshape(-1),
            )
            n = jnp.maximum(mask.sum(), 1)
            return loss + aux, {
                "critic_loss": loss,
                "value_clip_ratio": jnp.sum(stat["clip_mask"]) / n,
            }

        self._critic_loss_fn = critic_loss

    def inference(
        self, engine, sample: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample:
        outs = engine.forward(sample, mb_spec, value_output_fn)
        main = sample.main_key()
        return SequenceSample(
            keys={"values"},
            ids=list(sample.ids),
            seqlens={"values": [list(l) for l in sample.seqlens[main]]},
            data={"values": np.concatenate([o.astype(np.float32) for o in outs])},
        )

    def train_step(
        self, engine, sample: SequenceSample, mb_spec: MicroBatchSpec
    ) -> Dict[str, float]:
        hp = self.hp
        sample = self._actor_helper._prepare(sample)
        n_mb = int(
            multihost.allreduce_min(np.int64(min(hp.ppo_n_minibatches, sample.bs)))
        )
        mbs = sample.split(max(n_mb, 1))
        all_stats = engine.train_batches_pipelined(
            mbs, mb_spec, self._critic_loss_fn, fetch_stats=False
        )
        engine.version += 1
        out = engine_mod.mean_stats_dicts(all_stats)
        if not engine_mod.train_prefetch_enabled():
            out = engine_mod.fetch_stats_dict(out)
        return out
