"""Algorithm interfaces (≈ ``realhf/impl/model/interface/``).

Importing this package registers all built-in interfaces, mirroring the
reference's ``realhf/impl/model/__init__.py:114`` registration pattern.
"""

from areal_tpu.api.model import register_interface
from areal_tpu.interfaces.sft import SFTInterface
from areal_tpu.interfaces.ppo import PPOActorInterface, PPOCriticInterface
from areal_tpu.interfaces.reward import PairedRewardInterface

register_interface("sft", SFTInterface)
register_interface("ppo_actor", PPOActorInterface)
register_interface("ppo_critic", PPOCriticInterface)
