"""Reward-model interface: Bradley-Terry pairwise training + sequence scoring.

Counterpart of the reference's paired reward modeling
(``realhf/impl/dataset/rw_paired_dataset.py`` consumer) and the RM-scoring
side of its reward interfaces (``math_rw_interface.py`` — there rule-based;
here the TRAINED-RM path VERDICT row §2.5 asks for). The model is a
critic-architecture transformer (``is_critic=True``: scalar head); a
sequence's score is the head output at its LAST token.

Training: ``-log sigmoid(s_pos - s_neg)`` over one-to-one pairs. Pairs are
matched inside jit with a scatter: every sequence carries ``pair_id`` (pair
index within its item) and ``pair_sign`` (+1 pos / -1 neg); signed scores
scatter-add into per-(item, pair) buckets, so a bucket holds exactly
``s_pos - s_neg`` for a complete pair — no host-side pair bookkeeping in
the hot path.
"""

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.api.model import ModelInterface, register_interface
from areal_tpu.ops import ppo as ppo_ops
from areal_tpu.train.engine import vmapped_forward


def score_output_fn(params, cfg, arrays):
    """Per-sequence scores written at segment-end positions, 0 elsewhere
    — unpacks into one trailing scalar per sequence."""
    values = vmapped_forward(params, cfg, arrays)[..., 0]
    is_end = jax.vmap(ppo_ops.is_segment_end)(arrays["segment_ids"])
    return jnp.where(is_end, values, 0.0)


@dataclasses.dataclass
class PairedRewardInterface(ModelInterface):
    hf_family: Optional[str] = None
    max_pairs_per_prompt: int = 8   # static bucket factor for pair matching

    def __post_init__(self):
        K = self.max_pairs_per_prompt

        def rw_loss(params, cfg, arrays):
            values = vmapped_forward(params, cfg, arrays)[..., 0]  # [D, T]
            seg = arrays["segment_ids"]
            is_end = jax.vmap(ppo_ops.is_segment_end)(seg)
            D, T = seg.shape
            bucket = (arrays["item_ids"] * K + arrays["pair_id"]).reshape(-1)
            signed = (
                values * arrays["pair_sign"].astype(jnp.float32)
            ).reshape(-1)
            endf = is_end.reshape(-1)
            n_buckets = D * T * K
            bucket = jnp.where(endf, bucket, n_buckets)       # dropped
            diffs = jnp.zeros((n_buckets,), jnp.float32).at[bucket].add(
                jnp.where(endf, signed, 0.0), mode="drop"
            )
            counts = jnp.zeros((n_buckets,), jnp.int32).at[bucket].add(
                jnp.where(endf, 1, 0), mode="drop"
            )
            complete = counts == 2                            # full pos/neg pair
            n = jnp.maximum(complete.sum(), 1)
            loss = jnp.sum(
                jnp.where(complete, -jax.nn.log_sigmoid(diffs), 0.0)
            ) / n
            acc = jnp.sum(jnp.where(complete, (diffs > 0).astype(jnp.float32), 0.0)) / n
            return loss, {
                "rw_loss": loss,
                "rw_acc": acc,
                "score_diff": jnp.sum(jnp.where(complete, diffs, 0.0)) / n,
            }

        self._rw_loss_fn = rw_loss

    # ------------------------------------------------------------------ #

    def train_step(
        self, engine, sample: SequenceSample, mb_spec: MicroBatchSpec
    ) -> Dict[str, float]:
        max_pid = int(np.max(sample.data["pair_id"])) if sample.data["pair_id"].size else 0
        if max_pid >= self.max_pairs_per_prompt:
            raise ValueError(
                f"pair_id {max_pid} >= max_pairs_per_prompt "
                f"{self.max_pairs_per_prompt}: bucket indices would collide "
                "across items, silently corrupting the pairwise loss — raise "
                "the interface's max_pairs_per_prompt"
            )

        def pair_weight(pb):
            # weight micro-batches by their COMPLETE pair count so grad
            # accumulation equals a global pair mean
            ends = {}
            for p in pb.placements:
                key = (p.item_idx, int(pb.arrays["pair_id"][p.row, p.start]))
                ends[key] = ends.get(key, 0) + 1
            return float(sum(1 for v in ends.values() if v == 2))

        stats = engine.train_batch(
            sample, mb_spec, self._rw_loss_fn, loss_weight_fn=pair_weight
        )
        engine.version += 1
        return stats

    def inference(
        self, engine, sample: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample:
        """Score sequences: one scalar reward per sequence (the RM-scored
        rollout path — plugs into the PPO graph as a ``reward_inf`` node)."""
        outs = engine.forward(sample, mb_spec, score_output_fn)
        scores = np.asarray([float(o.sum()) for o in outs], np.float32)
        main = sample.main_key()
        n_per_item = [len(l) for l in sample.seqlens[main]]
        return SequenceSample(
            keys={"rewards"},
            ids=list(sample.ids),
            seqlens={"rewards": [[1] * n for n in n_per_item]},
            data={"rewards": scores},
        )

    def evaluate(self, engine, eval_samples) -> Dict[str, float]:
        # weight each eval batch by its PAIR count (the loss is a pair mean;
        # token-weighted averaging would skew toward long sequences)
        tot, n = 0.0, 0
        for s in eval_samples:
            r = engine.eval_batch(s, MicroBatchSpec(), self._rw_loss_fn)
            pairs = sum(len(inner) for inner in s.seqlens[s.main_key()]) // 2
            tot += r["loss"] * pairs
            n += pairs
        return {"loss": tot / max(n, 1)} if n else {}


register_interface("reward", PairedRewardInterface)
