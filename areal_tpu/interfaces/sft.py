"""Supervised fine-tuning interface.

TPU-native counterpart of ``realhf/impl/model/interface/sft_interface.py``
(146 LoC): next-token cross-entropy over non-prompt tokens of packed
sequences.
"""

import dataclasses
from typing import Dict

import jax
import jax.numpy as jnp

from areal_tpu.api.data import MicroBatchSpec, SequenceSample
from areal_tpu.api.model import ModelInterface
from areal_tpu.ops import ppo as ppo_ops
from areal_tpu.train.engine import (
    vmapped_forward,
    vmapped_next_token_logprobs,
)


def sft_loss_fn(params, cfg, arrays):
    """-mean log p(next token) over answer tokens (prompt_mask==0).
    ``cfg.loss_chunk_size`` routes through the chunked LM-head path — the
    [T, vocab] logits never materialize."""
    lp, aux = vmapped_next_token_logprobs(params, cfg, arrays, with_aux=True)
    seg = arrays["segment_ids"]
    has_next = (seg > 0) & ~jax.vmap(ppo_ops.is_segment_end)(seg)
    mask = has_next
    if "prompt_mask" in arrays:
        # the label of position t is token t+1: mask positions whose *label*
        # is still part of the prompt
        pm = arrays["prompt_mask"].astype(bool)
        label_is_prompt = jnp.concatenate(
            [pm[:, 1:], jnp.zeros_like(pm[:, :1])], axis=1
        )
        mask = mask & ~label_is_prompt
    n = jnp.maximum(mask.sum(), 1)
    loss = -jnp.sum(jnp.where(mask, lp, 0.0)) / n
    return loss + aux, {
        "ppl": jnp.exp(loss),
        "n_tokens": n.astype(jnp.float32),
    }


@dataclasses.dataclass
class SFTInterface(ModelInterface):
    token_normalize_scope: str = "global"

    def train_step(
        self, engine, sample: SequenceSample, mb_spec: MicroBatchSpec
    ) -> Dict[str, float]:
        stats = engine.train_batch(sample, mb_spec, sft_loss_fn)
        return stats

    def evaluate(self, engine, eval_samples) -> Dict[str, float]:
        tot, n = 0.0, 0
        for s in eval_samples:
            r = engine.eval_batch(s, MicroBatchSpec(), sft_loss_fn)
            tot += r["loss"]
            n += 1
        return {"loss": tot / max(n, 1)} if n else {}
