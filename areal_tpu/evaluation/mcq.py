"""Multiple-choice grading (gpqa_diamond).

The gpqa prompt (``evaluation/utils.py:187``) asks for the bare choice
letter in ``\\boxed{}``; gold is the letter (``answer`` field of
``evaluation/data/gpqa_diamond/test.jsonl``). Models still emit variants —
``\\boxed{(D)}``, ``\\boxed{D. 10^-4 ev}``, a trailing "D" with no box — so
extraction mirrors the reference's choice-parsing tail
(``evaluation/parser.py:630-660``): prefer the boxed payload, fall back to
the last standalone choice letter in the text.
"""

import re

_CHOICE = re.compile(r"\b([A-E])\b")


def extract_choice(text: str) -> str:
    """Best-effort choice letter from a model answer ('' if none)."""
    from areal_tpu.rewards.math_verify import extract_answer

    boxed = extract_answer(text, use_last_number=False)
    if boxed:
        m = _CHOICE.search(boxed.strip().strip("()."))
        if m:
            return m.group(1)
        # boxed but no letter inside (e.g. the option text itself): keep
        # searching the payload for a leading "A." style label
        m = re.match(r"\s*\(?([A-E])\)?[.:\s]", boxed)
        if m:
            return m.group(1)
    matches = _CHOICE.findall(text)
    return matches[-1] if matches else ""


def grade_choice(answer: str, gold: str) -> float:
    got = extract_choice(answer)
    return 1.0 if got and got.upper() == gold.strip().upper() else 0.0
