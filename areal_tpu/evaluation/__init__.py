"""Offline evaluation assets: bundled benchmark data, prompt templates,
per-benchmark loaders, and the process-pool grader.

Counterpart of the reference's ``evaluation/`` harness
(``evaluation/eval_and_aggregate.py``, ``evaluation/data_loader.py``,
``evaluation/utils.py``): the five headline benchmarks ship with the
package so ``eval_offline --benchmark aime24`` works standalone.
"""

from areal_tpu.evaluation.benchmarks import (  # noqa: F401
    BENCHMARKS,
    benchmark_names,
    load_benchmark,
    write_benchmark_jsonl,
)
from areal_tpu.evaluation.grading import PoolGrader  # noqa: F401
