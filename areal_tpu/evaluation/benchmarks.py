"""Bundled benchmark registry: data files, prompt templates, gold answers.

The five headline benchmarks the reference evaluates
(``evaluation/eval_and_aggregate.py``, data under ``evaluation/data/``):
aime24, aime25, amc23, gpqa_diamond, math_500. The raw ``test.jsonl``
files are public benchmark data vendored unchanged under
``areal_tpu/evaluation/data/<name>/test.jsonl``.

Each loader normalizes a heterogeneous record schema to::

    {"query_id": str, "prompt": str,      # templated, ready to tokenize
     "task": "math" | "gpqa",
     "solutions": [gold answer string]}

which is exactly the shape ``datasets/prompt.py`` (MathCodePromptDataset)
and the offline harness consume.

Prompt templates are the reference's fixed evaluation prompts
(``evaluation/utils.py:170-191``, keys ``r1-distilled-qwen`` and
``r1-distilled-qwen-gpqa``) — a fixed external protocol string, kept
byte-identical so scores are comparable.
"""

import json
import os
from typing import Dict, List, Optional

_DATA_DIR = os.path.join(os.path.dirname(__file__), "data")

# evaluation/utils.py:170 ("r1-distilled-qwen"): reason step by step,
# boxed final answer, assistant turn pre-opened with <think>.
R1_DISTILL_MATH = (
    "<｜User｜>{input}\nPlease reason step by step, and put your final "
    "answer within \\boxed{{}}.<｜Assistant｜><think>\n"
)
# evaluation/utils.py:187 ("r1-distilled-qwen-gpqa"): boxed choice letter.
R1_DISTILL_GPQA = (
    "<｜User｜>{input}\nPlease reason step-by-step and put your choice "
    "letter without any other text with \\boxed{{}} in the end."
    "<｜Assistant｜><think>\n"
)
# evaluation/utils.py ("qwen25-math-cot" family): a chat-format variant for
# Qwen-instruct checkpoints evaluated without the R1 distill markers.
QWEN_CHAT_MATH = (
    "<|im_start|>system\nPlease reason step by step, and put your final "
    "answer within \\boxed{{}}.<|im_end|>\n<|im_start|>user\n{input}"
    "<|im_end|>\n<|im_start|>assistant\n"
)

TEMPLATES = {
    "r1-distilled-qwen": R1_DISTILL_MATH,
    "r1-distilled-qwen-gpqa": R1_DISTILL_GPQA,
    "qwen25-math-cot": QWEN_CHAT_MATH,
}


class BenchmarkSpec:
    """One bundled benchmark: where its data lives and how to present it."""

    def __init__(self, name, n_items, task="math",
                 template="r1-distilled-qwen", question_keys=("question",
                 "problem"), answer_key="answer", default_max_gen=32768):
        self.name = name
        self.n_items = n_items          # sanity check against the data file
        self.task = task
        self.template = template
        self.question_keys = question_keys
        self.answer_key = answer_key
        # eval_and_aggregate.py defaults --max_gen_tokens 32768
        self.default_max_gen = default_max_gen

    def path(self) -> str:
        return os.path.join(_DATA_DIR, self.name, "test.jsonl")


BENCHMARKS: Dict[str, BenchmarkSpec] = {
    "aime24": BenchmarkSpec("aime24", 30),
    "aime25": BenchmarkSpec("aime25", 30),
    "amc23": BenchmarkSpec("amc23", 40),
    # gpqa "question" already carries the A-D labeled options; gold is the
    # choice letter (``answer``), graded by boxed-letter equality
    "gpqa_diamond": BenchmarkSpec(
        "gpqa_diamond", 198, task="gpqa", template="r1-distilled-qwen-gpqa"
    ),
    "math_500": BenchmarkSpec("math_500", 500),
}


def benchmark_names() -> List[str]:
    return list(BENCHMARKS)


def load_benchmark(
    name: str, template: Optional[str] = None, max_items: Optional[int] = None
) -> List[dict]:
    """Read the bundled data, apply the prompt template, normalize."""
    spec = BENCHMARKS[name]
    tmpl = TEMPLATES[template] if template else TEMPLATES[spec.template]
    out = []
    with open(spec.path()) as f:
        for i, line in enumerate(f):
            if max_items is not None and i >= max_items:
                break
            rec = json.loads(line)
            q = next(
                (rec[k] for k in spec.question_keys if rec.get(k)), None
            )
            if q is None:
                raise ValueError(f"{name} record {i}: no question field")
            gold = str(rec[spec.answer_key])
            out.append({
                "query_id": f"{name}-{rec.get('id', rec.get('unique_id', i))}",
                "prompt": tmpl.format(input=str(q).strip()),
                "task": spec.task,
                "solutions": [gold],
            })
    if max_items is None and len(out) != spec.n_items:
        raise ValueError(
            f"{name}: expected {spec.n_items} items, found {len(out)} — "
            "bundled data file corrupted?"
        )
    return out


def write_benchmark_jsonl(
    name: str, out_path: str, template: Optional[str] = None,
    max_items: Optional[int] = None,
) -> str:
    """Materialize a benchmark as a prompt-dataset jsonl for the harness."""
    records = load_benchmark(name, template=template, max_items=max_items)
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        for r in records:
            f.write(json.dumps(r, ensure_ascii=False) + "\n")
    return out_path
