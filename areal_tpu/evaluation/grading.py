"""Process-pool grading with per-item timeouts.

Counterpart of the reference's pebble ``ProcessPool(max_workers=...).map(
math_equal_process, params, timeout=3)`` loop
(``evaluation/evaluate.py:44-86``): sympy equivalence checks can hang on
adversarial model output, so each (answer, gold) comparison runs in a
worker PROCESS that can be killed on deadline — a thread pool or in-process
grading cannot be interrupted mid-sympy. pebble isn't in this image, so the
pool is built directly on ``multiprocessing``: N persistent workers pull
items from a queue; a worker that blows its deadline is terminated and
respawned, and the item scores as a WRONG answer for its task
(``failure_score``: -1 for math/code, 0 for gpqa — matching the
in-process graders' conventions), counted in ``timeout_cnt`` (the
reference's ``timeout_samples``).
"""

import logging
import multiprocessing as mp
import os
import queue as queue_mod
import time
from typing import Callable, List, Optional, Sequence, Tuple

logger = logging.getLogger("areal_tpu.evaluation.grading")


def grade_gpqa_answer(answer: str, gold_or_meta) -> float:
    """The gpqa grading convention in ONE place (first gold of a solutions
    list, stringified, through ``mcq.grade_choice``) — the env and the pool
    must agree or the same samples report different reward_mean."""
    from areal_tpu.evaluation.mcq import grade_choice

    gold = gold_or_meta
    if isinstance(gold, list):
        gold = gold[0] if gold else ""
    return grade_choice(answer, str(gold))


def _default_grade_one(task: str, answer: str, gold_or_meta) -> float:
    if task == "code":
        from areal_tpu.rewards.code_verify import verify_code_solution

        return 1.0 if verify_code_solution(answer, gold_or_meta or {}) else -1.0
    if task == "gpqa":
        return grade_gpqa_answer(answer, gold_or_meta)
    from areal_tpu.rewards.math_verify import grade_math_answers

    golds = gold_or_meta if isinstance(gold_or_meta, list) else [gold_or_meta]
    return grade_math_answers([answer], [str(g) for g in golds])[0]


def failure_score(task: str) -> float:
    """Score for a timed-out or crashed comparison — must match the
    wrong-answer convention of that task's grader (math/code grade wrong
    answers -1.0, gpqa 0.0), or pooled and in-process runs of the same
    samples report different reward_mean."""
    return 0.0 if task == "gpqa" else -1.0


def _worker(inq, outq, grade_one):
    # one item per message; the parent enforces the deadline and kills us if
    # sympy wedges, so no in-worker alarm machinery is needed. Warm the
    # heavy grader imports BEFORE taking items so the first item's deadline
    # measures grading, not ~1s of sympy import.
    try:
        import areal_tpu.rewards.math_verify  # noqa: F401
    except Exception:
        pass
    while True:
        msg = inq.get()
        if msg is None:
            return
        idx, task, answer, gold = msg
        # deadline starts when work starts, not when the item was queued —
        # spawn-context worker startup must not count against it
        outq.put(("start", idx))
        try:
            score = float(grade_one(task, answer, gold))
        except Exception as e:  # grader crash = wrong answer, not a crash
            logger.debug("grader error on item %d: %r", idx, e)
            score = failure_score(task)
        outq.put(("done", idx, score))


class PoolGrader:
    """Grade (task, answer, gold) triples in worker processes.

    ``grade(items)`` preserves input order; timed-out or crashed items
    score ``failure_score(task)`` (the task's wrong-answer value). Workers
    are persistent across calls (sympy import is ~1s); a killed worker is
    respawned lazily.
    """

    def __init__(
        self,
        n_workers: Optional[int] = None,
        timeout_s: float = 3.0,  # the reference's per-item deadline
        grade_one: Callable = _default_grade_one,
        code_timeout_s: float = 70.0,
    ):
        self.n_workers = n_workers or min(8, os.cpu_count() or 1)
        self.timeout_s = timeout_s
        # code verification legitimately runs several subprocess test cases
        # (up to ~8 x 8 s in code_verify.py) — the sympy deadline would
        # kill CORRECT solutions, so code items get their own budget
        self.code_timeout_s = max(code_timeout_s, timeout_s)
        self.grade_one = grade_one
        self.timeout_cnt = 0
        self._ctx = mp.get_context("spawn")  # never fork a JAX parent
        self._procs: List = []
        self._chans: List[Tuple] = []  # (inq, outq) per worker

    def _spawn(self, i):
        inq = self._ctx.Queue()
        outq = self._ctx.Queue()
        p = self._ctx.Process(
            target=_worker, args=(inq, outq, self.grade_one), daemon=True
        )
        p.start()
        self._procs[i] = p
        self._chans[i] = (inq, outq)

    def _ensure_workers(self, n):
        while len(self._procs) < n:
            self._procs.append(None)
            self._chans.append(None)
        for i in range(n):
            if self._procs[i] is None or not self._procs[i].is_alive():
                self._spawn(i)

    def grade(self, items: Sequence[Tuple[str, str, object]]) -> List[float]:
        n_workers = min(self.n_workers, max(len(items), 1))
        self._ensure_workers(n_workers)
        scores = [0.0] * len(items)
        pending = list(enumerate(items))  # (idx, item), FIFO
        busy = {}  # worker i -> (idx, deadline)

        SPAWN_ALLOWANCE = 120.0  # worker cold-start (interpreter + imports)

        def item_timeout(idx):
            task = items[idx][0]
            return self.code_timeout_s if task == "code" else self.timeout_s

        def dispatch(i):
            if not pending:
                return
            idx, (task, answer, gold) = pending.pop(0)
            self._chans[i][0].put((idx, task, answer, gold))
            # provisional deadline covers spawn; tightens to the item's
            # budget once the worker reports it has BEGUN this item
            busy[i] = (idx, time.monotonic() + item_timeout(idx)
                       + SPAWN_ALLOWANCE)

        for i in range(n_workers):
            dispatch(i)
        while busy:
            now = time.monotonic()
            progressed = False
            for i in list(busy):
                idx, deadline = busy[i]
                try:
                    msg = self._chans[i][1].get_nowait()
                except queue_mod.Empty:
                    proc = self._procs[i]
                    died = proc is not None and not proc.is_alive()
                    if now > deadline or died:
                        # wedged (deadline) or CRASHED (segfault/OOM-kill —
                        # detected immediately, not after the provisional
                        # spawn allowance): score as a wrong answer,
                        # respawn lazily
                        scores[idx] = failure_score(items[idx][0])
                        self.timeout_cnt += 1
                        logger.warning(
                            "grading item %d %s", idx,
                            "worker died" if died else
                            f"timed out after {item_timeout(idx):.1f}s",
                        )
                        if proc is not None and proc.is_alive():
                            proc.terminate()
                        if proc is not None:
                            proc.join(1.0)
                        self._procs[i] = None
                        del busy[i]
                        self._ensure_workers(n_workers)
                        dispatch(i)
                        progressed = True
                    continue
                if msg[0] == "start":
                    if msg[1] == idx:
                        busy[i] = (
                            idx, time.monotonic() + item_timeout(idx)
                        )
                    progressed = True
                    continue
                _, ridx, score = msg
                if ridx != idx:
                    # stale 'done' (an item already scored as timed out on
                    # this channel): the worker is STILL grading `idx` —
                    # freeing it here would strand `idx` at its 0.0
                    # placeholder forever instead of letting the deadline
                    # path record failure_score(task) for it
                    logger.warning(
                        "grading: dropped stale result for item %d "
                        "(worker %d is grading item %d)", ridx, i, idx,
                    )
                    progressed = True
                    continue
                scores[ridx] = score
                del busy[i]
                dispatch(i)
                progressed = True
            if not progressed:
                time.sleep(0.005)
        return scores

    def close(self):
        for i, p in enumerate(self._procs):
            if p is not None and p.is_alive():
                try:
                    self._chans[i][0].put(None)
                except Exception:
                    pass
        deadline = time.monotonic() + 2.0
        for p in self._procs:
            if p is not None:
                p.join(max(deadline - time.monotonic(), 0.1))
                if p.is_alive():
                    p.terminate()
        self._procs, self._chans = [], []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
