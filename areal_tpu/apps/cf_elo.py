"""Codeforces ELO estimation for offline evaluation.

Counterpart of ``evaluation/cf_elo_caculator.py`` (344 LoC): given
per-problem pass/fail attempts for problems drawn from real contests, plus
*cached* contest standings/rating-change data (the reference downloads and
caches the same shapes from the Codeforces API; zero-egress here, so the
cache files are an input), estimate the model's equivalent rating per
contest by the expected-seed inversion, then aggregate to a percentile
against a rating population.

Data shapes (identical to the reference's cache):
- standings: ``{"result": {"rows": [{"party": {"members": [{"handle"}]},
  "points", "penalty"}...], "problems": [{"contestId", "index",
  "points"?}...]}}``
- rating_changes: ``{"result": [{"handle", "oldRating"}...]}``
"""

import bisect
import json
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

MIN_PARTICIPANTS = 200  # reference drops contests with <=200 common handles


def expected_seed(rating: float, old_ratings: Sequence[float]) -> float:
    """1 + Σ_i P(participant i beats `rating`) — the ELO expected rank."""
    return 1.0 + sum(
        1.0 / (1.0 + 10.0 ** ((rating - r) / 400.0)) for r in old_ratings
    )


def rating_for_rank(
    rank: int, old_ratings: Sequence[float], max_rating: float
) -> int:
    """Largest integer rating whose expected seed is still >= rank
    (binary search, reference ``calc_elo_rating_offline:148-160``)."""
    lo, hi = 0, int(max_rating) + 100
    while hi - lo > 1:
        mid = (lo + hi) // 2
        if expected_seed(mid, old_ratings) < rank:
            hi = mid
        else:
            lo = mid
    return lo


def contest_score(
    problem_status: Dict[str, List[bool]],
    problems: List[dict],
    pass_n: Optional[int] = None,
) -> Tuple[float, float]:
    """(score, penalty) under the reference's submission model: the first
    passing attempt among the first ``pass_n`` counts, losing 50 points per
    earlier failed attempt (scored contests) or adding 10 penalty per failed
    attempt (ICPC-style contests without per-problem points)."""
    score = 0.0
    penalty = 0.0
    for problem in problems:
        prob = f"{problem['contestId']}{problem['index']}"
        attempts = problem_status.get(prob)
        if not attempts:
            continue
        n = len(attempts) if pass_n is None else pass_n
        for ith, status in enumerate(attempts[:n]):
            if status:
                if "points" in problem:
                    score += max(0.0, problem["points"] - 50.0 * ith)
                else:
                    score += 1.0
                    penalty += ith * 10.0
                break
    return score, penalty


def rank_in_standings(rows: List[dict], score: float, penalty: float) -> int:
    """Row index of the first standing strictly beaten by (score, penalty) —
    the reference's 0-based convention (``cf_elo_caculator.py:139-145``:
    ``rank = i``, default ``n``), kept bit-compatible so estimated ratings
    agree seed-for-seed."""
    for i, row in enumerate(rows):
        if row["points"] < score or (
            row["points"] == score and row["penalty"] > penalty
        ):
            return i
    return len(rows)


def calc_contest_elo(
    standings: dict,
    rating_changes: dict,
    problem_status: Dict[str, List[bool]],
    pass_n: Optional[int] = None,
) -> Optional[int]:
    """Equivalent rating for one contest, or None when the cached data is
    unusable (mismatched handles / too few participants — reference
    semantics)."""
    try:
        rows = standings["result"]["rows"]
        changes = rating_changes["result"]
        by_handle = {c["handle"]: c for c in changes}
        rows = [
            r for r in rows if r["party"]["members"][0]["handle"] in by_handle
        ]
        changes = [
            by_handle[r["party"]["members"][0]["handle"]] for r in rows
        ]
        if len(rows) <= MIN_PARTICIPANTS:
            return None
        old_ratings = [c["oldRating"] for c in changes]
        score, penalty = contest_score(
            problem_status, standings["result"]["problems"], pass_n
        )
        rank = rank_in_standings(rows, score, penalty)
        return rating_for_rank(rank, old_ratings, max(old_ratings))
    except (KeyError, IndexError, TypeError):
        return None


def percentile(rating: float, sorted_ratings: Sequence[float]) -> float:
    """Fraction of the population strictly below ``rating``."""
    if not sorted_ratings:
        return 0.0
    return bisect.bisect_left(list(sorted_ratings), rating) / len(sorted_ratings)


def calculate_cf_elo(
    submissions: Dict[str, List[bool]],
    cache_dir: str,
    ratings_path: Optional[str] = None,
    pass_n: Optional[int] = None,
) -> Dict[str, float]:
    """Aggregate ELO over every contest with cached data.

    ``submissions``: problem id (e.g. ``"1700A"``) -> pass/fail attempts.
    ``cache_dir``: per-contest JSON files ``{contest_id}.json`` holding
    ``{"standings": ..., "rating_changes": ...}``.
    ``ratings_path``: newline-separated rating population for percentile.
    """
    by_contest: Dict[int, Dict[str, List[bool]]] = {}
    for prob, attempts in submissions.items():
        # contest id = the LEADING digit run ("1700A1" -> 1700; indices may
        # contain digits); keys without one are malformed — skip, don't abort
        m = re.match(r"\d+", prob)
        if not m:
            continue
        by_contest.setdefault(int(m.group()), {})[prob] = attempts

    ratings: List[int] = []
    for cid, status in sorted(by_contest.items()):
        path = os.path.join(cache_dir, f"{cid}.json")
        if not os.path.exists(path):
            continue
        try:
            with open(path) as f:
                cached = json.load(f)
            r = calc_contest_elo(
                cached["standings"], cached["rating_changes"], status, pass_n
            )
        except (json.JSONDecodeError, KeyError, OSError):
            # a corrupt cache file skips this contest; per-contest shape
            # errors one level deeper already do the same
            continue
        if r is not None:
            ratings.append(r)

    # keys are unconditional so metric consumers never KeyError; 0.0 is the
    # no-usable-contest sentinel (n_contests disambiguates)
    out: Dict[str, float] = {
        "n_contests": float(len(ratings)),
        "elo": 0.0,
        "percentile": 0.0,
    }
    if ratings:
        est = sum(ratings) / len(ratings)
        out["elo"] = est
        if ratings_path and os.path.exists(ratings_path):
            with open(ratings_path) as f:
                pop = sorted(float(x) for x in f.read().split() if x.strip())
            out["percentile"] = percentile(est, pop)
    return out
