"""CLI entry point.

Counterpart of ``realhf/apps/main.py`` + the ``training/main_*.py`` scripts:

    python -m areal_tpu.apps.main sft --config cfg.yaml model.path=... control.total_train_steps=100
    python -m areal_tpu.apps.main async-ppo --config cfg.yaml actor.path=...
"""

import argparse
import logging
import sys


def main(argv=None):
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s: %(message)s",
    )
    parser = argparse.ArgumentParser(prog="areal_tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)
    for cmd in ("sft", "async-ppo", "sync-ppo", "rw"):
        p = sub.add_parser(cmd)
        p.add_argument("--config", default=None, help="YAML config path")
        p.add_argument(
            "overrides", nargs="*", help="dotted overrides, e.g. a.b=1"
        )
    sub.add_parser(
        "profile",
        description="timed train steps on synthetic data (see apps/profile.py)",
    )
    # profile owns its full argument surface (apps/profile.py): parse only
    # the subcommand here and forward the rest
    args, rest = parser.parse_known_args(argv)
    if args.cmd == "profile":
        from areal_tpu.apps.profile import main as profile_main

        return profile_main(rest)
    if rest:  # only profile forwards unknown args
        parser.error(f"unrecognized arguments: {' '.join(rest)}")

    from areal_tpu.apps import launcher
    from areal_tpu.experiments import (
        AsyncPPOExperiment,
        RWExperiment,
        SFTExperiment,
        SyncPPOExperiment,
        load_config,
    )

    if args.cmd == "sft":
        cfg = load_config(SFTExperiment, args.config, args.overrides)
        return launcher.run_sft(cfg)
    if args.cmd == "rw":
        cfg = load_config(RWExperiment, args.config, args.overrides)
        return launcher.run_rw(cfg)
    if args.cmd == "sync-ppo":
        cfg = load_config(SyncPPOExperiment, args.config, args.overrides)
        return launcher.run_sync_ppo(cfg)
    cfg = load_config(AsyncPPOExperiment, args.config, args.overrides)
    return launcher.run_async_ppo(cfg)


if __name__ == "__main__":
    sys.exit(main())
