"""Entry points & launchers (≈ ``realhf/apps`` + ``realhf/scheduler``)."""
