"""Live fleet-observability CLI (docs/observability.md).

Tails the telemetry plane of a running (or just-finished) trial and renders
a per-worker table plus the merged fleet view — staleness/latency
percentiles, fleet-total counters, and per-server breaker states — straight
from the per-worker snapshots the exporters publish through name_resolve.
No trainer involvement: this reads the same channel the trainer's ``fleet/``
jsonl record is built from.

Usage::

    python -m areal_tpu.apps.obs <fileroot> [--experiment E --trial T]
        [--once] [--interval 2.0] [--json] [--trace <request-id|qid>]

``<fileroot>`` is the experiment fileroot (the launcher's ``fileroot``
config); the file-backed name_resolve lives under ``<fileroot>/
name_resolve``. Without ``--experiment/--trial`` the trial with the newest
snapshot is picked. ``--once`` renders a single frame (scripts/tests);
the default loops until Ctrl-C. Workers only publish when
``AREAL_TELEMETRY_EXPORT`` is enabled on the trial.

``--trace`` switches to the distributed-tracing view
(docs/observability.md "Distributed tracing"): it joins the per-worker
span flushes under ``<fileroot>/trace_spans/`` and renders one request's
span tree. The needle may be a trace id (or ≥8-char prefix), a gateway
request id (``gw-<16hex>``), or an RL ``qid``.
"""

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

from areal_tpu.base import name_resolve, names
from areal_tpu.system import telemetry, tracejoin


def _configure_name_resolve(fileroot: str):
    name_resolve.reconfigure(
        name_resolve.NameResolveConfig(
            type="file", root=os.path.join(fileroot, "name_resolve")
        )
    )


def discover_trials() -> List[Tuple[str, str, float]]:
    """(experiment, trial, newest-snapshot-time) for every trial with
    published telemetry."""
    try:
        keys = name_resolve.find_subtree(names.ROOT)
    except name_resolve.NameEntryNotFoundError:
        return []
    newest: Dict[Tuple[str, str], float] = {}
    for k in keys:
        parts = k.split("/")
        # areal_tpu/<exp>/<trial>/telemetry/<worker...>
        if len(parts) >= 5 and parts[0] == names.ROOT and parts[3] == "telemetry":
            exp, trial = parts[1], parts[2]
            t = newest.get((exp, trial), 0.0)
            try:
                snap = json.loads(name_resolve.get(k))
                t = max(t, float(snap.get("time", 0.0)))
            except Exception:
                pass
            newest[(exp, trial)] = t
    return sorted(
        [(e, t, ts) for (e, t), ts in newest.items()], key=lambda r: -r[2]
    )


def _fmt(v: float) -> str:
    if v == 0:
        return "0"
    if abs(v) >= 1000:
        return f"{v:,.0f}"
    if abs(v) >= 1:
        return f"{v:.2f}".rstrip("0").rstrip(".")
    return f"{v:.4f}".rstrip("0").rstrip(".")


_ROLE_HEADLINE = {
    # role -> (label, counter key) shown in the per-worker "work" column
    "trainer": ("steps", "train/steps"),
    "rollout": ("pushed", "rollout/pushed"),
    "gen_server": ("served", "gen/served"),
    "manager": ("scheduled", "manager/schedule_requests"),
    "gateway": ("completed", "gw/completed"),
    # elastic world supervisor (docs/fault_tolerance.md "Elastic
    # multihost"): rank relaunches headline the recovery activity; its
    # step gauge is the current world epoch
    "supervisor": ("restarts", "ft/rank_restarts"),
}


def render(agg: "telemetry.FleetAggregate", now: Optional[float] = None) -> str:
    now = time.time() if now is None else now
    lines: List[str] = []
    w_col = max([len("worker")] + [len(w["worker"]) for w in agg.workers])
    lines.append(
        f"{'worker':<{w_col}}  {'role':<10} {'pid':>7} {'step':>7} "
        f"{'age_s':>6}  {'work':>16}  {'ft_total':>8}  longest open span"
    )
    for w in agg.workers:
        label, key = _ROLE_HEADLINE.get(w["role"], ("-", None))
        work = (
            f"{label}={_fmt(w['counters'].get(key, 0.0))}"
            if key is not None
            else "-"
        )
        ft_total = sum(
            v for k, v in w["counters"].items() if k.startswith("ft/")
        )
        spans = sorted(
            w.get("spans") or [], key=lambda s: -s.get("elapsed_s", 0.0)
        )
        span = (
            f"{spans[0]['name']} ({spans[0]['elapsed_s']:.1f}s)"
            if spans
            else "-"
        )
        lines.append(
            f"{w['worker']:<{w_col}}  {w['role']:<10} "
            f"{w.get('pid') or '-':>7} {w['step']:>7} "
            f"{max(now - w['time'], 0.0):>6.1f}  {work:>16}  "
            f"{_fmt(ft_total):>8}  {span}"
        )
    if agg.server_states:
        lines.append("")
        lines.append("gen-server breakers:")
        for url, state in sorted(agg.server_states.items()):
            lines.append(f"  {url:<40} {state}")
    drafted = agg.counters.get("gen/spec_draft_tokens", 0.0)
    if drafted:
        # speculative-decoding fleet summary: realized accept rate (the
        # breakeven signal /spec_decode acts on) plus the draft-model
        # pool bytes when a TransformerDrafter is serving
        accepted = agg.counters.get("gen/spec_accepted_tokens", 0.0)
        draft_bytes = sum(
            (w.get("gauges") or {}).get("draft_kv_pool_bytes", 0.0)
            for w in agg.workers
        )
        row = (
            f"spec decode: drafted={_fmt(drafted)} "
            f"accepted={_fmt(accepted)} "
            f"accept_rate={accepted / max(drafted, 1.0):.3f}"
        )
        if draft_bytes:
            row += f"  draft_kv_pool={draft_bytes / 2**20:.1f}MiB"
        lines.append("")
        lines.append(row)
    if agg.histograms:
        lines.append("")
        lines.append(
            f"{'distribution':<22} {'count':>8} {'mean':>10} {'p50':>10} "
            f"{'p95':>10} {'p99':>10} {'max':>10}"
        )
        for name in sorted(agg.histograms):
            s = agg.histograms[name].summary()
            if not s.get("count"):
                continue
            lines.append(
                f"{name:<22} {int(s['count']):>8} {_fmt(s['mean']):>10} "
                f"{_fmt(s['p50']):>10} {_fmt(s['p95']):>10} "
                f"{_fmt(s['p99']):>10} {_fmt(s['max']):>10}"
            )
    nonzero = {
        k: v
        for k, v in sorted(agg.counters.items())
        if v and agg.kinds.get(k) != "histogram"
    }
    if nonzero:
        lines.append("")
        lines.append("fleet totals (nonzero):")
        for k, v in nonzero.items():
            lines.append(f"  {k:<40} {_fmt(v)}")
    return "\n".join(lines)


def render_frame(experiment: str, trial: str, as_json: bool) -> Optional[str]:
    snaps = telemetry.collect_snapshots(experiment, trial)
    if not snaps:
        return None
    agg = telemetry.aggregate(snaps)
    if as_json:
        return json.dumps(agg.scalars(), sort_keys=True)
    header = (
        f"trial {experiment}/{trial} — {len(agg.workers)} workers, "
        f"{time.strftime('%H:%M:%S')}"
    )
    return header + "\n" + render(agg)


def render_trace(fileroot: str, needle: str) -> Optional[str]:
    """The ``--trace`` view: resolve the needle against the flushed spans
    and render the request's span tree (None when nothing matches)."""
    spans = tracejoin.scan(fileroot)
    trace_id = tracejoin.resolve_trace_id(spans, needle)
    if trace_id is None:
        return None
    return tracejoin.render_tree(spans, trace_id)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="areal_tpu.apps.obs", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("fileroot", help="experiment fileroot (launcher config)")
    p.add_argument("--experiment", default=None)
    p.add_argument("--trial", default=None)
    p.add_argument("--once", action="store_true", help="render one frame")
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the flat fleet/ scalar dict as JSON")
    p.add_argument("--trace", default=None, metavar="ID",
                   help="render one request's span tree: trace id (or "
                        "prefix), gateway rid, or RL qid")
    args = p.parse_args(argv)

    if args.trace is not None:
        tree = render_trace(args.fileroot, args.trace)
        if tree is None:
            print(
                f"no trace matches {args.trace!r} under "
                f"{args.fileroot}/trace_spans — are span flushes enabled "
                "(AREAL_TRACE_SPANS) and has a flush interval elapsed?",
                file=sys.stderr,
            )
            return 1
        print(tree)
        return 0

    _configure_name_resolve(args.fileroot)
    experiment, trial = args.experiment, args.trial
    if experiment is None or trial is None:
        trials = discover_trials()
        if not trials:
            print(
                "no telemetry published under "
                f"{args.fileroot}/name_resolve — is AREAL_TELEMETRY_EXPORT "
                "enabled on the trial?",
                file=sys.stderr,
            )
            return 1
        experiment, trial = trials[0][0], trials[0][1]

    while True:
        frame = render_frame(experiment, trial, args.as_json)
        if frame is None:
            print(
                f"no telemetry for {experiment}/{trial}", file=sys.stderr
            )
            return 1
        print(frame, flush=True)
        if args.once:
            return 0
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
