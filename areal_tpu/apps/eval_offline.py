"""Offline evaluation harness: generate → grade → aggregate, standalone.

Counterpart of the reference's ``evaluation/eval_and_aggregate.py`` +
``math_eval.py`` protocol: one CLI call evaluates a checkpoint on MULTIPLE
benchmark files (math and code), runs an optional greedy pass next to the
sampling pass, and aggregates per benchmark —

- ``pass@k`` via the unbiased estimator ``1 - prod(1 - k/arange(n-c+1, n+1))``
  (``eval_and_aggregate.py:75-88``) at k = 1 and every power of two <= n,
- ``maj@k`` majority voting over answer-equivalence groups
  (``rm_maj_eval.py:group_pred``),
- mean generated length (tokens), greedy accuracy, mean reward,
- CF ELO when a contest cache is provided (``cf_elo_caculator.py``).

    python -m areal_tpu.apps.eval_offline --model-path /ckpts/step100 \
        --dataset aime=aime24.jsonl --dataset mathd=math_500.jsonl \
        --output-dir /tmp/eval --n-sampling 8 --with-greedy

Per-benchmark sampling overrides ride ``--sampling-config cfg.json``:
``{"aime": {"temperature": 1.0, "max_gen_tokens": 4096}}``.

Writes ``<output-dir>/<name>/samples.jsonl`` per benchmark and ONE
``<output-dir>/aggregate.json`` across all of them.
"""

import argparse
import json
import logging
import os
import sys
import time
from typing import Dict, List, Optional

logger = logging.getLogger("areal_tpu.eval_offline")


def unbiased_pass_at_k(n: int, c: int, k: int) -> float:
    """P(at least one of k draws without replacement is correct) given c of
    n samples were correct — the estimator from the reference
    (``eval_and_aggregate.py:77-80``) and Codex (Chen et al. 2021)."""
    import numpy as np

    if n - c < k:
        return 1.0
    return float(1.0 - np.prod(1.0 - k / np.arange(n - c + 1, n + 1)))


def majority_score(answers: List[str], scores: List[float], k: int) -> float:
    """maj@k: group the first k answers by answer-equivalence, take the
    largest group's representative score (``rm_maj_eval.py:group_pred``)."""
    from areal_tpu.rewards.math_verify import answers_equal, extract_answer

    preds = [
        extract_answer(a, use_last_number=True) or "" for a in answers[:k]
    ]
    groups: List[List] = []  # [representative, member indices]
    for i, p in enumerate(preds):
        placed = False
        for g in groups:
            if p == g[0] or (p and g[0] and answers_equal(p, g[0])):
                g[1].append(i)
                placed = True
                break
        if not placed:
            groups.append([p, [i]])
    best = max(groups, key=lambda g: len(g[1]))
    return float(scores[best[1][0]] > 0)


def grade_answers(
    qid: str, answers: List[str], metadata: dict, pool=None
) -> List[float]:
    """Task-dispatching grader: math via the parity verifier, gpqa via
    boxed-choice-letter equality, code via the subprocess test runner (the
    reference's functioncall/code path). With ``pool`` (a
    ``evaluation.grading.PoolGrader``) each comparison runs in a killable
    worker process under a deadline — the reference's pebble-pool protocol
    (``evaluation/evaluate.py:44-60``)."""
    task = metadata.get("task", "math")
    gold = (
        metadata.get("input_output", {}) if task == "code"
        else metadata.get("solutions", [])
    )
    items = [(task, a, gold) for a in answers]
    if pool is not None:
        return pool.grade(items)
    from areal_tpu.evaluation.grading import _default_grade_one

    return [_default_grade_one(*item) for item in items]


def aggregate_from_records(
    per_prompt: List[dict], n_sampling: int, path: str = ""
) -> dict:
    """Metric table from per-prompt sample records — the schema of the
    reference's aggregate (``eval_and_aggregate.py:163-189``:
    num_questions / greedy_length / sample_length / greedy_acc /
    sample_pass@1 / pass@k / maj@k). Shared by the live harness and the
    ``--from-generated`` re-aggregation path
    (``aggregate_acc_from_generated.py``)."""
    import numpy as np

    ks = [1] + [k for k in (2, 4, 8, 16, 32) if k <= n_sampling]
    agg: dict = {
        "dataset": path,
        "n_prompts": len(per_prompt),
        "num_questions": len(per_prompt),
        "n_sampling": n_sampling,
        "sample_length": float(np.mean(
            [l for r in per_prompt for l in r["gen_lens"]]
        )) if per_prompt else 0.0,
        "reward_mean": float(np.mean(
            [x for r in per_prompt for x in r["rewards"]]
        )) if per_prompt else 0.0,
    }
    for k in ks:
        agg[f"pass@{k}"] = float(np.mean([
            unbiased_pass_at_k(
                len(r["rewards"]), sum(x > 0 for x in r["rewards"]), k
            )
            for r in per_prompt
        ])) if per_prompt else 0.0
    agg["sample_pass@1"] = agg.get("pass@1", 0.0)
    for k in (k for k in (8, 16, 32) if k <= n_sampling):
        agg[f"maj@{k}"] = float(np.mean([
            majority_score(r["answers"], r["rewards"], k) for r in per_prompt
        ])) if per_prompt else 0.0
    if per_prompt and "greedy_reward" in per_prompt[0]:
        agg["greedy_acc"] = float(np.mean(
            [r["greedy_reward"] > 0 for r in per_prompt]
        ))
        agg["greedy_length"] = float(np.mean(
            [r["greedy_len"] for r in per_prompt]
        ))
    return agg


def _parse_datasets(specs: List[str]) -> Dict[str, str]:
    out = {}
    for spec in specs:
        name, sep, path = spec.partition("=")
        # 'name=path' only when the prefix looks like a NAME — a bare path
        # containing '=' (e.g. /data/run=3/x.jsonl) must stay whole
        if not sep or os.sep in name or not name:
            name, path = "", spec
            name = os.path.splitext(os.path.basename(path))[0]
        if name in out:
            raise ValueError(
                f"duplicate benchmark name {name!r} ({out[name]} vs {path});"
                " disambiguate with an explicit 'name=path' spec"
            )
        out[name] = path
    return out


def evaluate_benchmark(
    gen,
    name: str,
    path: str,
    out_dir: str,
    ghp_base,
    decode,
    *,
    tokenizer=None,
    n_sampling: int,
    batch_prompts: int,
    max_prompts: Optional[int],
    seed: int,
    with_greedy: bool,
    cf_cache_dir: Optional[str],
    cf_ratings: Optional[str],
    cf_pass_n: Optional[int],
    grader_pool=None,
) -> dict:
    import dataclasses

    import numpy as np

    from areal_tpu.api.dataset import (
        DatasetUtility,
        dataset_metadata,
        make_dataset,
    )

    util = DatasetUtility(
        seed=seed, dp_rank=0, world_size=1, tokenizer=tokenizer
    )
    dataset = make_dataset("math_code_prompt", util, path=path)
    metadata = dataset_metadata(dataset)
    n = len(dataset) if max_prompts is None else min(max_prompts, len(dataset))
    os.makedirs(out_dir, exist_ok=True)

    per_prompt: List[dict] = []
    cf_submissions = {}
    t0 = time.time()
    timeouts0 = grader_pool.timeout_cnt if grader_pool else 0
    with open(os.path.join(out_dir, "samples.jsonl"), "w") as f:
        for lo in range(0, n, batch_prompts):
            samples = [
                dataset[i] for i in range(lo, min(lo + batch_prompts, n))
            ]
            qids = [str(s.ids[0]) for s in samples]
            prompts = [
                np.asarray(s.data["packed_prompts"]).tolist() for s in samples
            ]
            groups = gen.generate(prompts, ghp_base, seed=seed + lo)
            if with_greedy:
                ghp_g = dataclasses.replace(ghp_base, n=1, greedy=True)
                greedy_groups = gen.generate(prompts, ghp_g, seed=seed)
            else:
                greedy_groups = [None] * len(prompts)
            for qid, prompt, group, ggroup in zip(
                qids, prompts, groups, greedy_groups
            ):
                answers = [
                    decode(o.tokens[len(prompt):].tolist()) for o in group
                ]
                rws = grade_answers(
                    qid, answers, metadata.get(qid, {}), pool=grader_pool
                )
                rec = {
                    "qid": qid,
                    "answers": answers,
                    "rewards": rws,
                    "gen_lens": [len(o.gen_logprobs) for o in group],
                    "no_eos": [bool(o.no_eos) for o in group],
                }
                if ggroup is not None:
                    g_ans = decode(ggroup[0].tokens[len(prompt):].tolist())
                    g_rw = grade_answers(
                        qid, [g_ans], metadata.get(qid, {}), pool=grader_pool
                    )
                    rec["greedy_answer"] = g_ans
                    rec["greedy_reward"] = g_rw[0]
                    rec["greedy_len"] = len(ggroup[0].gen_logprobs)
                if cf_cache_dir:
                    cf_submissions[qid] = [r > 0 for r in rws]
                per_prompt.append(rec)
                f.write(json.dumps(rec) + "\n")
            logger.info(
                "[%s] evaluated %d/%d prompts",
                name, min(lo + batch_prompts, n), n,
            )

    agg = aggregate_from_records(per_prompt, n_sampling, path)
    agg["wall_s"] = time.time() - t0
    if grader_pool is not None:  # the reference's ``timeout_samples`` count
        agg["timeout_samples"] = grader_pool.timeout_cnt - timeouts0
    if cf_cache_dir:
        from areal_tpu.apps import cf_elo

        agg["cf"] = cf_elo.calculate_cf_elo(
            cf_submissions, cf_cache_dir, cf_ratings, pass_n=cf_pass_n
        )
    return agg


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser()
    ap.add_argument("--model-path", required=True, help="HF checkpoint dir")
    ap.add_argument(
        "--dataset", action="append", default=[],
        help="benchmark jsonl, repeatable; 'name=path' or bare path "
             "(name defaults to the file stem)",
    )
    ap.add_argument(
        "--benchmark", action="append", default=[],
        help="bundled benchmark name, repeatable (or 'all'): "
             "aime24, aime25, amc23, gpqa_diamond, math_500 — data + prompt "
             "template + grading ship with the package "
             "(areal_tpu/evaluation/data)",
    )
    ap.add_argument(
        "--prompt-template", default=None,
        help="override the bundled benchmarks' prompt template "
             "(r1-distilled-qwen, qwen25-math-cot, ...)",
    )
    ap.add_argument("--output-dir", required=True)
    ap.add_argument("--tokenizer", default=None, help="tokenizer path (defaults to model)")
    ap.add_argument("--parallel", default="d1m1")
    ap.add_argument("--n-sampling", type=int, default=8)
    ap.add_argument("--max-gen-tokens", type=int, default=1024)
    ap.add_argument("--temperature", type=float, default=0.6)
    ap.add_argument("--top-p", type=float, default=0.95)
    ap.add_argument("--greedy", action="store_true",
                    help="sampling pass itself decodes greedily (n forced 1)")
    ap.add_argument("--with-greedy", action="store_true",
                    help="ALSO run a greedy pass per benchmark (greedy_acc)")
    ap.add_argument(
        "--sampling-config", default=None,
        help="JSON file: benchmark name -> overrides (temperature, top_p, "
             "max_gen_tokens, n_sampling) — the reference's per-benchmark "
             "prompt/sampling configs",
    )
    ap.add_argument("--max-prompts", type=int, default=None)
    ap.add_argument("--batch-prompts", type=int, default=32)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--overwrite", action="store_true")
    ap.add_argument(
        "--cf-cache-dir", default=None,
        help="directory of cached Codeforces contest data ({cid}.json); "
             "when set, prompts whose qid is a contest problem id (e.g. "
             "1700A) also aggregate to an estimated ELO",
    )
    ap.add_argument(
        "--cf-ratings", default=None,
        help="rating population file for the ELO percentile",
    )
    ap.add_argument(
        "--cf-pass-n", type=int, default=None,
        help="submission budget per problem for the ELO model (default: all "
             "n_sampling generations count as ordered submissions)",
    )
    ap.add_argument(
        "--grade-workers", type=int, default=8,
        help="grading worker processes (0 = grade in-process, no timeouts)",
    )
    ap.add_argument(
        "--grade-timeout", type=float, default=3.0,
        help="per-comparison deadline in seconds (the reference's pebble "
             "pool timeout); a wedged check scores as a wrong answer. "
             "Code items get a larger budget (subprocess test cases).",
    )
    ap.add_argument(
        "--from-generated", action="store_true",
        help="skip generation: re-grade + re-aggregate existing "
             "<output-dir>/<name>/samples.jsonl (the reference's "
             "aggregate_acc_from_generated.py)",
    )
    ap.add_argument(
        "--allow-token-id-answers", action="store_true",
        help="debug only: grade space-joined token-id strings when no "
             "tokenizer is available (real grading needs one)",
    )
    args = ap.parse_args(argv)

    out_agg = os.path.join(args.output_dir, "aggregate.json")
    # --from-generated EXISTS to rewrite the aggregate of a finished sweep,
    # so the idempotence guard must not apply to it
    if os.path.exists(out_agg) and not args.overwrite \
            and not args.from_generated:
        logger.info("aggregate exists (%s); pass --overwrite to redo", out_agg)
        return 0
    os.makedirs(args.output_dir, exist_ok=True)

    datasets = _parse_datasets(args.dataset)
    overrides = {}
    if args.sampling_config:
        with open(args.sampling_config) as f:
            overrides = json.load(f)

    # bundled benchmarks: materialize data + prompt template into the
    # output dir, then treat like any --dataset entry
    from areal_tpu.evaluation import benchmarks as bench_mod

    bench_names = list(args.benchmark)
    if "all" in bench_names:
        bench_names = bench_mod.benchmark_names()
    for bname in bench_names:
        if bname not in bench_mod.BENCHMARKS:
            raise SystemExit(
                f"unknown benchmark {bname!r}; bundled: "
                f"{', '.join(bench_mod.benchmark_names())}"
            )
        if bname in datasets:
            raise ValueError(f"benchmark {bname!r} also given as --dataset")
        datasets[bname] = bench_mod.write_benchmark_jsonl(
            bname,
            os.path.join(args.output_dir, bname, "prompts.jsonl"),
            template=args.prompt_template,
            max_items=args.max_prompts,
        )
    if not datasets:
        raise SystemExit("nothing to evaluate: pass --dataset or --benchmark")

    if args.from_generated:
        # re-grade + re-aggregate existing samples.jsonl without a model
        # (the reference's aggregate_acc_from_generated.py): answers are
        # re-run through the CURRENT graders, so verifier fixes retro-
        # actively correct old sweeps
        from areal_tpu.datasets.prompt import metadata_from_records

        grader_pool = None
        if args.grade_workers > 0:
            from areal_tpu.evaluation.grading import PoolGrader

            grader_pool = PoolGrader(
                n_workers=args.grade_workers, timeout_s=args.grade_timeout
            )
        aggregate = {"model": args.model_path, "benchmarks": {}}
        try:
            for name, path in datasets.items():
                samples = os.path.join(
                    args.output_dir, name, "samples.jsonl"
                )
                if not os.path.exists(samples):
                    raise SystemExit(f"--from-generated: {samples} missing")
                with open(samples) as f:
                    per_prompt = [json.loads(line) for line in f]
                with open(path) as f:
                    meta = metadata_from_records(
                        json.loads(line) for line in f
                    )
                missing = [
                    r["qid"] for r in per_prompt if r["qid"] not in meta
                ]
                if missing:
                    # re-grading against empty metadata would silently
                    # score every such record wrong (e.g. a --max-prompts
                    # smaller than the original sweep)
                    raise SystemExit(
                        f"--from-generated: {len(missing)} sample qids "
                        f"missing from {path} (first: {missing[:3]}); "
                        "regenerate with the original dataset/--max-prompts"
                    )
                for r in per_prompt:
                    m = meta.get(r["qid"], {})
                    r["rewards"] = grade_answers(
                        r["qid"], r["answers"], m, pool=grader_pool
                    )
                    if "greedy_answer" in r:
                        r["greedy_reward"] = grade_answers(
                            r["qid"], [r["greedy_answer"]], m,
                            pool=grader_pool,
                        )[0]
                n_sampling = max(
                    (len(r["rewards"]) for r in per_prompt), default=0
                )
                aggregate["benchmarks"][name] = aggregate_from_records(
                    per_prompt, n_sampling, path
                )
        finally:
            if grader_pool is not None:
                grader_pool.close()
        with open(out_agg, "w") as f:
            json.dump(aggregate, f, indent=2)
        logger.info("aggregate: %s", json.dumps(aggregate, indent=2))
        return 0

    from areal_tpu.api.model import GenerationHyperparameters
    from areal_tpu.experiments.config import ModelSpec
    from areal_tpu.train.engine import TrainEngine
    from areal_tpu.train.generation import SyncGenerator

    tokenizer = None
    tok_path = args.tokenizer or args.model_path
    try:
        import transformers

        tokenizer = transformers.AutoTokenizer.from_pretrained(tok_path)
    except Exception as e:
        if not args.allow_token_id_answers:
            # silently grading token-id strings would burn the whole
            # generation sweep to report a meaningless pass@1 = 0
            raise SystemExit(
                f"no tokenizer at {tok_path} ({e}); pass --tokenizer or "
                "--allow-token-id-answers (debug)"
            )
        logger.warning("no tokenizer at %s; decoding as token-id strings", tok_path)
    decode = (
        (lambda ids: tokenizer.decode(ids, skip_special_tokens=True))
        if tokenizer is not None
        else (lambda ids: " ".join(map(str, ids)))
    )

    spec = ModelSpec(path=args.model_path, parallel=args.parallel)
    eng = TrainEngine(spec.model_config(), spec.parallel_config())
    eng.load_hf(args.model_path)
    gen = SyncGenerator(eng)

    grader_pool = None
    if args.grade_workers > 0:
        from areal_tpu.evaluation.grading import PoolGrader

        grader_pool = PoolGrader(
            n_workers=args.grade_workers, timeout_s=args.grade_timeout
        )

    aggregate = {"model": args.model_path, "benchmarks": {}}
    try:
        for name, path in datasets.items():
            ov = overrides.get(name, {})
            n_sampling = int(ov.get("n_sampling", args.n_sampling))
            ghp = GenerationHyperparameters(
                n=1 if args.greedy else n_sampling,
                max_new_tokens=int(
                    ov.get("max_gen_tokens", args.max_gen_tokens)
                ),
                greedy=args.greedy,
                temperature=float(ov.get("temperature", args.temperature)),
                top_p=float(ov.get("top_p", args.top_p)),
                stop_token_ids=(
                    [tokenizer.eos_token_id]
                    if tokenizer is not None
                    and tokenizer.eos_token_id is not None
                    else []
                ),
            )
            aggregate["benchmarks"][name] = evaluate_benchmark(
                gen, name, path, os.path.join(args.output_dir, name), ghp,
                decode,
                tokenizer=tokenizer,
                n_sampling=ghp.n, batch_prompts=args.batch_prompts,
                max_prompts=args.max_prompts, seed=args.seed,
                with_greedy=args.with_greedy, cf_cache_dir=args.cf_cache_dir,
                cf_ratings=args.cf_ratings, cf_pass_n=args.cf_pass_n,
                grader_pool=grader_pool,
            )
    finally:
        if grader_pool is not None:
            grader_pool.close()
    with open(out_agg, "w") as f:
        json.dump(aggregate, f, indent=2)
    logger.info("aggregate: %s", json.dumps(aggregate, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
