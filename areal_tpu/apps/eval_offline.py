"""Offline evaluation harness: generate → grade → aggregate, standalone.

Counterpart of the reference's ``evaluation/eval_and_aggregate.py`` (math
answer grading + pass@k aggregation over sampled generations; the CF-ELO
half is dataset-specific and out of scope). Runs against any HF checkpoint
this framework exports:

    python -m areal_tpu.apps.eval_offline \
        --model-path /ckpts/step100 --dataset math_test.jsonl \
        --output-dir /tmp/eval --n-sampling 8 --max-gen-tokens 1024

Writes per-sample generations to ``samples.jsonl`` and the aggregate
(pass@1, pass@k, mean reward) to ``aggregate.json``.
"""

import argparse
import json
import logging
import os
import sys
import time

logger = logging.getLogger("areal_tpu.eval_offline")


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser()
    ap.add_argument("--model-path", required=True, help="HF checkpoint dir")
    ap.add_argument("--dataset", required=True, help="prompt jsonl (math_code_prompt format)")
    ap.add_argument("--output-dir", required=True)
    ap.add_argument("--tokenizer", default=None, help="tokenizer path (defaults to model)")
    ap.add_argument("--parallel", default="d1m1")
    ap.add_argument("--n-sampling", type=int, default=8)
    ap.add_argument("--max-gen-tokens", type=int, default=1024)
    ap.add_argument("--temperature", type=float, default=0.6)
    ap.add_argument("--top-p", type=float, default=0.95)
    ap.add_argument("--greedy", action="store_true")
    ap.add_argument("--max-prompts", type=int, default=None)
    ap.add_argument("--batch-prompts", type=int, default=32)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--overwrite", action="store_true")
    ap.add_argument(
        "--cf-cache-dir", default=None,
        help="directory of cached Codeforces contest data ({cid}.json); "
             "when set, prompts whose qid is a contest problem id (e.g. "
             "1700A) also aggregate to an estimated ELO",
    )
    ap.add_argument(
        "--cf-ratings", default=None,
        help="rating population file for the ELO percentile",
    )
    ap.add_argument(
        "--cf-pass-n", type=int, default=None,
        help="submission budget per problem for the ELO model (default: all "
             "n_sampling generations count as ordered submissions)",
    )
    ap.add_argument(
        "--allow-token-id-answers", action="store_true",
        help="debug only: grade space-joined token-id strings when no "
             "tokenizer is available (real math grading needs one)",
    )
    args = ap.parse_args(argv)

    out_samples = os.path.join(args.output_dir, "samples.jsonl")
    out_agg = os.path.join(args.output_dir, "aggregate.json")
    if os.path.exists(out_agg) and not args.overwrite:
        logger.info("aggregate exists (%s); pass --overwrite to redo", out_agg)
        return 0
    os.makedirs(args.output_dir, exist_ok=True)

    import numpy as np

    from areal_tpu.api.dataset import DatasetUtility, make_dataset
    from areal_tpu.api.model import GenerationHyperparameters
    from areal_tpu.parallel.mesh import ParallelConfig
    from areal_tpu.system.sync_trainer import math_reward_fn
    from areal_tpu.train.engine import TrainEngine
    from areal_tpu.train.generation import SyncGenerator

    tokenizer = None
    tok_path = args.tokenizer or args.model_path
    try:
        import transformers

        tokenizer = transformers.AutoTokenizer.from_pretrained(tok_path)
    except Exception as e:
        if not args.allow_token_id_answers:
            # silently grading token-id strings would burn the whole
            # generation sweep to report a meaningless pass@1 = 0
            raise SystemExit(
                f"no tokenizer at {tok_path} ({e}); pass --tokenizer or "
                "--allow-token-id-answers (debug)"
            )
        logger.warning("no tokenizer at %s; decoding as token-id strings", tok_path)
    util = DatasetUtility(seed=args.seed, dp_rank=0, world_size=1, tokenizer=tokenizer)
    dataset = make_dataset("math_code_prompt", util, path=args.dataset)
    from areal_tpu.api.dataset import dataset_metadata

    metadata = dataset_metadata(dataset)
    n = len(dataset) if args.max_prompts is None else min(args.max_prompts, len(dataset))

    from areal_tpu.experiments.config import ModelSpec

    spec = ModelSpec(path=args.model_path, parallel=args.parallel)
    eng = TrainEngine(spec.model_config(), spec.parallel_config())
    eng.load_hf(args.model_path)
    gen = SyncGenerator(eng)
    ghp = GenerationHyperparameters(
        n=args.n_sampling,
        max_new_tokens=args.max_gen_tokens,
        greedy=args.greedy,
        temperature=args.temperature,
        top_p=args.top_p,
        stop_token_ids=(
            [tokenizer.eos_token_id]
            if tokenizer is not None and tokenizer.eos_token_id is not None
            else []
        ),
    )
    decode = (
        (lambda ids: tokenizer.decode(ids, skip_special_tokens=True))
        if tokenizer is not None
        else (lambda ids: " ".join(map(str, ids)))
    )

    pass1, passk, rewards_all = [], [], []
    cf_submissions = {}
    t0 = time.time()
    with open(out_samples, "w") as f:
        for lo in range(0, n, args.batch_prompts):
            samples = [dataset[i] for i in range(lo, min(lo + args.batch_prompts, n))]
            qids = [str(s.ids[0]) for s in samples]
            prompts = [np.asarray(s.data["packed_prompts"]).tolist() for s in samples]
            groups = gen.generate(prompts, ghp, seed=args.seed + lo)
            for qid, prompt, group in zip(qids, prompts, groups):
                answers = [decode(o.tokens[len(prompt):].tolist()) for o in group]
                rws = math_reward_fn(qid, answers, metadata.get(qid, {}))
                oks = [r > 0 for r in rws]
                if args.cf_cache_dir:
                    cf_submissions[qid] = oks
                pass1.append(float(np.mean(oks)))
                passk.append(float(any(oks)))
                rewards_all.extend(rws)
                f.write(json.dumps({
                    "qid": qid,
                    "answers": answers,
                    "rewards": rws,
                    "gen_lens": [len(o.gen_logprobs) for o in group],
                    "no_eos": [bool(o.no_eos) for o in group],
                }) + "\n")
            logger.info("evaluated %d/%d prompts", min(lo + args.batch_prompts, n), n)

    agg = {
        "model": args.model_path,
        "dataset": args.dataset,
        "n_prompts": n,
        "n_sampling": args.n_sampling,
        "pass@1": float(np.mean(pass1)) if pass1 else 0.0,
        f"pass@{args.n_sampling}": float(np.mean(passk)) if passk else 0.0,
        "reward_mean": float(np.mean(rewards_all)) if rewards_all else 0.0,
        "wall_s": time.time() - t0,
    }
    if args.cf_cache_dir:
        from areal_tpu.apps import cf_elo

        agg["cf"] = cf_elo.calculate_cf_elo(
            cf_submissions, args.cf_cache_dir, args.cf_ratings,
            pass_n=args.cf_pass_n,
        )
    with open(out_agg, "w") as f:
        json.dump(agg, f, indent=2)
    logger.info("aggregate: %s", agg)
    return 0


if __name__ == "__main__":
    sys.exit(main())
