"""Local multiprocess launcher + worker entry functions.

Counterpart of the reference's local scheduler + Ray launcher + recover loop
(``realhf/scheduler/local/client.py``, ``training/utils.py:119``,
``apps/main.py:226-288``): each worker role runs as a spawned subprocess;
the launcher watches them and, on a failure with ``recover_mode=auto``,
kills the world and restarts it up to ``recover_retries`` times
(restart-the-world elasticity, like the reference).

Worker processes rendezvous through the file-backed name_resolve under the
experiment fileroot — the same mechanism the reference uses on NFS.
"""

import dataclasses
import json
import logging
import multiprocessing as mp
import os
import signal
import sys
import time
from typing import Dict, List, Optional

logger = logging.getLogger("areal_tpu.launcher")


def _setup_worker_env(cfg, device: str = ""):
    """Common per-process setup: fileroot, name_resolve, devices, seeding."""
    import os

    if cfg.fileroot:
        os.environ["AREAL_FILEROOT"] = cfg.fileroot
    os.environ.setdefault(
        "AREAL_NAME_RESOLVE_ROOT",
        os.path.join(cfg.fileroot or "/tmp/areal_tpu", "name_resolve"),
    )
    if device == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    from areal_tpu.base import constants, name_resolve, seeding

    # cross-process rendezvous: the TCP server when one is advertised
    # (multi-node, no shared FS — AREAL_NAME_RESOLVE_RPC=host:port), else
    # the shared-filesystem backend (the in-memory default only works
    # within one process)
    rpc_addr = constants.name_resolve_rpc()
    if rpc_addr:
        name_resolve.reconfigure(
            name_resolve.NameResolveConfig(type="rpc", root=rpc_addr)
        )
    else:
        name_resolve.reconfigure(
            name_resolve.NameResolveConfig(
                type="file", root=constants.name_resolve_root()
            )
        )

    constants.set_experiment_trial_names(cfg.experiment_name, cfg.trial_name)
    if cfg.fileroot:
        constants.set_fileroot(cfg.fileroot)
    seeding.set_random_seed(cfg.seed, "worker")


def _load_engine(spec, is_critic=False, with_optimizer=True, total_steps=100):
    from areal_tpu.train.engine import TrainEngine

    cfg = spec.model_config(is_critic=is_critic)
    eng = TrainEngine(
        cfg,
        spec.parallel_config(),
        spec.optimizer if with_optimizer else None,
        param_dtype=getattr(spec, "param_dtype", "float32"),
    )
    if spec.path:
        eng.load_hf(spec.path, init_critic_head=is_critic)
    else:
        eng.init_random(0)
    if with_optimizer:
        eng.setup_optimizer(total_steps)
    return eng


# --------------------------------------------------------------------------- #
# worker mains (multiprocessing spawn targets)
# --------------------------------------------------------------------------- #


def gen_server_main(cfg, server_idx: int):
    import asyncio

    _setup_worker_env(cfg, cfg.gen.device)
    import jax

    from areal_tpu.base import constants, name_resolve, names, network
    from areal_tpu.gen.engine import GenerationEngine
    from areal_tpu.gen.server import serve
    from areal_tpu.models import hf as hf_conv

    mcfg = cfg.actor.model_config()
    mesh = None
    tp = getattr(cfg.gen, "tp_size", 1)
    if tp > 1:
        import numpy as np
        from jax.sharding import Mesh

        devs = jax.local_devices()
        lo = server_idx * tp
        if lo + tp > len(devs):
            raise ValueError(
                f"gen server {server_idx} needs devices [{lo}, {lo + tp}) "
                f"but only {len(devs)} local devices exist; lower "
                "gen.n_servers or gen.tp_size"
            )
        mesh = Mesh(np.array(devs[lo : lo + tp]), ("model",))
    if cfg.actor.path:
        _, host_params = hf_conv.load_hf_checkpoint(cfg.actor.path)
    else:
        from areal_tpu.models import transformer as tfm

        host_params = tfm.init_params(mcfg, jax.random.key(0))
    # draft MODEL for spec decode: config beats the env knob; None lets
    # the engine fall through to AREAL_SPEC_DRAFT_MODEL (then the n-gram
    # self-drafter). Same gate as the engine's env path: an explicit
    # drafter is kept by the engine regardless of the spec flag, so
    # loading one here for a spec-disabled fleet would make every engine
    # pay draft-pool HBM + a per-vanilla-step maintenance sweep while
    # never speculating.
    drafter = None
    draft_path = getattr(cfg.gen, "spec_draft_model", None)
    spec_on = (
        cfg.gen.spec_decode
        if cfg.gen.spec_decode is not None
        else constants.spec_decode_enabled()
    )
    if draft_path and spec_on:
        from areal_tpu.gen.drafter import TransformerDrafter

        drafter = TransformerDrafter.from_hf(
            draft_path,
            kv_dtype=getattr(cfg.gen, "spec_draft_kv_dtype", None),
        )
    elif draft_path:
        logger.warning(
            "gen.spec_draft_model is set but spec decode is disabled for "
            "the gen fleet; not loading the draft model (set "
            "gen.spec_decode=true or %s to serve it)",
            constants.SPEC_DECODE_ENV,
        )
    engine = GenerationEngine(
        mcfg,
        host_params,  # cast + TP-shard happen inside (prepare_params)
        max_slots=cfg.gen.max_slots,
        max_seqlen=cfg.gen.max_seqlen,
        max_new_tokens_cap=cfg.gen.max_new_tokens_cap,
        stop_token_ids=cfg.gen.stop_token_ids,
        seed=cfg.seed + server_idx,
        page_size=cfg.gen.page_size,
        n_pages=cfg.gen.n_pages,
        kv_dtype=cfg.gen.kv_dtype,
        mesh=mesh,
        spec_decode=cfg.gen.spec_decode,
        spec_k=cfg.gen.spec_k,
        drafter=drafter,
    )

    async def main():
        from areal_tpu.system.worker_base import (
            ExperimentStatusWatch,
            Heartbeat,
            TelemetryExporter,
        )

        port = network.find_free_port()
        host = "127.0.0.1"
        from areal_tpu.base import constants as _constants

        runner = await serve(
            engine, host, port, decode_steps=cfg.gen.decode_steps_per_chunk,
            metrics_dump_path=os.path.join(
                _constants.get_log_root(), f"gen_server_{server_idx}.json"
            ),
        )
        name_resolve.add(
            names.gen_server(cfg.experiment_name, cfg.trial_name, server_idx),
            f"http://{host}:{port}",
            replace=True,
        )
        # orphan protection: exit when the experiment dies
        # (≈ reference generation_server.py:209-222)
        watch = ExperimentStatusWatch(cfg.experiment_name, cfg.trial_name)
        hb = Heartbeat(
            cfg.experiment_name, cfg.trial_name, f"gen_server/{server_idx}"
        ).start()
        tele = TelemetryExporter(
            cfg.experiment_name, cfg.trial_name,
            f"gen_server/{server_idx}", "gen_server",
            step_fn=lambda: max(engine.version, 0),
            gauges_fn=lambda: {
                "gen_running": float(engine.n_running()),
                "gen_pending": float(engine.n_pending()),
                # HBM-headroom gauges (docs/observability.md): the fleet
                # aggregator sums these per server; kv_dtype itself is a
                # string and lives on /metrics_json instead
                "kv_pool_bytes": float(engine.kv_pool_bytes()),
                "kv_pool_occupancy": engine.kv_pool_occupancy(),
                # admission/autoscale signal: excludes evictable
                # prefix-cache-only pages
                "kv_pool_demand_occupancy": (
                    engine.kv_pool_demand_occupancy()
                ),
                "n_pages_free": float(engine.pool.n_free),
                # draft-model spec decode: pool bytes (0 without a draft
                # model; occupancy is shared with the target pool — the
                # pages move in lockstep) and the draft weight generation
                "draft_kv_pool_bytes": float(engine.draft_kv_pool_bytes()),
                "draft_version": float(engine.draft_version),
            },
        ).maybe_start()
        while watch.alive():
            await asyncio.sleep(1.0)
        tele.stop()
        hb.stop()
        await runner.cleanup()

    asyncio.run(main())


def gserver_manager_main(cfg):
    import asyncio

    _setup_worker_env(cfg, "cpu")
    from areal_tpu.base import name_resolve, names, network
    from areal_tpu.system.gserver_manager import (
        GserverManager,
        GserverManagerConfig,
        serve_manager,
    )

    gconfig_n = cfg.gconfig.n if not isinstance(cfg.gconfig, dict) else cfg.gconfig.get("n", 1)
    mcfg = GserverManagerConfig(
        experiment_name=cfg.experiment_name,
        trial_name=cfg.trial_name,
        model_name="actor",
        # the staleness gate counts SEQUENCES (the trainer bumps
        # training_samples by groups x gconfig.n), so the divisor must be
        # sequences per train step too (≈ reference train_rpcs[0].n_seqs)
        train_batch_size=cfg.train_batch_size * gconfig_n,
        max_head_offpolicyness=cfg.manager.max_head_offpolicyness,
        max_concurrent_rollouts=cfg.manager.max_concurrent_rollouts,
        schedule_policy=cfg.manager.schedule_policy,
    )

    async def main():
        from areal_tpu.system.worker_base import (
            ExperimentStatusWatch,
            Heartbeat,
            TelemetryExporter,
        )

        manager = GserverManager(mcfg)
        # wait for all advertised gen servers
        for i in range(cfg.gen.n_servers):
            name_resolve.wait(
                names.gen_server(cfg.experiment_name, cfg.trial_name, i),
                timeout=300,
            )
        manager.discover_servers()
        await serve_manager(manager, "127.0.0.1", network.find_free_port())
        watch = ExperimentStatusWatch(cfg.experiment_name, cfg.trial_name)
        hb = Heartbeat(cfg.experiment_name, cfg.trial_name, "gserver_manager").start()
        tele = TelemetryExporter(
            cfg.experiment_name, cfg.trial_name,
            "gserver_manager", "manager",
            step_fn=lambda: max(manager.version, 0),
            gauges_fn=lambda: {
                "rollouts_running": float(manager.rollout_stat.running),
                "rollouts_submitted": float(manager.rollout_stat.submitted),
                "rollouts_accepted": float(manager.rollout_stat.accepted),
            },
            # per-server breaker states feed the fleet/ servers_* tallies
            # and the ops CLI's breaker column
            server_states_fn=lambda: {
                u: s["state"] for u, s in manager.fleet.snapshot().items()
            },
        ).maybe_start()
        while watch.alive():
            await asyncio.sleep(1.0)
        tele.stop()
        hb.stop()

    asyncio.run(main())


def gateway_main(cfg):
    """Serving-gateway worker (docs/serving.md): OpenAI-compatible API +
    continuous-batching scheduler over the discovered gen servers, with
    an optional autoscaler resizing the ROUTED subset live (and mirroring
    every add/remove to the gserver manager so RL sticky routing follows)."""
    import asyncio

    _setup_worker_env(cfg, "cpu")
    from areal_tpu.base import constants as _constants
    from areal_tpu.base import name_resolve, names, network
    from areal_tpu.gateway.api import (
        ByteFallbackCodec,
        GatewayConfig,
        GatewayServer,
        HFTokenizerCodec,
        serve_gateway,
    )
    from areal_tpu.gateway.autoscaler import (
        Autoscaler,
        AutoscalerConfig,
        ScaleSignals,
    )
    from areal_tpu.gateway.qos import TenantSpec
    from areal_tpu.gateway.scheduler import ContinuousBatchScheduler
    from areal_tpu.system import telemetry

    gspec = cfg.gateway

    async def main():
        from areal_tpu.system.worker_base import (
            ExperimentStatusWatch,
            Heartbeat,
            TelemetryExporter,
        )

        # discovered fleet = scale-out ceiling; routed set starts full
        all_urls = []
        for i in range(cfg.gen.n_servers):
            name_resolve.wait(
                names.gen_server(cfg.experiment_name, cfg.trial_name, i),
                timeout=300,
            )
            all_urls.append(
                name_resolve.get(
                    names.gen_server(cfg.experiment_name, cfg.trial_name, i)
                )
            )
        # spec value 0 defers to the env knobs — for NAMED tenants too,
        # or listing a tenant in tenant_weights would silently strip its
        # rate limit while the anonymous tenant kept one
        rate = gspec.rate_tokens_per_s or _constants.gateway_rate_tps()
        burst = gspec.burst_tokens or _constants.gateway_burst()
        tenants = {
            name: TenantSpec(
                name=name, weight=w,
                rate_tokens_per_s=rate, burst_tokens=burst,
                default_deadline_s=gspec.default_deadline_s,
            )
            for name, w in gspec.tenant_weights.items()
        }
        scheduler = ContinuousBatchScheduler(
            list(all_urls),
            tenants,
            default_tenant=TenantSpec(
                name=gspec.default_tenant,
                rate_tokens_per_s=rate,
                burst_tokens=burst,
                default_deadline_s=gspec.default_deadline_s,
            ),
            max_queue=gspec.max_queue if gspec.max_queue >= 0 else None,
            admit_occupancy=(
                gspec.admit_occupancy if gspec.admit_occupancy >= 0 else None
            ),
            hedge_enabled=gspec.hedge,
        )
        await scheduler.start()
        tok_path = cfg.tokenizer_path or cfg.actor.path
        codec = (
            HFTokenizerCodec(tok_path) if tok_path
            else ByteFallbackCodec(cfg.actor.model_config().vocab_size)
        )
        gw = GatewayServer(
            scheduler, codec,
            GatewayConfig(
                model_id=cfg.experiment_name,
                default_tenant=gspec.default_tenant,
                api_keys=dict(gspec.api_keys),
                require_api_key=gspec.require_api_key,
                max_tokens_cap=cfg.gen.max_new_tokens_cap,
            ),
        )
        port = gspec.port or _constants.gateway_port() or network.find_free_port()
        runner = await serve_gateway(gw, "127.0.0.1", port)
        name_resolve.add(
            names.gateway(cfg.experiment_name, cfg.trial_name),
            f"http://127.0.0.1:{port}",
            replace=True,
        )

        autoscaler_task = None
        if gspec.autoscale:
            mgr_url = None

            async def _sync_manager(url: str, add: bool):
                nonlocal mgr_url
                from areal_tpu.gen.client import GenAPIClient

                if mgr_url is None:
                    try:
                        mgr_url = name_resolve.get(
                            names.gserver_manager(
                                cfg.experiment_name, cfg.trial_name
                            )
                        )
                    except name_resolve.NameEntryNotFoundError:
                        return
                try:
                    async with GenAPIClient(timeout=10.0) as c:
                        await c.post_json(
                            mgr_url,
                            "/add_server" if add else "/remove_server",
                            {"url": url}, op="autoscale",
                        )
                except Exception:
                    logger.exception("manager routed-set sync failed")

            def fetch_signals():
                scalars = telemetry.collect_fleet_scalars(
                    cfg.experiment_name, cfg.trial_name
                ) or {}
                routed = scheduler.server_urls()
                # occupancy averages over the ROUTED set: idle unrouted
                # servers report ~0 and would dilute routed-pool
                # saturation below the grow threshold
                sig = ScaleSignals.from_fleet_scalars(
                    scalars, routed=len(routed),
                    n_gen_servers=max(len(routed), 1),
                )
                # the gateway's own queue is live, not telemetry-lagged
                sig.queue_depth = float(scheduler.queue_depth())
                return sig

            def grow(n: int) -> int:
                routed = scheduler.server_urls()
                spare = [u for u in all_urls if u not in routed][:n]
                if spare:
                    scheduler.set_servers(routed + spare)
                    for u in spare:
                        t = asyncio.get_event_loop().create_task(
                            _sync_manager(u, add=True)
                        )
                        _bg_tasks.add(t)
                        t.add_done_callback(_bg_tasks.discard)
                return len(spare)

            def shrink(n: int) -> int:
                routed = scheduler.server_urls()
                n = min(n, max(len(routed) - gspec.min_servers, 0))
                victims = routed[len(routed) - n:] if n else []
                if victims:
                    scheduler.set_servers(
                        [u for u in routed if u not in victims]
                    )
                    for u in victims:
                        t = asyncio.get_event_loop().create_task(
                            _sync_manager(u, add=False)
                        )
                        _bg_tasks.add(t)
                        t.add_done_callback(_bg_tasks.discard)
                return len(victims)

            _bg_tasks: set = set()
            autoscaler = Autoscaler(
                AutoscalerConfig(
                    min_servers=gspec.min_servers,
                    max_servers=cfg.gen.n_servers,
                    interval_s=gspec.autoscale_interval_s,
                    cooldown_s=gspec.autoscale_cooldown_s,
                ),
                fetch_signals, grow, shrink,
            )
            autoscaler_task = asyncio.get_event_loop().create_task(
                autoscaler.run()
            )

        brownout_task = None
        if gspec.brownout:
            from areal_tpu.gateway.brownout import (
                BrownoutConfig,
                wire_brownout,
            )

            controller = wire_brownout(
                BrownoutConfig(
                    interval_s=gspec.brownout_interval_s,
                    min_hold_s=gspec.brownout_min_hold_s,
                    clamp_max_tokens=gspec.brownout_clamp_max_tokens,
                    weight_floor=gspec.brownout_weight_floor,
                ),
                scheduler, gw.config, scheduler._client,
            )
            brownout_task = asyncio.get_event_loop().create_task(
                controller.run()
            )

        watch = ExperimentStatusWatch(cfg.experiment_name, cfg.trial_name)
        hb = Heartbeat(cfg.experiment_name, cfg.trial_name, "gateway").start()
        tele = TelemetryExporter(
            cfg.experiment_name, cfg.trial_name, "gateway", "gateway",
            gauges_fn=lambda: {
                "gw_queue_depth": float(scheduler.queue_depth()),
                "gw_inflight": float(scheduler.inflight()),
                "gw_routed_servers": float(len(scheduler.server_urls())),
            },
        ).maybe_start()
        while watch.alive():
            await asyncio.sleep(1.0)
        tele.stop()
        hb.stop()
        if autoscaler_task is not None:
            autoscaler_task.cancel()
        if brownout_task is not None:
            brownout_task.cancel()
        await scheduler.stop()
        await runner.cleanup()

    asyncio.run(main())


def rollout_worker_main(cfg, worker_idx: int):
    import asyncio

    _setup_worker_env(cfg, "cpu")
    from areal_tpu.api.agent import make_agent
    from areal_tpu.api.dataset import DatasetUtility, make_dataset
    from areal_tpu.api.env import make_env
    from areal_tpu.api.model import GenerationHyperparameters
    from areal_tpu.system.rollout_worker import RolloutWorker

    util = DatasetUtility(
        seed=cfg.dataset.seed,
        dp_rank=worker_idx,
        world_size=cfg.rollout.n_workers,
    )
    dataset = make_dataset(
        cfg.dataset.name, util, path=cfg.dataset.path,
        max_length=cfg.dataset.max_length,
    )
    env_args = dict(cfg.rollout.env_args)
    if hasattr(dataset, "load_metadata") and "dataset_metadata" not in env_args:
        env_args["dataset_metadata"] = dataset.load_metadata()
    env = make_env(cfg.rollout.env, **env_args)
    agent_args = dict(cfg.rollout.agent_args)
    gconfig = cfg.gconfig
    if isinstance(gconfig, dict):
        gconfig = GenerationHyperparameters(**gconfig)
    agent = make_agent(cfg.rollout.agent, gconfig=gconfig, **agent_args)
    worker = RolloutWorker(
        experiment_name=cfg.experiment_name,
        trial_name=cfg.trial_name,
        worker_index=worker_idx,
        n_workers=cfg.rollout.n_workers,
        n_pullers=1,
        agent=agent,
        env=env,
        dataset=dataset,
        new_tokens_per_chunk=cfg.rollout.new_tokens_per_chunk,
        max_concurrent_tasks=cfg.rollout.max_concurrent_tasks,
    )
    from areal_tpu.system.worker_base import (
        ExperimentStatusWatch,
        Heartbeat,
        TelemetryExporter,
    )

    watch = ExperimentStatusWatch(cfg.experiment_name, cfg.trial_name)
    hb = Heartbeat(
        cfg.experiment_name, cfg.trial_name, f"rollout_worker/{worker_idx}"
    ).start()
    tele = TelemetryExporter(
        cfg.experiment_name, cfg.trial_name,
        f"rollout_worker/{worker_idx}", "rollout",
        step_fn=lambda: worker.push_cnt,
        gauges_fn=lambda: {
            "rollout_tasks_running": float(worker.n_tasks()),
            "rollout_requeued": float(worker.requeued_cnt),
            "rollout_dropped": float(worker.dropped_cnt),
        },
    ).maybe_start()
    try:
        asyncio.run(worker.run_async(should_stop=lambda: not watch.alive()))
    finally:
        tele.stop()
        hb.stop()


def _load_ppo_engines(cfg, total_steps):
    """actor / optional ref / optional critic from an experiment config —
    ONE place for the gating rules shared by the sync and async recipes."""
    actor = _load_engine(cfg.actor, total_steps=total_steps)
    ref = None
    if cfg.use_ref_model and (cfg.ppo.kl_ctl != 0 or cfg.ema_ref_eta is not None):
        ref = _load_engine(cfg.actor, with_optimizer=False)
    critic = None
    if cfg.critic is not None and not cfg.ppo.disable_value:
        critic = _load_engine(cfg.critic, is_critic=True, total_steps=total_steps)
    reward = None
    if getattr(cfg, "reward", None) is not None:
        reward = _load_engine(cfg.reward, is_critic=True, with_optimizer=False)
    return actor, ref, critic, reward


def trainer_main(cfg):
    _setup_worker_env(cfg, cfg.trainer_device)
    # pod-scale runs: each host's launcher sets AREAL_COORDINATOR/_NUM_
    # PROCESSES/_PROCESS_ID (or AREAL_COORDINATOR=auto on Cloud TPU) and the
    # trainer joins the jax.distributed world before building its mesh.
    # With AREAL_ELASTIC on, the world comes up through the world-epoch
    # protocol instead: a WorldSupervisor owns the epoch record, this rank
    # joins it, and a rank death/hang mid-run reforms the world surgically
    # rather than crashing it (docs/fault_tolerance.md "Elastic multihost").
    from areal_tpu.base import constants
    from areal_tpu.parallel import multihost

    elastic_mgr = None
    try:
        n_ranks = constants.multihost_num_processes()
    except KeyError:
        n_ranks = 0
    if constants.elastic_enabled() and n_ranks > 1:
        from areal_tpu.parallel import elastic as elastic_mod

        multihost.enable_cpu_collectives()
        elastic_mgr = elastic_mod.WorldEpochManager(
            elastic_mod.ElasticConfig(
                experiment_name=cfg.experiment_name,
                trial_name=cfg.trial_name,
                num_processes=n_ranks,
                process_id=constants.multihost_process_id(),
            )
        )
        elastic_mgr.join()
    else:
        if constants.elastic_enabled():
            # elastic mode needs a WorldSupervisor-managed multi-rank
            # world (AREAL_NUM_PROCESSES + a supervisor writing the
            # world-epoch record); the single-process local launcher has
            # neither — waiting for a record nobody writes would stall
            # every recover attempt for the full join timeout
            logger.warning(
                "AREAL_ELASTIC set but no multi-rank world "
                "(AREAL_NUM_PROCESSES absent or 1); running the standard "
                "restart-the-world path"
            )
        multihost.maybe_initialize_from_env()
    from areal_tpu.base.metrics import MetricLogger
    from areal_tpu.system.stream_dataset import PullerStreamDataset
    from areal_tpu.system.trainer_worker import (
        AsyncPPOTrainerWorker,
        TrainerControl,
    )

    from areal_tpu.system import worker_base

    # preemption plane: SIGTERM/SIGINT (how a preemptible slice ends a
    # trial) flips a flag the train loop polls; the worker then commits a
    # recover checkpoint within the deadline and we exit EXIT_PREEMPTED,
    # which run_async_ppo maps to "preempted, restart-the-world"
    shutdown = worker_base.GracefulShutdown.from_env()
    watchdog_timeout = worker_base.watchdog_timeout_from_env()
    total = cfg.control.total_train_steps
    # bind the puller first so rollout workers can rendezvous while the
    # engines load/compile
    stream = PullerStreamDataset(
        cfg.experiment_name, cfg.trial_name, 0, offline_dataset_size=10_000
    )
    actor, ref, critic, reward = _load_ppo_engines(cfg, total)
    worker = AsyncPPOTrainerWorker(
        experiment_name=cfg.experiment_name,
        trial_name=cfg.trial_name,
        actor_engine=actor,
        stream=stream,
        hp=cfg.ppo,
        control=TrainerControl(
            total_train_steps=total,
            save_freq_steps=cfg.control.save_freq_steps,
            ckpt_freq_steps=cfg.control.ckpt_freq_steps,
            ckpt_freq_secs=cfg.control.ckpt_freq_secs,
            weight_sync_freq_steps=cfg.control.weight_sync_freq_steps,
            watchdog_timeout_secs=watchdog_timeout,
        ),
        train_batch_size=cfg.train_batch_size,
        mb_spec=cfg.mb_spec,
        ref_engine=ref,
        critic_engine=critic,
        reward_engine=reward,
        hf_family=cfg.hf_family,
        metric_logger=MetricLogger(constants.get_log_root()),
        ema_ref_eta=cfg.ema_ref_eta,
        max_head_offpolicyness=cfg.manager.max_head_offpolicyness,
    )
    recovered = False
    if elastic_mgr is not None:
        # elastic startup (initial OR a relaunched rank rejoining a live
        # trial): restore without publishing, then the COLLECTIVE version
        # agreement + single publish — the exact sequence survivors run
        # in _elastic_recover, so a relaunched rank's collectives line up
        # with theirs and every rank adopts the same new version. The
        # restore is UNCONDITIONAL (not gated on recover_mode): survivors
        # always restore during a reform, and a relaunched rank skipping
        # the (collective) restore would desynchronize the new epoch;
        # recover_mode keeps governing only the outer restart-the-world
        # loop.
        recovered = worker.load_recover_checkpoint(publish=False)
        worker._agree_version_and_publish(floor=0)
    else:
        if cfg.recover_mode in ("auto", "resume"):
            # a successful recover republishes the restored model_version
            # + training_samples itself (load_recover_checkpoint)
            recovered = worker.load_recover_checkpoint()
        if not recovered:
            # publish v0 weights so the fleet starts from the trainer's
            # init
            worker.publish_weights()
    tele = None
    if multihost.is_main():
        tele = worker_base.TelemetryExporter(
            cfg.experiment_name, cfg.trial_name, "trainer", "trainer",
            step_fn=lambda: worker.step,
            gauges_fn=worker.telemetry_gauges,
        ).maybe_start()
    rc = 0
    try:
        worker.run(
            shutdown=shutdown,
            elastic=elastic_mgr,
            # surgical recovery rebuilds the engines from scratch (every
            # device array died with the old world epoch) and re-restores
            # them from the committed recover checkpoint
            engine_factory=(
                (lambda: _load_ppo_engines(cfg, total))
                if elastic_mgr is not None
                else None
            ),
        )
    except Exception:
        if elastic_mgr is None:
            raise
        # an elastic rank must not unwind through normal interpreter
        # teardown (parked runtime objects LOG(FATAL) on destruction);
        # EXIT_WORLD_FAILED tells the supervisor/launcher to escalate to
        # restart-the-world
        logger.exception("trainer rank failed beyond surgical recovery")
        rc = worker_base.EXIT_WORLD_FAILED
    finally:
        if tele is not None:
            tele.stop()
    if worker.preempted:
        rc = worker_base.EXIT_PREEMPTED
    if elastic_mgr is not None:
        elastic_mgr.stop()
        from areal_tpu.parallel import elastic as elastic_mod

        elastic_mod.hard_exit(rc)
    if rc:
        sys.exit(rc)


def evaluator_main(cfg, stop_event=None):
    """Checkpoint-watching evaluator role (≈ ``scheduler/evaluator.py:160``):
    polls the save root, scores each new ``step{N}`` export on a held-out
    set, appends to eval_result.jsonl + metric logs. ``stop_event`` (an
    mp.Event) requests a graceful exit — one final sweep runs after it is
    set so the LAST checkpoint is always evaluated."""
    _setup_worker_env(cfg, cfg.evaluator.device)
    from areal_tpu.api.dataset import DatasetUtility, make_dataset
    from areal_tpu.base import constants
    from areal_tpu.base.metrics import MetricLogger
    from areal_tpu.system.evaluator import (
        AutomaticEvaluator,
        make_generation_eval_fn,
    )

    spec = cfg.evaluator
    ds_spec = spec.dataset or cfg.dataset
    tokenizer = None
    tok_path = getattr(cfg, "tokenizer_path", None)
    if not tok_path and getattr(cfg, "rollout", None) is not None:
        # async experiments configure the tokenizer on the rollout agent
        tok_path = cfg.rollout.agent_args.get("tokenizer_path")
    if tok_path:
        import transformers

        tokenizer = transformers.AutoTokenizer.from_pretrained(tok_path)
    util = DatasetUtility(
        seed=ds_spec.seed, dp_rank=0, world_size=1, tokenizer=tokenizer
    )
    dataset = make_dataset(
        ds_spec.name, util, path=ds_spec.path, max_length=ds_spec.max_length
    )
    decode_fn = None
    if tokenizer is not None:
        decode_fn = lambda ids: tokenizer.decode(ids, skip_special_tokens=True)
    eval_fn = make_generation_eval_fn(
        cfg.actor.model_config(),
        cfg.actor.parallel_config(),
        dataset,
        spec.gconfig,
        decode_fn=decode_fn,
        max_prompts=spec.max_prompts,
    )
    ev = AutomaticEvaluator(
        constants.get_save_root(),
        eval_fn,
        os.path.join(constants.get_log_root(), "eval_result.jsonl"),
        metric_logger=MetricLogger(constants.get_log_root()),
        poll_interval=spec.poll_interval,
    )
    from areal_tpu.system.worker_base import ExperimentStatusWatch

    watch = ExperimentStatusWatch(cfg.experiment_name, cfg.trial_name)

    def should_stop():
        if stop_event is not None and stop_event.is_set():
            return True
        return not watch.alive()

    ev.run(should_stop=should_stop)


ROLE_MAINS = {
    "gen_server": gen_server_main,
    "gserver_manager": gserver_manager_main,
    "gateway": gateway_main,
    "rollout_worker": rollout_worker_main,
    "trainer": trainer_main,
    "evaluator": evaluator_main,
}


# --------------------------------------------------------------------------- #
# orchestration
# --------------------------------------------------------------------------- #


import contextlib


@contextlib.contextmanager
def _cpu_child_env(force_cpu: bool):
    """Spawned children inherit the parent env at exec, and the TPU-plugin
    sitecustomize claims the (single) accelerator at interpreter boot —
    before any code of ours runs. For CPU-designated workers, scrub the
    plugin triggers from the parent env around ``Process.start()``."""
    if not force_cpu:
        yield
        return
    saved = {
        k: os.environ.pop(k, None)
        for k in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE")
    }
    old_plat = os.environ.get("JAX_PLATFORMS")  # arealint: ok(save/restore around child spawn, not a knob read)
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is not None:
                os.environ[k] = v
        if old_plat is None:
            os.environ.pop("JAX_PLATFORMS", None)
        else:
            os.environ["JAX_PLATFORMS"] = old_plat


def _spawn_all(cfg) -> Dict[str, mp.Process]:
    ctx = mp.get_context("spawn")
    procs: Dict[str, mp.Process] = {}

    def start(name, p, force_cpu):
        with _cpu_child_env(force_cpu):
            p.start()
        procs[name] = p
        logger.info("started %s (pid %d)", name, p.pid)

    gen_cpu = cfg.gen.device == "cpu"
    for i in range(cfg.gen.n_servers):
        start(
            f"gen_server/{i}",
            ctx.Process(target=gen_server_main, args=(cfg, i), daemon=True),
            gen_cpu,
        )
    start(
        "gserver_manager",
        ctx.Process(target=gserver_manager_main, args=(cfg,), daemon=True),
        True,
    )
    if getattr(cfg, "gateway", None) is not None and cfg.gateway.enabled:
        start(
            "gateway",
            ctx.Process(target=gateway_main, args=(cfg,), daemon=True),
            True,
        )
    for i in range(cfg.rollout.n_workers):
        start(
            f"rollout_worker/{i}",
            ctx.Process(target=rollout_worker_main, args=(cfg, i), daemon=True),
            True,
        )
    start(
        "trainer",
        ctx.Process(target=trainer_main, args=(cfg,), daemon=True),
        cfg.trainer_device == "cpu",
    )
    if getattr(cfg, "evaluator", None) is not None and cfg.evaluator.enabled:
        start(
            "evaluator",
            ctx.Process(target=evaluator_main, args=(cfg,), daemon=True),
            cfg.evaluator.device == "cpu",
        )
    return procs


# --------------------------------------------------------------------------- #
# Elastic world supervision (docs/fault_tolerance.md "Elastic multihost")
# --------------------------------------------------------------------------- #


@dataclasses.dataclass
class WorldSupervisorConfig:
    """Config for one supervised N-rank elastic trainer world."""

    experiment_name: str
    trial_name: str
    num_processes: int
    # argv for rank r's process (the rank body must run the
    # parallel/elastic.py join/reform protocol; see tools/chaos.py)
    rank_cmd: "object" = None                 # Callable[[int], List[str]]
    rank_env: Optional[dict] = None           # extra env for every rank
    poll_s: float = 0.25
    # must match the ranks' AREAL_COLLECTIVE_TIMEOUT_S: the hang-path
    # grace is derived from it (see run())
    collective_timeout_s: float = 120.0
    # coalescing window for simultaneous rank exits
    exit_grace_s: float = 1.0
    # extra margin on top of collective_timeout_s before an alive,
    # unreported rank is declared wedged (covers the spread between the
    # first and last survivor reaching its collective deadline)
    report_grace_s: float = 10.0
    # total rank relaunches before the supervisor gives up and lets the
    # launcher's restart-the-world loop take over
    max_rank_restarts: int = 8
    # bound on detect -> every rank live at the new epoch
    reform_timeout_s: float = 300.0
    log_dir: Optional[str] = None             # per-rank stdout capture


class WorldSupervisor:
    """Launcher-side owner of the elastic world-epoch protocol.

    Spawns ``num_processes`` rank subprocesses, then watches two failure
    signals, handled differently:

    - **rank exit** (a dead rank): reform immediately — sweep the dead
      ranks' name_resolve residue, bump the monotonic world epoch with a
      fresh coordinator port, relaunch ONLY the dead ranks with the same
      ``--process-id``. Nobody is killed: survivors detect the broken
      world on their own (transport error or bounded-collective timeout),
      detach, and rejoin at the new epoch in place.
    - **timeout reports with no exit** (a wedged rank): surviving ranks'
      bounded collectives expired and they reported; the wedged rank is
      the alive rank that did NOT report. Because a slow-to-detect
      survivor is indistinguishable from a wedged rank until its own
      collective deadline passes, the supervisor waits a full
      ``collective_timeout_s + report_grace_s`` after the first report
      before SIGKILLing the non-reporters (a hung rank never exits on
      its own) and reforming as above.

    Counters: ``ft/rank_restarts``, ``ft/world_epochs``, and a
    ``recovery_time_s`` histogram (detection -> every rank's lease live at
    the new epoch). The supervisor is the ONLY writer of the world record
    AND the host of every epoch's coordination service
    (``elastic.host_service``) — so no rank death can close a service
    socket that surviving clients poll, there is no leader election, and
    a dead rank 0 recovers exactly like any other rank.
    """

    def __init__(self, cfg: WorldSupervisorConfig):
        self.cfg = cfg
        self.epoch = -1
        self.procs: Dict[int, "object"] = {}
        self.rank_restarts = 0
        self.recovery_times: List[float] = []
        self._log_files: Dict[int, object] = {}

    # -- spawning --------------------------------------------------------

    def _spawn_rank(self, rank: int):
        import subprocess

        from areal_tpu.base import constants

        env = dict(os.environ)
        env.update(constants.get_env_vars(
            AREAL_NUM_PROCESSES=self.cfg.num_processes,
            AREAL_PROCESS_ID=rank,
        ))
        # per-world overrides win over inherited/forwarded values
        env.update(self.cfg.rank_env or {})
        stdout = None
        if self.cfg.log_dir:
            os.makedirs(self.cfg.log_dir, exist_ok=True)
            prev = self._log_files.pop(rank, None)
            if prev is not None:
                try:  # a relaunch must not leak the old incarnation's fd
                    prev.close()
                except OSError:
                    pass
            f = open(
                os.path.join(self.cfg.log_dir, f"rank{rank}.log"), "ab"
            )
            self._log_files[rank] = f
            stdout = f
        self.procs[rank] = subprocess.Popen(
            self.cfg.rank_cmd(rank), env=env,
            stdout=stdout, stderr=subprocess.STDOUT if stdout else None,
        )
        logger.info(
            "world rank %d spawned (pid %d)", rank, self.procs[rank].pid
        )

    def _write_world(self):
        from areal_tpu.base import network
        from areal_tpu.parallel import elastic as elastic_mod

        port = network.find_free_port()
        # the supervisor hosts the epoch's coordination service itself —
        # see the class docstring; the service must be up before the
        # record is visible, or a fast rank's connect would race it
        elastic_mod.host_service(port, self.cfg.num_processes)
        elastic_mod.write_world(
            self.cfg.experiment_name, self.cfg.trial_name,
            elastic_mod.WorldState(
                epoch=self.epoch,
                coordinator=f"127.0.0.1:{port}",
                num_processes=self.cfg.num_processes,
            ),
        )
        logger.info(
            "world epoch %d published (coordinator port %d)",
            self.epoch, port,
        )

    def start(self):
        """Publish epoch 0 and spawn every rank. When the telemetry knob
        is on, the supervisor also exports its own snapshots (role
        ``supervisor``, step = world epoch) so ``ft/rank_restarts`` /
        ``ft/world_epochs`` and the ``recovery_time_s`` histogram reach
        the ``fleet/`` aggregate and the obs CLI's supervisor row."""
        from areal_tpu.system import worker_base

        self.epoch = 0
        self._write_world()
        for r in range(self.cfg.num_processes):
            self._spawn_rank(r)
        self._tele = worker_base.TelemetryExporter(
            self.cfg.experiment_name, self.cfg.trial_name,
            "world_supervisor", "supervisor",
            step_fn=lambda: self.epoch,
            gauges_fn=lambda: {
                "world_epoch": float(self.epoch),
                "ranks_alive": float(sum(
                    1 for p in self.procs.values() if p.poll() is None
                )),
            },
        ).maybe_start()
        return self

    # -- failure handling ------------------------------------------------

    @staticmethod
    def decide_culprits(
        exited: Dict[int, int],
        reports: Dict[int, dict],
        alive: List[int],
        wedge_deadline_passed: bool = False,
    ) -> List[int]:
        """Who must be relaunched: every non-zero exit always; *alive*
        ranks without a survivor report only once the wedge deadline
        (collective timeout + grace since the first report) has passed —
        before that, a slow-to-detect survivor is indistinguishable from a
        wedged rank. Clean exits (code 0) are never culprits."""
        culprits = {r for r, code in exited.items() if code != 0}
        if wedge_deadline_passed:
            culprits |= {r for r in alive if r not in reports}
        return sorted(culprits)

    def _reform(
        self,
        culprits: List[int],
        exited: Dict[int, int],
        reports: Dict[int, dict],
        detect_t: float,
    ) -> None:
        import signal as signal_mod

        from areal_tpu.base import metrics as metrics_mod
        from areal_tpu.parallel import elastic as elastic_mod

        logger.warning(
            "world epoch %d failed: exited=%s reports=%s -> culprits=%s",
            self.epoch, exited, sorted(reports), culprits,
        )
        for r in culprits:
            p = self.procs.get(r)
            if p is not None and p.poll() is None:
                logger.warning("SIGKILLing wedged rank %d (pid %d)", r, p.pid)
                p.send_signal(signal_mod.SIGKILL)
                p.wait()
        # lease hygiene: dead ranks' keys must not accumulate across
        # reformations (regression-tested in tests/test_elastic.py)
        for r in culprits:
            elastic_mod.sweep_rank_keys(
                self.cfg.experiment_name, self.cfg.trial_name, r
            )
        elastic_mod.sweep_timeout_reports(
            self.cfg.experiment_name, self.cfg.trial_name, self.epoch
        )
        self.epoch += 1
        self._write_world()
        for r in culprits:
            self._spawn_rank(r)
        self.rank_restarts += len(culprits)
        metrics_mod.counters.add(metrics_mod.FT_RANK_RESTARTS, len(culprits))
        metrics_mod.counters.add(metrics_mod.FT_WORLD_EPOCHS)
        # recovery completes when every rank's lease is live at the new
        # epoch (the world actually re-formed, not merely re-published)
        deadline = time.monotonic() + self.cfg.reform_timeout_s
        while time.monotonic() < deadline:
            leases = elastic_mod.read_leases(
                self.cfg.experiment_name, self.cfg.trial_name
            )
            at_epoch = [
                r for r, d in leases.items()
                if d.get("epoch") == self.epoch
            ]
            if len(at_epoch) >= self.cfg.num_processes:
                break
            if any(
                p.poll() is not None and p.returncode != 0
                for p in self.procs.values()
            ):
                break  # the new epoch is already failing; next loop turn
            time.sleep(self.cfg.poll_s)
        took = time.monotonic() - detect_t
        self.recovery_times.append(took)
        metrics_mod.counters.observe(metrics_mod.RECOVERY_TIME_S, took)
        logger.warning(
            "world reformed into epoch %d in %.1fs (%d rank restarts total)",
            self.epoch, took, self.rank_restarts,
        )

    def run(self, timeout: Optional[float] = None) -> int:
        """Supervise until every rank exits 0 (returns 0), the restart
        budget is exhausted, or ``timeout`` expires (returns 1 after
        tearing the world down)."""
        from areal_tpu.parallel import elastic as elastic_mod

        t0 = time.monotonic()
        first_report_t: Optional[float] = None
        try:
            while True:
                if timeout is not None and time.monotonic() - t0 > timeout:
                    logger.error("world supervision timed out")
                    return 1
                codes = {r: p.poll() for r, p in self.procs.items()}
                if all(c == 0 for c in codes.values()):
                    return 0
                exited = {
                    r: c for r, c in codes.items()
                    if c is not None and c != 0
                }
                # Two exit codes end supervision instead of triggering a
                # relaunch: EXIT_WORLD_FAILED (a rank explicitly
                # escalating — its reform budget is spent; a fresh budget
                # would multiply the churn the code exists to stop) and
                # EXIT_PREEMPTED (the slice is being reclaimed — the rank
                # committed its recover checkpoint and relaunching it just
                # burns the preemption grace window on churn).
                from areal_tpu.system import worker_base as wb

                gave_up = [
                    r for r, c in exited.items()
                    if c == wb.EXIT_WORLD_FAILED
                ]
                if gave_up:
                    logger.error(
                        "rank(s) %s exited EXIT_WORLD_FAILED: escalating "
                        "to restart-the-world", gave_up,
                    )
                    return 1
                preempted = [
                    r for r, c in exited.items()
                    if c == wb.EXIT_PREEMPTED
                ]
                if preempted:
                    logger.warning(
                        "rank(s) %s exited EXIT_PREEMPTED: world preempted"
                        " — state is the committed checkpoint; not "
                        "relaunching", preempted,
                    )
                    return wb.EXIT_PREEMPTED
                reports = elastic_mod.read_timeout_reports(
                    self.cfg.experiment_name, self.cfg.trial_name, self.epoch
                )
                if not exited and not reports:
                    first_report_t = None
                    time.sleep(self.cfg.poll_s)
                    continue
                if self.rank_restarts >= self.cfg.max_rank_restarts:
                    logger.error(
                        "rank-restart budget (%d) exhausted; giving up on "
                        "surgical recovery", self.cfg.max_rank_restarts,
                    )
                    return 1
                if exited:
                    # dead-rank path: reform NOW, relaunch only the dead.
                    # Survivors detect the broken world on their own
                    # (transport error / bounded timeout) and rejoin —
                    # nobody gets killed on a guess.
                    detect_t = time.monotonic()
                    time.sleep(self.cfg.exit_grace_s)  # coalesce siblings
                    exited = {
                        r: p.returncode
                        for r, p in self.procs.items()
                        if p.poll() is not None and p.returncode != 0
                    }
                    reports = elastic_mod.read_timeout_reports(
                        self.cfg.experiment_name, self.cfg.trial_name,
                        self.epoch,
                    )
                    alive = [
                        r for r, p in self.procs.items() if p.poll() is None
                    ]
                    culprits = self.decide_culprits(
                        exited, reports, alive, wedge_deadline_passed=False
                    )
                    self._reform(culprits, exited, reports, detect_t)
                    first_report_t = None
                    continue
                # hang path: reports but no exit. A wedged rank can only
                # be told apart from a slow-to-detect survivor after every
                # survivor's own collective deadline had a chance to fire.
                if first_report_t is None:
                    first_report_t = time.monotonic()
                alive = [
                    r for r, p in self.procs.items() if p.poll() is None
                ]
                deadline_passed = (
                    time.monotonic() - first_report_t
                    > self.cfg.collective_timeout_s + self.cfg.report_grace_s
                )
                if deadline_passed or all(r in reports for r in alive):
                    culprits = self.decide_culprits(
                        {}, reports, alive,
                        wedge_deadline_passed=deadline_passed,
                    )
                    self._reform(culprits, {}, reports, first_report_t)
                    first_report_t = None
                    continue
                time.sleep(self.cfg.poll_s)
        finally:
            self.terminate()

    def terminate(self):
        tele = getattr(self, "_tele", None)
        if tele is not None:
            tele.stop()
            self._tele = None
        for r, p in self.procs.items():
            if p.poll() is None:
                p.kill()
        for p in self.procs.values():
            try:
                p.wait(timeout=10)
            except Exception:
                pass
        for f in self._log_files.values():
            try:
                f.close()
            except OSError:
                pass
        self._log_files.clear()


def run_async_ppo(cfg) -> int:
    """Launch the full async-PPO world; restart on failure per recover_mode.
    Returns the trainer's exit code of the final attempt."""
    attempts = 1 + (cfg.recover_retries if cfg.recover_mode == "auto" else 0)
    # the launcher owns the experiment lifecycle record: workers poll it and
    # self-terminate when it goes away (system/worker_base.py)
    _setup_worker_env(cfg, "")
    from areal_tpu.system import worker_base

    for attempt in range(attempts):
        if attempt > 0:
            logger.warning("recover attempt %d/%d", attempt, attempts - 1)
            cfg = dataclasses.replace(cfg, recover_mode="resume")
        worker_base.mark_experiment_running(cfg.experiment_name, cfg.trial_name)
        procs = _spawn_all(cfg)
        trainer = procs["trainer"]
        failed = False
        try:
            while trainer.is_alive():
                trainer.join(timeout=5)
                for name, p in procs.items():
                    # the evaluator is best-effort: its death never restarts
                    # the world (matching the reference's detached eval jobs)
                    if name in ("trainer", "evaluator"):
                        continue
                    if not p.is_alive():
                        logger.error("%s died (exit %s)", name, p.exitcode)
                        failed = True
                        break
                if failed:
                    break
        finally:
            # graceful first: flip the status so watchers exit on their own,
            # then terminate stragglers
            worker_base.mark_experiment_stopped(cfg.experiment_name, cfg.trial_name)
            deadline = time.time() + 5
            for name, p in procs.items():
                if name != "evaluator":
                    p.join(timeout=max(0.1, deadline - time.time()))
            for name, p in procs.items():
                if name != "evaluator" and p.is_alive():
                    p.terminate()
            ev = procs.get("evaluator")
            if ev is not None:
                # the evaluator notices the stop on its next poll and runs a
                # final sweep so the LAST checkpoint is always scored — give
                # it real time before terminating
                ev.join(timeout=300)
            for p in procs.values():
                if p.is_alive():
                    p.terminate()
            for p in procs.values():
                p.join(timeout=10)
            # SIGKILL escalation: the trainer's GracefulShutdown turns
            # SIGTERM into a (possibly minutes-long) preemption save, and a
            # straggler outliving the join would overlap the next attempt's
            # freshly spawned world (same staging dirs, same devices). The
            # commit protocol makes the hard kill safe: the previous
            # committed checkpoint survives a death mid-save.
            for name, p in procs.items():
                if p.is_alive():
                    logger.warning(
                        "%s survived terminate(); escalating to kill", name
                    )
                    p.kill()
                    p.join(timeout=10)
        if trainer.exitcode == 0 and not failed:
            return 0
        if trainer.exitcode == worker_base.EXIT_PREEMPTED and not failed:
            # NOT a crash: the trainer committed a recover checkpoint inside
            # its deadline — restart-the-world resumes it (recover_mode
            # auto), or the code propagates so an outer scheduler can.
            # (With `failed` set, exit 75 just means OUR teardown SIGTERMed
            # the trainer after a sibling died — that is the crash path.)
            logger.warning(
                "trainer preempted (exit %d): recover checkpoint committed; "
                "restart-the-world", worker_base.EXIT_PREEMPTED,
            )
        if cfg.recover_mode != "auto":
            break
    rc = trainer.exitcode if trainer.exitcode is not None else 1
    if failed and rc == worker_base.EXIT_PREEMPTED:
        # a sibling worker's crash triggered the teardown; reporting the
        # trainer's teardown-induced exit code would tell an outer
        # scheduler "state intact, try again" about a reproducible crash
        rc = 1
    return rc


def run_sync_ppo(cfg) -> int:
    """Sync PPO runs in-process: generation happens on the trainer's own
    mesh/params (no fleet, no weight publish); the evaluator (if enabled)
    runs as a side process on host 0."""
    _setup_worker_env(cfg, cfg.trainer_device)
    from areal_tpu.parallel import multihost

    multihost.maybe_initialize_from_env()
    from areal_tpu.api.dataset import DatasetUtility, make_dataset
    from areal_tpu.base import constants
    from areal_tpu.base.metrics import MetricLogger
    from areal_tpu.system.sync_trainer import SyncPPOTrainerWorker
    from areal_tpu.system.trainer_worker import TrainerControl

    from areal_tpu.system import worker_base

    if multihost.is_main():
        worker_base.mark_experiment_running(cfg.experiment_name, cfg.trial_name)
    ev_proc = ev_stop = None
    if cfg.evaluator.enabled and multihost.is_main():
        ctx = mp.get_context("spawn")
        ev_stop = ctx.Event()
        with _cpu_child_env(cfg.evaluator.device == "cpu"):
            ev_proc = ctx.Process(
                target=evaluator_main, args=(cfg, ev_stop), daemon=True
            )
            ev_proc.start()

    tokenizer = None
    if cfg.tokenizer_path:
        import transformers

        tokenizer = transformers.AutoTokenizer.from_pretrained(cfg.tokenizer_path)
    util = DatasetUtility(
        seed=cfg.dataset.seed, dp_rank=0, world_size=1, tokenizer=tokenizer
    )
    dataset = make_dataset(
        cfg.dataset.name, util, path=cfg.dataset.path,
        max_length=cfg.dataset.max_length,
    )
    total = cfg.control.total_train_steps
    actor, ref, critic, _ = _load_ppo_engines(cfg, total)
    decode_fn = None
    if tokenizer is not None:
        decode_fn = lambda ids: tokenizer.decode(ids, skip_special_tokens=True)
    worker = SyncPPOTrainerWorker(
        experiment_name=cfg.experiment_name,
        trial_name=cfg.trial_name,
        actor_engine=actor,
        dataset=dataset,
        hp=cfg.ppo,
        ghp=cfg.gconfig,
        control=TrainerControl(
            total_train_steps=total,
            save_freq_steps=cfg.control.save_freq_steps,
        ),
        batch_size=cfg.batch_size,
        mb_spec=cfg.mb_spec,
        ref_engine=ref,
        critic_engine=critic,
        ema_ref_eta=cfg.ema_ref_eta,
        decode_fn=decode_fn,
        hf_family=cfg.hf_family,
        metric_logger=MetricLogger(constants.get_log_root()),
        seed=cfg.seed,
    )
    try:
        worker.run()
    finally:
        if multihost.is_main():
            worker_base.mark_experiment_stopped(cfg.experiment_name, cfg.trial_name)
        if ev_proc is not None:
            # graceful stop: the evaluator runs one final sweep so the last
            # checkpoint export is always scored
            ev_stop.set()
            ev_proc.join(timeout=300)
            if ev_proc.is_alive():
                ev_proc.terminate()
                ev_proc.join(timeout=10)
    return 0


def _run_supervised(cfg, *, is_critic: bool, interface_name: str,
                    dataset_kwargs=None, interface_kwargs=None) -> int:
    """Shared body of the in-process supervised recipes (SFT / paired-RW):
    one trainer program, no fleet — only the objective differs."""
    _setup_worker_env(cfg, "")
    from areal_tpu.api.data import MicroBatchSpec
    from areal_tpu.api.dataset import DatasetUtility, make_dataset
    from areal_tpu.base import constants
    from areal_tpu.base.metrics import MetricLogger
    from areal_tpu.system.trainer_worker import SFTTrainerWorker, TrainerControl

    dataset_kwargs = dataset_kwargs or {}
    tokenizer = None
    if cfg.tokenizer_path:
        import transformers

        tokenizer = transformers.AutoTokenizer.from_pretrained(cfg.tokenizer_path)
    util = DatasetUtility(
        seed=cfg.dataset.seed, dp_rank=0, world_size=1, tokenizer=tokenizer
    )
    dataset = make_dataset(
        cfg.dataset.name, util, path=cfg.dataset.path,
        max_length=cfg.dataset.max_length, **dataset_kwargs,
    )
    eval_ds = None
    if cfg.eval_dataset is not None:
        eval_ds = make_dataset(
            cfg.eval_dataset.name, util, path=cfg.eval_dataset.path,
            max_length=cfg.eval_dataset.max_length, **dataset_kwargs,
        )
    engine = _load_engine(
        cfg.model, is_critic=is_critic, total_steps=cfg.control.total_train_steps
    )
    worker = SFTTrainerWorker(
        experiment_name=cfg.experiment_name,
        trial_name=cfg.trial_name,
        engine=engine,
        dataset=dataset,
        eval_dataset=eval_ds,
        control=TrainerControl(
            total_train_steps=cfg.control.total_train_steps,
            save_freq_steps=cfg.control.save_freq_steps,
        ),
        batch_size=cfg.batch_size,
        mb_spec=MicroBatchSpec(max_tokens_per_mb=cfg.max_tokens_per_mb),
        hf_family=cfg.hf_family,
        metric_logger=MetricLogger(constants.get_log_root()),
        interface_name=interface_name,
        interface_kwargs=interface_kwargs,
    )
    worker.run()
    return 0


def run_rw(cfg) -> int:
    """Paired reward-model training (≈ the reference's rw experiment):
    critic-architecture model + Bradley-Terry pairwise loss over
    ``rw_paired`` data; exports HF checkpoints usable as the "reward"
    engine in RM-scored PPO."""
    return _run_supervised(
        cfg,
        is_critic=True,
        interface_name="reward",
        dataset_kwargs={"max_pairs_per_prompt": cfg.max_pairs_per_prompt},
        interface_kwargs={"max_pairs_per_prompt": cfg.max_pairs_per_prompt},
    )




def run_sft(cfg) -> int:
    """SFT runs in-process: one trainer program, no fleet."""
    return _run_supervised(cfg, is_critic=False, interface_name="sft")
