"""Profiling experiment: timed train steps on synthetic data.

Counterpart of the reference's null/profile experiment
(``realhf/experiments/common/null_exp.py`` + ``training/main_profile.py``):
run N timed SFT steps of a given model/parallelism on synthetic packed
batches, print per-step wall time and achieved TFLOP/s as one JSON line.
Combine with ``AREAL_DUMP_TRACE=1`` to capture ``jax.profiler`` traces of
exactly these steps (``base/tracing.py``).
"""

import argparse
import dataclasses
import json
import logging
import sys
import time
from typing import List

logger = logging.getLogger("areal_tpu.profile")


def run_profile(
    model_spec,
    seqlens: List[int],
    n_steps: int = 8,
    n_warmup: int = 2,  # >= 1: the first step compiles
    n_mbs: int = 1,
    peak_flops: float = 197e12,
    seed: int = 0,
) -> dict:
    import numpy as np

    import jax

    from areal_tpu.api.data import MicroBatchSpec, SequenceSample
    from areal_tpu.base import flops as flops_mod
    from areal_tpu.base.tracing import maybe_trace
    from areal_tpu.interfaces.sft import sft_loss_fn
    from areal_tpu.train.engine import TrainEngine

    cfg = model_spec.model_config()
    eng = TrainEngine(
        cfg, model_spec.parallel_config(), model_spec.optimizer
    )
    eng.init_random(seed)
    eng.setup_optimizer(total_train_steps=max(n_steps * 10, 100))

    T = sum(seqlens)
    rng = np.random.default_rng(seed)
    sample = SequenceSample.from_default(
        ids=list(range(len(seqlens))),
        seqlens=list(seqlens),
        data={
            "packed_input_ids": rng.integers(0, cfg.vocab_size, T).astype(
                np.int64
            ),
            "prompt_mask": np.zeros(T, bool),
        },
    )
    spec = MicroBatchSpec(n_mbs=n_mbs, max_tokens_per_mb=T)

    if n_steps < 1:
        raise ValueError(f"n_steps must be >= 1, got {n_steps}")
    for _ in range(max(n_warmup, 1)):  # at least one: the first step compiles
        stats = eng.train_batch(sample, spec, sft_loss_fn, fetch_stats=False)
    jax.device_get(stats["loss"])

    with maybe_trace("profile"):
        t0 = time.perf_counter()
        for _ in range(n_steps):
            stats = eng.train_batch(
                sample, spec, sft_loss_fn, fetch_stats=False
            )
        jax.device_get(stats["loss"])
        dt = (time.perf_counter() - t0) / n_steps

    fl = flops_mod.train_flops(cfg, T, seqlens=seqlens)
    return {
        "metric": "profile_step",
        "step_time_s": round(dt, 5),
        "tokens_per_s": round(T / dt, 1),
        "tflops_per_s": round(fl / dt / 1e12, 2),
        "mfu": round(fl / dt / peak_flops, 4),
        "n_params": int(flops_mod.param_count(cfg)),
        "seqlens": list(seqlens),
        "n_steps": n_steps,
    }


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    ap = argparse.ArgumentParser(prog="areal_tpu profile")
    ap.add_argument("--config", default=None, help="YAML with a ModelSpec")
    ap.add_argument("--seqlens", default="512x8",
                    help="'LENxN' or comma list, e.g. 512x8 or 8192")
    ap.add_argument("--n-steps", type=int, default=8)
    ap.add_argument("--n-mbs", type=int, default=1)
    ap.add_argument("--peak-flops", type=float, default=197e12)
    ap.add_argument("overrides", nargs="*")
    args = ap.parse_args(argv)

    from areal_tpu.experiments.config import ModelSpec
    from areal_tpu.experiments import load_config

    spec = load_config(ModelSpec, args.config, args.overrides)
    if "x" in args.seqlens:
        ln, n = args.seqlens.split("x")
        seqlens = [int(ln)] * int(n)
    else:
        seqlens = [int(x) for x in args.seqlens.split(",")]
    out = run_profile(
        spec, seqlens, n_steps=args.n_steps, n_mbs=args.n_mbs,
        peak_flops=args.peak_flops,
    )
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
