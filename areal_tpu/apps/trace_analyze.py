"""CLI for the xplane trace analyzer.

::

    python -m areal_tpu.apps.trace_analyze /tmp/areal_trace [--top 20] \
        [--json]

Prints the per-plane device-time breakdown (compute / p2p_comm /
coll_comm / memoryIO / idle / misc) the reference derives from chrome
traces (``realhf/base/monitor.py:404-610``) — one command instead of the
by-hand accounting earlier rounds used.
"""

import argparse
import json
import sys

from areal_tpu.base.trace_analyzer import analyze_xspace, find_xplane_files


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir", help="dir passed to jax.profiler.trace "
                    "(or a .xplane.pb file)")
    ap.add_argument("--top", type=int, default=15)
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    if args.trace_dir.endswith(".xplane.pb"):
        files = [args.trace_dir]
    else:
        files = find_xplane_files(args.trace_dir)
    if not files:
        print(f"no .xplane.pb under {args.trace_dir}", file=sys.stderr)
        return 1
    summaries = []
    for f in files:
        summaries.extend(analyze_xspace(f))
    if args.as_json:
        print(json.dumps([s.as_dict() for s in summaries], indent=2))
    else:
        for s in summaries:
            print(s.format_table(args.top))
            print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
