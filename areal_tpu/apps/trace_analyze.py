"""CLI for the xplane trace analyzer.

::

    python -m areal_tpu.apps.trace_analyze /tmp/areal_trace [--top 20] \
        [--json]

Prints the per-plane device-time breakdown (compute / p2p_comm /
coll_comm / memoryIO / idle / misc) the reference derives from chrome
traces (``realhf/base/monitor.py:404-610``) — one command instead of the
by-hand accounting earlier rounds used.
"""

import argparse
import json
import sys

from areal_tpu.base.trace_analyzer import (
    BUCKETS,
    TraceAnalyzerUnavailable,
    analyze_xspace,
    find_xplane_files,
)


def _load(path):
    if path.endswith(".xplane.pb"):
        files = [path]
    else:
        files = find_xplane_files(path)
    if not files:
        print(f"no .xplane.pb under {path}", file=sys.stderr)
        return None
    summaries = []
    try:
        for f in files:
            summaries.extend(analyze_xspace(f))
    except TraceAnalyzerUnavailable as e:
        print(str(e), file=sys.stderr)
        return None
    if not summaries:
        print(
            f"{path}: xplane files parsed but no device/op plane found",
            file=sys.stderr,
        )
    return summaries


def _compare(a, b, top):
    """Side-by-side bucket + top-op deltas of two runs' first planes —
    the A/B reading (e.g. a kernel flag on vs off) rounds used to do by
    hand across two analyzer dumps."""
    sa, sb = a[0], b[0]
    print(f"{'':<12} {'A (s)':>12} {'B (s)':>12} {'B/A':>7}")
    ta, tb = sa.device_total_s, sb.device_total_s
    rt = f"{tb / ta:7.3f}" if ta > 1e-12 else "      -"
    print(f"{'device':<12} {ta:>12.6f} {tb:>12.6f} {rt}")
    for k in BUCKETS:
        va = sa.buckets_s.get(k, 0.0)
        vb = sb.buckets_s.get(k, 0.0)
        ratio = f"{vb / va:7.3f}" if va > 1e-12 else "      -"
        print(f"{k:<12} {va:>12.6f} {vb:>12.6f} {ratio}")
    ops_a = {n: s for n, s, _, _ in sa.top_ops}
    ops_b = {n: s for n, s, _, _ in sb.top_ops}
    print(f"\n{'top op':<48} {'A (s)':>10} {'B (s)':>10}")
    seen = sorted(
        set(list(ops_a)[:top]) | set(list(ops_b)[:top]),
        key=lambda n: -(ops_a.get(n, 0.0) + ops_b.get(n, 0.0)),
    )
    for n in seen[:top]:
        print(f"{n[:48]:<48} {ops_a.get(n, 0.0):>10.6f} "
              f"{ops_b.get(n, 0.0):>10.6f}")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir", help="dir passed to jax.profiler.trace "
                    "(or a .xplane.pb file)")
    ap.add_argument("--top", type=int, default=15)
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument(
        "--compare", metavar="TRACE_B", default=None,
        help="second trace: print bucket + top-op deltas (A/B a flag)",
    )
    args = ap.parse_args(argv)

    summaries = _load(args.trace_dir)
    if not summaries:
        return 1
    if args.compare:
        if args.as_json:
            ap.error("--json is not supported with --compare")
        other = _load(args.compare)
        if not other:
            return 1
        _compare(summaries, other, args.top)
        return 0
    if args.as_json:
        print(json.dumps([s.as_dict() for s in summaries], indent=2))
    else:
        for s in summaries:
            print(s.format_table(args.top))
            print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
