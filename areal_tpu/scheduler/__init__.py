"""Scheduler backends (≈ ``realhf/scheduler/``)."""

from areal_tpu.scheduler.client import (  # noqa: F401
    JobException,
    JobInfo,
    JobState,
    LocalSchedulerClient,
    SchedulerClient,
    SlurmSchedulerClient,
    make_scheduler,
)
