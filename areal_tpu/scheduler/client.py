"""Scheduler clients: submit/track/stop worker jobs on a cluster.

Counterpart of the reference's scheduler layer
(``realhf/scheduler/client.py:52`` contract, ``scheduler/local/client.py``
subprocess backend, ``scheduler/slurm/client.py`` sbatch backend). The
local multiprocess launcher (``apps/launcher.py``) covers the common
single-host path; these clients are the multi-node story: each worker role
becomes a scheduled job running ``python -m areal_tpu.apps.launcher_worker``
(or any command), and the launcher polls job states instead of process
handles.

The Slurm client builds standard ``sbatch --wrap`` submissions (one job per
worker, TPU hosts requested via ``--gres``); command construction is pure
and unit-tested, submission requires a live Slurm control plane.
"""

import dataclasses
import enum
import logging
import re
import shutil
import subprocess
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger("areal_tpu.scheduler")


class JobState(enum.Enum):
    NOT_FOUND = 0
    PENDING = 1
    RUNNING = 2
    COMPLETED = 3
    FAILED = 4
    CANCELLED = 5


class JobException(Exception):
    def __init__(self, run_name: str, worker_type: str, host: str, reason: JobState):
        super().__init__(f"Job {run_name}:{worker_type} {reason} at {host}")
        self.run_name = run_name
        self.worker_type = worker_type
        self.host = host
        self.reason = reason


@dataclasses.dataclass
class JobInfo:
    name: str
    state: JobState
    host: Optional[str] = None
    submit_time: Optional[float] = None
    slurm_id: Optional[str] = None


class SchedulerClient:
    """≈ ``realhf/scheduler/client.py:52``."""

    def __init__(self, expr_name: str, trial_name: str):
        self.expr_name = expr_name
        self.trial_name = trial_name
        self.run_name = f"{expr_name}_{trial_name}"

    def submit(self, worker_type: str, cmd: List[str], **kwargs) -> str:
        raise NotImplementedError()

    def submit_array(self, worker_type: str, cmd: List[str], count: int, **kwargs):
        return [
            self.submit(f"{worker_type}/{i}", cmd + [f"--worker-index={i}"], **kwargs)
            for i in range(count)
        ]

    def stop(self, job_name: str):
        raise NotImplementedError()

    def stop_all(self):
        for name in list(self._jobs()):
            self.stop(name)

    def find(self, job_name: str) -> JobInfo:
        raise NotImplementedError()

    def find_all(self, regex: str = ".*") -> List[JobInfo]:
        pat = re.compile(regex)
        return [self.find(n) for n in self._jobs() if pat.match(n)]

    def _jobs(self) -> List[str]:
        raise NotImplementedError()

    def wait(self, timeout: Optional[float] = None, poll: float = 2.0,
             raise_on_failure: bool = True) -> List[JobInfo]:
        """Block until every job reaches a terminal state (or timeout).
        ≈ the reference's wait loop with check_status semantics."""
        t0 = time.time()
        while True:
            infos = self.find_all()
            bad = [i for i in infos if i.state in (JobState.FAILED, JobState.CANCELLED)]
            if bad and raise_on_failure:
                self.stop_all()
                b = bad[0]
                raise JobException(self.run_name, b.name, b.host or "?", b.state)
            if all(
                i.state in (JobState.COMPLETED, JobState.FAILED,
                            JobState.CANCELLED, JobState.NOT_FOUND)
                for i in infos
            ):
                return infos
            if timeout is not None and time.time() - t0 > timeout:
                raise TimeoutError(f"jobs still running after {timeout}s")
            time.sleep(poll)


class LocalSchedulerClient(SchedulerClient):
    """Subprocess backend (≈ ``scheduler/local/client.py``): one OS process
    per job on this host."""

    def __init__(self, expr_name: str, trial_name: str):
        super().__init__(expr_name, trial_name)
        self._procs: Dict[str, subprocess.Popen] = {}
        self._hosts: Dict[str, str] = {}

    def submit(self, worker_type: str, cmd: List[str], env=None, **kwargs) -> str:
        if worker_type in self._procs:
            raise ValueError(f"job {worker_type} already submitted")
        p = subprocess.Popen(cmd, env=env)
        self._procs[worker_type] = p
        logger.info("local job %s: pid %d: %s", worker_type, p.pid, cmd)
        return str(p.pid)

    def _jobs(self):
        return list(self._procs)

    def find(self, job_name: str) -> JobInfo:
        p = self._procs.get(job_name)
        if p is None:
            return JobInfo(name=job_name, state=JobState.NOT_FOUND)
        rc = p.poll()
        if rc is None:
            state = JobState.RUNNING
        elif rc == 0:
            state = JobState.COMPLETED
        elif rc in (-15, -9):
            state = JobState.CANCELLED
        else:
            state = JobState.FAILED
        return JobInfo(name=job_name, state=state, host="localhost")

    def stop(self, job_name: str):
        p = self._procs.get(job_name)
        if p is not None and p.poll() is None:
            p.terminate()
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=5)


@dataclasses.dataclass
class SlurmArraySubmission:
    """One worker role as ONE sbatch job with N jobsteps (pure description;
    nothing touches the filesystem until ``SlurmSchedulerClient.submit_array``
    writes it). ≈ the reference's ``SlurmLaunchInfo`` + ``commit()``
    (``realhf/scheduler/slurm/utils.py:140-420``): a batch script whose
    ``srun -K --multi-prog`` fans one task per worker, a multiprog file
    mapping task ranks to commands, and an optional hostfile pinning ranks
    to hosts via ``--distribution=arbitrary``."""

    worker_type: str
    ntasks: int
    batch_script: str
    multiprog_content: str
    hostfile_content: Optional[str]
    script_path: str
    multiprog_path: str
    hostfile_path: Optional[str]


# Slurm state names -> JobState (≈ scheduler/slurm/utils.py)
_SLURM_STATES = {
    "PENDING": JobState.PENDING,
    "CONFIGURING": JobState.PENDING,
    "RUNNING": JobState.RUNNING,
    "COMPLETING": JobState.RUNNING,
    "COMPLETED": JobState.COMPLETED,
    "FAILED": JobState.FAILED,
    "OUT_OF_MEMORY": JobState.FAILED,
    "TIMEOUT": JobState.FAILED,
    "NODE_FAIL": JobState.FAILED,
    "CANCELLED": JobState.CANCELLED,
    "PREEMPTED": JobState.CANCELLED,
}


class SlurmSchedulerClient(SchedulerClient):
    """sbatch backend (≈ ``scheduler/slurm/client.py``). Each worker role is
    one sbatch job; TPU hosts are whole-node allocations (the per-host chips
    are not a Slurm GRES on TPU pods — one task per node, jax.distributed
    wires the slice)."""

    def __init__(
        self,
        expr_name: str,
        trial_name: str,
        partition: Optional[str] = None,
        container_image: Optional[str] = None,
        log_dir: str = "/tmp/areal_tpu_slurm",
        extra_sbatch_args: Optional[List[str]] = None,
    ):
        super().__init__(expr_name, trial_name)
        self.partition = partition
        self.container_image = container_image
        self.log_dir = log_dir
        self.extra = list(extra_sbatch_args or [])
        self._job_ids: Dict[str, str] = {}
        # last state seen per job: transient controller errors fall back to
        # this instead of crashing the wait() loop (or worse, reporting
        # NOT_FOUND, which wait() treats as terminal)
        self._last_state: Dict[str, JobState] = {}

    # -- command construction (pure; unit-testable without slurm) -------- #

    def build_sbatch_cmd(
        self,
        worker_type: str,
        cmd: List[str],
        nodes: int = 1,
        cpus_per_task: int = 8,
        mem_gb: int = 32,
        time_limit: Optional[str] = None,
    ) -> List[str]:
        import shlex

        name = f"{self.run_name}:{worker_type}"
        wrapped = shlex.join(cmd)  # --wrap goes through sh: quote everything
        if self.container_image:
            wrapped = (
                f"srun --container-image={self.container_image} "
                f"--container-mounts=/tmp:/tmp {wrapped}"
            )
        out = [
            "sbatch",
            f"--job-name={name}",
            f"--nodes={nodes}",
            "--ntasks-per-node=1",
            f"--cpus-per-task={cpus_per_task}",
            f"--mem={mem_gb}G",
            f"--output={self.log_dir}/{worker_type.replace('/', '_')}.out",
            "--parsable",
        ]
        if self.partition:
            out.append(f"--partition={self.partition}")
        if time_limit:
            out.append(f"--time={time_limit}")
        out += self.extra
        out += [f"--wrap={wrapped}"]
        return out

    def build_array_submission(
        self,
        worker_type: str,
        cmd: List[str],
        count: int,
        cpus_per_task: int = 8,
        mem_gb_per_task: int = 32,
        hosts: Optional[List[str]] = None,
        tasks_per_host: int = 1,
        env: Optional[Dict[str, str]] = None,
        time_limit: Optional[str] = None,
    ) -> SlurmArraySubmission:
        """Pure construction of a pod-scale worker-array submission.

        - ``count`` workers become ``--ntasks=count`` jobsteps of ONE job;
          each rank runs ``cmd --worker-index=<rank>`` via the multiprog
          file (the reference's wrapped ``srun --multi-prog``,
          ``slurm/utils.py:392-396``).
        - ``hosts`` pins ranks to machines round-robin (``tasks_per_host``
          ranks each, in order) through a hostfile +
          ``--distribution=arbitrary`` — how a TPU-pod launch puts trainer
          rank k on the host holding slice shard k.
        - ``env`` becomes explicit ``export`` lines: worker env (name
          resolve address, JAX_COORDINATOR, per-role flags) must not depend
          on the submitting shell surviving.
        - ``srun -K``: one dead jobstep kills the whole array, so the
          launcher's restart-the-world recovery sees ONE failed job instead
          of a half-dead fleet (reference's exact flag, slurm/utils.py:390).
        """
        import shlex

        if hosts is not None and len(hosts) * tasks_per_host < count:
            raise ValueError(
                f"{count} tasks need {-(-count // tasks_per_host)} hosts "
                f"x {tasks_per_host}, got {len(hosts)}"
            )
        if any("\n" in a for a in cmd):
            # srun's multi-prog file is line-oriented: an embedded newline
            # (even shlex-quoted) splits one rank's entry across lines and
            # the whole array dies with a quoting error at RUN time
            raise ValueError(
                "array command args must not contain newlines "
                "(srun --multi-prog is line-oriented)"
            )
        name = f"{self.run_name}:{worker_type}"
        tag = worker_type.replace("/", "_")
        multiprog = "\n".join(
            f"{rank} {shlex.join(cmd + [f'--worker-index={rank}'])}"
            for rank in range(count)
        ) + "\n"
        hostfile = None
        if hosts is not None:
            lines = []
            for h in hosts:
                lines.extend([h] * tasks_per_host)
            hostfile = "\n".join(lines[:count]) + "\n"
        script_path = f"{self.log_dir}/{tag}.sbatch"
        # srun reads the multiprog/hostfile AT RUN TIME on the batch node —
        # a submit-host log_dir (node-local /tmp by default) would not exist
        # there, silently failing the whole array. The batch script writes
        # both files itself into a job-local mktemp dir: only the script has
        # to travel, and sbatch captures that at submit.
        multiprog_path = "$AREAL_JOBDIR/multiprog"
        hostfile_path = "$AREAL_JOBDIR/hostfile" if hostfile else None
        srun = (
            f"srun -K -l --ntasks={count} --cpus-per-task={cpus_per_task} "
            f"--mem-per-cpu={mem_gb_per_task * 1024 // max(cpus_per_task, 1)}M "
            f"--multi-prog {multiprog_path}"
        )
        if self.container_image:
            srun += (
                f" --container-image={self.container_image}"
                f" --container-mounts=/tmp:/tmp"
            )
        lines = [
            "#!/bin/bash",
            f"#SBATCH --job-name={name}",
            f"#SBATCH --output={self.log_dir}/{tag}.out",
            "#SBATCH --open-mode=append",
            f"#SBATCH --ntasks={count}",
            f"#SBATCH --cpus-per-task={cpus_per_task}",
            f"#SBATCH --mem-per-cpu={mem_gb_per_task * 1024 // max(cpus_per_task, 1)}M",
        ]
        if self.partition:
            lines.append(f"#SBATCH --partition={self.partition}")
        if time_limit:
            lines.append(f"#SBATCH --time={time_limit}")
        if hostfile:
            lines.append("#SBATCH --distribution=arbitrary")
        lines += [f"#SBATCH {a}" for a in self.extra]
        for k, v in (env or {}).items():
            lines.append(f"export {k}={shlex.quote(str(v))}")
        lines += [
            "AREAL_JOBDIR=$(mktemp -d)",
            "cat > $AREAL_JOBDIR/multiprog <<'AREAL_EOF'",
            multiprog.rstrip("\n"),
            "AREAL_EOF",
        ]
        if hostfile:
            lines += [
                "cat > $AREAL_JOBDIR/hostfile <<'AREAL_EOF'",
                hostfile.rstrip("\n"),
                "AREAL_EOF",
                f"export SLURM_HOSTFILE={hostfile_path}",
            ]
        lines += [
            'echo "[areal] start: $(date -u) on $(hostname)"',
            srun,
            "RETCODE=$?",
            "rm -rf $AREAL_JOBDIR",
            'echo "[areal] done: $(date -u) rc=$RETCODE"',
            "exit $RETCODE",
        ]
        return SlurmArraySubmission(
            worker_type=worker_type,
            ntasks=count,
            batch_script="\n".join(lines) + "\n",
            multiprog_content=multiprog,
            hostfile_content=hostfile,
            script_path=script_path,
            multiprog_path=multiprog_path,
            hostfile_path=hostfile_path,
        )

    def submit_array(
        self, worker_type: str, cmd: List[str], count: int, **kwargs
    ) -> List[str]:
        """One sbatch job with ``count`` jobsteps (NOT count separate
        ``--wrap`` jobs): writes the batch script (which self-materializes
        its multiprog/hostfile on the batch node) and submits it. Tracked
        under ``worker_type``; ``srun -K`` makes any dead step fail the
        whole job, which ``wait()`` surfaces."""
        import os

        self._require_slurm()
        sub = self.build_array_submission(worker_type, cmd, count, **kwargs)
        os.makedirs(self.log_dir, exist_ok=True)
        with open(sub.script_path, "w") as f:
            f.write(sub.batch_script)
        job_id = subprocess.check_output(
            ["sbatch", "--parsable", sub.script_path], text=True
        ).strip().split(";")[0]
        self._job_ids[worker_type] = job_id
        self._last_state.pop(worker_type, None)
        logger.info(
            "slurm array %s: id %s (%d tasks)", worker_type, job_id, count
        )
        return [job_id]

    # -- live control plane --------------------------------------------- #

    def _require_slurm(self):
        if shutil.which("sbatch") is None:
            raise RuntimeError(
                "Slurm control plane not available (no `sbatch` in PATH); "
                "use LocalSchedulerClient or the multiprocess launcher"
            )

    def submit(self, worker_type: str, cmd: List[str], **kwargs) -> str:
        self._require_slurm()
        sbatch = self.build_sbatch_cmd(worker_type, cmd, **kwargs)
        job_id = subprocess.check_output(sbatch, text=True).strip().split(";")[0]
        self._job_ids[worker_type] = job_id
        # resubmission under the same name: the old terminal state must not
        # mask the fresh job in find_all's cache branch
        self._last_state.pop(worker_type, None)
        logger.info("slurm job %s: id %s", worker_type, job_id)
        return job_id

    def _jobs(self):
        return list(self._job_ids)

    def _fallback(self, job_name: str, job_id: str) -> JobInfo:
        """Controller hiccup (squeue/sacct non-zero exit): report the last
        known state — never crash the poll loop, never fake a terminal
        NOT_FOUND."""
        return JobInfo(
            name=job_name,
            state=self._last_state.get(job_name, JobState.PENDING),
            slurm_id=job_id,
        )

    def find(self, job_name: str) -> JobInfo:
        self._require_slurm()
        job_id = self._job_ids.get(job_name)
        if job_id is None:
            return JobInfo(name=job_name, state=JobState.NOT_FOUND)
        try:
            out = subprocess.check_output(
                ["squeue", "-j", job_id, "-h", "-o", "%T|%N"], text=True,
                stderr=subprocess.DEVNULL,
            ).strip()
        except subprocess.CalledProcessError:
            # jobs purged from the controller exit non-zero: ask sacct
            out = ""
        if not out:  # left the queue: ask the accountant
            try:
                out = subprocess.check_output(
                    ["sacct", "-j", job_id, "-n", "-X", "-o", "State"],
                    text=True, stderr=subprocess.DEVNULL,
                ).strip()
            except subprocess.CalledProcessError:
                return self._fallback(job_name, job_id)
            state = _SLURM_STATES.get(out.split()[0].rstrip("+") if out else "",
                                      JobState.NOT_FOUND)
            self._last_state[job_name] = state
            return JobInfo(name=job_name, state=state, slurm_id=job_id)
        st, node = (out.split("|") + [None])[:2]
        state = _SLURM_STATES.get(st, JobState.PENDING)
        self._last_state[job_name] = state
        return JobInfo(name=job_name, state=state, host=node, slurm_id=job_id)

    def find_all(self, regex: str = ".*") -> List[JobInfo]:
        """ONE squeue call for every tracked job (per-job polling hammers
        the controller; squeue takes a comma-separated id list), with sacct
        / last-known fallbacks per job that left the queue."""
        self._require_slurm()
        pat = re.compile(regex)
        names = [n for n in self._job_ids if pat.match(n)]
        if not names:
            return []
        terminal = (JobState.COMPLETED, JobState.FAILED, JobState.CANCELLED)
        # jobs already seen terminal are purged from the controller — ONE
        # stale id in the comma list fails the whole squeue call and would
        # degrade every poll to per-job fallbacks, so keep them out
        live = [
            n for n in names if self._last_state.get(n) not in terminal
        ]
        by_id: Dict[str, tuple] = {}
        if live:
            ids = ",".join(self._job_ids[n] for n in live)
            try:
                out = subprocess.check_output(
                    ["squeue", "-j", ids, "-h", "-o", "%i|%T|%N"], text=True,
                    stderr=subprocess.DEVNULL,
                )
                for line in out.splitlines():
                    jid, st, node = (line.strip().split("|") + [None])[:3]
                    by_id[jid] = (st, node)
            except subprocess.CalledProcessError:
                pass  # fall through to per-job sacct below
        infos = []
        for n in names:
            jid = self._job_ids[n]
            if self._last_state.get(n) in terminal:
                infos.append(JobInfo(name=n, state=self._last_state[n], slurm_id=jid))
            elif jid in by_id:
                st, node = by_id[jid]
                state = _SLURM_STATES.get(st, JobState.PENDING)
                self._last_state[n] = state
                infos.append(JobInfo(name=n, state=state, host=node, slurm_id=jid))
            else:
                infos.append(self.find(n))
        return infos

    def stop(self, job_name: str):
        self._require_slurm()
        job_id = self._job_ids.get(job_name)
        if job_id is not None:
            subprocess.run(["scancel", job_id], check=False)


class RaySchedulerClient(SchedulerClient):
    """Ray-cluster backend: each worker command runs as a named Ray remote
    task pinned to the requested resources — the TPU-native counterpart of
    the reference's Ray actor fleet (``training/utils.py:119-254``, which
    wraps worker classes in ``RayWorker`` actors). Here workers stay the
    same subprocess entrypoints the local/Slurm backends launch, so one
    worker implementation serves all three schedulers; Ray only does
    placement, restarts and log capture. Jobs are keyed by ``worker_type``,
    matching the local backend's find/stop contract.

    ``ray`` is not bundled with this image: construction raises a clear
    error when it is missing (install ray on the cluster driver)."""

    def __init__(
        self,
        expr_name: str,
        trial_name: str,
        num_cpus: float = 1.0,
        num_tpus: float = 0.0,
        runtime_env: Optional[dict] = None,
        address: Optional[str] = None,
    ):
        super().__init__(expr_name, trial_name)
        try:
            import ray
        except ImportError as e:  # pragma: no cover - ray absent in CI image
            raise ImportError(
                "scheduler mode 'ray' needs the ray package (not bundled "
                "with this image): pip install 'ray[default]' on the "
                "cluster driver, or use mode 'local'/'slurm'"
            ) from e
        self._ray = ray
        self._default_resources = {"num_cpus": num_cpus}
        if num_tpus:
            self._default_resources["resources"] = {"TPU": num_tpus}
        if ray.is_initialized():
            if address or runtime_env:
                logger.warning(
                    "Ray already initialized; ignoring address=%r / "
                    "runtime_env", address,
                )
        else:
            ray.init(
                address=address, runtime_env=runtime_env,
                ignore_reinit_error=True,
            )

        @ray.remote
        def _run(cmd, env):
            import os as _os
            import signal as _signal
            import subprocess as _sp

            full_env = dict(_os.environ)
            full_env.update(env or {})
            # own session so a cancel kills the whole worker process group,
            # not just the Ray task wrapper (orphaned workers would keep
            # holding TPU devices across a restart-the-world relaunch)
            proc = _sp.Popen(cmd, env=full_env, start_new_session=True)
            try:
                return proc.wait()
            finally:
                if proc.poll() is None:
                    try:
                        _os.killpg(proc.pid, _signal.SIGTERM)
                        try:
                            proc.wait(timeout=10)
                        except _sp.TimeoutExpired:
                            _os.killpg(proc.pid, _signal.SIGKILL)
                    except ProcessLookupError:
                        pass

        self._run_remote = _run
        self._refs: Dict[str, Any] = {}
        self._cancelled: set = set()

    def submit(self, worker_type: str, cmd: List[str], env=None,
               **resources) -> str:
        if worker_type in self._refs:
            raise ValueError(f"job {worker_type} already submitted")
        opts = dict(self._default_resources)
        opts.update(resources)
        ref = self._run_remote.options(
            name=f"{self.run_name}/{worker_type}", **opts
        ).remote(list(cmd), dict(env or {}))
        self._refs[worker_type] = ref
        return worker_type

    def _jobs(self) -> List[str]:
        return list(self._refs)

    def find(self, job_name: str) -> JobInfo:
        ref = self._refs.get(job_name)
        if ref is None:
            return JobInfo(name=job_name, state=JobState.NOT_FOUND)
        ready, _ = self._ray.wait([ref], timeout=0)
        if not ready:
            return JobInfo(name=job_name, state=JobState.RUNNING)
        try:
            rc = self._ray.get(ref)
        except self._ray.exceptions.TaskCancelledError:
            return JobInfo(name=job_name, state=JobState.CANCELLED)
        except Exception:  # noqa: BLE001 - task died
            state = (
                JobState.CANCELLED if job_name in self._cancelled
                else JobState.FAILED
            )
            return JobInfo(name=job_name, state=state)
        state = JobState.COMPLETED if rc == 0 else JobState.FAILED
        return JobInfo(name=job_name, state=state)

    def stop(self, job_name: str):
        ref = self._refs.get(job_name)
        if ref is not None:
            self._cancelled.add(job_name)
            # non-force: interrupts the task so its finally kills the
            # worker's process group (SIGTERM, then SIGKILL after 10 s)
            self._ray.cancel(ref)


def make_scheduler(mode: str, expr_name: str, trial_name: str, **kwargs) -> SchedulerClient:
    if mode == "local":
        return LocalSchedulerClient(expr_name, trial_name)
    if mode == "slurm":
        return SlurmSchedulerClient(expr_name, trial_name, **kwargs)
    if mode == "ray":
        return RaySchedulerClient(expr_name, trial_name, **kwargs)
    raise ValueError(f"unknown scheduler mode {mode!r}")
