"""Multi-sink metric logging (≈ ``logging.log_swanlab_wandb_tensorboard`` in
the reference, ``realhf/base/logging.py``).

Sinks: stdout (always), tensorboardX (if importable), jsonl file (always —
the judge/bench harness reads it). wandb/swanlab are not available in this
image; the API accepts and ignores their configs.
"""

import json
import os
import time
from typing import Dict, Optional

from areal_tpu.base import logging

logger = logging.getLogger("metrics")


class MetricLogger:
    def __init__(self, logdir: str, backends: tuple = ("jsonl", "tensorboard")):
        os.makedirs(logdir, exist_ok=True)
        self._jsonl = None
        self._tb = None
        if "jsonl" in backends:
            self._jsonl = open(os.path.join(logdir, "metrics.jsonl"), "a")
        if "tensorboard" in backends:
            try:
                from tensorboardX import SummaryWriter

                self._tb = SummaryWriter(logdir=os.path.join(logdir, "tb"))
            except ImportError:
                pass

    def log(self, data: Dict[str, float], step: int, prefix: Optional[str] = None):
        if prefix:
            data = {f"{prefix}/{k}": v for k, v in data.items()}
        if self._jsonl:
            self._jsonl.write(
                json.dumps(dict(step=step, time=time.time(), **data)) + "\n"
            )
            self._jsonl.flush()
        if self._tb:
            for k, v in data.items():
                try:
                    self._tb.add_scalar(k, v, step)
                except Exception:
                    pass

    def close(self):
        if self._jsonl:
            self._jsonl.close()
        if self._tb:
            self._tb.close()
