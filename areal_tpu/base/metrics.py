"""Multi-sink metric logging (≈ ``logging.log_swanlab_wandb_tensorboard`` in
the reference, ``realhf/base/logging.py``) plus process-global pipeline
counters.

Sinks: stdout (always), tensorboardX (if importable), jsonl file (always —
the judge/bench harness reads it). wandb/swanlab are not available in this
image; the API accepts and ignores their configs.

``counters`` instruments the host↔device data plane (dispatch-ahead
forward, prefetched train minibatches, deferred stats fetches): cheap
monotonic host counters the bench/tests read to PROVE overlap happened
(e.g. ``fwd_pipe/max_in_flight`` ≥ 2) instead of inferring it from wall
time alone.
"""

import json
import os
import threading
import time
from typing import Dict, Optional

from areal_tpu.base import logging

logger = logging.getLogger("metrics")


class CounterRegistry:
    """Process-global named counters/gauges for data-plane observability.

    Thread-safe (the train prefetcher packs on a background thread).
    ``add`` accumulates, ``peak`` keeps a running maximum (pipeline depth),
    ``snapshot``/``delta`` give dict views the trainer folds into its
    per-step stats under ``pipe/``.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._vals: Dict[str, float] = {}

    def add(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._vals[name] = self._vals.get(name, 0.0) + float(value)

    def peak(self, name: str, value: float) -> None:
        with self._lock:
            if float(value) > self._vals.get(name, float("-inf")):
                self._vals[name] = float(value)

    def get(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._vals.get(name, default)

    def clear(self, name: str) -> None:
        """Drop one counter. Peaks (``peak``) are process-lifetime maxima —
        a measurement that wants the peak OF ITS OWN interval must clear
        the key at the interval start; snapshot-and-subtract is meaningless
        for a maximum."""
        with self._lock:
            self._vals.pop(name, None)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._vals)

    def delta(self, before: Dict[str, float]) -> Dict[str, float]:
        """Per-interval view: current snapshot minus ``before`` (peaks are
        reported as-is — a maximum has no meaningful difference)."""
        now = self.snapshot()
        return {
            k: (v if k.endswith("max_in_flight") else v - before.get(k, 0.0))
            for k, v in now.items()
        }

    def reset(self) -> None:
        with self._lock:
            self._vals.clear()


# The process-global registry (≈ the reference's monotonic perf counters in
# ``realhf/base/monitor.py``). Engines/trainers import this single instance.
counters = CounterRegistry()


# --------------------------------------------------------------------- #
# Counter catalog. Every fixed counter name is an UPPERCASE constant in
# this module — the single authoritative name list dashboards and tests
# key off. Enforced statically by the ``unregistered-counter`` rule of
# ``tools/arealint`` (string-literal names at call sites must match a
# value here; constant references must be defined here). Dynamic families
# (``tracing.span``'s ``<name>_s``/``<name>_n``, ``faults/<point>``) are
# exempt — they cannot be checked statically.
# --------------------------------------------------------------------- #

# Data-plane pipeline namespace (``fwd_pipe/`` / ``train_pipe/`` /
# ``stats_fetch/``) — proves the host<->device overlap happened
# (docs/pipelined_data_plane.md) instead of inferring it from wall time.
PIPE_STATS_FETCH_BLOCKING = "stats_fetch/blocking"   # blocking device pulls
PIPE_PREFETCHED_MINIBATCHES = "train_pipe/prefetched_minibatches"
PIPE_STATS_FLUSHES = "train_pipe/stats_flushes"      # deferred-stats flushes
PIPE_FWD_DISPATCHED = "fwd_pipe/dispatched"          # forward mbs dispatched
PIPE_FWD_MAX_IN_FLIGHT = "fwd_pipe/max_in_flight"    # realized pipeline depth
PIPE_FWD_DEVICE_IDLE_GAP_S = "fwd_pipe/device_idle_gap_s"


# --------------------------------------------------------------------- #
# Fault-tolerance counter namespace (``ft/``) — every retry / eviction /
# requeue decision the fleet-health subsystem makes is observable here
# (docs/fault_tolerance.md).  Tests assert on these instead of scraping
# logs.  ``faults/<point>`` counts injected faults per injection point.
# --------------------------------------------------------------------- #

FT_CLIENT_RETRIES = "ft/client_retries"            # GenAPIClient backoff retries
FT_GEN_SERVER_FAILURES = "ft/gen_server_failures"  # generate failed after retries
FT_ROLLOUT_REQUEUES = "ft/rollout_requeues"        # failed sample requeued
FT_ROLLOUT_DROPPED = "ft/rollout_dropped"          # attempts exhausted; sample lost
FT_FAILURES_OBSERVED = "ft/failures_observed"      # health-plane failure observations
FT_EVICTIONS = "ft/evictions"                      # breaker closed → open
FT_READMISSIONS = "ft/readmissions"                # probe + catch-up succeeded
FT_PROBE_FAILURES = "ft/probe_failures"            # half-open probe failed
FT_WEIGHT_UPDATE_FAILURES = "ft/weight_update_failures"
FT_STICKY_REMAPS = "ft/sticky_remaps"              # qid→server remapped off corpse
FT_ROUTE_NO_HEALTHY = "ft/route_no_healthy"        # routed with zero healthy servers
FT_PRUNE_DEFERRED = "ft/prune_deferred"            # ckpt prune blocked by un-acked server
FT_PUSH_DROPS = "ft/push_drops"                    # ZMQ push timed out; trajectory dropped
FT_DRAIN_ABANDONED = "ft/drain_abandoned"          # tasks cancelled at drain timeout
FT_STALE_DROPPED_ON_RECOVER = "ft/stale_dropped_on_recover"
FT_PUBLISH_FAILURES = "ft/publish_failures"        # background weight publish raised
FT_PREEMPTIONS = "ft/preemptions"                  # graceful-stop requests honored


# --------------------------------------------------------------------- #
# Trainer guardrail namespace (``guard/``) — the step-level anomaly plane
# (docs/fault_tolerance.md "Trainer survivability"): on-device finite-ness
# checks, skipped optimizer updates, rollbacks to the last committed
# checkpoint, watchdog stack dumps.
# --------------------------------------------------------------------- #

GUARD_ANOMALOUS_STEPS = "guard/anomalous_steps"    # non-finite loss/grad_norm observed
GUARD_SKIPPED_UPDATES = "guard/update_skipped"     # optimizer update selected away on device
GUARD_ROLLBACKS = "guard/rollbacks"                # K consecutive anomalies -> ckpt rollback
GUARD_ROLLBACK_FAILED = "guard/rollback_failed"    # wanted to roll back; no committed ckpt
GUARD_CKPT_FALLBACKS = "guard/ckpt_fallbacks"      # committed sibling promoted over a missing/uncommitted canonical dir
GUARD_WATCHDOG_DUMPS = "guard/watchdog_dumps"      # hang watchdog dumped thread stacks


class MetricLogger:
    def __init__(self, logdir: str, backends: tuple = ("jsonl", "tensorboard")):
        os.makedirs(logdir, exist_ok=True)
        self._jsonl = None
        self._tb = None
        if "jsonl" in backends:
            self._jsonl = open(os.path.join(logdir, "metrics.jsonl"), "a")
        if "tensorboard" in backends:
            try:
                from tensorboardX import SummaryWriter

                self._tb = SummaryWriter(logdir=os.path.join(logdir, "tb"))
            except ImportError:
                pass

    def log(
        self,
        data: Dict[str, float],
        step: int,
        prefix: Optional[str] = None,
        wall_time: Optional[float] = None,
    ):
        """``wall_time`` lets deferred-stats flushes stamp each step with the
        time the step actually RAN (captured at step time), not the flush
        time — steady-state rates derived from jsonl timestamps stay valid
        when the trainer batches several steps into one device pull."""
        if prefix:
            data = {f"{prefix}/{k}": v for k, v in data.items()}
        if self._jsonl:
            self._jsonl.write(
                json.dumps(
                    dict(
                        step=step,
                        time=time.time() if wall_time is None else wall_time,
                        **data,
                    )
                )
                + "\n"
            )
            self._jsonl.flush()
        if self._tb:
            for k, v in data.items():
                try:
                    self._tb.add_scalar(k, v, step, walltime=wall_time)
                except Exception:
                    pass

    def close(self):
        if self._jsonl:
            self._jsonl.close()
        if self._tb:
            self._tb.close()
