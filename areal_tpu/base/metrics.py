"""Multi-sink metric logging (≈ ``logging.log_swanlab_wandb_tensorboard`` in
the reference, ``realhf/base/logging.py``) plus process-global pipeline
counters.

Sinks: stdout (always), tensorboardX (if importable), jsonl file (always —
the judge/bench harness reads it). wandb/swanlab are not available in this
image; the API accepts and ignores their configs.

``counters`` instruments the host↔device data plane (dispatch-ahead
forward, prefetched train minibatches, deferred stats fetches): cheap
monotonic host counters the bench/tests read to PROVE overlap happened
(e.g. ``fwd_pipe/max_in_flight`` ≥ 2) instead of inferring it from wall
time alone.
"""

import bisect
import json
import os
import threading
import time
from typing import Dict, List, Optional

from areal_tpu.base import logging

logger = logging.getLogger("metrics")


# --------------------------------------------------------------------- #
# Metric kinds. Every registered key has exactly one kind, declared in
# the METRIC_KINDS catalog below (unknown keys default to ``sum``); the
# per-interval ``delta()`` view and the fleet aggregator merge by kind
# (sum: subtract/add, peak: report/max, histogram: bucket-wise merge)
# instead of guessing from name suffixes.
# --------------------------------------------------------------------- #

KIND_SUM = "sum"
KIND_PEAK = "peak"
KIND_HISTOGRAM = "histogram"
KIND_GAUGE = "gauge"


def _log_spaced(lo: float, hi: float, per_decade: int) -> List[float]:
    import math

    k0 = round(math.log10(lo) * per_decade)
    k1 = round(math.log10(hi) * per_decade)
    return [round(10 ** (k / per_decade), 10) for k in range(k0, k1 + 1)]


# Default bucket edges for duration-like histograms: 100 µs … 10 000 s,
# 4 buckets per decade (±~33% relative resolution — enough to tell p50
# from p99 of any latency this system produces, small enough to ship in
# every exporter snapshot).
DEFAULT_HISTOGRAM_BOUNDARIES: List[float] = _log_spaced(1e-4, 1e4, 4)

# Integer-centered edges for version-lag histograms: staleness is a small
# integer and log buckets would smear 0/1/2 (the values the paper's
# bounded-staleness story is about) into one bucket.
VERSION_LAG_BOUNDARIES: List[float] = [
    0.5, 1.5, 2.5, 3.5, 4.5, 6.5, 8.5, 12.5, 16.5, 24.5, 32.5, 48.5,
    64.5, 96.5, 128.5,
]


class Histogram:
    """Fixed-boundary histogram: mergeable across processes, cheap to
    observe (one bisect + three adds), summarizable to count/sum/mean and
    interpolated percentiles. NOT thread-safe on its own — the owning
    :class:`CounterRegistry` serializes access under its lock.

    ``counts`` has ``len(boundaries) + 1`` entries; entry ``i`` counts
    values ``<= boundaries[i]`` (and greater than the previous edge), the
    last entry is the overflow bucket.
    """

    __slots__ = ("boundaries", "counts", "sum", "count", "min", "max")

    def __init__(self, boundaries: Optional[List[float]] = None):
        self.boundaries = list(
            boundaries if boundaries is not None
            else DEFAULT_HISTOGRAM_BOUNDARIES
        )
        assert self.boundaries == sorted(self.boundaries), "edges must ascend"
        self.counts = [0] * (len(self.boundaries) + 1)
        self.sum = 0.0
        self.count = 0
        self.min = float("inf")
        self.max = float("-inf")

    def observe(self, value: float, n: int = 1) -> None:
        """Record ``value`` ``n`` times (``n > 1`` lets batched producers —
        e.g. the spec-decode chunk folding a whole ``[steps, slots]``
        accept-length grid — record without a per-observation loop)."""
        v = float(value)
        self.counts[bisect.bisect_left(self.boundaries, v)] += n
        self.sum += v * n
        self.count += n
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    def merge(self, other: "Histogram") -> None:
        if other.boundaries != self.boundaries:
            raise ValueError(
                "cannot merge histograms with different boundaries "
                f"({len(self.boundaries)} vs {len(other.boundaries)} edges)"
            )
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.sum += other.sum
        self.count += other.count
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)

    def percentile(self, q: float) -> float:
        """Interpolated q-th percentile (q in [0, 100]); 0.0 when empty.
        Bucket-local linear interpolation, clamped to the observed
        min/max so all-identical observations report exactly that value."""
        if self.count == 0:
            return 0.0
        rank = q / 100.0 * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if seen + c >= rank:
                lo = self.boundaries[i - 1] if i > 0 else 0.0
                hi = (
                    self.boundaries[i]
                    if i < len(self.boundaries)
                    else self.max
                )
                frac = (rank - seen) / c
                est = lo + (hi - lo) * max(0.0, min(frac, 1.0))
                return max(self.min, min(est, self.max))
            seen += c
        return self.max

    def summary(self) -> Dict[str, float]:
        if self.count == 0:
            return {"count": 0.0}
        return {
            "count": float(self.count),
            "sum": self.sum,
            "mean": self.sum / self.count,
            "min": self.min,
            "max": self.max,
            "p50": self.percentile(50),
            "p95": self.percentile(95),
            "p99": self.percentile(99),
        }

    def state(self) -> dict:
        """JSON-serializable full state (for the telemetry exporter)."""
        return {
            "boundaries": self.boundaries,
            "counts": list(self.counts),
            "sum": self.sum,
            "count": self.count,
            "min": self.min if self.count else None,
            "max": self.max if self.count else None,
        }

    @classmethod
    def from_state(cls, d: dict) -> "Histogram":
        h = cls(boundaries=d["boundaries"])
        h.counts = [int(c) for c in d["counts"]]
        h.sum = float(d["sum"])
        h.count = int(d["count"])
        h.min = float("inf") if d.get("min") is None else float(d["min"])
        h.max = float("-inf") if d.get("max") is None else float(d["max"])
        return h

    def copy(self) -> "Histogram":
        return Histogram.from_state(self.state())


class CounterRegistry:
    """Process-global named counters/gauges for data-plane observability.

    Thread-safe (the train prefetcher packs on a background thread).
    ``add`` accumulates, ``peak`` keeps a running maximum (pipeline depth),
    ``observe`` records into a fixed-boundary histogram, ``snapshot``/
    ``delta`` give scalar dict views the trainer folds into its per-step
    stats under ``pipe/``, and ``export_state`` serializes everything for
    the per-worker telemetry exporter.

    Metric kinds come from the module-level METRIC_KINDS catalog (plus
    ``register_kind`` for dynamic names); unknown keys default to ``sum``.
    """

    def __init__(self, kinds: Optional[Dict[str, str]] = None):
        self._lock = threading.Lock()
        self._vals: Dict[str, float] = {}
        self._hists: Dict[str, Histogram] = {}
        # per-registry overrides; the catalog below is the shared default
        self._kinds: Dict[str, str] = dict(kinds or {})

    def kind(self, name: str) -> str:
        k = self._kinds.get(name)
        if k is None:
            k = METRIC_KINDS.get(name, KIND_SUM)
        return k

    def register_kind(self, name: str, kind: str) -> None:
        assert kind in (KIND_SUM, KIND_PEAK, KIND_HISTOGRAM, KIND_GAUGE), kind
        with self._lock:
            self._kinds[name] = kind

    def add(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._vals[name] = self._vals.get(name, 0.0) + float(value)

    def peak(self, name: str, value: float) -> None:
        with self._lock:
            self._kinds.setdefault(name, KIND_PEAK)
            if float(value) > self._vals.get(name, float("-inf")):
                self._vals[name] = float(value)

    def gauge(self, name: str, value: float) -> None:
        """Last-value-wins gauge (a live setting, not an accumulation —
        e.g. the adaptive speculative-K currently in force). Reported
        as-is in ``delta`` views; the fleet aggregator takes the max
        across workers."""
        with self._lock:
            self._kinds.setdefault(name, KIND_GAUGE)
            self._vals[name] = float(value)

    def observe(self, name: str, value: float, n: int = 1) -> None:
        """Record ``n`` observations of ``value`` into the histogram
        ``name`` (created on first use with the catalog's boundaries for
        that key)."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = Histogram(HISTOGRAM_BOUNDARIES.get(name))
                self._hists[name] = h
                self._kinds.setdefault(name, KIND_HISTOGRAM)
            h.observe(value, n)

    def get(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._vals.get(name, default)

    def histogram(self, name: str) -> Optional[Histogram]:
        """Copy of one histogram (None when nothing was observed)."""
        with self._lock:
            h = self._hists.get(name)
            return h.copy() if h is not None else None

    def clear(self, name: str) -> None:
        """Drop one counter. Peaks (``peak``) are process-lifetime maxima —
        a measurement that wants the peak OF ITS OWN interval must clear
        the key at the interval start; snapshot-and-subtract is meaningless
        for a maximum."""
        with self._lock:
            self._vals.pop(name, None)
            self._hists.pop(name, None)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._vals)

    def delta(self, before: Dict[str, float]) -> Dict[str, float]:
        """Per-interval scalar view: current snapshot minus ``before`` for
        sum-kind keys; peak-kind and gauge-kind keys report as-is (a
        maximum or a live setting has no meaningful difference).
        Histograms are not part of the scalar delta — read them via
        ``histogram``/``histogram_summaries``."""
        now = self.snapshot()
        return {
            k: (
                v
                if self.kind(k) in (KIND_PEAK, KIND_GAUGE)
                else v - before.get(k, 0.0)
            )
            for k, v in now.items()
        }

    def histogram_summaries(self) -> Dict[str, Dict[str, float]]:
        """``{name: {count, sum, mean, min, max, p50, p95, p99}}`` for every
        histogram with at least one observation."""
        with self._lock:
            hists = {k: h.copy() for k, h in self._hists.items() if h.count}
        return {k: h.summary() for k, h in hists.items()}

    def export_state(self) -> dict:
        """Full serializable state: scalar counters with their kinds plus
        histogram bucket states — the payload the per-worker telemetry
        exporter publishes and the fleet aggregator merges."""
        with self._lock:
            vals = dict(self._vals)
            hists = {k: h.state() for k, h in self._hists.items()}
        return {
            "counters": vals,
            "kinds": {k: self.kind(k) for k in vals},
            "histograms": hists,
        }

    def reset(self) -> None:
        with self._lock:
            self._vals.clear()
            self._hists.clear()


# The process-global registry (≈ the reference's monotonic perf counters in
# ``realhf/base/monitor.py``). Engines/trainers import this single instance.
counters = CounterRegistry()


# --------------------------------------------------------------------- #
# Counter catalog. Every fixed counter name is an UPPERCASE constant in
# this module — the single authoritative name list dashboards and tests
# key off. Enforced statically by the ``unregistered-counter`` rule of
# ``tools/arealint`` (string-literal names at call sites must match a
# value here; constant references must be defined here). Dynamic families
# (``tracing.span``'s ``<name>_s``/``<name>_n``, ``faults/<point>``) are
# exempt — they cannot be checked statically.
# --------------------------------------------------------------------- #

# Data-plane pipeline namespace (``fwd_pipe/`` / ``train_pipe/`` /
# ``stats_fetch/``) — proves the host<->device overlap happened
# (docs/pipelined_data_plane.md) instead of inferring it from wall time.
PIPE_STATS_FETCH_BLOCKING = "stats_fetch/blocking"   # blocking device pulls
PIPE_PREFETCHED_MINIBATCHES = "train_pipe/prefetched_minibatches"
PIPE_STATS_FLUSHES = "train_pipe/stats_flushes"      # deferred-stats flushes
PIPE_FWD_DISPATCHED = "fwd_pipe/dispatched"          # forward mbs dispatched
PIPE_FWD_MAX_IN_FLIGHT = "fwd_pipe/max_in_flight"    # realized pipeline depth
PIPE_FWD_DEVICE_IDLE_GAP_S = "fwd_pipe/device_idle_gap_s"


# --------------------------------------------------------------------- #
# Fault-tolerance counter namespace (``ft/``) — every retry / eviction /
# requeue decision the fleet-health subsystem makes is observable here
# (docs/fault_tolerance.md).  Tests assert on these instead of scraping
# logs.  ``faults/<point>`` counts injected faults per injection point.
# --------------------------------------------------------------------- #

FT_CLIENT_RETRIES = "ft/client_retries"            # GenAPIClient backoff retries
FT_GEN_SERVER_FAILURES = "ft/gen_server_failures"  # generate failed after retries
FT_ROLLOUT_REQUEUES = "ft/rollout_requeues"        # failed sample requeued
FT_ROLLOUT_DROPPED = "ft/rollout_dropped"          # attempts exhausted; sample lost
FT_FAILURES_OBSERVED = "ft/failures_observed"      # health-plane failure observations
FT_EVICTIONS = "ft/evictions"                      # breaker closed → open
FT_READMISSIONS = "ft/readmissions"                # probe + catch-up succeeded
FT_PROBE_FAILURES = "ft/probe_failures"            # half-open probe failed
FT_WEIGHT_UPDATE_FAILURES = "ft/weight_update_failures"
FT_STICKY_REMAPS = "ft/sticky_remaps"              # qid→server remapped off corpse
FT_ROUTE_NO_HEALTHY = "ft/route_no_healthy"        # routed with zero healthy servers
FT_PRUNE_DEFERRED = "ft/prune_deferred"            # ckpt prune blocked by un-acked server
FT_PUSH_DROPS = "ft/push_drops"                    # ZMQ push timed out; trajectory dropped
FT_DRAIN_ABANDONED = "ft/drain_abandoned"          # tasks cancelled at drain timeout
FT_STALE_DROPPED_ON_RECOVER = "ft/stale_dropped_on_recover"
FT_PUBLISH_FAILURES = "ft/publish_failures"        # background weight publish raised
FT_PREEMPTIONS = "ft/preemptions"                  # graceful-stop requests honored

# Elastic multihost (docs/fault_tolerance.md "Elastic multihost"): the
# surgical rank-recovery plane. rank_restarts/world_epochs are counted by
# the WorldSupervisor; collective_timeouts by the rank that aborted a
# bounded collective. recovery_time_s (histogram below) measures fault
# detection -> every rank live at the new epoch.
FT_RANK_RESTARTS = "ft/rank_restarts"              # dead/wedged ranks relaunched
FT_WORLD_EPOCHS = "ft/world_epochs"                # world reformations committed
FT_COLLECTIVE_TIMEOUTS = "ft/collective_timeouts"  # bounded collectives aborted
RECOVERY_TIME_S = "recovery_time_s"                # histogram: detect -> reformed


# --------------------------------------------------------------------- #
# Trainer guardrail namespace (``guard/``) — the step-level anomaly plane
# (docs/fault_tolerance.md "Trainer survivability"): on-device finite-ness
# checks, skipped optimizer updates, rollbacks to the last committed
# checkpoint, watchdog stack dumps.
# --------------------------------------------------------------------- #

GUARD_ANOMALOUS_STEPS = "guard/anomalous_steps"    # non-finite loss/grad_norm observed
GUARD_SKIPPED_UPDATES = "guard/update_skipped"     # optimizer update selected away on device
GUARD_ROLLBACKS = "guard/rollbacks"                # K consecutive anomalies -> ckpt rollback
GUARD_ROLLBACK_FAILED = "guard/rollback_failed"    # wanted to roll back; no committed ckpt
GUARD_CKPT_FALLBACKS = "guard/ckpt_fallbacks"      # committed sibling promoted over a missing/uncommitted canonical dir
GUARD_WATCHDOG_DUMPS = "guard/watchdog_dumps"      # hang watchdog dumped thread stacks


# --------------------------------------------------------------------- #
# Trajectory lifecycle histograms (docs/observability.md): every accepted
# rollout is stamped submit → first-chunk → reward → enqueue on its way
# through partial_rollout → push_pull_stream → buffer, and consumption
# (buffer.record_batch_consumption at the trainer's multihost commit
# point) turns the stamps into distributions — the
# paper's staleness/latency story as measured percentiles, not averages.
# --------------------------------------------------------------------- #

STALENESS_VERSIONS = "staleness_versions"  # trainer version - version_start at consumption
QUEUE_WAIT_S = "queue_wait_s"              # rollout enqueue -> trainer consumption
E2E_LATENCY_S = "e2e_latency_s"            # generation submit -> trainer consumption
TTFC_S = "ttfc_s"                          # generation submit -> first chunk back
REWARD_LAG_S = "reward_lag_s"              # generation submit -> reward computed


# --------------------------------------------------------------------- #
# Per-role activity counters: the always-on heartbeat numbers each worker
# publishes through the telemetry exporter, so a fleet/ record proves
# every role did work (failure counters stay zero in a healthy run).
# --------------------------------------------------------------------- #

ROLLOUT_PUSHED = "rollout/pushed"          # trajectories pushed to the trainer
ROLLOUT_ACCEPTED = "rollout/accepted"      # rollouts finished accepted
GEN_SERVED = "gen/served"                  # generate requests completed
GEN_TOKENS = "gen/tokens"                  # tokens generated
MANAGER_SCHEDULED = "manager/schedule_requests"
MANAGER_ALLOCATED = "manager/allocated"    # rollouts admitted by the gate
TRAIN_STEPS = "train/steps"                # optimizer steps taken

# Speculative decoding (docs/performance.md "Speculative decoding"):
# drafted vs accepted draft tokens (sums; their ratio is the accept rate)
# plus an accept-length distribution per (slot, spec step) — the drafter
# quality signal the bench and the ops CLI read.
GEN_SPEC_DRAFT_TOKENS = "gen/spec_draft_tokens"
GEN_SPEC_ACCEPTED_TOKENS = "gen/spec_accepted_tokens"
GEN_SPEC_ACCEPT_LEN = "gen/spec_accept_len"

# Draft-MODEL speculative decoding: the per-position acceptance
# probability min(1, p/q) the rejection sampler computes for sampled
# (general-q) drafters — the draft-quality signal independent of where
# the first rejection lands — plus the draft pool's occupancy histogram
# (its pages move in lockstep with the target pool's, so this mirrors
# gen/kv_pool_occupancy whenever a draft model is configured; bytes ride
# the per-worker gauge channel and /metrics_json).
GEN_SPEC_Q_ACCEPT_PROB = "gen/spec_q_accept_prob"
GEN_DRAFT_KV_POOL_OCCUPANCY = "gen/draft_kv_pool_occupancy"

# Fused sampling epilogue (docs/performance.md "Fused sampling
# epilogue"): decode steps sampled through the streamed LM-head epilogue
# vs rows that fell back to the sorted reference path (top-p / oversize
# top-k slots) — their ratio is the fused coverage of live traffic —
# plus the adaptive speculative-K currently in force (a gauge: last value
# wins locally, fleet aggregation takes the max across workers).
GEN_FUSED_SAMPLE_STEPS = "gen/fused_sample_steps"
GEN_SAMPLER_FALLBACK_ROWS = "gen/sampler_fallback_rows"
GEN_SPEC_K_CURRENT = "gen/spec_k_current"

# Chunk-boundary sync protocol (docs/performance.md "Speculative
# decoding" / chunk pipelining): every decode chunk's harvest-flag fetch
# is dispatch-ahead (the D2H copy is enqueued at dispatch, resolved one
# chunk later under AREAL_DECODE_PIPELINE) — ``blocked`` counts resolves
# that found the copy not yet landed (a fresh host<->device round trip,
# the thing the protocol exists to eliminate). Steady-state pipelined
# decode keeps blocked at zero; the overlap test pins it.
GEN_CHUNK_FLAG_FETCHES = "gen/chunk_flag_fetches"
GEN_CHUNK_FLAG_BLOCKED = "gen/chunk_flag_blocked"

# KV-pool quantization (docs/performance.md "KV quantization"): pages
# allocated into an int8 pool (their KV lands quantized at the post-scan
# scatter) plus a pool-occupancy histogram — the HBM-headroom signal the
# fleet aggregator and the gen server's /metrics_json gauges expose.
GEN_KVQ_PAGES_QUANTIZED = "gen/kvq_pages_quantized"
GEN_KV_POOL_OCCUPANCY = "gen/kv_pool_occupancy"

# --------------------------------------------------------------------- #
# Serving-gateway namespace (``gw/``, docs/serving.md): every admission /
# QoS / scaling decision the OpenAI-compatible frontend makes. The queue
# histograms are the autoscaler's primary latency signals; the per-tenant
# token family (``gw/tenant_tokens/<tenant>``) is dynamic and therefore
# registered by its prefix constant only (same exemption as
# ``faults/<point>`` — it cannot be enumerated statically).
# --------------------------------------------------------------------- #

GW_REQUESTS = "gw/requests"               # API requests past validation
GW_ADMITTED = "gw/admitted"               # requests dispatched into a slot
GW_REJECTED_429 = "gw/rejected_429"       # rate-limit / queue-full rejections
GW_REJECTED_4XX = "gw/rejected_4xx"       # validation rejections (400/401)
GW_COMPLETED = "gw/completed"             # requests finished (any reason)
GW_STREAMED_TOKENS = "gw/streamed_tokens" # tokens emitted to API clients
GW_RESUBMITS = "gw/resubmits"             # interrupted gens resumed transparently
GW_QUEUE_WAIT_S = "gw/queue_wait_s"       # histogram: enqueue -> dispatch
GW_TTFT_S = "gw/ttft_s"                   # histogram: enqueue -> first token
GW_SCALE_UPS = "gw/scale_ups"             # autoscaler grew the routed set
GW_SCALE_DOWNS = "gw/scale_downs"         # autoscaler shrank the routed set
GW_TENANT_TOKENS_PREFIX = "gw/tenant_tokens/"  # + <tenant>: per-tenant sums

# Survivability plane (docs/serving.md "Survivability"): deadline
# propagation, hedged dispatch and the brownout ladder.
GW_DEADLINE_SHED = "gw/deadline_shed"     # expired in queue / mid-stream
GW_HEDGES = "gw/hedges"                   # hedge streams opened
GW_HEDGE_WINS = "gw/hedge_wins"           # hedge beat the primary's 1st chunk
GW_STREAM_RESUMES = "gw/stream_resumes"   # streams resumed after backend death
GW_BROWNOUT_LEVEL = "gw/brownout_level"   # gauge: current degradation level
GW_BROWNOUT_TRANSITIONS = "gw/brownout_transitions"  # ladder level changes

# --------------------------------------------------------------------- #
# Distributed tracing namespace (``trace/``, docs/observability.md
# "Distributed tracing"): the span ring / flush plane plus flight-
# recorder dumps. ``trace/span_s`` is a histogram over every recorded
# span's duration (one distribution across names — per-name wall time
# already rides the ``<name>_s`` sums ``tracing.span`` has always kept).
# --------------------------------------------------------------------- #

TRACE_SPANS = "trace/spans"                 # spans recorded into the ring
TRACE_SPAN_ERRORS = "trace/span_errors"     # spans that exited via exception
TRACE_DROPPED = "trace/dropped"             # ring overwrote an unflushed span
TRACE_FLUSHES = "trace/flushes"             # ring drains to the fileroot
TRACE_FLUSHED_SPANS = "trace/flushed_spans" # spans written by those drains
TRACE_FLIGHT_DUMPS = "trace/flight_dumps"   # flight-recorder dumps written
TRACE_SPAN_S = "trace/span_s"               # histogram: recorded span durations


# Fraction edges for the pool-occupancy histogram: occupancy lives in
# [0, 1] and the log-spaced duration edges would put the whole range into
# two buckets; 0.9+ gets finer edges because that is where admission
# starts deferring (the signal an autoscaler acts on).
POOL_OCCUPANCY_BOUNDARIES: List[float] = [
    0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 0.95, 0.99,
]

# Small-integer edges for the accept-length histogram: accept lengths are
# 0..K (K = AREAL_SPEC_K, typically <= 8) and the duration edges would
# smear 0/1/2 — the values that decide whether spec decode pays — into
# one bucket.
SPEC_ACCEPT_LEN_BOUNDARIES: List[float] = [
    0.5, 1.5, 2.5, 3.5, 4.5, 5.5, 6.5, 7.5, 8.5, 12.5, 16.5,
]

# Probability edges for the general-q acceptance-probability histogram:
# values live in [0, 1]; finer edges toward 1.0 because that is where a
# good draft model lives (0.9 vs 0.99 mean accept is the difference
# between spec paying and not at large K).
SPEC_Q_ACCEPT_PROB_BOUNDARIES: List[float] = [
    0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 0.95, 0.99,
]


# Per-key metric kinds; unknown keys default to KIND_SUM. The arealint
# ``unregistered-counter`` rule keys off the UPPERCASE constants above;
# this map adds the KIND so delta()/the fleet aggregator merge correctly.
METRIC_KINDS: Dict[str, str] = {
    PIPE_FWD_MAX_IN_FLIGHT: KIND_PEAK,
    STALENESS_VERSIONS: KIND_HISTOGRAM,
    QUEUE_WAIT_S: KIND_HISTOGRAM,
    E2E_LATENCY_S: KIND_HISTOGRAM,
    TTFC_S: KIND_HISTOGRAM,
    REWARD_LAG_S: KIND_HISTOGRAM,
    GEN_SPEC_ACCEPT_LEN: KIND_HISTOGRAM,
    GEN_SPEC_K_CURRENT: KIND_GAUGE,
    GEN_SPEC_Q_ACCEPT_PROB: KIND_HISTOGRAM,
    GEN_KV_POOL_OCCUPANCY: KIND_HISTOGRAM,
    GEN_DRAFT_KV_POOL_OCCUPANCY: KIND_HISTOGRAM,
    RECOVERY_TIME_S: KIND_HISTOGRAM,
    GW_QUEUE_WAIT_S: KIND_HISTOGRAM,
    GW_TTFT_S: KIND_HISTOGRAM,
    GW_BROWNOUT_LEVEL: KIND_GAUGE,
    TRACE_SPAN_S: KIND_HISTOGRAM,
}

# Non-default bucket edges per histogram key (default: the log-spaced
# duration edges).
HISTOGRAM_BOUNDARIES: Dict[str, List[float]] = {
    STALENESS_VERSIONS: VERSION_LAG_BOUNDARIES,
    GEN_SPEC_ACCEPT_LEN: SPEC_ACCEPT_LEN_BOUNDARIES,
    GEN_SPEC_Q_ACCEPT_PROB: SPEC_Q_ACCEPT_PROB_BOUNDARIES,
    GEN_KV_POOL_OCCUPANCY: POOL_OCCUPANCY_BOUNDARIES,
    GEN_DRAFT_KV_POOL_OCCUPANCY: POOL_OCCUPANCY_BOUNDARIES,
}


class MetricLogger:
    def __init__(self, logdir: str, backends: tuple = ("jsonl", "tensorboard")):
        os.makedirs(logdir, exist_ok=True)
        self._jsonl = None
        self._tb = None
        self._tb_failed_keys: set = set()
        if "jsonl" in backends:
            self._jsonl = open(os.path.join(logdir, "metrics.jsonl"), "a")
        if "tensorboard" in backends:
            try:
                from tensorboardX import SummaryWriter

                self._tb = SummaryWriter(logdir=os.path.join(logdir, "tb"))
            except ImportError:
                pass

    def log(
        self,
        data: Dict[str, float],
        step: int,
        prefix: Optional[str] = None,
        wall_time: Optional[float] = None,
    ):
        """``wall_time`` lets deferred-stats flushes stamp each step with the
        time the step actually RAN (captured at step time), not the flush
        time — steady-state rates derived from jsonl timestamps stay valid
        when the trainer batches several steps into one device pull."""
        if prefix:
            data = {f"{prefix}/{k}": v for k, v in data.items()}
        if self._jsonl:
            self._jsonl.write(
                json.dumps(
                    dict(
                        step=step,
                        time=time.time() if wall_time is None else wall_time,
                        **data,
                    )
                )
                + "\n"
            )
            self._jsonl.flush()
        if self._tb:
            for k, v in data.items():
                try:
                    self._tb.add_scalar(k, v, step, walltime=wall_time)
                except Exception:
                    # a non-scalar (or a broken writer) must not spam once
                    # per step, but the FIRST failure per key is logged —
                    # silently pass-ing every exception hid whole metric
                    # families from tensorboard without a trace
                    if k not in self._tb_failed_keys:
                        self._tb_failed_keys.add(k)
                        logger.warning(
                            "tensorboard add_scalar(%r) failed; further "
                            "failures for this key are suppressed",
                            k, exc_info=True,
                        )

    def close(self):
        """Idempotent: a trainer's exit path may close through both its
        own finally and the caller's teardown."""
        if self._jsonl:
            self._jsonl.close()
            self._jsonl = None
        if self._tb:
            self._tb.close()
            self._tb = None
