"""Denominator-keyed statistics tracker (≈ ``realhf/base/stats_tracker.py:20``).

Collects per-step scalar/vector statistics with named *denominators* (boolean
masks) so means are computed over exactly the tokens/sequences that matter.
Scopes compose hierarchically (``with tracker.scope("actor")``). ``export``
reduces everything to plain python floats.

In the reference, export performs a torch.distributed all-reduce; here the
trainer is a single pjit program per host group, so values arriving at the
tracker are already global (device arrays are converted via ``np.asarray``).
Cross-process aggregation, when needed, happens at the master via metadata
messages.
"""

import contextlib
from enum import Enum
from typing import Dict, List, Optional, Union

import numpy as np


class ReduceType(Enum):
    AVG = "avg"
    SUM = "sum"
    MIN = "min"
    MAX = "max"
    SCALAR = "scalar"


_MOE_AUX = "moe_aux"  # reserved scope example


class DistributedStatsTracker:
    def __init__(self, name: str = ""):
        self._scope: List[str] = [name] if name else []
        self._denominators: Dict[str, List[np.ndarray]] = {}
        self._stats: Dict[str, List[np.ndarray]] = {}
        self._meta: Dict[str, dict] = {}

    def _key(self, name: str) -> str:
        return "/".join(self._scope + [name]) if self._scope else name

    @contextlib.contextmanager
    def scope(self, name: str):
        self._scope.append(name)
        try:
            yield
        finally:
            self._scope.pop()

    def denominator(self, **kwargs):
        """Register boolean-mask denominators, e.g. ``mask=loss_mask``."""
        for name, mask in kwargs.items():
            mask = np.asarray(mask)
            if mask.dtype != bool:
                mask = mask.astype(bool)
            key = self._key(name)
            self._denominators.setdefault(key, []).append(mask)
            self._meta[key] = dict(is_denominator=True)

    def stat(
        self,
        denominator: str,
        reduce_type: ReduceType = ReduceType.AVG,
        **kwargs,
    ):
        """Record vector stats reduced over a registered denominator mask.

        The value is paired with the *latest* mask recorded under
        ``denominator`` at call time.
        """
        denom_key = self._key(denominator)
        if denom_key not in self._denominators:
            raise ValueError(f"Unknown denominator {denom_key}")
        mask = self._denominators[denom_key][-1]
        for name, value in kwargs.items():
            value = np.asarray(value, dtype=np.float32)
            key = self._key(name)
            if value.shape != mask.shape:
                raise ValueError(
                    f"stat {key}: shape {value.shape} != denominator "
                    f"{denom_key} shape {mask.shape}"
                )
            # Store the (value, mask) pair so export never has to re-align.
            self._stats.setdefault(key, []).append((value, mask))
            self._meta[key] = dict(
                denominator=denom_key, reduce_type=reduce_type
            )

    def scalar(self, **kwargs):
        """Record plain scalars, averaged over occurrences at export."""
        for name, value in kwargs.items():
            key = self._key(name)
            self._stats.setdefault(key, []).append(
                np.asarray(float(value), dtype=np.float32)
            )
            self._meta[key] = dict(reduce_type=ReduceType.SCALAR)

    def export(self, reset: bool = True) -> Dict[str, float]:
        result: Dict[str, float] = {}
        for key, values in self._stats.items():
            meta = self._meta[key]
            rt = meta.get("reduce_type", ReduceType.SCALAR)
            if rt == ReduceType.SCALAR:
                result[key] = float(np.mean([v for v in values]))
                continue
            vcat = np.concatenate([v.reshape(-1) for v, _ in values])
            mcat = np.concatenate([m.reshape(-1) for _, m in values])
            n = mcat.sum()
            if rt == ReduceType.AVG:
                result[key] = float((vcat * mcat).sum() / max(n, 1))
            elif rt == ReduceType.SUM:
                result[key] = float((vcat * mcat).sum())
            elif rt == ReduceType.MIN:
                result[key] = float(
                    np.where(mcat, vcat, np.inf).min()
                ) if n else 0.0
            elif rt == ReduceType.MAX:
                result[key] = float(
                    np.where(mcat, vcat, -np.inf).max()
                ) if n else 0.0
        for key, masks in self._denominators.items():
            result[f"{key}/n"] = float(sum(m.sum() for m in masks))
        if reset:
            self._stats.clear()
            self._denominators.clear()
        return result


# Default process-level tracker, mirroring reference module-level API.
DEFAULT = DistributedStatsTracker()

denominator = DEFAULT.denominator
stat = DEFAULT.stat
scalar = DEFAULT.scalar
scope = DEFAULT.scope
export = DEFAULT.export
