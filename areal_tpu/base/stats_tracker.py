"""Denominator-keyed statistics tracker (≈ ``realhf/base/stats_tracker.py:20``).

Collects per-step scalar/vector statistics with named *denominators* (boolean
masks) so means are computed over exactly the tokens/sequences that matter.
Scopes compose hierarchically (``with tracker.scope("actor")``). ``export``
reduces everything to plain python floats.

In the reference, export performs a torch.distributed all-reduce
(``realhf/base/stats_tracker.py:20``); here values recorded from inside pjit
are already global, but HOST-side stats (reward scores, rollout latencies,
python counters) are process-local. ``export(cross_host=True)`` reduces those
across processes: each key's partial (numerator, denominator) or (min, max)
pair is allgathered and combined, after a loud key-agreement check.
"""

import contextlib
from enum import Enum
from typing import Dict, List, Optional, Union

import numpy as np


class ReduceType(Enum):
    AVG = "avg"
    SUM = "sum"
    MIN = "min"
    MAX = "max"
    SCALAR = "scalar"


_MOE_AUX = "moe_aux"  # reserved scope example


class DistributedStatsTracker:
    def __init__(self, name: str = ""):
        self._scope: List[str] = [name] if name else []
        self._denominators: Dict[str, List[np.ndarray]] = {}
        self._stats: Dict[str, List[np.ndarray]] = {}
        self._meta: Dict[str, dict] = {}

    def _key(self, name: str) -> str:
        return "/".join(self._scope + [name]) if self._scope else name

    @contextlib.contextmanager
    def scope(self, name: str):
        self._scope.append(name)
        try:
            yield
        finally:
            self._scope.pop()

    def denominator(self, **kwargs):
        """Register boolean-mask denominators, e.g. ``mask=loss_mask``."""
        for name, mask in kwargs.items():
            mask = np.asarray(mask)
            if mask.dtype != bool:
                mask = mask.astype(bool)
            key = self._key(name)
            self._denominators.setdefault(key, []).append(mask)
            self._meta[key] = dict(is_denominator=True)

    def stat(
        self,
        denominator: str,
        reduce_type: ReduceType = ReduceType.AVG,
        **kwargs,
    ):
        """Record vector stats reduced over a registered denominator mask.

        The value is paired with the *latest* mask recorded under
        ``denominator`` at call time.
        """
        denom_key = self._key(denominator)
        if denom_key not in self._denominators:
            raise ValueError(f"Unknown denominator {denom_key}")
        mask = self._denominators[denom_key][-1]
        for name, value in kwargs.items():
            value = np.asarray(value, dtype=np.float32)
            key = self._key(name)
            if value.shape != mask.shape:
                raise ValueError(
                    f"stat {key}: shape {value.shape} != denominator "
                    f"{denom_key} shape {mask.shape}"
                )
            # Store the (value, mask) pair so export never has to re-align.
            self._stats.setdefault(key, []).append((value, mask))
            self._meta[key] = dict(
                denominator=denom_key, reduce_type=reduce_type
            )

    def scalar(self, **kwargs):
        """Record plain scalars, averaged over occurrences at export."""
        for name, value in kwargs.items():
            key = self._key(name)
            self._stats.setdefault(key, []).append(
                np.asarray(float(value), dtype=np.float32)
            )
            self._meta[key] = dict(reduce_type=ReduceType.SCALAR)

    def _partials(self) -> Dict[str, tuple]:
        """Per-key reduction partials: (reduce_type, a, b) where
        AVG/SCALAR -> (weighted sum, count); SUM -> (sum, 0);
        MIN/MAX -> (extreme, valid-count)."""
        out: Dict[str, tuple] = {}
        for key, values in self._stats.items():
            rt = self._meta[key].get("reduce_type", ReduceType.SCALAR)
            if rt == ReduceType.SCALAR:
                out[key] = (rt, float(np.sum(values)), float(len(values)))
                continue
            vcat = np.concatenate([v.reshape(-1) for v, _ in values])
            mcat = np.concatenate([m.reshape(-1) for _, m in values])
            n = float(mcat.sum())
            if rt in (ReduceType.AVG, ReduceType.SUM):
                out[key] = (rt, float((vcat * mcat).sum()), n)
            elif rt == ReduceType.MIN:
                out[key] = (rt, float(np.where(mcat, vcat, np.inf).min()) if n else np.inf, n)
            elif rt == ReduceType.MAX:
                out[key] = (rt, float(np.where(mcat, vcat, -np.inf).max()) if n else -np.inf, n)
        for key, masks in self._denominators.items():
            out[f"{key}/n"] = (ReduceType.SUM, float(sum(m.sum() for m in masks)), 0.0)
        return out

    def export(self, reset: bool = True, cross_host: bool = False) -> Dict[str, float]:
        parts = self._partials()
        if cross_host:
            parts = _cross_host_reduce(parts)
        result: Dict[str, float] = {}
        for key, (rt, a, b) in parts.items():
            if rt in (ReduceType.AVG, ReduceType.SCALAR):
                result[key] = a / max(b, 1)
            elif rt == ReduceType.SUM:
                result[key] = a
            elif rt == ReduceType.MIN:
                result[key] = a if b else 0.0
            elif rt == ReduceType.MAX:
                result[key] = a if b else 0.0
        if reset:
            self._stats.clear()
            self._denominators.clear()
        return result


def _cross_host_reduce(parts: Dict[str, tuple]) -> Dict[str, tuple]:
    """Combine per-process partials across all processes (no-op single-host).
    Keys must agree across processes — divergence raises instead of silently
    skewing metrics."""
    from areal_tpu.parallel import multihost

    if not multihost.is_multihost():
        return parts
    keys = sorted(parts)
    multihost.assert_same_across_hosts("stats_tracker keys", "\x00".join(keys))
    mat = np.asarray([[parts[k][1], parts[k][2]] for k in keys], np.float64)
    gathered = multihost.allgather_rows(mat)  # [P, n_keys, 2]
    out: Dict[str, tuple] = {}
    for i, k in enumerate(keys):
        rt = parts[k][0]
        a_all, b_all = gathered[:, i, 0], gathered[:, i, 1]
        if rt == ReduceType.MIN:
            out[k] = (rt, float(a_all.min()), float(b_all.sum()))
        elif rt == ReduceType.MAX:
            out[k] = (rt, float(a_all.max()), float(b_all.sum()))
        else:
            out[k] = (rt, float(a_all.sum()), float(b_all.sum()))
    return out


# Default process-level tracker, mirroring reference module-level API.
DEFAULT = DistributedStatsTracker()

denominator = DEFAULT.denominator
stat = DEFAULT.stat
scalar = DEFAULT.scalar
scope = DEFAULT.scope
export = DEFAULT.export
