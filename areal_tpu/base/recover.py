"""Failure recovery bookkeeping (≈ ``realhf/base/recover.py:19-80``).

``RecoverInfo`` captures everything the master needs to resume a trial after
restart-the-world recovery: step counters, frequency-control states, the hashes
of data ids already consumed, and the dataloader epoch position. Dumped
atomically as JSON at every checkpoint tick; model/optimizer state itself is
checkpointed separately via Orbax.
"""

import dataclasses
import json
import os
from typing import Dict, List, Optional

from areal_tpu.base import constants, logging

logger = logging.getLogger("recover")

RECOVER_INFO_FILE = "recover_info.json"


@dataclasses.dataclass
class StepInfo:
    epoch: int = 0
    epoch_step: int = 0
    global_step: int = 0

    def next(self, steps_per_epoch: Optional[int] = None) -> "StepInfo":
        epoch, epoch_step = self.epoch, self.epoch_step + 1
        if steps_per_epoch is not None and epoch_step >= steps_per_epoch:
            epoch, epoch_step = epoch + 1, 0
        return StepInfo(epoch, epoch_step, self.global_step + 1)


@dataclasses.dataclass
class RecoverInfo:
    recover_start: StepInfo = dataclasses.field(default_factory=StepInfo)
    last_step_info: StepInfo = dataclasses.field(default_factory=StepInfo)
    save_ctl_states: Dict[str, dict] = dataclasses.field(default_factory=dict)
    ckpt_ctl_states: Dict[str, dict] = dataclasses.field(default_factory=dict)
    eval_ctl_states: Dict[str, dict] = dataclasses.field(default_factory=dict)
    data_loading_dp_idx: int = 0
    hash_vals_to_ignore: List[int] = dataclasses.field(default_factory=list)
    # async-RL restart-the-world state: the resumed trainer republishes both
    # so the gserver manager's staleness gate and the fleet's weight version
    # converge on the restored run instead of the crashed one
    samples_consumed: int = 0
    model_version: int = 0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "RecoverInfo":
        d = dict(d)
        for k in ("recover_start", "last_step_info"):
            d[k] = StepInfo(**d[k])
        return cls(**d)


def dump(info: RecoverInfo, root: Optional[str] = None):
    root = root or constants.get_recover_root()
    path = os.path.join(root, RECOVER_INFO_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(info.to_dict(), f, indent=2)
    os.replace(tmp, path)
    logger.debug("Dumped recover info to %s", path)


def load(root: Optional[str] = None) -> Optional[RecoverInfo]:
    root = root or constants.get_recover_root()
    path = os.path.join(root, RECOVER_INFO_FILE)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return RecoverInfo.from_dict(json.load(f))
