"""Failure recovery bookkeeping (≈ ``realhf/base/recover.py:19-80``).

``RecoverInfo`` captures everything the master needs to resume a trial after
restart-the-world recovery: step counters, frequency-control states, the hashes
of data ids already consumed, and the dataloader epoch position. Dumped
atomically as JSON at every checkpoint tick; model/optimizer state itself is
checkpointed separately via Orbax.

This module is also THE checkpoint commit helper: every checkpoint dir
(Orbax recover state, HF weight-sync export) is written to a
``<path>.tmp-<tag>`` staging dir, a ``COMMIT.json`` manifest (step, version,
param-tree checksums) is fsynced into it, and the staging dir is atomically
renamed over ``<path>``. A preemption at ANY instant leaves either the old
committed checkpoint or the new one — never a half-written dir that a
restarted trainer would try to restore. ``shutil.rmtree`` on a path that can
hold a live checkpoint is only legal inside this module (enforced by
``tools/check_async_hygiene.py``).
"""

import dataclasses
import glob as glob_mod
import hashlib
import json
import os
import shutil
from typing import Dict, List, Optional

from areal_tpu.base import constants, logging

logger = logging.getLogger("recover")

RECOVER_INFO_FILE = "recover_info.json"
CKPT_MANIFEST = "COMMIT.json"
_TMP_MARK = ".tmp-"
_OLD_MARK = ".old-"


@dataclasses.dataclass
class StepInfo:
    epoch: int = 0
    epoch_step: int = 0
    global_step: int = 0

    def next(self, steps_per_epoch: Optional[int] = None) -> "StepInfo":
        epoch, epoch_step = self.epoch, self.epoch_step + 1
        if steps_per_epoch is not None and epoch_step >= steps_per_epoch:
            epoch, epoch_step = epoch + 1, 0
        return StepInfo(epoch, epoch_step, self.global_step + 1)


@dataclasses.dataclass
class RecoverInfo:
    recover_start: StepInfo = dataclasses.field(default_factory=StepInfo)
    last_step_info: StepInfo = dataclasses.field(default_factory=StepInfo)
    save_ctl_states: Dict[str, dict] = dataclasses.field(default_factory=dict)
    ckpt_ctl_states: Dict[str, dict] = dataclasses.field(default_factory=dict)
    eval_ctl_states: Dict[str, dict] = dataclasses.field(default_factory=dict)
    data_loading_dp_idx: int = 0
    hash_vals_to_ignore: List[int] = dataclasses.field(default_factory=list)
    # async-RL restart-the-world state: the resumed trainer republishes both
    # so the gserver manager's staleness gate and the fleet's weight version
    # converge on the restored run instead of the crashed one
    samples_consumed: int = 0
    model_version: int = 0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "RecoverInfo":
        d = dict(d)
        for k in ("recover_start", "last_step_info"):
            d[k] = StepInfo(**d[k])
        return cls(**d)


def dump(info: RecoverInfo, root: Optional[str] = None):
    root = root or constants.get_recover_root()
    path = os.path.join(root, RECOVER_INFO_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(info.to_dict(), f, indent=2)
    os.replace(tmp, path)
    logger.debug("Dumped recover info to %s", path)


def load(root: Optional[str] = None) -> Optional[RecoverInfo]:
    root = root or constants.get_recover_root()
    path = os.path.join(root, RECOVER_INFO_FILE)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return RecoverInfo.from_dict(json.load(f))


# --------------------------------------------------------------------- #
# Checkpoint commit protocol (stage → manifest → atomic rename)
# --------------------------------------------------------------------- #


def _fsync_path(p: str) -> None:
    """Best-effort fsync of a file or directory (a rename is only durable
    once the parent directory's entry is flushed)."""
    try:
        fd = os.open(p, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass  # e.g. a filesystem that cannot fsync directories


def tree_checksum(tree) -> str:
    """Structural checksum of a pytree: sha256 over every leaf's key path,
    shape, and dtype. Cheap (no value hashing — that would gather every
    shard to host) yet catches the corruption modes that matter at restore
    time: a manifest paired with the wrong tree, a truncated save, a model-
    or optimizer-config drift between save and load."""
    from jax import tree_util

    h = hashlib.sha256()
    leaves, _ = tree_util.tree_flatten_with_path(tree)
    for path, leaf in leaves:
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
        h.update(f"{tree_util.keystr(path)}|{shape}|{dtype}\n".encode())
    return h.hexdigest()


def staging_path(path: str, tag: str) -> str:
    """The staging dir for one save attempt. ``tag`` must be identical on
    every host of a multihost save (all processes write shards into the same
    dir), so callers derive it from the step counter, not a random nonce."""
    return f"{path}{_TMP_MARK}{tag}"


def prepare_staging(path: str, tag: str) -> str:
    """Clear leftovers of a previously crashed attempt with the same tag.
    Returns the staging path WITHOUT creating it (Orbax insists on creating
    its target itself)."""
    tmp = staging_path(path, tag)
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    return tmp


def write_manifest(dirpath: str, manifest: dict) -> None:
    """Fsync ``COMMIT.json`` into ``dirpath`` — the presence of a parseable
    manifest IS the committed bit."""
    p = os.path.join(dirpath, CKPT_MANIFEST)
    tmp = p + ".part"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, p)
    _fsync_path(dirpath)


def read_manifest(dirpath: str) -> Optional[dict]:
    """The manifest of a committed checkpoint dir, or None when the dir is
    missing, uncommitted (no manifest: a crashed mid-save leftover), or the
    manifest itself is corrupt."""
    p = os.path.join(dirpath, CKPT_MANIFEST)
    try:
        with open(p) as f:
            m = json.load(f)
        return m if isinstance(m, dict) else None
    except (OSError, ValueError):
        return None


def is_committed(dirpath: str) -> bool:
    return read_manifest(dirpath) is not None


def commit_checkpoint(staging: str, path: str, manifest: dict) -> str:
    """Commit ``staging`` as ``path``: fsync the manifest into the staging
    dir, move any previous committed dir aside, atomically rename the
    staging dir into place, then delete the old one. Every intermediate
    state is recoverable by :func:`resolve_committed`."""
    write_manifest(staging, manifest)
    parent = os.path.dirname(os.path.abspath(path))
    old = None
    if os.path.exists(path):
        old = f"{path}{_OLD_MARK}displaced"
        if os.path.exists(old):
            shutil.rmtree(old)
        os.rename(path, old)
    os.rename(staging, path)
    _fsync_path(parent)
    if old is not None:
        shutil.rmtree(old, ignore_errors=True)
    logger.debug("committed checkpoint %s (manifest %s)", path, manifest)
    return path


def _candidates(path: str) -> List[str]:
    return (
        [path]
        + sorted(glob_mod.glob(glob_mod.escape(path) + _TMP_MARK + "*"))
        + sorted(glob_mod.glob(glob_mod.escape(path) + _OLD_MARK + "*"))
    )


def resolve_committed(path: str) -> Optional[str]:
    """Newest committed checkpoint for the canonical ``path``.

    Handles every crash window of :func:`commit_checkpoint`: an uncommitted
    staging dir is discarded; a COMMITTED staging/displaced sibling that is
    newer than ``path`` (crash between the manifest fsync and the renames)
    is promoted into place; stale committed siblings are cleaned. Returns
    ``path`` when a committed checkpoint ends up there, else None.
    """
    best, best_key = None, None
    for cand in _candidates(path):
        m = read_manifest(cand)
        if m is None:
            continue
        # prefer the canonical path on ties: it finished its swap
        key = (m.get("step", -1), m.get("version", -1), cand == path)
        if best_key is None or key > best_key:
            best, best_key = cand, key
    if best is None:
        return None
    if best != path:
        from areal_tpu.base import metrics as metrics_mod

        # THE fallback event the guard/ counter documents: the canonical
        # dir was missing/uncommitted/stale and a committed sibling (a
        # crash between manifest fsync and the renames) is promoted
        metrics_mod.counters.add(metrics_mod.GUARD_CKPT_FALLBACKS)
        logger.warning(
            "promoting newest committed checkpoint %s -> %s "
            "(a previous save crashed mid-commit)", best, path,
        )
        if os.path.exists(path):
            shutil.rmtree(path)
        os.rename(best, path)
        _fsync_path(os.path.dirname(os.path.abspath(path)))
    # strays (uncommitted staging dirs, superseded committed siblings) are
    # now garbage — a restarted save would otherwise trip over them
    for cand in _candidates(path):
        if cand != path:
            shutil.rmtree(cand, ignore_errors=True)
    return path


def discard_checkpoint(path: str) -> None:
    """THE sanctioned way to delete a dir that may hold a live checkpoint
    (e.g. weight-sync pruning). Centralized here so the async-hygiene pass
    can flag every other ``rmtree`` on checkpoint-capable paths."""
    shutil.rmtree(path, ignore_errors=True)
